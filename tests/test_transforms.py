"""gluon.data.vision.transforms (reference: gluon/data/vision/transforms.py;
reference tests: tests/python/unittest/test_gluon_data_vision.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.gluon.data.vision import transforms as T


def _img(h=8, w=10, c=3, seed=0):
    return mx.nd.array(np.random.RandomState(seed)
                       .randint(0, 256, (h, w, c)).astype(np.uint8),
                       dtype="uint8")


def test_to_tensor_and_normalize():
    x = _img()
    t = T.ToTensor()(x)
    assert t.shape == (3, 8, 10) and t.dtype == np.float32
    assert float(t.max().asnumpy()) <= 1.0
    n = T.Normalize(mean=(0.5, 0.5, 0.5), std=(0.25, 0.25, 0.25))(t)
    np.testing.assert_allclose(
        n.asnumpy(), (t.asnumpy() - 0.5) / 0.25, atol=1e-6)


def test_compose_and_cast():
    out = T.Compose([T.ToTensor(), T.Cast("float32")])(_img())
    assert out.shape == (3, 8, 10)


def test_center_crop_and_crop_resize():
    x = _img(10, 12)
    c = T.CenterCrop(6)(x)
    assert c.shape == (6, 6, 3)
    np.testing.assert_array_equal(c.asnumpy(), x.asnumpy()[2:8, 3:9])
    cr = T.CropResize(x=2, y=1, width=5, height=4)(x)
    np.testing.assert_array_equal(cr.asnumpy(), x.asnumpy()[1:5, 2:7])
    cr2 = T.CropResize(x=2, y=1, width=5, height=4, size=(8, 8))(x)
    assert cr2.shape == (8, 8, 3)


def test_resize_and_random_resized_crop():
    assert T.Resize(16)(_img()).shape == (16, 16, 3)
    out = T.RandomResizedCrop(7)(_img(20, 20))
    assert out.shape == (7, 7, 3)


def test_flips_cover_both_branches():
    x = _img()
    np.random.seed(0)
    seen = {T.RandomFlipLeftRight()(x).asnumpy().tobytes()
            for _ in range(20)}
    assert len(seen) == 2  # identity + flipped both observed
    flipped = x.asnumpy()[:, ::-1]
    assert flipped.tobytes() in seen


def test_color_jitters_stay_in_range_and_vary():
    x = _img()
    np.random.seed(1)
    for t in (T.RandomBrightness(0.5), T.RandomContrast(0.5),
              T.RandomSaturation(0.5), T.RandomHue(0.5),
              T.RandomLighting(0.3),
              T.RandomColorJitter(0.3, 0.3, 0.3, 0.3)):
        outs = [t(x).asnumpy() for _ in range(3)]
        for o in outs:
            assert o.min() >= 0.0 and o.max() <= 255.0, type(t).__name__
        assert any(not np.array_equal(outs[0], o) for o in outs[1:]), \
            "%s never varied" % type(t).__name__


def test_random_hue_zero_delta_is_identity():
    x = _img()
    out = T.RandomHue(0.0)(x).asnumpy()
    # the YIQ round-trip matrices compose to identity within ~1.4e-3 per
    # coefficient, i.e. under one grey level at uint8 scale
    np.testing.assert_allclose(out, x.asnumpy().astype(np.float32),
                               atol=1.0)


def test_random_lighting_zero_alpha_is_identity():
    x = _img()
    out = T.RandomLighting(0.0)(x).asnumpy()
    np.testing.assert_allclose(out, x.asnumpy().astype(np.float32),
                               atol=1e-5)


def test_transforms_in_dataloader():
    """transform_first through a DataLoader — the reference's standard
    train-pipeline composition."""
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader

    imgs = np.random.RandomState(2).randint(
        0, 256, (8, 8, 10, 3)).astype(np.uint8)
    labels = np.arange(8).astype(np.float32)
    ds = ArrayDataset(mx.nd.array(imgs, dtype="uint8"),
                      mx.nd.array(labels))
    tf = T.Compose([T.ToTensor(),
                    T.Normalize((0.5,) * 3, (0.5,) * 3)])
    loader = DataLoader(ds.transform_first(tf), batch_size=4)
    batches = list(loader)
    assert len(batches) == 2
    xb, yb = batches[0]
    assert xb.shape == (4, 3, 8, 10)
