"""NDArray basics (mirrors reference tests/python/unittest/test_ndarray.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx


def test_creation():
    a = mx.nd.zeros((2, 3))
    assert a.shape == (2, 3)
    assert a.dtype == np.float32
    b = mx.nd.ones((2, 3), dtype="int32")
    assert b.dtype == np.int32
    c = mx.nd.array([[1, 2], [3, 4]])
    assert c.dtype == np.float32  # reference default
    assert np.allclose(c.asnumpy(), [[1, 2], [3, 4]])
    d = mx.nd.full((2,), 7.0)
    assert np.allclose(d.asnumpy(), [7, 7])
    e = mx.nd.arange(0, 10, 2)
    assert np.allclose(e.asnumpy(), [0, 2, 4, 6, 8])


def test_arithmetic():
    a = mx.nd.array([1.0, 2.0, 3.0])
    b = mx.nd.array([4.0, 5.0, 6.0])
    assert np.allclose((a + b).asnumpy(), [5, 7, 9])
    assert np.allclose((a - b).asnumpy(), [-3, -3, -3])
    assert np.allclose((a * b).asnumpy(), [4, 10, 18])
    assert np.allclose((b / a).asnumpy(), [4, 2.5, 2])
    assert np.allclose((a + 1).asnumpy(), [2, 3, 4])
    assert np.allclose((1 + a).asnumpy(), [2, 3, 4])
    assert np.allclose((10 - a).asnumpy(), [9, 8, 7])
    assert np.allclose((a ** 2).asnumpy(), [1, 4, 9])
    assert np.allclose((2 / a).asnumpy(), [2, 1, 2 / 3])
    assert np.allclose((-a).asnumpy(), [-1, -2, -3])


def test_inplace():
    a = mx.nd.ones((3,))
    a += 2
    assert np.allclose(a.asnumpy(), [3, 3, 3])
    a *= 2
    assert np.allclose(a.asnumpy(), [6, 6, 6])
    a[:] = 0
    assert np.allclose(a.asnumpy(), [0, 0, 0])
    a[1] = 5
    assert np.allclose(a.asnumpy(), [0, 5, 0])


def test_indexing():
    a = mx.nd.array(np.arange(12).reshape(3, 4))
    assert np.allclose(a[1].asnumpy(), [4, 5, 6, 7])
    assert np.allclose(a[1:3, 0].asnumpy(), [4, 8])
    assert a[1, 2].asscalar() == 6.0


def test_reshape_magic():
    a = mx.nd.zeros((2, 3, 4))
    assert a.reshape((6, 4)).shape == (6, 4)
    assert a.reshape((-1,)).shape == (24,)
    assert a.reshape((0, -1)).shape == (2, 12)
    assert a.reshape((-2,)).shape == (2, 3, 4)
    assert a.reshape((-3, 4)).shape == (6, 4)
    assert a.reshape((2, -4, 3, 1, 4)).shape == (2, 3, 1, 4)
    assert a.reshape((0, 0, -1)).shape == (2, 3, 4)


def test_dot_semantics():
    a = mx.nd.array(np.random.rand(2, 3, 4).astype(np.float32))
    b = mx.nd.array(np.random.rand(4, 5).astype(np.float32))
    out = mx.nd.dot(a, b)
    assert out.shape == (2, 3, 5)
    ref = np.tensordot(a.asnumpy(), b.asnumpy(), axes=([2], [0]))
    assert np.allclose(out.asnumpy(), ref, atol=1e-5)
    # batch_dot
    x = mx.nd.array(np.random.rand(5, 2, 3).astype(np.float32))
    y = mx.nd.array(np.random.rand(5, 3, 4).astype(np.float32))
    out = mx.nd.batch_dot(x, y)
    assert out.shape == (5, 2, 4)


def test_reduce():
    a = mx.nd.array(np.arange(24).reshape(2, 3, 4))
    assert a.sum().asscalar() == 276
    assert a.sum(axis=1).shape == (2, 4)
    assert a.sum(axis=(0, 2)).shape == (3,)
    assert a.mean(axis=0, keepdims=True).shape == (1, 3, 4)
    assert mx.nd.sum(a, axis=1, exclude=True).shape == (3,)
    assert a.max().asscalar() == 23
    assert a.argmax(axis=2).shape == (2, 3)


def test_concat_split_stack():
    a = mx.nd.ones((2, 3))
    b = mx.nd.zeros((2, 3))
    c = mx.nd.concat(a, b, dim=1)
    assert c.shape == (2, 6)
    parts = mx.nd.split(c, num_outputs=2, axis=1)
    assert len(parts) == 2 and parts[0].shape == (2, 3)
    s = mx.nd.stack(a, b, axis=0)
    assert s.shape == (2, 2, 3)


def test_take_onehot_where():
    w = mx.nd.array(np.arange(12).reshape(4, 3))
    idx = mx.nd.array([0, 2], dtype="int32")
    out = mx.nd.take(w, idx)
    assert np.allclose(out.asnumpy(), [[0, 1, 2], [6, 7, 8]])
    oh = mx.nd.one_hot(idx, depth=4)
    assert oh.shape == (2, 4)
    cond = mx.nd.array([1, 0, 1])
    x = mx.nd.array([1, 2, 3])
    y = mx.nd.array([10, 20, 30])
    assert np.allclose(mx.nd.where(cond, x, y).asnumpy(), [1, 20, 3])


def test_transfer_and_sync():
    a = mx.nd.ones((4,), ctx=mx.cpu())
    b = a.as_in_context(mx.cpu(0))
    assert np.allclose(b.asnumpy(), 1)
    a.wait_to_read()
    mx.nd.waitall()


def test_save_load(tmp_path):
    fname = str(tmp_path / "arrs")
    d = {"w": mx.nd.ones((2, 2)), "b": mx.nd.zeros((3,))}
    mx.nd.save(fname, d)
    loaded = mx.nd.load(fname)
    assert set(loaded) == {"w", "b"}
    assert np.allclose(loaded["w"].asnumpy(), 1)


def test_astype_cast():
    a = mx.nd.ones((2,))
    assert a.astype("int32").dtype == np.int32
    assert a.astype(np.float16).dtype == np.float16


def test_topk_sort():
    a = mx.nd.array([[3, 1, 2], [0, 5, 4]])
    idx = mx.nd.topk(a, k=2)
    assert idx.shape == (2, 2)
    v = mx.nd.topk(a, k=1, ret_typ="value")
    assert np.allclose(v.asnumpy(), [[3], [5]])
    s = mx.nd.sort(a, is_ascend=False)
    assert np.allclose(s.asnumpy(), [[3, 2, 1], [5, 4, 0]])


def test_dlpack_roundtrip_numpy_and_torch():
    """DLPack interop (reference: ndarray.py:2231 to_dlpack_for_read /
    from_dlpack over 3rdparty/dlpack): exchange with torch and back."""
    x = mx.nd.array(np.arange(12, dtype=np.float32).reshape(3, 4))
    # self-roundtrip via capsule
    y = mx.nd.from_dlpack(x.to_dlpack_for_read())
    np.testing.assert_array_equal(y.asnumpy(), x.asnumpy())

    torch = pytest.importorskip("torch")
    t = torch.from_dlpack(x.to_dlpack_for_read())
    assert t.shape == (3, 4)
    np.testing.assert_array_equal(t.numpy(), x.asnumpy())
    # torch -> mx
    t2 = torch.arange(6, dtype=torch.float32).reshape(2, 3) + 1
    z = mx.nd.from_dlpack(t2)
    np.testing.assert_array_equal(z.asnumpy(), t2.numpy())
    # write-capsule exists (copy-on-write divergence documented)
    assert mx.nd.from_dlpack(x.to_dlpack_for_write()).shape == (3, 4)


def test_int64_policy():
    """r3 int64 audit (VERDICT #8): in-range int64 narrows silently to
    int32 on device; out-of-range RAISES instead of silently corrupting
    (2**40 used to round-trip as 0); host-side dgl paths keep full
    int64; no x64 truncation warnings from int64-emitting ops."""
    import warnings

    from mxnet_tpu.base import MXNetError

    a = mx.nd.array(np.array([5, -7], np.int64), dtype=np.int64)
    np.testing.assert_array_equal(a.asnumpy(), [5, -7])

    with pytest.raises(MXNetError, match="int32 range"):
        mx.nd.array(np.array([2 ** 40], np.int64), dtype=np.int64)

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        cnt, edges = mx.nd.histogram(
            mx.nd.array(np.random.uniform(0, 1, 64).astype(np.float32)),
            bin_cnt=4)
        assert int(cnt.asnumpy().sum()) == 64
    trunc = [x for x in w if "int64" in str(x.message)]
    assert not trunc, [str(x.message) for x in trunc]

    # the dgl host path round-trips in-range int64 edge values exactly
    indices = np.array([0, 1], np.int64)
    indptr = np.array([0, 1, 2], np.int64)
    small = mx.nd.sparse.csr_matrix(
        (np.array([7, 9], np.int64), indices, indptr), shape=(2, 2))
    u = mx.nd.array(np.array([0, 1], np.int64), dtype=np.int64)
    v = mx.nd.array(np.array([0, 1], np.int64), dtype=np.int64)
    out = mx.nd.contrib.edge_id(small, u, v)
    np.testing.assert_array_equal(out.asnumpy(), [7, 9])
