"""serving.generate tests: KV page allocator units, paged decode-attention
Pallas-vs-jnp parity, sampling-op contracts, continuous-batching scheduler
semantics (stub engine), Transformer-LM engine greedy parity against the
gluon full-sequence oracle, the HTTP ``:generate`` surface, and THE
acceptance e2e: a 2-replica pooled LM under >=8 concurrent generations
with unequal budgets, late joiners, zero post-warm compiles and full
KV-page reclaim.

Everything runs on CPU with tiny configs (2 layers, d<=32, vocab<=128) —
the tier-1 budget has no headroom (ROADMAP.md).
"""
import json
import os
import tempfile
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon.model_zoo.transformer import TransformerLM, lm_mini
from mxnet_tpu.serving import (
    DeadlineExceededError, GenerateScheduler, KVPageAllocator,
    ModelRepository, QueueFullError, ServedLM, ServingServer,
    TransformerLMEngine, load_lm, save_lm,
)


# ---------------------------------------------------------------------------
# KV page allocator units
# ---------------------------------------------------------------------------

def test_kv_allocator_alloc_free_roundtrip():
    a = KVPageAllocator(8, 4, name="alloc/1")
    assert a.pages_for(1) == 1 and a.pages_for(4) == 1
    assert a.pages_for(5) == 2 and a.pages_for(12) == 3
    g1 = a.alloc(3)
    g2 = a.alloc(5)
    assert len(g1) == 3 and len(g2) == 5
    assert not set(g1) & set(g2)          # disjoint grants
    assert a.free_pages == 0 and a.used_pages == 8
    assert a.alloc(1) is None             # exhausted: None, not partial
    a.free(g1)
    assert a.free_pages == 3
    g3 = a.alloc(2)
    assert set(g3) <= set(g1)             # freed pages are reused
    a.free(g3)
    a.free(g2)
    assert a.free_pages == 8 and a.used_pages == 0


def test_kv_allocator_fragmentation_interleaved():
    """Interleaved alloc/free must keep serving from a fragmented free
    list — pages are identity-only, any free page serves any grant."""
    a = KVPageAllocator(6, 2, name="alloc/2")
    grants = [a.alloc(2) for _ in range(3)]
    a.free(grants[1])                      # free the MIDDLE grant
    g = a.alloc(2)
    assert g is not None and set(g) == set(grants[1])
    # page-table reuse after sequence completion: all pages cycle
    a.free(grants[0])
    a.free(grants[2])
    a.free(g)
    seen = set()
    for _ in range(3):
        g = a.alloc(2)
        seen.update(g)
        a.free(g)
    assert a.used_pages == 0


def test_kv_allocator_double_free_raises():
    a = KVPageAllocator(4, 2, name="alloc/3")
    g = a.alloc(2)
    a.free(g)
    with pytest.raises(MXNetError):
        a.free(g)
    with pytest.raises(MXNetError):
        a.free([99])
    with pytest.raises(MXNetError):
        KVPageAllocator(0, 2)


def test_kv_allocator_gauges():
    a = KVPageAllocator(5, 2, name="allocg/1")
    snap = telemetry.snapshot()
    assert snap['mxtpu_serve_kv_pages_total{model="allocg/1"}'][
        "value"] == 5
    g = a.alloc(3)
    assert telemetry.snapshot()[
        'mxtpu_serve_kv_pages_used{model="allocg/1"}']["value"] == 3
    a.free(g)
    assert telemetry.snapshot()[
        'mxtpu_serve_kv_pages_used{model="allocg/1"}']["value"] == 0


# ---------------------------------------------------------------------------
# paged decode attention: Pallas (interpret) vs dense-gather jnp oracle
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("dtype,tol", [("float32", 2e-6), ("bfloat16", 4e-2)])
def test_paged_attention_pallas_vs_jnp(monkeypatch, dtype, tol):
    import jax.numpy as jnp

    from mxnet_tpu.ops import pallas_kernels as pk

    rng = np.random.RandomState(7)
    b, h, d, pages, ps, maxp = 4, 2, 32, 16, 4, 5
    q = jnp.asarray(rng.randn(b, h, d), dtype=dtype)
    kp = jnp.asarray(rng.randn(pages, h, ps, d), dtype=dtype)
    vp = jnp.asarray(rng.randn(pages, h, ps, d), dtype=dtype)
    tbl = jnp.asarray(rng.randint(0, pages, (b, maxp)), jnp.int32)
    # ragged lengths incl. a full row, a page-straddling row, a 1-token
    # row and an INERT row (length 0 — the scheduler's batch padding)
    lens = jnp.asarray([maxp * ps, 7, 1, 0], jnp.int32)
    ref = pk.paged_attention_reference(q, kp, vp, tbl, lens,
                                       1.0 / np.sqrt(d))
    monkeypatch.setenv("MXTPU_PALLAS_DECODE", "1")   # force the kernel
    out = pk.paged_attention(q, kp, vp, tbl, lens)
    # live rows match to dtype tolerance; the inert row is unused garbage
    err = np.max(np.abs(np.asarray(ref, np.float32)[:3]
                        - np.asarray(out, np.float32)[:3]))
    assert err < tol, err
    assert np.all(np.isfinite(np.asarray(out, np.float32)))


def test_paged_attention_gate_fallback(monkeypatch):
    """`0` forces the jnp path; `auto` off-TPU is the jnp path too — all
    three spellings agree numerically."""
    import jax.numpy as jnp

    from mxnet_tpu.ops import pallas_kernels as pk

    rng = np.random.RandomState(3)
    q = jnp.asarray(rng.randn(2, 2, 16), jnp.float32)
    kp = jnp.asarray(rng.randn(8, 2, 4, 16), jnp.float32)
    vp = jnp.asarray(rng.randn(8, 2, 4, 16), jnp.float32)
    tbl = jnp.asarray(rng.randint(0, 8, (2, 3)), jnp.int32)
    lens = jnp.asarray([5, 9], jnp.int32)
    outs = {}
    for gate in ("0", "auto", "1"):
        monkeypatch.setenv("MXTPU_PALLAS_DECODE", gate)
        outs[gate] = np.asarray(pk.paged_attention(q, kp, vp, tbl, lens))
    assert np.allclose(outs["0"], outs["auto"])
    assert np.max(np.abs(outs["0"] - outs["1"])) < 2e-6


# ---------------------------------------------------------------------------
# sampling ops
# ---------------------------------------------------------------------------

def test_sample_token_greedy_is_argmax():
    logits = mx.nd.array(np.random.RandomState(0).randn(6, 24)
                         .astype(np.float32))
    out = mx.nd.sample_token(logits, temperature=0.0).asnumpy()
    assert np.array_equal(out, np.argmax(logits.asnumpy(), axis=-1))


def test_sample_token_top_k_top_p_masks():
    rng = np.random.RandomState(1)
    logits = mx.nd.array(rng.randn(64, 16).astype(np.float32))
    top3 = np.argsort(logits.asnumpy(), axis=-1)[:, -3:]
    out = mx.nd.sample_token(logits, temperature=1.0, top_k=3).asnumpy()
    for o, allowed in zip(out, top3):
        assert o in allowed, (o, allowed)
    # top_k=1 degenerates to greedy regardless of temperature
    out1 = mx.nd.sample_token(logits, temperature=5.0, top_k=1).asnumpy()
    assert np.array_equal(out1, np.argmax(logits.asnumpy(), axis=-1))
    # a tiny top_p keeps only the argmax too
    outp = mx.nd.sample_token(logits, temperature=5.0,
                              top_p=1e-6).asnumpy()
    assert np.array_equal(outp, np.argmax(logits.asnumpy(), axis=-1))


def test_sample_token_seeded_reproducible_and_symbolic():
    import mxnet_tpu.symbol as sym

    logits = mx.nd.array(np.random.RandomState(2).randn(8, 32)
                         .astype(np.float32))
    mx.random.seed(11)
    a = mx.nd.sample_token(logits, temperature=1.0).asnumpy()
    mx.random.seed(11)
    b = mx.nd.sample_token(logits, temperature=1.0).asnumpy()
    assert np.array_equal(a, b)
    # registered in the symbol namespace too (nd+symbol parity)
    s = sym.sample_token(sym.var("logits"), temperature=0.0)
    ex = s.bind(mx.cpu(), {"logits": logits})
    (out,) = ex.forward()
    assert np.array_equal(out.asnumpy(),
                          np.argmax(logits.asnumpy(), axis=-1))


def test_sample_token_logits_per_row_params():
    """The decode executable's form: per-row temperature/top_k/top_p
    arrays — greedy rows exact, stochastic rows inside their top-k."""
    import jax

    from mxnet_tpu.ops.random_ops import sample_token_logits

    rng = np.random.RandomState(4)
    logits = rng.randn(5, 12).astype(np.float32)
    temps = np.asarray([0.0, 1.0, 0.0, 2.0, 0.0], np.float32)
    ks = np.asarray([0, 2, 0, 4, 0], np.int32)
    ps = np.ones(5, np.float32)
    out = np.asarray(sample_token_logits(
        jax.random.PRNGKey(0), logits, temps, ks, ps))
    greedy = np.argmax(logits, axis=-1)
    for i in (0, 2, 4):
        assert out[i] == greedy[i]
    assert out[1] in np.argsort(logits[1])[-2:]
    assert out[3] in np.argsort(logits[3])[-4:]


# ---------------------------------------------------------------------------
# scheduler semantics on a stub engine (no jax compiles: fast, exact)
# ---------------------------------------------------------------------------

class StubEngine:
    """Deterministic no-model engine: prefill answers (sum(prompt)+1)
    mod vocab, decode answers last+1 mod vocab. Records each decode
    step's live-row count so tests can assert batch composition."""

    def __init__(self, vocab=64, buckets=(1, 2, 4), page_size=2,
                 num_pages=12, max_prompt=4, max_new_tokens=8,
                 eos_id=None, step_sleep=0.0, prefill_gate=None):
        self.vocab_size = vocab
        self.buckets = list(buckets)
        self.page_size = page_size
        self.num_pages = num_pages
        self.max_prompt = max_prompt
        self.max_new_tokens = max_new_tokens
        self.max_pages_per_seq = -(-(max_prompt + max_new_tokens)
                                   // page_size)
        self.eos_id = eos_id
        self.step_sleep = step_sleep
        self.prefill_gate = prefill_gate   # Event: hold prefill (tests)
        self.step_counts = []

    def warm(self):
        return 0.0

    def prefill(self, tokens, page_row, sampling, key):
        if self.prefill_gate is not None:
            self.prefill_gate.wait(5.0)
        return (sum(tokens) + 1) % self.vocab_size

    def decode_step(self, tokens, positions, dest_pages, dest_slots,
                    tables, lengths, temps, top_ks, top_ps, key):
        if self.step_sleep:
            time.sleep(self.step_sleep)
        self.step_counts.append(int((np.asarray(lengths) > 0).sum()))
        return ((np.asarray(tokens) + 1) % self.vocab_size).astype(np.int32)

    def geometry(self):
        return {"num_pages": self.num_pages, "page_size": self.page_size}


def _stub_expected(prompt, n, vocab=64):
    first = (sum(prompt) + 1) % vocab
    out = [first]
    for _ in range(n - 1):
        out.append((out[-1] + 1) % vocab)
    return out


def test_scheduler_stub_continuous_batching_join_leave():
    eng = StubEngine(step_sleep=0.01)
    sched = GenerateScheduler(eng, name="stub/1", queue_depth=8)
    try:
        ra = sched.submit([1, 2], max_new_tokens=8)
        time.sleep(0.05)                       # A is decoding alone
        rb = sched.submit([3], max_new_tokens=3)   # late joiner
        a = ra.wait(10)
        b = rb.wait(10)
        assert a == _stub_expected([1, 2], 8)
        assert b == _stub_expected([3], 3)
        # the batch really changed size at step granularity: A ran alone,
        # then A+B together, then A alone again after B finished
        assert 2 in eng.step_counts and 1 in eng.step_counts
        assert eng.step_counts.index(2) > 0    # A started solo
        assert sched.allocator.used_pages == 0
    finally:
        sched.close(drain=False, timeout=0)


def test_scheduler_stub_eos_and_validation():
    eng = StubEngine(eos_id=7)
    sched = GenerateScheduler(eng, name="stub/2", queue_depth=8)
    try:
        # (sum=4)+1=5, then 6, then 7=eos: stops early with reason "eos"
        r = sched.submit([4], max_new_tokens=8)
        out = r.wait(10)
        assert out[-1] == 7 and len(out) == 3
        assert r.finish_reason == "eos"
        with pytest.raises(MXNetError):
            sched.submit([], max_new_tokens=2)
        with pytest.raises(MXNetError):
            sched.submit([1] * 99, max_new_tokens=2)   # prompt too long
        with pytest.raises(MXNetError):
            sched.submit([1], max_new_tokens=0)
        with pytest.raises(MXNetError):
            sched.submit([999], max_new_tokens=2)      # token out of range
    finally:
        sched.close(drain=False, timeout=0)


def test_scheduler_stub_deadline_and_queue_full():
    gate = threading.Event()
    eng = StubEngine(prefill_gate=gate)
    sched = GenerateScheduler(eng, name="stub/3", queue_depth=1)
    try:
        r1 = sched.submit([1], max_new_tokens=2)   # worker parks in prefill
        time.sleep(0.05)
        r2 = sched.submit([2], max_new_tokens=2)   # fills the queue
        with pytest.raises(QueueFullError):
            sched.submit([3], max_new_tokens=2)
        gate.set()
        assert r1.wait(10) == _stub_expected([1], 2)
        assert r2.wait(10) == _stub_expected([2], 2)
        # expired-in-queue: deadline already past at admission
        gate.clear()
        r4 = sched.submit([1], max_new_tokens=2,
                          deadline=time.monotonic() - 0.001)
        gate.set()
        with pytest.raises(DeadlineExceededError):
            r4.wait(10)
        assert sched.allocator.used_pages == 0
    finally:
        sched.close(drain=False, timeout=0)


def test_scheduler_stub_page_pressure_serializes():
    """Worst-case page reservation: two sequences that each need the
    whole pool run one after the other — pressure queues admissions,
    never deadlocks or evicts a running sequence."""
    eng = StubEngine(page_size=2, num_pages=6, max_prompt=4,
                     max_new_tokens=8)
    assert eng.max_pages_per_seq == 6          # one seq = the whole pool
    sched = GenerateScheduler(eng, name="stub/4", queue_depth=8)
    try:
        r1 = sched.submit([1, 2, 3, 4], max_new_tokens=8)
        r2 = sched.submit([2, 2, 2, 2], max_new_tokens=8)
        assert r1.wait(10) == _stub_expected([1, 2, 3, 4], 8)
        assert r2.wait(10) == _stub_expected([2, 2, 2, 2], 8)
        # never more than one resident batch: every step ran solo
        assert set(eng.step_counts) == {1}
        assert sched.allocator.used_pages == 0
    finally:
        sched.close(drain=False, timeout=0)


def test_scheduler_abort_reclaims_pages():
    eng = StubEngine(step_sleep=0.02)
    sched = GenerateScheduler(eng, name="stub/5", queue_depth=8)
    try:
        r = sched.submit([1], max_new_tokens=8)
        time.sleep(0.05)                       # mid-decode
        n = sched.abort_pending()
        assert n >= 1
        with pytest.raises(Exception):
            r.wait(5)
        deadline = time.monotonic() + 5
        while sched.allocator.used_pages and time.monotonic() < deadline:
            time.sleep(0.01)                   # worker lap reclaims
        assert sched.allocator.used_pages == 0
    finally:
        sched.close(drain=False, timeout=0)


# ---------------------------------------------------------------------------
# the real engine: greedy parity vs the gluon full-sequence oracle
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny_lm():
    lm = lm_mini(vocab_size=96)
    lm.initialize(mx.init.Xavier())
    return lm


def _gluon_greedy(lm, prompt, n):
    toks = list(prompt)
    out = []
    for _ in range(n):
        logits = lm(mx.nd.array([toks], dtype="int32")).asnumpy()[0, -1]
        t = int(np.argmax(logits))
        out.append(t)
        toks.append(t)
    return out


@pytest.fixture(scope="module")
def lm_scheduler(tiny_lm):
    eng = TransformerLMEngine(lm=tiny_lm, num_pages=32, page_size=4,
                              max_prompt=8, max_new_tokens=12, max_batch=4)
    sched = GenerateScheduler(eng, name="lm/1", queue_depth=16)
    yield sched
    sched.close(drain=False, timeout=0)


def test_engine_greedy_matches_gluon_oracle(lm_scheduler, tiny_lm):
    """THE correctness core: incremental paged-KV decode computes the
    same function as the gluon block's full causal forward — greedy
    token sequences match exactly, and batching requests together
    changes nothing (batch invariance)."""
    prompts = [[3, 5, 7], [2], [9, 4, 6, 1, 8], [1, 2, 3, 4]]
    budgets = [5, 9, 3, 7]
    oracles = [_gluon_greedy(tiny_lm, p, n)
               for p, n in zip(prompts, budgets)]
    misses = telemetry.get_registry().counter("mxtpu_jit_cache_miss_total")
    base = misses.value
    reqs = [lm_scheduler.submit(p, max_new_tokens=n)
            for p, n in zip(prompts, budgets)]
    outs = [r.wait(60) for r in reqs]
    assert outs == oracles
    # zero-compile steady state: every bucket was covered by warm
    assert misses.value - base == 0
    assert lm_scheduler.allocator.used_pages == 0


def test_engine_sampled_tokens_stay_in_vocab(lm_scheduler):
    r = lm_scheduler.submit([5, 6], max_new_tokens=6, temperature=0.8,
                            top_k=4)
    out = r.wait(60)
    assert len(out) == 6
    assert all(0 <= t < 96 for t in out)
    assert lm_scheduler.allocator.used_pages == 0


# ---------------------------------------------------------------------------
# artifact roundtrip + HTTP surface (in-process ServedLM)
# ---------------------------------------------------------------------------

def test_save_load_lm_roundtrip(tiny_lm, tmp_path):
    prefix = save_lm(tiny_lm, str(tmp_path / "lm"))
    lm2 = load_lm(prefix)
    ids = mx.nd.array(np.random.RandomState(0).randint(0, 96, (2, 5)),
                      dtype="int32")
    assert np.array_equal(tiny_lm(ids).asnumpy(), lm2(ids).asnumpy())
    with pytest.raises(MXNetError):
        load_lm(str(tmp_path / "nope"))


def test_http_generate_e2e(tiny_lm, tmp_path):
    prefix = save_lm(tiny_lm, str(tmp_path / "lm"))
    repo = ModelRepository()
    model = repo.load("lm", prefix, generate=True,
                      generate_opts=dict(num_pages=32, page_size=4,
                                         max_prompt=8, max_new_tokens=12,
                                         max_batch=4))
    srv = ServingServer(repo, port=0, addr="127.0.0.1").start()
    url = "http://127.0.0.1:%d/v1/models/lm:generate" % srv.port
    try:
        oracle = _gluon_greedy(tiny_lm, [3, 1, 4], 6)
        body = json.dumps({"tokens": [3, 1, 4], "max_new_tokens": 6,
                           "timeout_ms": 60000}).encode()
        req = urllib.request.Request(
            url, data=body, headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=90) as r:
            resp = json.loads(r.read())
        assert resp["tokens"] == oracle
        assert resp["num_generated"] == 6
        assert resp["finish_reason"] == "length"
        # repository listing carries the generate geometry + kv state
        desc = repo.describe()["models"][0]
        assert desc["kind"] == "generate"
        assert desc["kv"]["pages_used"] == 0
        # malformed bodies are the client's fault: 400, not 500
        for bad in ({"tokens": "abc"}, {"tokens": []},
                    {"tokens": [1], "max_new_tokens": 0},
                    {"tokens": [1], "max_new_tokens": "abc"},
                    {"tokens": [1], "temperature": []}, {}):
            breq = urllib.request.Request(
                url, data=json.dumps(bad).encode(),
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(breq, timeout=30)
            ei.value.read()
            assert ei.value.code == 400, bad
    finally:
        srv.shutdown()
        repo.unload("lm", timeout=1.0)


def test_generate_on_predict_model_is_400(tmp_path):
    """:generate against a predict model answers a clear 400."""
    from mxnet_tpu import gluon

    net = gluon.nn.HybridSequential(prefix="p_")
    with net.name_scope():
        net.add(gluon.nn.Dense(4))
    net.initialize(mx.init.Xavier(), ctx=mx.cpu())
    net.hybridize()
    net(mx.nd.array(np.zeros((1, 3), np.float32)))
    prefix = str(tmp_path / "model")
    net.export(prefix, epoch=0)
    repo = ModelRepository()
    repo.load("p", prefix, input_shapes={"data": (3,)}, max_batch=2)
    srv = ServingServer(repo, port=0, addr="127.0.0.1").start()
    try:
        req = urllib.request.Request(
            "http://127.0.0.1:%d/v1/models/p:generate" % srv.port,
            data=json.dumps({"tokens": [1]}).encode())
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=30)
        ei.value.read()
        assert ei.value.code == 400
    finally:
        srv.shutdown()
        repo.unload("p", timeout=1.0)


# ---------------------------------------------------------------------------
# THE acceptance e2e (ISSUE 13): 2-replica pooled LM, >=8 concurrent
# generations with unequal budgets, late joiner mid-decode, zero
# post-warm compiles, KV pages fully reclaimed at drain
# ---------------------------------------------------------------------------

def test_pooled_lm_generate_e2e(tmp_path):
    lm = lm_mini(vocab_size=96)
    lm.initialize(mx.init.Xavier())
    prefix = save_lm(lm, str(tmp_path / "lm"))
    repo = ModelRepository()
    model = repo.load(
        "lm", prefix, generate=True, replicas=2,
        generate_opts=dict(num_pages=32, page_size=4, max_prompt=8,
                           max_new_tokens=16, max_batch=4),
        heartbeat_ms=500, backoff_ms=50, teardown_grace=1.0)
    srv = ServingServer(repo, port=0, addr="127.0.0.1").start()
    url = "http://127.0.0.1:%d/v1/models/lm:generate" % srv.port
    try:
        assert model.pool.describe()["mode"] == "generate"
        prompts = [[3, 5, 7], [2], [9, 4, 6, 1, 8], [1, 2, 3, 4],
                   [8, 8], [5], [7, 6, 5, 4, 3], [1]]
        budgets = [5, 9, 3, 7, 4, 8, 6, 2]   # unequal: sequences leave
        #                                      the running batch early
        oracles = [_gluon_greedy(lm, p, n)
                   for p, n in zip(prompts, budgets)]

        results = [None] * len(prompts)

        def client(i, delay=0.0):
            if delay:
                time.sleep(delay)
            body = json.dumps({"tokens": prompts[i],
                               "max_new_tokens": budgets[i],
                               "timeout_ms": 90000}).encode()
            req = urllib.request.Request(
                url, data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=120) as r:
                results[i] = json.loads(r.read())

        # 6 immediate clients + 2 LATE JOINERS landing mid-decode: they
        # must be admitted into the running batches without restarting
        # anyone (every output still matches the one-request oracle)
        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(6)]
        threads += [threading.Thread(target=client, args=(i, 0.15))
                    for i in (6, 7)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(180)
        assert not any(t.is_alive() for t in threads)
        for i in range(len(prompts)):
            assert results[i] is not None, i
            assert results[i]["tokens"] == oracles[i], \
                (i, results[i]["tokens"], oracles[i])
            assert results[i]["finish_reason"] == "length"
        # worker-side acceptance counters via the stats round trip:
        # ZERO jit_compile events after warm on every replica, and the
        # KV used-gauge back to 0 at drain
        for rid in (0, 1):
            s = model.pool.replica_stats(rid)
            assert s is not None, rid
            assert s["jit_after_warm"] == 0, s
            assert s["kv_pages_used"] == 0, s
            assert s["pending"] == 0, s
    finally:
        srv.shutdown()
        model.close(drain=False, timeout=0)
