"""Behavioral shape/edge sweep (reference: test_operator.py's broadcast /
reduction / indexing batteries — e.g. test_broadcast_binary_op,
test_reduce, test_take — which sweep shape combinations rather than single
fixed cases). Seeded, numpy as the oracle, gradients via the tape where
meaningful."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd

RNG = np.random.RandomState(7)

BCAST_SHAPES = [
    ((2, 3, 4), (2, 3, 4)),
    ((2, 3, 4), (1, 3, 1)),
    ((2, 3, 4), (4,)),
    ((1,), (5, 1)),
    ((3, 1, 5), (1, 4, 1)),
    ((2, 3), (1, 1)),
]

BINARY = [
    ("broadcast_add", np.add),
    ("broadcast_sub", np.subtract),
    ("broadcast_mul", np.multiply),
    ("broadcast_maximum", np.maximum),
    ("broadcast_minimum", np.minimum),
    ("broadcast_power", None),  # positive base below
]


@pytest.mark.parametrize("opname,npop", BINARY)
@pytest.mark.parametrize("sa,sb", BCAST_SHAPES)
def test_broadcast_binary(opname, npop, sa, sb):
    a = RNG.uniform(0.5, 2.0, sa).astype(np.float32)
    b = RNG.uniform(0.5, 2.0, sb).astype(np.float32)
    fn = getattr(mx.nd, opname)
    an, bn = mx.nd.array(a), mx.nd.array(b)
    an.attach_grad()
    bn.attach_grad()
    with autograd.record():
        out = fn(an, bn)
        out.sum().backward()
    want = np.power(a, b) if npop is None else npop(a, b)
    np.testing.assert_allclose(out.asnumpy(), want, rtol=1e-5, atol=1e-6)
    # gradient shapes must match the inputs (the broadcast is summed back)
    assert an.grad.shape == sa and bn.grad.shape == sb
    assert np.all(np.isfinite(an.grad.asnumpy()))
    assert np.all(np.isfinite(bn.grad.asnumpy()))


REDUCE_AXES = [None, 0, 1, -1, (0, 1), (0, -1)]


@pytest.mark.parametrize("opname,npfn", [
    ("sum", np.sum), ("mean", np.mean), ("max", np.max), ("min", np.min),
    ("prod", np.prod),
])
@pytest.mark.parametrize("axis", REDUCE_AXES)
@pytest.mark.parametrize("keepdims", [False, True])
def test_reductions(opname, npfn, axis, keepdims):
    x = RNG.uniform(0.5, 1.5, (3, 4, 5)).astype(np.float32)
    got = getattr(mx.nd, opname)(mx.nd.array(x), axis=axis,
                                 keepdims=keepdims)
    want = npfn(x, axis=axis, keepdims=keepdims)
    np.testing.assert_allclose(got.asnumpy(), np.asarray(want,
                                                         dtype=np.float32),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("mode,idx", [
    ("clip", [0, 2, 3]),
    ("clip", [-1, 5, 99]),      # out-of-range clips (reference take clip)
    ("wrap", [-1, 4, 7]),       # wraps modulo axis size
])
def test_take_modes(mode, idx):
    x = RNG.randn(4, 3).astype(np.float32)
    got = mx.nd.take(mx.nd.array(x),
                     mx.nd.array(np.array(idx, np.float32)),
                     axis=0, mode=mode).asnumpy()
    n = x.shape[0]
    if mode == "clip":
        ref_idx = np.clip(idx, 0, n - 1)
    else:
        ref_idx = np.mod(idx, n)
    np.testing.assert_allclose(got, x[ref_idx], rtol=1e-6)


@pytest.mark.parametrize("begin,end,step", [
    ((0, 0), (2, 3), None),
    ((1, None), (None, None), None),
    ((0, 4), (4, 0), (1, -1)),   # negative step
    ((-2, -3), (None, None), None),
])
def test_slice_semantics(begin, end, step):
    x = RNG.randn(4, 5).astype(np.float32)
    kwargs = {"begin": begin, "end": end}
    if step is not None:
        kwargs["step"] = step
    got = mx.nd.slice(mx.nd.array(x), **kwargs).asnumpy()
    sl = []
    for i in range(2):
        b = begin[i]
        e = end[i] if end else None
        s = step[i] if step else None
        sl.append(slice(b, e, s))
    np.testing.assert_allclose(got, x[tuple(sl)], rtol=1e-6)


def test_broadcast_grad_values():
    """Broadcast grads reduce correctly: d/db sum(a*b) with b broadcast
    over axis 0 = sum_rows(a)."""
    a = RNG.randn(6, 4).astype(np.float32)
    b = RNG.randn(1, 4).astype(np.float32)
    an, bn = mx.nd.array(a), mx.nd.array(b)
    bn.attach_grad()
    with autograd.record():
        (mx.nd.broadcast_mul(an, bn)).sum().backward()
    np.testing.assert_allclose(bn.grad.asnumpy(),
                               a.sum(0, keepdims=True), rtol=1e-5)


@pytest.mark.parametrize("shape,reps", [
    ((2, 3), (2, 2)), ((3,), (4,)), ((2, 1, 2), (1, 3, 1)),
])
def test_tile_repeat(shape, reps):
    x = RNG.randn(*shape).astype(np.float32)
    np.testing.assert_allclose(
        mx.nd.tile(mx.nd.array(x), reps=reps).asnumpy(),
        np.tile(x, reps), rtol=1e-6)


def test_where_and_clip_edges():
    x = np.array([-np.inf, -2.0, 0.0, 3.0, np.inf], np.float32)
    got = mx.nd.clip(mx.nd.array(x), a_min=-1.0, a_max=1.0).asnumpy()
    np.testing.assert_allclose(got, np.clip(x, -1, 1), rtol=1e-6)
    cond = np.array([1, 0, 1, 0, 1], np.float32)
    a = np.arange(5, dtype=np.float32)
    b = -a
    got = mx.nd.where(mx.nd.array(cond), mx.nd.array(a),
                      mx.nd.array(b)).asnumpy()
    np.testing.assert_allclose(got, np.where(cond > 0, a, b), rtol=1e-6)


@pytest.mark.parametrize("k", [1, 3])
@pytest.mark.parametrize("ret_typ", ["value", "indices"])
def test_topk_semantics(k, ret_typ):
    x = RNG.randn(3, 7).astype(np.float32)
    got = mx.nd.topk(mx.nd.array(x), k=k, ret_typ=ret_typ,
                     axis=-1).asnumpy()
    order = np.argsort(-x, axis=-1)[:, :k]
    if ret_typ == "indices":
        np.testing.assert_array_equal(got.astype(np.int64), order)
    else:
        np.testing.assert_allclose(got, np.take_along_axis(x, order, -1),
                                   rtol=1e-6)


def test_concat_stack_split_roundtrip():
    xs = [RNG.randn(2, 3).astype(np.float32) for _ in range(4)]
    cat = mx.nd.concat(*[mx.nd.array(x) for x in xs], dim=0)
    np.testing.assert_allclose(cat.asnumpy(), np.concatenate(xs, 0),
                               rtol=1e-6)
    st = mx.nd.stack(*[mx.nd.array(x) for x in xs], axis=0)
    np.testing.assert_allclose(st.asnumpy(), np.stack(xs, 0), rtol=1e-6)
    parts = mx.nd.split(mx.nd.array(np.concatenate(xs, 0)), num_outputs=4,
                        axis=0)
    for p, x in zip(parts, xs):
        np.testing.assert_allclose(p.asnumpy(), x, rtol=1e-6)
