"""Worker body for the dist-trainer regression test: gluon.Trainer with a
dist_sync kvstore and ONE local device must still allreduce gradients
across ranks (reference trainer.py:169 — 'dist' in kvstore.type engages
the kvstore regardless of local device count; the standard
1-GPU-per-worker mode).

Each rank trains linear regression on a different data shard; with grad
sync the ranks stay bit-identical and converge to the true weights. The
parent greps the per-rank weight checksum to prove cross-rank identity."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")  # axon sitecustomize override

from mxnet_tpu.parallel import collectives  # noqa: E402

collectives.init_process_group()

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, gluon  # noqa: E402
from mxnet_tpu.gluon import nn  # noqa: E402


def main():
    kv = mx.kv.create("dist_sync")
    r, n = kv.rank, kv.num_workers

    np.random.seed(42)  # same data-generating process on every rank
    w_true = np.random.normal(size=(8, 1)).astype(np.float32)
    x_all = np.random.normal(size=(128, 8)).astype(np.float32)
    y_all = x_all @ w_true
    xr, yr = x_all[r::n], y_all[r::n]  # per-rank shard

    # deliberately DIFFERENT init per rank: the dist kvstore's init-time
    # broadcast must make rank 0's draw authoritative, or the replicas
    # train permanently diverged (identical grad sums never close an
    # initial offset)
    np.random.seed(1000 + r)
    net = nn.Dense(1, in_units=8, use_bias=False)
    net.initialize(mx.init.Normal(0.5))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1}, kvstore=kv)
    l2 = gluon.loss.L2Loss()
    for _ in range(60):
        with autograd.record():
            loss = l2(net(mx.nd.array(xr)), mx.nd.array(yr))
        loss.backward()
        trainer.step(len(xr) * n)

    w = net.weight.data().asnumpy()
    err = float(np.abs(w.flatten() - w_true.flatten()).max())
    assert err < 0.05, "rank %d did not converge: err=%s" % (r, err)
    # checksum must be IDENTICAL across ranks (grad sync every step)
    print("DIST_TRAINER_OK rank=%d/%d wsum=%.6f" % (r, n, float(w.sum())),
          flush=True)


if __name__ == "__main__":
    main()
