"""Parallel subsystem tests on the 8-virtual-device CPU mesh (conftest sets
xla_force_host_platform_device_count=8 — SURVEY §4 test-strategy note)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import parallel as par
from mxnet_tpu.gluon import nn, loss as gloss


def test_make_mesh_shapes():
    m = par.make_mesh()
    assert m.devices.size == 8 and m.axis_names == ("dp",)
    m2 = par.make_mesh([("dp", 2), ("tp", -1)])
    assert m2.devices.shape == (2, 4)
    with pytest.raises(ValueError):
        par.make_mesh([("dp", 3)])


def test_sharding_rules():
    m = par.make_mesh([("dp", 2), ("tp", 4)])
    rules = par.ShardingRules()
    spec = rules.spec_for("dense0_weight", (16, 8), m)
    assert spec[0] == "tp"
    # explicit rule wins
    rules2 = par.ShardingRules({r".*_bias": (None,)})
    assert tuple(rules2.spec_for("dense0_bias", (16,), m)) == (None,)
    # scalar replicated
    assert tuple(rules.spec_for("gamma", (), m)) == ()


def _mlp(prefix=None):
    # explicit prefixes: auto-numbered names (dense9_/dense10_) sort
    # differently as global counters grow, breaking sorted-name pairing
    # between two nets when the whole suite runs
    net = nn.HybridSequential(prefix=prefix)
    with net.name_scope():
        net.add(nn.Dense(32, activation="relu", prefix="d1_"))
        net.add(nn.Dense(10, prefix="d2_"))
    net.initialize()
    return net


def test_distributed_trainer_dp_matches_local():
    np.random.seed(0)
    x = np.random.randn(16, 20).astype("float32")
    y = np.random.randint(0, 10, (16,)).astype("float32")

    # local single-device reference run
    mx.random.seed(42)
    net_a = _mlp(prefix="neta_")
    net_a(mx.nd.array(x))  # materialize deferred shapes
    mx.random.seed(7)
    net_b = _mlp(prefix="netb_")
    net_b(mx.nd.array(x))
    # copy A's weights into B so both start identical
    pa = sorted(net_a.collect_params().items())
    pb = sorted(net_b.collect_params().items())
    for (_, a), (_, b) in zip(pa, pb):
        b.set_data(a.data())

    l2 = gloss.SoftmaxCrossEntropyLoss()
    trainer_local = mx.gluon.Trainer(net_a.collect_params(), "sgd",
                                     {"learning_rate": 0.1})
    from mxnet_tpu import autograd
    for _ in range(3):
        with autograd.record():
            l = l2(net_a(mx.nd.array(x)), mx.nd.array(y))
        l.backward()
        trainer_local.step(16)

    mesh = par.make_mesh([("dp", 8)])
    dt = par.DistributedTrainer(net_b, "sgd", {"learning_rate": 0.1},
                                loss=l2, mesh=mesh)
    for _ in range(3):
        dt.step(x, y)
    dt.sync_params()

    for (_, a), (_, b) in zip(pa, pb):
        np.testing.assert_allclose(a.data().asnumpy(), b.data().asnumpy(),
                                   rtol=2e-4, atol=2e-5)


def test_distributed_trainer_loss_decreases_tp():
    np.random.seed(1)
    x = np.random.randn(16, 20).astype("float32")
    y = np.random.randint(0, 10, (16,)).astype("float32")
    net = _mlp()
    net(mx.nd.array(x))
    mesh = par.make_mesh([("dp", 2), ("tp", 4)])
    dt = par.DistributedTrainer(net, "adam", {"learning_rate": 0.01},
                                loss=gloss.SoftmaxCrossEntropyLoss(),
                                mesh=mesh)
    losses = [float(dt.step(x, y).asscalar()) for _ in range(8)]
    assert losses[-1] < losses[0]


def test_distributed_trainer_fsdp_runs():
    np.random.seed(2)
    x = np.random.randn(8, 16).astype("float32")
    y = np.random.randint(0, 4, (8,)).astype("float32")
    net = nn.HybridSequential()
    net.add(nn.Dense(64, activation="relu"))
    net.add(nn.Dense(4))
    net.initialize()
    net(mx.nd.array(x))
    mesh = par.make_mesh([("fsdp", 8)])
    dt = par.DistributedTrainer(net, "sgd", {"learning_rate": 0.05,
                                             "momentum": 0.9},
                                loss=gloss.SoftmaxCrossEntropyLoss(),
                                mesh=mesh, rules=par.ShardingRules(fsdp_min_size=8))
    l0 = float(dt.step(x, y).asscalar())
    l1 = float(dt.step(x, y).asscalar())
    assert np.isfinite(l0) and np.isfinite(l1)
    # fsdp params must actually be sharded
    sharded = [s for s in dt._shardings if not s.is_fully_replicated]
    assert sharded


def test_batchnorm_aux_state_updates():
    np.random.seed(3)
    x = np.random.randn(32, 8).astype("float32")
    y = np.random.randint(0, 3, (32,)).astype("float32")
    net = nn.HybridSequential()
    net.add(nn.Dense(16))
    net.add(nn.BatchNorm())
    net.add(nn.Dense(3))
    net.initialize()
    net(mx.nd.array(x))
    mesh = par.make_mesh([("dp", 8)])
    dt = par.DistributedTrainer(net, "sgd", {"learning_rate": 0.1},
                                loss=gloss.SoftmaxCrossEntropyLoss(), mesh=mesh)
    aux_i = [i for i, p in enumerate(dt._params) if "running_mean" in
             dt._param_names[i]]
    assert aux_i
    before = np.asarray(dt._arrays[aux_i[0]])
    dt.step(x, y)
    after = np.asarray(dt._arrays[aux_i[0]])
    assert not np.allclose(before, after)


def test_collectives_eager_allreduce():
    import jax

    devs = jax.devices()[:4]
    arrs = [jax.device_put(np.full((3,), float(i + 1), np.float32), d)
            for i, d in enumerate(devs)]
    out = par.all_reduce_arrays(arrs)
    for i, o in enumerate(out):
        np.testing.assert_allclose(np.asarray(o), np.full((3,), 10.0))
        assert list(o.devices())[0] == devs[i]


def _ref_attention(q, k, v, causal):
    s = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(q.shape[-1])
    if causal:
        L = q.shape[1]
        mask = np.tril(np.ones((L, L), bool))
        s = np.where(mask, s, -np.inf)
    p = np.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    return np.einsum("bhqk,bkhd->bqhd", p, v)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_exact(causal):
    np.random.seed(4)
    B, L, H, D = 2, 32, 2, 8
    q = np.random.randn(B, L, H, D).astype(np.float32)
    k = np.random.randn(B, L, H, D).astype(np.float32)
    v = np.random.randn(B, L, H, D).astype(np.float32)
    mesh = par.make_mesh([("dp", 2), ("sp", 4)])
    out = np.asarray(par.ring_attention_sharded(q, k, v, mesh=mesh,
                                                causal=causal))
    ref = _ref_attention(q, k, v, causal)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_trainer_save_load_states(tmp_path):
    np.random.seed(5)
    x = np.random.randn(8, 8).astype("float32")
    y = np.random.randint(0, 2, (8,)).astype("float32")
    net = nn.HybridSequential()
    net.add(nn.Dense(2))
    net.initialize()
    net(mx.nd.array(x))
    mesh = par.make_mesh([("dp", 8)])
    dt = par.DistributedTrainer(net, "adam", {"learning_rate": 0.01},
                                loss=gloss.SoftmaxCrossEntropyLoss(), mesh=mesh)
    dt.step(x, y)
    f = str(tmp_path / "states.bin")
    dt.save_states(f)
    dt.load_states(f)
    dt.step(x, y)


def test_sync_batchnorm_sharded_equals_global_stats():
    """The SyncBatchNorm claim (gluon/contrib/nn.py): under the distributed
    trainer with the batch sharded over dp, XLA's mean/var reductions insert
    the cross-replica psum, so BN stats equal the GLOBAL batch stats — not
    per-shard stats. Verified against a single-device full-batch run
    (VERDICT round-1 weak item 6)."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import contrib as gcontrib
    from mxnet_tpu.parallel import DistributedTrainer, make_mesh

    def build():
        np.random.seed(11)
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Conv2D(4, 3, padding=1, use_bias=False),
                gcontrib.nn.SyncBatchNorm(),
                gluon.nn.Flatten(), gluon.nn.Dense(3))
        net.initialize(mx.init.Xavier())
        return net

    rng = np.random.RandomState(5)
    # per-shard distributions differ wildly: shard 0..3 get different scales,
    # so per-shard BN stats would diverge hard from global-batch stats
    x_np = np.concatenate([
        rng.normal(loc=i - 1.5, scale=0.5 + i, size=(2, 3, 8, 8))
        for i in range(4)]).astype(np.float32)
    y_np = rng.randint(0, 3, (8,)).astype(np.float32)
    x, y = mx.nd.array(x_np), mx.nd.array(y_np)

    losses, stats = [], []
    for ndev in (1, 4):
        net = build()
        net(x)  # init params identically (seeded)
        import jax

        mesh = make_mesh([("dp", ndev)], devices=jax.devices()[:ndev])
        trainer = DistributedTrainer(
            net, "sgd", {"learning_rate": 0.0},
            loss=gluon.loss.SoftmaxCrossEntropyLoss(), mesh=mesh)
        losses.append(float(trainer.step(x, y).asnumpy()))
        trainer.sync_params()
        params = net.collect_params()
        mean = [v.data().asnumpy() for k, v in params.items()
                if "running_mean" in k][0]
        var = [v.data().asnumpy() for k, v in params.items()
               if "running_var" in k][0]
        stats.append((mean, var))

    # same loss and identical running stats whether the batch is sharded
    # over 4 devices or seen whole on 1
    assert abs(losses[0] - losses[1]) < 1e-4, losses
    np.testing.assert_allclose(stats[0][0], stats[1][0], rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(stats[0][1], stats[1][1], rtol=1e-4, atol=1e-5)


def test_pipeline_parallel_matches_sequential():
    """pp=4 GPipe schedule vs running the stages sequentially: forward and
    grads identical (SURVEY §2.3: PP is absent in the reference; this is
    the TPU-native stage-parallel path)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mxnet_tpu.parallel.mesh import make_mesh
    from mxnet_tpu.parallel.pipeline import (pipeline_apply,
                                             pipeline_stack_params)

    rng = np.random.RandomState(0)
    d, b = 16, 8
    params = [{"w": jnp.asarray(rng.normal(0, 0.5, (d, d)).astype(np.float32)),
               "b": jnp.asarray(rng.normal(0, 0.1, (d,)).astype(np.float32))}
              for _ in range(4)]
    stacked = pipeline_stack_params(params)
    x = jnp.asarray(rng.normal(size=(b, d)).astype(np.float32))

    def stage(p, a):
        return jnp.tanh(a @ p["w"] + p["b"])

    def seq(ps, a):
        for p in ps:
            a = stage(p, a)
        return a

    mesh = make_mesh([("pp", 4)], devices=jax.devices()[:4])
    out = pipeline_apply(stage, stacked, x, num_microbatches=4, mesh=mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(seq(params, x)),
                               atol=1e-5)

    g_pl = jax.grad(lambda s, xx: (pipeline_apply(
        stage, s, xx, num_microbatches=4, mesh=mesh) ** 2).sum())(stacked, x)
    g_sq = pipeline_stack_params(
        jax.grad(lambda ps, xx: (seq(ps, xx) ** 2).sum())(params, x))
    for a, b_ in zip(jax.tree_util.tree_leaves(g_pl),
                     jax.tree_util.tree_leaves(g_sq)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=1e-4)


def test_pipeline_microbatch_count_independent():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mxnet_tpu.parallel.mesh import make_mesh
    from mxnet_tpu.parallel.pipeline import (pipeline_apply,
                                             pipeline_stack_params)

    rng = np.random.RandomState(1)
    params = [{"w": jnp.asarray(rng.normal(0, 0.5, (8, 8)).astype(np.float32))}
              for _ in range(2)]
    stacked = pipeline_stack_params(params)
    x = jnp.asarray(rng.normal(size=(12, 8)).astype(np.float32))
    mesh = make_mesh([("pp", 2)], devices=jax.devices()[:2])

    def stage(p, a):
        return jnp.tanh(a @ p["w"])

    outs = [np.asarray(pipeline_apply(stage, stacked, x, num_microbatches=m,
                                      mesh=mesh)) for m in (2, 3, 6)]
    np.testing.assert_allclose(outs[0], outs[1], atol=1e-6)
    np.testing.assert_allclose(outs[0], outs[2], atol=1e-6)


def test_moe_expert_parallel_training():
    """MoEFFN under DistributedTrainer on a dp x ep mesh: expert tables
    shard over `ep`, the step compiles and trains, and the sharded forward
    equals the single-device forward."""
    import jax
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.contrib.moe import MoEFFN
    from mxnet_tpu.parallel import DistributedTrainer, make_mesh

    np.random.seed(3)

    class Net(gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.embed = gluon.nn.Dense(16, flatten=False)
                self.moe = MoEFFN(units=16, hidden_size=32, num_experts=4,
                                  capacity_factor=2.0)
                self.out = gluon.nn.Dense(4, flatten=False)

        def hybrid_forward(self, F, x):
            return self.out(self.moe(self.embed(x)))

    net = Net()
    net.initialize(mx.init.Xavier())
    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.normal(size=(8, 6, 12)).astype(np.float32))
    y = mx.nd.array(rng.randint(0, 4, (8, 6)).astype(np.float32))
    ref = net(x).asnumpy()

    mesh = make_mesh([("dp", 2), ("ep", 4)], devices=jax.devices()[:8])
    trainer = DistributedTrainer(
        net, "adam", {"learning_rate": 1e-3},
        loss=gluon.loss.SoftmaxCrossEntropyLoss(), mesh=mesh)
    # expert tables actually sharded over ep
    i = trainer._param_names.index(
        [n for n in trainer._param_names if "expert_w_in" in n][0])
    spec = trainer._shardings[i].spec
    assert "ep" in str(spec), spec
    losses = [float(trainer.step(x, y).asnumpy()) for _ in range(5)]
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses
    trainer.sync_params()
    got = net(x).asnumpy()
    assert np.isfinite(got).all()


def test_moe_aux_loss_in_distributed_trainer():
    """return_aux MoE + a plain-callable loss under DistributedTrainer: the
    trainer hands the FULL output tuple to the loss, so the load-balance/
    z-loss terms fold into the compiled objective (regression: extra
    outputs were silently dropped, making aux untrainable in the sharded
    step)."""
    import jax
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.contrib.moe import MoEFFN
    from mxnet_tpu.parallel import DistributedTrainer, make_mesh

    class Net(gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.embed = gluon.nn.Dense(16, flatten=False)
                self.moe = MoEFFN(units=16, hidden_size=32, num_experts=4,
                                  num_experts_per_token=2, z_loss_coef=1e-3,
                                  capacity_factor=2.0, return_aux=True)
                self.out = gluon.nn.Dense(4, flatten=False)

        def hybrid_forward(self, F, x):
            h, aux = self.moe(self.embed(x))
            return self.out(h), aux

    sce = gluon.loss.SoftmaxCrossEntropyLoss()
    seen_aux = []

    def loss_fn(out, label):
        logits, aux = out
        seen_aux.append(aux)  # proves the tuple reached the callable
        return sce(logits, label) + 0.01 * aux

    net = Net()
    net.initialize(mx.init.Xavier())
    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.normal(size=(8, 6, 12)).astype(np.float32))
    y = mx.nd.array(rng.randint(0, 4, (8, 6)).astype(np.float32))
    net(x)

    gate0 = net.moe.gate_weight.data().asnumpy().copy()
    mesh = make_mesh([("dp", 2), ("ep", 4)], devices=jax.devices()[:8])
    trainer = DistributedTrainer(net, "adam", {"learning_rate": 1e-3},
                                 loss=loss_fn, mesh=mesh)
    losses = [float(trainer.step(x, y).asnumpy()) for _ in range(5)]
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses
    assert seen_aux, "loss callable never saw the output tuple"
    # the router must receive gradient (through combine-weights + aux)
    trainer.sync_params()
    gate1 = net.moe.gate_weight.data().asnumpy()
    assert not np.allclose(gate0, gate1), "gate weights never updated"


def test_sharded_checkpoint_resume_and_remesh(tmp_path):
    """orbax/tensorstore sharded checkpoint (SURVEY §5.4 TPU extension):
    save on a dp2 x fsdp2 x tp2 mesh, resume bit-exact on the same mesh AND
    on a different topology (dp4 x tp2) — arrays land directly on their new
    shardings, no single-host gather."""
    import jax
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.parallel import (DistributedTrainer, ShardingRules,
                                    make_mesh)

    def mknet():
        net = gluon.nn.HybridSequential(prefix="ckptnet_")
        with net.name_scope():
            net.add(gluon.nn.Dense(32, activation="relu"), gluon.nn.Dense(4))
        net.initialize(mx.init.Xavier())
        return net

    def mktrainer(net, mesh):
        return DistributedTrainer(
            net, "adam", {"learning_rate": 1e-2},
            loss=gluon.loss.SoftmaxCrossEntropyLoss(), mesh=mesh,
            rules=ShardingRules(fsdp_min_size=1))

    np.random.seed(0)
    x = mx.nd.array(np.random.uniform(-1, 1, (8, 16)).astype(np.float32))
    y = mx.nd.array((np.arange(8) % 4).astype(np.float32))
    mesh = make_mesh([("dp", 2), ("fsdp", 2), ("tp", 2)],
                     devices=jax.devices()[:8])
    net = mknet()
    net(x)
    tr = mktrainer(net, mesh)
    for _ in range(4):
        tr.step(x, y)
    tr.save_checkpoint(tmp_path, step=4)

    net2 = mknet()
    net2(x)
    tr2 = mktrainer(net2, mesh)
    tr2.load_checkpoint(tmp_path, step=4)

    mesh2 = make_mesh([("dp", 4), ("tp", 2)], devices=jax.devices()[:8])
    net3 = mknet()
    net3(x)
    tr3 = mktrainer(net3, mesh2)
    tr3.load_checkpoint(tmp_path, step=4)

    la = float(tr.step(x, y).asnumpy())
    lb = float(tr2.step(x, y).asnumpy())
    lc = float(tr3.step(x, y).asnumpy())
    assert abs(la - lb) < 1e-6, (la, lb)
    assert abs(la - lc) < 1e-5, (la, lc)


def test_moe_aux_loss_channels():
    """Eager: aux_loss attribute holds a concrete value. Traced/hybridized:
    return_aux=True returns (out, aux) — attribute side-channels would leak
    dead tracers (review finding)."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import autograd
    from mxnet_tpu.gluon.contrib.moe import MoEFFN

    np.random.seed(0)
    x = mx.nd.array(np.random.normal(size=(2, 6, 16)).astype(np.float32))

    moe = MoEFFN(units=16, hidden_size=8, num_experts=2)
    moe.initialize(mx.init.Xavier())
    with autograd.record():
        out = moe(x)
        L = (out * out).mean() + 0.01 * moe.aux_loss
    L.backward()
    assert float(moe.aux_loss.asnumpy()) >= 1.0 - 1e-5

    moe2 = MoEFFN(units=16, hidden_size=8, num_experts=2, return_aux=True)
    moe2.initialize(mx.init.Xavier())
    moe2.hybridize()
    out2, aux2 = moe2(x)
    assert out2.shape == x.shape and aux2.shape == ()
    # hybridized attribute must NOT hold a stale tracer
    assert moe2.aux_loss is None or hasattr(moe2.aux_loss, "asnumpy")


def test_moe_topk_routing():
    """num_experts_per_token=2 + z_loss_coef routes through topk_moe: output
    differs from top-1 routing on the same weights, aux folds in the z-loss,
    and gradients reach every expert table."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import autograd
    from mxnet_tpu.gluon.contrib.moe import MoEFFN

    np.random.seed(1)
    x = mx.nd.array(np.random.normal(size=(2, 6, 16)).astype(np.float32))

    top1 = MoEFFN(units=16, hidden_size=8, num_experts=4, return_aux=True)
    top2 = MoEFFN(units=16, hidden_size=8, num_experts=4, return_aux=True,
                  num_experts_per_token=2, z_loss_coef=1e-3,
                  capacity_factor=4.0)
    top1.initialize(mx.init.Xavier())
    top2.initialize(mx.init.Xavier())
    # same weights in both blocks
    for p1, p2 in zip(top1.collect_params().values(),
                      top2.collect_params().values()):
        p2.set_data(p1.data())

    o1, a1 = top1(x)
    with autograd.record():
        o2, a2 = top2(x)
        L = (o2 * o2).mean() + 0.01 * a2
    L.backward()
    assert o2.shape == x.shape and a2.shape == ()
    # top-2 blends a second expert in -> outputs must differ from top-1
    assert not np.allclose(o1.asnumpy(), o2.asnumpy(), atol=1e-5)
    # z-loss actually folds in: identical weights with z_loss_coef=0 must
    # report a strictly smaller aux
    top2_noz = MoEFFN(units=16, hidden_size=8, num_experts=4,
                      return_aux=True, num_experts_per_token=2,
                      capacity_factor=4.0)
    top2_noz.initialize(mx.init.Xavier())
    for p1, p2 in zip(top2.collect_params().values(),
                      top2_noz.collect_params().values()):
        p2.set_data(p1.data())
    _, a2_noz = top2_noz(x)
    assert float(a2.asnumpy()) > float(a2_noz.asnumpy())
    for p in top2.collect_params().values():
        g = p.grad().asnumpy()
        assert np.isfinite(g).all() and np.abs(g).sum() > 0

    # hybridized path compiles and agrees with eager
    top2h = MoEFFN(units=16, hidden_size=8, num_experts=4, return_aux=True,
                   num_experts_per_token=2, z_loss_coef=1e-3,
                   capacity_factor=4.0)
    top2h.initialize(mx.init.Xavier())
    for p1, p2 in zip(top2.collect_params().values(),
                      top2h.collect_params().values()):
        p2.set_data(p1.data())
    top2h.hybridize()
    oh, ah = top2h(x)
    np.testing.assert_allclose(oh.asnumpy(), o2.asnumpy(), atol=1e-5)
