"""Developer-tool smoke tests (reference: tools/parse_log.py,
tools/diagnose.py)."""
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_parse_log_markdown(tmp_path):
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import parse_log
    finally:
        sys.path.pop(0)

    lines = [
        "INFO:root:Epoch[0] Train-accuracy=0.412000\n",
        "INFO:root:Epoch[0] Time cost=12.340\n",
        "INFO:root:Epoch[0] Validation-accuracy=0.520000\n",
        "INFO:root:Epoch[1] Train-accuracy=0.683000\n",
        "INFO:root:Epoch[1] Validation-accuracy=0.707000\n",
        "unrelated line\n",
    ]
    data = parse_log.parse(lines, ["accuracy"])
    assert data[0] == {"train-accuracy": 0.412, "time": 12.34,
                       "val-accuracy": 0.52}
    assert data[1]["val-accuracy"] == 0.707
    md = parse_log.to_markdown(data, ["accuracy"])
    assert md.splitlines()[0].startswith("| epoch |")
    assert "0.683" in md
    # epoch 1 has no time entry -> empty cell, not a crash
    assert md.splitlines()[-1].endswith("|  |")


def test_parse_log_matches_fit_output(tmp_path):
    """The parser consumes the exact lines module.fit() logs
    (base_module.py:187-204)."""
    import logging

    import numpy as np

    import mxnet_tpu as mx

    log = tmp_path / "fit.log"
    handler = logging.FileHandler(str(log))
    logger = logging.getLogger("parse_log_fit_test")
    logger.setLevel(logging.INFO)
    logger.addHandler(handler)
    try:
        rng = np.random.RandomState(0)
        X = rng.randn(64, 8).astype(np.float32)
        Y = (X.sum(axis=1) > 0).astype(np.float32)
        data = mx.io.NDArrayIter(X, Y, batch_size=16)
        val = mx.io.NDArrayIter(X, Y, batch_size=16)
        net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=2)
        net = mx.sym.SoftmaxOutput(net, name="softmax")
        mod = mx.mod.Module(net, logger=logger)
        mod.fit(data, eval_data=val, num_epoch=2,
                optimizer_params={"learning_rate": 0.1})
    finally:
        handler.close()
        logger.removeHandler(handler)

    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import parse_log
    finally:
        sys.path.pop(0)
    data = parse_log.parse(log.read_text().splitlines(), ["accuracy"])
    assert 0 in data and 1 in data
    assert "train-accuracy" in data[0]
    assert "val-accuracy" in data[0]
    assert "time" in data[0]


def test_diagnose_runs_and_reports(monkeypatch):
    """diagnose.py must terminate and report each section even when the
    accelerator dial hangs (probes run in subprocesses under timeouts)."""
    env = dict(os.environ, MXTPU_DIAG_TIMEOUT_S="10", JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "diagnose.py")],
        capture_output=True, text=True, timeout=240, env=env)
    assert out.returncode == 0, out.stderr[-500:]
    for section in ("Python Info", "System Info", "Dependencies",
                    "mxnet_tpu", "Accelerator"):
        assert section in out.stdout
    assert "import       : ok" in out.stdout
