"""Symbol/Executor/NDArray-IO sections of the flat C ABI (VERDICT r3
Missing #2 — the c_api.h surface beyond the imperative core): drive
MXSymbolCreateVariable/CreateAtomicSymbol/Compose, ListArguments/Outputs,
InferShape (CSR marshalling), SaveToJSON/CreateFromJSON, ExecutorBind/
Forward/Backward/Outputs, and MXNDArraySave/Load through ctypes exactly
as a C host would, comparing against the in-process Python API."""
import ctypes
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.lib import native


def _capi():
    lib = native.get_capi()
    if lib is None:
        pytest.skip("native toolchain unavailable")
    c = ctypes
    lib.MXGetLastError.restype = c.c_char_p
    lib.MXNDArrayCreateEx.argtypes = [
        c.POINTER(c.c_uint), c.c_uint, c.c_int, c.c_int, c.c_int, c.c_int,
        c.POINTER(c.c_void_p)]
    lib.MXNDArraySyncCopyFromCPU.argtypes = [
        c.c_void_p, c.c_void_p, c.c_size_t]
    lib.MXNDArraySyncCopyToCPU.argtypes = [
        c.c_void_p, c.c_void_p, c.c_size_t]
    lib.MXNDArrayFree.argtypes = [c.c_void_p]
    lib.MXSymbolCreateVariable.argtypes = [c.c_char_p,
                                           c.POINTER(c.c_void_p)]
    lib.MXSymbolCreateAtomicSymbol.argtypes = [
        c.c_void_p, c.c_uint, c.POINTER(c.c_char_p),
        c.POINTER(c.c_char_p), c.POINTER(c.c_void_p)]
    lib.MXSymbolCompose.argtypes = [
        c.c_void_p, c.c_char_p, c.c_uint, c.POINTER(c.c_char_p),
        c.POINTER(c.c_void_p)]
    lib.MXSymbolFree.argtypes = [c.c_void_p]
    lib.MXSymbolCopy.argtypes = [c.c_void_p, c.POINTER(c.c_void_p)]
    lib.MXSymbolGetInternals.argtypes = [c.c_void_p, c.POINTER(c.c_void_p)]
    lib.MXSymbolGetOutput.argtypes = [c.c_void_p, c.c_uint,
                                      c.POINTER(c.c_void_p)]
    lib.MXSymbolListArguments.argtypes = [
        c.c_void_p, c.POINTER(c.c_uint), c.POINTER(c.POINTER(c.c_char_p))]
    lib.MXSymbolListOutputs.argtypes = lib.MXSymbolListArguments.argtypes
    lib.MXSymbolListAuxiliaryStates.argtypes = \
        lib.MXSymbolListArguments.argtypes
    lib.MXSymbolSaveToJSON.argtypes = [c.c_void_p, c.POINTER(c.c_char_p)]
    lib.MXSymbolCreateFromJSON.argtypes = [c.c_char_p,
                                           c.POINTER(c.c_void_p)]
    UINTP = c.POINTER(c.c_uint)
    lib.MXSymbolInferShape.argtypes = [
        c.c_void_p, c.c_uint, c.POINTER(c.c_char_p), UINTP, UINTP,
        UINTP, c.POINTER(UINTP), c.POINTER(c.POINTER(UINTP)),
        UINTP, c.POINTER(UINTP), c.POINTER(c.POINTER(UINTP)),
        UINTP, c.POINTER(UINTP), c.POINTER(c.POINTER(UINTP)),
        c.POINTER(c.c_int)]
    lib.MXExecutorBind.argtypes = [
        c.c_void_p, c.c_int, c.c_int, c.c_uint, c.POINTER(c.c_void_p),
        c.POINTER(c.c_void_p), c.POINTER(c.c_uint), c.c_uint,
        c.POINTER(c.c_void_p), c.POINTER(c.c_void_p)]
    lib.MXExecutorForward.argtypes = [c.c_void_p, c.c_int]
    lib.MXExecutorBackward.argtypes = [c.c_void_p, c.c_uint,
                                       c.POINTER(c.c_void_p)]
    lib.MXExecutorOutputs.argtypes = [
        c.c_void_p, c.POINTER(c.c_uint), c.POINTER(c.POINTER(c.c_void_p))]
    lib.MXExecutorFree.argtypes = [c.c_void_p]
    lib.MXNDArraySave.argtypes = [c.c_char_p, c.c_uint,
                                  c.POINTER(c.c_void_p),
                                  c.POINTER(c.c_char_p)]
    lib.MXNDArrayLoad.argtypes = [
        c.c_char_p, c.POINTER(c.c_uint),
        c.POINTER(c.POINTER(c.c_void_p)), c.POINTER(c.c_uint),
        c.POINTER(c.POINTER(c.c_char_p))]
    lib.MXSymbolListAtomicSymbolCreators.argtypes = [
        c.POINTER(c.c_uint), c.POINTER(c.POINTER(c.c_void_p))]
    lib.MXSymbolGetAtomicSymbolName.argtypes = [
        c.c_void_p, c.POINTER(c.c_char_p)]
    return lib


def _ok(rc, lib):
    assert rc == 0, lib.MXGetLastError().decode()


def _creator(lib, name):
    n = ctypes.c_uint()
    arr = ctypes.POINTER(ctypes.c_void_p)()
    _ok(lib.MXSymbolListAtomicSymbolCreators(
        ctypes.byref(n), ctypes.byref(arr)), lib)
    for i in range(n.value):
        cname = ctypes.c_char_p()
        _ok(lib.MXSymbolGetAtomicSymbolName(arr[i], ctypes.byref(cname)),
            lib)
        if cname.value.decode() == name:
            return ctypes.c_void_p(arr[i])
    raise AssertionError("creator %s not found" % name)


def _variable(lib, name):
    h = ctypes.c_void_p()
    _ok(lib.MXSymbolCreateVariable(name.encode(), ctypes.byref(h)), lib)
    return h


def _atomic(lib, op, attrs):
    keys = (ctypes.c_char_p * len(attrs))(*[k.encode() for k in attrs])
    vals = (ctypes.c_char_p * len(attrs))(
        *[str(v).encode() for v in attrs.values()])
    h = ctypes.c_void_p()
    _ok(lib.MXSymbolCreateAtomicSymbol(
        _creator(lib, op), len(attrs), keys, vals, ctypes.byref(h)), lib)
    return h


def _compose(lib, sym, name, kwargs):
    keys = (ctypes.c_char_p * len(kwargs))(*[k.encode() for k in kwargs])
    args = (ctypes.c_void_p * len(kwargs))(
        *[v.value for v in kwargs.values()])
    _ok(lib.MXSymbolCompose(sym, name.encode(), len(kwargs), keys, args),
        lib)


def _str_list(lib, fn, sym):
    n = ctypes.c_uint()
    arr = ctypes.POINTER(ctypes.c_char_p)()
    _ok(fn(sym, ctypes.byref(n), ctypes.byref(arr)), lib)
    return [arr[i].decode() for i in range(n.value)]


def _create_nd(lib, arr):
    shape = (ctypes.c_uint * arr.ndim)(*arr.shape)
    h = ctypes.c_void_p()
    _ok(lib.MXNDArrayCreateEx(shape, arr.ndim, 1, 0, 0, 0,
                              ctypes.byref(h)), lib)
    buf = np.ascontiguousarray(arr.astype(np.float32))
    _ok(lib.MXNDArraySyncCopyFromCPU(h, buf.ctypes.data, buf.size), lib)
    return h


def _to_numpy(lib, h, shape):
    out = np.empty(shape, np.float32)
    _ok(lib.MXNDArraySyncCopyToCPU(h, out.ctypes.data,
                                   int(np.prod(shape))), lib)
    return out


def _build_fc_graph(lib):
    """data -> FullyConnected(num_hidden=4) -> relu, via compose."""
    data = _variable(lib, "data")
    w = _variable(lib, "fc_weight")
    b = _variable(lib, "fc_bias")
    fc = _atomic(lib, "FullyConnected", {"num_hidden": 4})
    _compose(lib, fc, "fc", {"data": data, "weight": w, "bias": b})
    act = _atomic(lib, "Activation", {"act_type": "relu"})
    _compose(lib, act, "act", {"data": fc})
    return act, (data, w, b, fc)


def test_symbol_compose_and_listing():
    lib = _capi()
    act, _ = _build_fc_graph(lib)
    args = _str_list(lib, lib.MXSymbolListArguments, act)
    assert args == ["data", "fc_weight", "fc_bias"]
    outs = _str_list(lib, lib.MXSymbolListOutputs, act)
    assert len(outs) == 1 and outs[0].startswith("act")
    assert _str_list(lib, lib.MXSymbolListAuxiliaryStates, act) == []

    # copy + internals + get_output round-trips
    cp = ctypes.c_void_p()
    _ok(lib.MXSymbolCopy(act, ctypes.byref(cp)), lib)
    assert _str_list(lib, lib.MXSymbolListArguments, cp) == args
    internals = ctypes.c_void_p()
    _ok(lib.MXSymbolGetInternals(act, ctypes.byref(internals)), lib)
    int_outs = _str_list(lib, lib.MXSymbolListOutputs, internals)
    assert any(o.startswith("fc") for o in int_outs)
    out0 = ctypes.c_void_p()
    _ok(lib.MXSymbolGetOutput(act, 0, ctypes.byref(out0)), lib)
    assert len(_str_list(lib, lib.MXSymbolListOutputs, out0)) == 1
    for h in (cp, internals, out0, act):
        lib.MXSymbolFree(h)


def test_symbol_name_attrs_and_creator_info():
    lib = _capi()
    c = ctypes
    lib.MXSymbolGetName.argtypes = [c.c_void_p, c.POINTER(c.c_char_p),
                                    c.POINTER(c.c_int)]
    lib.MXSymbolGetAttr.argtypes = [c.c_void_p, c.c_char_p,
                                    c.POINTER(c.c_char_p),
                                    c.POINTER(c.c_int)]
    lib.MXSymbolSetAttr.argtypes = [c.c_void_p, c.c_char_p, c.c_char_p]
    lib.MXSymbolListAttrShallow.argtypes = [
        c.c_void_p, c.POINTER(c.c_uint), c.POINTER(c.POINTER(c.c_char_p))]
    lib.MXSymbolListAttr.argtypes = lib.MXSymbolListAttrShallow.argtypes
    lib.MXSymbolGetAtomicSymbolInfo.argtypes = [
        c.c_void_p, c.POINTER(c.c_char_p), c.POINTER(c.c_char_p),
        c.POINTER(c.c_uint), c.POINTER(c.POINTER(c.c_char_p)),
        c.POINTER(c.POINTER(c.c_char_p)), c.POINTER(c.POINTER(c.c_char_p)),
        c.POINTER(c.c_char_p), c.POINTER(c.c_char_p)]

    act, _ = _build_fc_graph(lib)
    out, ok = c.c_char_p(), c.c_int()
    _ok(lib.MXSymbolGetName(act, c.byref(out), c.byref(ok)), lib)
    assert ok.value == 1 and out.value.decode().startswith("act")

    # set + get + list attributes
    _ok(lib.MXSymbolSetAttr(act, b"ctx_group", b"dev1"), lib)
    _ok(lib.MXSymbolGetAttr(act, b"ctx_group", c.byref(out), c.byref(ok)),
        lib)
    assert ok.value == 1 and out.value == b"dev1"
    _ok(lib.MXSymbolGetAttr(act, b"nope", c.byref(out), c.byref(ok)), lib)
    assert ok.value == 0
    n = c.c_uint()
    arr = c.POINTER(c.c_char_p)()
    _ok(lib.MXSymbolListAttrShallow(act, c.byref(n), c.byref(arr)), lib)
    pairs = {arr[2 * i].decode(): arr[2 * i + 1].decode()
             for i in range(n.value)}
    assert pairs.get("ctx_group") == "dev1"
    _ok(lib.MXSymbolListAttr(act, c.byref(n), c.byref(arr)), lib)
    deep = {arr[2 * i].decode(): arr[2 * i + 1].decode()
            for i in range(n.value)}
    assert any(k.endswith("$ctx_group") for k in deep), deep
    lib.MXSymbolFree(act)

    # creator introspection: FullyConnected surfaces its param names
    name, desc = c.c_char_p(), c.c_char_p()
    na = c.c_uint()
    an = c.POINTER(c.c_char_p)()
    at = c.POINTER(c.c_char_p)()
    ad = c.POINTER(c.c_char_p)()
    kv, rt = c.c_char_p(), c.c_char_p()
    _ok(lib.MXSymbolGetAtomicSymbolInfo(
        _creator(lib, "FullyConnected"), c.byref(name), c.byref(desc),
        c.byref(na), c.byref(an), c.byref(at), c.byref(ad), c.byref(kv),
        c.byref(rt)), lib)
    assert name.value == b"FullyConnected"
    names = [an[i].decode() for i in range(na.value)]
    assert "num_hidden" in names and "data" in names


def test_symbol_json_roundtrip_matches_python():
    lib = _capi()
    act, _ = _build_fc_graph(lib)
    js = ctypes.c_char_p()
    _ok(lib.MXSymbolSaveToJSON(act, ctypes.byref(js)), lib)
    # the JSON loads through the Python API (shared format)
    s = mx.sym.load_json(js.value.decode())
    assert s.list_arguments() == ["data", "fc_weight", "fc_bias"]
    # and back through the C API
    h2 = ctypes.c_void_p()
    _ok(lib.MXSymbolCreateFromJSON(js.value, ctypes.byref(h2)), lib)
    assert _str_list(lib, lib.MXSymbolListArguments, h2) == \
        ["data", "fc_weight", "fc_bias"]
    lib.MXSymbolFree(h2)
    lib.MXSymbolFree(act)


def test_infer_shape_csr_marshalling():
    lib = _capi()
    act, _ = _build_fc_graph(lib)
    c = ctypes
    keys = (c.c_char_p * 1)(b"data")
    ind_ptr = (c.c_uint * 2)(0, 2)
    shape_data = (c.c_uint * 2)(8, 16)
    UINTP = c.POINTER(c.c_uint)
    in_n, out_n, aux_n = c.c_uint(), c.c_uint(), c.c_uint()
    in_nd, out_nd, aux_nd = UINTP(), UINTP(), UINTP()
    in_d = c.POINTER(UINTP)()
    out_d = c.POINTER(UINTP)()
    aux_d = c.POINTER(UINTP)()
    complete = c.c_int()
    _ok(lib.MXSymbolInferShape(
        act, 1, keys, ind_ptr, shape_data,
        c.byref(in_n), c.byref(in_nd), c.byref(in_d),
        c.byref(out_n), c.byref(out_nd), c.byref(out_d),
        c.byref(aux_n), c.byref(aux_nd), c.byref(aux_d),
        c.byref(complete)), lib)
    assert complete.value == 1
    assert in_n.value == 3
    got = [[in_d[i][dd] for dd in range(in_nd[i])] for i in range(3)]
    assert got == [[8, 16], [4, 16], [4]]
    assert out_n.value == 1
    assert [out_d[0][dd] for dd in range(out_nd[0])] == [8, 4]
    lib.MXSymbolFree(act)


def test_executor_bind_forward_backward():
    lib = _capi()
    act, _ = _build_fc_graph(lib)
    rng = np.random.RandomState(0)
    x = rng.uniform(-1, 1, (8, 16)).astype(np.float32)
    w = rng.uniform(-1, 1, (4, 16)).astype(np.float32)
    b = rng.uniform(-1, 1, (4,)).astype(np.float32)

    in_args = [_create_nd(lib, a) for a in (x, w, b)]
    grads = [_create_nd(lib, np.zeros_like(a)) for a in (x, w, b)]
    reqs = (ctypes.c_uint * 3)(1, 1, 1)
    ins = (ctypes.c_void_p * 3)(*[h.value for h in in_args])
    gs = (ctypes.c_void_p * 3)(*[h.value for h in grads])
    exe = ctypes.c_void_p()
    _ok(lib.MXExecutorBind(act, 1, 0, 3, ins, gs, reqs, 0, None,
                           ctypes.byref(exe)), lib)
    _ok(lib.MXExecutorForward(exe, 1), lib)
    n_out = ctypes.c_uint()
    outs = ctypes.POINTER(ctypes.c_void_p)()
    _ok(lib.MXExecutorOutputs(exe, ctypes.byref(n_out),
                              ctypes.byref(outs)), lib)
    assert n_out.value == 1
    got = _to_numpy(lib, ctypes.c_void_p(outs[0]), (8, 4))
    ref = np.maximum(x @ w.T + b, 0)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)

    _ok(lib.MXExecutorBackward(exe, 0, None), lib)
    gw = _to_numpy(lib, grads[1], (4, 16))
    mask = (ref > 0).astype(np.float32)
    np.testing.assert_allclose(gw, mask.T @ x, rtol=1e-4, atol=1e-4)

    lib.MXNDArrayFree(ctypes.c_void_p(outs[0]))
    lib.MXExecutorFree(exe)
    for h in in_args + grads:
        lib.MXNDArrayFree(h)
    lib.MXSymbolFree(act)


def test_ndarray_save_load(tmp_path):
    lib = _capi()
    rng = np.random.RandomState(1)
    a = rng.rand(3, 4).astype(np.float32)
    b = rng.rand(2,).astype(np.float32)
    ha, hb = _create_nd(lib, a), _create_nd(lib, b)
    fname = str(tmp_path / "nd.params").encode()
    handles = (ctypes.c_void_p * 2)(ha.value, hb.value)
    keys = (ctypes.c_char_p * 2)(b"a", b"b")
    _ok(lib.MXNDArraySave(fname, 2, handles, keys), lib)

    # readable from Python (shared on-disk format)
    loaded = mx.nd.load(fname.decode())
    np.testing.assert_allclose(loaded["a"].asnumpy(), a)

    n = ctypes.c_uint()
    arrs = ctypes.POINTER(ctypes.c_void_p)()
    n_names = ctypes.c_uint()
    names = ctypes.POINTER(ctypes.c_char_p)()
    _ok(lib.MXNDArrayLoad(fname, ctypes.byref(n), ctypes.byref(arrs),
                          ctypes.byref(n_names), ctypes.byref(names)), lib)
    assert n.value == 2 and n_names.value == 2
    by_name = {names[i].decode(): ctypes.c_void_p(arrs[i])
               for i in range(2)}
    np.testing.assert_allclose(_to_numpy(lib, by_name["b"], (2,)), b)
    for i in range(2):
        lib.MXNDArrayFree(ctypes.c_void_p(arrs[i]))
    for h in (ha, hb):
        lib.MXNDArrayFree(h)
