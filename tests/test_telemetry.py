"""Telemetry subsystem tests (ISSUE 3 acceptance):

  * unit: registry metric semantics, Prometheus text exposition (served
    over a real HTTP socket), JSONL flush format, observe_step wiring,
    flight-recorder ring + dump contents;
  * overhead: enabled-vs-disabled per-step cost of the full step
    instrumentation < 2% on a CPU step-loop microbenchmark;
  * process level: SIGUSR1 produces a dump without killing the process;
  * END-TO-END: a 2-process launch.py group with
    `MXTPU_FAULT_INJECT=hang@step=5,rank=1` and a short watchdog —
    the hung rank dumps thread stacks + recent events to a per-rank file
    and aborts, the launcher tears the group down (SIGUSR1 first), and its
    log references the dump.
"""
import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

import mxnet_tpu as mx  # noqa: F401  (conftest pins CPU before jax loads)
from mxnet_tpu import telemetry
from mxnet_tpu.telemetry import core as tm_core

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_LAUNCH = os.path.join(_ROOT, "tools", "launch.py")
_WORKER = os.path.join(_ROOT, "tests", "flightrec_worker.py")


def _clean_env(**extra):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env.pop("MXTPU_TELEMETRY_DIR", None)
    env.pop("MXTPU_WATCHDOG_TIMEOUT", None)
    env.pop("MXTPU_FAULT_INJECT", None)
    env["PYTHONPATH"] = _ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env.update(extra)
    return env


# --------------------------------------------------------------------------
# registry
# --------------------------------------------------------------------------

def test_counter_gauge_histogram_semantics():
    c = telemetry.counter("t_unit_total")
    v0 = c.value
    c.inc()
    c.inc(4)
    assert c.value == v0 + 5
    # float counters (seconds accumulators)
    fc = telemetry.counter("t_unit_seconds_total")
    fc.inc(0.25)
    fc.inc(0.25)
    assert abs(fc.value - 0.5) < 1e-9 or fc.value >= 0.5

    g = telemetry.gauge("t_unit_gauge")
    g.set(3.5)
    assert g.value == 3.5
    g.inc()
    g.dec(2)
    assert g.value == 2.5

    h = telemetry.histogram("t_unit_hist_seconds")
    for v in (0.0002, 0.0002, 0.03, 7.0):
        h.observe(v)
    snap = h.snapshot()
    assert snap["count"] == 4
    assert abs(snap["sum"] - 7.0304) < 1e-6
    assert snap["min"] == 0.0002 and snap["max"] == 7.0
    # cumulative buckets: everything <= 0.00025 counts 2, +Inf counts all
    assert snap["buckets"]["0.00025"] == 2
    assert snap["buckets"]["+Inf"] == 4

    # same name+labels -> same object; name reuse across kinds is an error
    assert telemetry.counter("t_unit_total") is c
    with pytest.raises(TypeError):
        telemetry.gauge("t_unit_total")
    # labeled metrics are distinct series
    a = telemetry.counter("t_unit_lab_total", {"op": "a"})
    b = telemetry.counter("t_unit_lab_total", {"op": "b"})
    assert a is not b


def test_prometheus_text_and_http_endpoint():
    telemetry.counter("t_expo_total", {"op": "x"}).inc(2)
    telemetry.histogram("t_expo_seconds").observe(0.004)
    text = telemetry.prometheus_text()
    assert "# TYPE t_expo_total counter" in text
    assert 't_expo_total{op="x"} 2' in text
    assert "# TYPE t_expo_seconds histogram" in text
    assert 't_expo_seconds_bucket{le="+Inf"} ' in text
    assert "t_expo_seconds_count 1" in text

    port = telemetry.start_http_server(port=0, addr="127.0.0.1")
    assert port and port > 0
    body = urllib.request.urlopen(
        "http://127.0.0.1:%d/metrics" % port, timeout=10).read().decode()
    assert 't_expo_total{op="x"}' in body
    # idempotent: second call returns the same bound port
    assert telemetry.start_http_server(port=0, addr="127.0.0.1") == port


def test_jsonl_flush_and_event_queue(tmp_path):
    telemetry.counter("t_flush_total").inc(3)
    telemetry.record_event("unit_test_event", detail="abc")
    path = telemetry.flush(directory=str(tmp_path), reason="unit")
    assert path and os.path.exists(path)
    assert os.path.basename(path) == "telemetry-rank0-pid%d.jsonl" % os.getpid()
    records = [json.loads(ln) for ln in open(path) if ln.strip()]
    kinds = [r["kind"] for r in records]
    assert "metrics" in kinds and "event" in kinds
    metrics = [r for r in records if r["kind"] == "metrics"][-1]
    assert metrics["rank"] == 0 and metrics["reason"] == "unit"
    assert metrics["metrics"]["t_flush_total"]["value"] >= 3
    evs = [r for r in records if r["kind"] == "event"]
    assert any(r["event"] == "unit_test_event"
               and r["fields"]["detail"] == "abc" for r in evs)
    # queue drained: a second flush re-emits metrics but not the old event
    path2 = telemetry.flush(directory=str(tmp_path), reason="unit2")
    records2 = [json.loads(ln) for ln in open(path2) if ln.strip()]
    assert sum(1 for r in records2 if r["kind"] == "event"
               and r["event"] == "unit_test_event") == 1


def test_observe_step_and_ring():
    steps0 = telemetry.counter("mxtpu_steps_total", {"kind": "unit"}).value
    telemetry.observe_step(0.01, examples=64, step=11, kind="unit")
    assert telemetry.counter("mxtpu_steps_total",
                             {"kind": "unit"}).value == steps0 + 1
    assert telemetry.gauge("mxtpu_examples_per_sec",
                           {"kind": "unit"}).value == pytest.approx(6400.0)
    last = telemetry.last_step()
    assert last is not None and last[0] == 11
    evs = telemetry.events()
    assert any(e["event"] == "step" and e["fields"]["step"] == 11
               for e in evs)


def test_disabled_is_noop():
    telemetry.set_enabled(False)
    try:
        before = telemetry.counter("t_disabled_total")
        before.inc(5)
        assert before.value == 0  # null metric
        telemetry.observe_step(0.01, examples=8, step=1, kind="disabled")
        assert telemetry.flush(directory="/nonexistent-dir-unused") is None
    finally:
        telemetry.set_enabled(True)
    # the real registry never saw the disabled-phase series
    assert "t_disabled_total" not in telemetry.snapshot()


def test_dump_contents(tmp_path):
    telemetry.record_event("pre_dump_marker", k=1)
    path = telemetry.dump("unit-test", path=str(tmp_path / "d.json"))
    data = json.load(open(path))
    assert data["reason"] == "unit-test"
    assert data["rank"] == 0 and data["pid"] == os.getpid()
    names = [t["name"] for t in data["threads"]]
    assert "MainThread" in names
    main = data["threads"][names.index("MainThread")]
    assert any("test_dump_contents" in ln for ln in main["stack"])
    assert any(e["event"] == "pre_dump_marker" for e in data["events"])
    assert "mxtpu_op_dispatch_total" in str(data["metrics"]) or data["metrics"]


# --------------------------------------------------------------------------
# training-path wiring
# --------------------------------------------------------------------------

def test_trainer_step_publishes_metrics():
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon import nn

    steps0 = telemetry.counter("mxtpu_steps_total", {"kind": "train"}).value
    disp0 = telemetry.counter("mxtpu_op_dispatch_total",
                              {"cat": "imperative"}).value
    net = nn.Dense(2, in_units=3)
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    l2 = gluon.loss.L2Loss()
    x = mx.nd.array(np.ones((4, 3), np.float32))
    y = mx.nd.array(np.zeros((4, 2), np.float32))
    for _ in range(2):
        with autograd.record():
            loss = l2(net(x), y)
        loss.backward()
        tr.step(4)
    assert telemetry.counter("mxtpu_steps_total",
                             {"kind": "train"}).value == steps0 + 2
    assert telemetry.counter("mxtpu_op_dispatch_total",
                             {"cat": "imperative"}).value > disp0
    h = telemetry.histogram("mxtpu_step_seconds", {"kind": "train"})
    assert h.count >= 2
    # jit executable-cache accounting: lookups >= misses, both nonzero
    lookups = telemetry.counter("mxtpu_jit_cache_lookup_total").value
    misses = telemetry.counter("mxtpu_jit_cache_miss_total").value
    assert lookups >= misses > 0


def test_collectives_metrics():
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.parallel import collectives

    calls0 = telemetry.counter("mxtpu_collective_calls_total",
                               {"op": "all_reduce"}).value
    arrays = [jax.device_put(jnp.ones((8,), jnp.float32), d)
              for d in jax.devices()[:2]]
    out = collectives.all_reduce_arrays(arrays)
    assert float(out[0][0]) == 2.0
    assert telemetry.counter("mxtpu_collective_calls_total",
                             {"op": "all_reduce"}).value == calls0 + 1
    # bytes: 2 arrays x 8 floats x 4B
    assert telemetry.counter("mxtpu_collective_bytes_total",
                             {"op": "all_reduce"}).value >= 64


def test_checkpoint_metrics(tmp_path):
    from mxnet_tpu.parallel.resilience import CheckpointManager

    mgr = CheckpointManager(str(tmp_path), rank0_only=False)
    saves0 = telemetry.histogram("mxtpu_checkpoint_seconds",
                                 {"what": "save"}).count
    mgr.save(1, save_params=lambda p: open(p, "wb").write(b"x" * 100))
    assert telemetry.histogram("mxtpu_checkpoint_seconds",
                               {"what": "save"}).count == saves0 + 1
    assert telemetry.counter("mxtpu_checkpoint_bytes_total",
                             {"what": "save"}).value > 0
    assert any(e["event"] == "checkpoint_save" for e in telemetry.events())
    mgr.restore(load_params=lambda p: open(p, "rb").read())
    assert any(e["event"] == "checkpoint_restore"
               for e in telemetry.events())


def test_dataloader_wait_compute_split():
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader

    wait0 = telemetry.counter("mxtpu_data_wait_seconds_total",
                              {"src": "dataloader"}).value
    n0 = telemetry.counter("mxtpu_data_batches_total",
                           {"src": "dataloader"}).value
    ds = ArrayDataset(np.arange(32, dtype=np.float32),
                      np.arange(32, dtype=np.float32))
    loader = DataLoader(ds, batch_size=8)
    seen = 0
    for _batch in loader:
        time.sleep(0.002)  # "compute"
        seen += 1
    assert seen == 4
    assert telemetry.counter("mxtpu_data_batches_total",
                             {"src": "dataloader"}).value == n0 + 4
    assert telemetry.counter("mxtpu_data_wait_seconds_total",
                             {"src": "dataloader"}).value > wait0
    assert telemetry.counter("mxtpu_data_compute_seconds_total",
                             {"src": "dataloader"}).value >= 0.006


# --------------------------------------------------------------------------
# overhead (acceptance: < 2% per step, enabled vs disabled)
# --------------------------------------------------------------------------

def test_step_instrumentation_overhead_under_2pct():
    """Enabled-vs-disabled per-step cost of the full step instrumentation
    (observe_step: histogram + counters/gauges + ring heartbeat) must be
    <2% of a realistic ~1ms CPU step.

    Measured as (enabled-call cost − disabled-call cost) / step time, each
    taken as a min over many small chunks — min-filtering makes every term
    robust to suite-load spikes, where differencing two long serially-timed
    loops is not (a 100ms loop pair can drift 10% on a busy box while the
    true per-step cost is ~3µs)."""
    def per_call_cost(chunks=40, inner=500):
        best = float("inf")
        for c in range(chunks):
            t0 = time.perf_counter()
            for i in range(inner):
                telemetry.observe_step(0.001, examples=32, step=i,
                                       kind="bench")
            best = min(best, (time.perf_counter() - t0) / inner)
        return best

    telemetry.observe_step(0.001, examples=32, step=0, kind="bench")  # warm
    cost_on = per_call_cost()
    telemetry.set_enabled(False)
    try:
        cost_off = per_call_cost()
    finally:
        telemetry.set_enabled(True)
    cost = max(0.0, cost_on - cost_off)

    # a realistic CPU training step to compare against (min over chunks)
    a = np.random.rand(384, 384).astype(np.float32)
    a.dot(a)
    step = min((lambda t0=time.perf_counter(): (
        [a.dot(a) for _ in range(10)],
        (time.perf_counter() - t0) / 10)[1])() for _ in range(20))

    overhead = cost / step
    assert overhead < 0.02, \
        "telemetry per-step overhead %.3f%% (cost %.2fus vs step %.0fus)" \
        % (overhead * 100.0, cost * 1e6, step * 1e6)
    # absolute guard too: the instrumentation itself must stay micro-scale
    assert cost < 50e-6, "observe_step cost %.1fus" % (cost * 1e6)


# --------------------------------------------------------------------------
# process level: SIGUSR1 dump (no launcher, no hang)
# --------------------------------------------------------------------------

def test_sigusr1_dumps_without_killing(tmp_path):
    if not hasattr(signal, "SIGUSR1"):
        pytest.skip("no SIGUSR1 on this platform")
    body = (
        "import os, sys, time\n"
        "import mxnet_tpu.telemetry as t\n"
        "t.record_step(3)\n"
        "print('READY', flush=True)\n"
        "time.sleep(120)\n"
        "print('SURVIVED', flush=True)\n"
    )
    proc = subprocess.Popen(
        [sys.executable, "-c", body],
        env=_clean_env(MXTPU_TELEMETRY_DIR=str(tmp_path)),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    try:
        line = proc.stdout.readline()
        assert "READY" in line, line
        proc.send_signal(signal.SIGUSR1)
        dump = os.path.join(str(tmp_path),
                            "flightrec-rank0-pid%d.json" % proc.pid)
        deadline = time.time() + 30
        while time.time() < deadline and not os.path.exists(dump):
            assert proc.poll() is None, "process died on SIGUSR1"
            time.sleep(0.1)
        assert os.path.exists(dump), os.listdir(str(tmp_path))
        data = json.load(open(dump))
        assert data["reason"] == "SIGUSR1"
        assert data["last_step"]["step"] == 3
        assert any(t_["name"] == "MainThread" for t_ in data["threads"])
        assert proc.poll() is None  # dump-on-signal, not die-on-signal
    finally:
        proc.kill()
        proc.wait(timeout=30)


# --------------------------------------------------------------------------
# END-TO-END: hang -> watchdog dump + abort -> launcher teardown
# --------------------------------------------------------------------------

def test_flight_recorder_hang_e2e(tmp_path):
    """Acceptance: MXTPU_FAULT_INJECT=hang@step=5,rank=1 under a 2-process
    launch.py group produces a per-rank dump (thread stacks + recent
    events), and the launcher tears the run down with the dump referenced
    in its log."""
    tdir = tmp_path / "telemetry"
    env = _clean_env(
        MXTPU_TELEMETRY_DIR=str(tdir),
        MXTPU_WATCHDOG_TIMEOUT="3",
        MXTPU_FAULT_INJECT="hang@step=5,rank=1",
        MXTPU_TEST_TOTAL_STEPS="600",
        MXTPU_TEST_STEP_SLEEP="0.05",
        MXTPU_TEARDOWN_GRACE="5",
        MXTPU_DUMP_GRACE="2",
    )
    proc = subprocess.run(
        [sys.executable, _LAUNCH, "-n", "2", "--",
         sys.executable, _WORKER],
        env=env, capture_output=True, text=True, timeout=300)
    out = proc.stdout + proc.stderr
    # torn down by the launcher after the watchdog abort (exit 43), never
    # a clean exit and never a pytest-level hang
    assert proc.returncode != 0, out[-4000:]

    dumps = sorted(tdir.glob("flightrec-rank1-*.json"))
    assert dumps, "no rank-1 flight dump; telemetry dir: %s\n%s" % (
        sorted(os.listdir(str(tdir))) if tdir.exists() else "missing",
        out[-4000:])
    data = json.load(open(str(dumps[-1])))
    assert data["rank"] == 1
    assert "watchdog" in data["reason"]
    assert data["last_step"]["step"] == 5
    # thread stacks show WHERE it hung: the injected sleep inside the
    # fault-injection hook, reached from trainer.step
    main = next(t_ for t_ in data["threads"] if t_["name"] == "MainThread")
    stack = "\n".join(main["stack"])
    assert "maybe_inject_fault" in stack or "_fire" in stack, stack
    # recent events include the completed steps
    steps = [e["fields"].get("step") for e in data["events"]
             if e["event"] == "step"]
    assert 5 in steps, data["events"]

    # the launcher log references the dump (the worker's announce line is
    # rank-prefixed by the launcher pump) and shows the SIGUSR1 teardown
    assert "[flight-recorder]" in out and "dumped to" in out, out[-4000:]
    assert "SIGUSR1" in out, out[-4000:]

    # launcher-side telemetry events landed in the shared directory
    lev = tdir / "launcher-events.jsonl"
    assert lev.exists()
    kinds = [json.loads(ln)["event"] for ln in open(str(lev)) if ln.strip()]
    assert "launcher_generation_start" in kinds
    assert "launcher_teardown" in kinds
