"""SLO engine tests (docs/observability.md §SLOs): windowed-delta ring
math, burn-rate computation against synthetic traffic, spec parsing,
the /statusz surface, and THE acceptance e2e — a pooled serving run
with injected `slow_reply` faults flips the latency verdict to
breaching within one fast window, /statusz reports it with a burn rate
and an exemplar trace id, and the verdict recovers after the fault
clears.

Everything runs on CPU with tiny windows (the tier-1 budget has no
headroom — ROADMAP.md): unit tests drive rolls with synthetic
timestamps instead of sleeping, and the e2e uses a stub-echo replica
pool, not a real model.
"""
import json
import re
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from mxnet_tpu import telemetry
from mxnet_tpu.telemetry import core, recorder, slo
from mxnet_tpu.telemetry.core import Counter, Gauge, Histogram
from mxnet_tpu.telemetry.slo import Objective, SLOSpecError


# ---------------------------------------------------------------------------
# windowed-delta ring math
# ---------------------------------------------------------------------------

def test_counter_window_roll_rate_and_partial_coverage():
    c = Counter("mxtpu_test_win_total")
    t0 = time.time()
    assert c.windowed_delta(60, t0) is None  # no ring before the first roll
    c.inc(10)
    c._roll(t0, 8)
    c.inc(20)
    c._roll(t0 + 10, 8)
    c.inc(5)
    # full coverage: baseline is the newest entry at-or-before the cutoff
    # (t0+10, cumulative 30) — the window sees only the 5 since
    delta, elapsed = c.windowed_delta(10, t0 + 20)
    assert delta == 5 and abs(elapsed - 10) < 1e-6
    assert c.windowed_rate(10, t0 + 20) == pytest.approx(0.5)
    # a wider window reaches the older baseline (t0, cumulative 10)
    delta, elapsed = c.windowed_delta(15, t0 + 20)
    assert delta == 25 and abs(elapsed - 20) < 1e-6
    # window wider than the ring: partial coverage diffs against the
    # OLDEST entry and reports the real elapsed, not the asked window
    delta, elapsed = c.windowed_delta(10_000, t0 + 20)
    assert delta == 25 and abs(elapsed - 20) < 1e-6


def test_counter_ring_expiry_is_bounded():
    c = Counter("mxtpu_test_win_expiry_total")
    t0 = time.time()
    for i in range(10):  # maxlen 4: the first rolls age out
        c.inc(1)
        c._roll(t0 + i, 4)
    assert len(c._win) == 4
    # baseline can only be as old as the oldest surviving entry (t0+6)
    delta, elapsed = c.windowed_delta(1000, t0 + 9)
    assert delta == 3 and abs(elapsed - 3) < 1e-6


def test_counter_staleness_tracking():
    c = Counter("mxtpu_test_stale_total")
    t0 = time.time()
    c.inc()
    c._roll(t0, 8)
    c._roll(t0 + 5, 8)       # no growth: changed stamp stays at t0
    assert c.seconds_since_change(t0 + 5) == pytest.approx(5.0)
    c.inc()
    c._roll(t0 + 7, 8)       # growth seen at this roll
    assert c.seconds_since_change(t0 + 9) == pytest.approx(2.0)


def test_histogram_window_quantile_and_empty_window():
    h = Histogram("mxtpu_test_win_seconds")
    t0 = time.time()
    assert h.windowed(60, t0) is None
    for v in (0.01, 0.01, 0.01):
        h.observe(v)
    h._roll(t0, 16)
    for v in (0.01, 0.01, 0.01, 0.4):
        h.observe(v)
    w = h.windowed(60, t0 + 10)
    assert w["count"] == 4 and w["sum"] == pytest.approx(0.43)
    assert w["rate"] == pytest.approx(0.4)
    # 3/4 at 10ms, 1/4 at 400ms: the p99 lands in the 0.25..0.5 bucket
    q99 = h.windowed_quantile(0.99, 60, t0 + 10)
    assert 0.25 < q99 <= 0.5
    assert h.windowed_quantile(0.5, 60, t0 + 10) <= 0.01
    # a later roll with no traffic: the window over the quiet period is
    # EMPTY (count 0, quantile None) — the old observations aged out
    h._roll(t0 + 20, 16)
    w2 = h.windowed(5, t0 + 24)
    assert w2["count"] == 0
    assert h.windowed_quantile(0.99, 5, t0 + 24) is None


def test_gauge_window_stats():
    g = Gauge("mxtpu_test_win_gauge")
    t0 = time.time()
    assert g.windowed_stats(60, t0) is None  # live value alone is no window
    g.set(5)
    g._roll(t0, 8)
    g.set(15)
    g._roll(t0 + 1, 8)
    g.set(10)
    s = g.windowed_stats(60, t0 + 2)
    assert s["min"] == 5 and s["max"] == 15 and s["samples"] == 3
    assert s["avg"] == pytest.approx(10.0)
    # a narrow window keeps only fresh samples + the live value
    s2 = g.windowed_stats(1.5, t0 + 2)
    assert s2["min"] == 10 and s2["samples"] == 2


def test_roll_windows_throttle_and_force():
    c = core.get_registry().counter("mxtpu_test_roll_throttle_total")
    assert core.roll_windows(force=True) > 0
    n_immediate = core.roll_windows()  # throttled: within the resolution
    assert n_immediate == 0
    assert core.roll_windows(force=True) > 0
    assert c._win is not None and len(c._win) >= 2


# ---------------------------------------------------------------------------
# burn-rate computation against synthetic traffic
# ---------------------------------------------------------------------------

def _mk_latency_obj(model, threshold=0.1, fast=(60.0,), slow=3600.0):
    return Objective("t-p99:%s" % model, "latency_quantile",
                     metric="mxtpu_serve_request_seconds",
                     labels={"model": model}, quantile=0.99,
                     threshold=threshold, fast_windows=list(fast),
                     slow_window=slow)


def test_latency_burn_rate_breach_and_recovery_synthetic():
    reg = core.get_registry()
    h = reg.histogram("mxtpu_serve_request_seconds", {"model": "syn/1"})
    obj = _mk_latency_obj("syn/1")
    t0 = time.time()
    # healthy traffic: 50 fast requests, then a roll snapshot
    for _ in range(50):
        h.observe(0.01)
    h._roll(t0, 256)
    v = slo._eval_objective(obj, t0 + 1)
    # the window between the roll and now is empty — no data, healthy
    assert v["healthy"] and v["no_data"]
    # slow traffic: half the window's requests over the 100ms threshold
    for _ in range(5):
        h.observe(0.01)
    for _ in range(5):
        h.observe(0.4, exemplar="feedfacecafebeef")
    v = slo._eval_objective(obj, t0 + 30)
    assert not v["healthy"] and v["page"]
    # bad fraction 0.5 against a 1% budget: burn ~50x
    assert v["burn_rate"] == pytest.approx(50.0, rel=0.05)
    assert v["value"] > 0.25  # windowed p99 reflects the slow half
    assert v["exemplar_trace"] == "feedfacecafebeef"
    assert v["budget_remaining"] == 0.0
    # the fault clears: a roll captures the bad epoch as baseline, fresh
    # traffic is all fast — the verdict recovers within one window
    h._roll(t0 + 60, 256)
    for _ in range(20):
        h.observe(0.01)
    v = slo._eval_objective(obj, t0 + 100)
    assert v["healthy"] and not v["page"] and not v["no_data"]
    assert v["burn_rate"] == 0.0
    # the SLOW window still remembers the incident: budget stays charred
    # even though the fast windows (and the page verdict) recovered
    assert v["budget_remaining"] < 1.0


def test_multiwindow_page_needs_every_fast_window():
    reg = core.get_registry()
    h = reg.histogram("mxtpu_serve_request_seconds", {"model": "mw/1"})
    obj = _mk_latency_obj("mw/1", fast=(10.0, 100.0), slow=3600.0)
    t0 = time.time()
    h._roll(t0, 256)
    for _ in range(10):
        h.observe(0.4)
    h._roll(t0 + 50, 256)   # bad burst, then quiet
    v = slo._eval_objective(obj, t0 + 70)
    # the 100s window still burns, but the 10s window is empty — the
    # blip does NOT page (SRE multi-window), though the long window shows
    assert not v["page"] and v["healthy"]
    assert v["windows"]["10s"]["no_data"]
    assert v["windows"]["100s"]["burn"] > 1.0


def test_error_rate_burn_synthetic():
    reg = core.get_registry()
    good = reg.counter("mxtpu_serve_requests_total", {"model": "er/1"})
    bad = reg.counter("mxtpu_serve_rejected_total",
                      {"model": "er/1", "reason": "deadline"})
    obj = Objective("t-avail:er/1", "error_rate",
                    bad=[("mxtpu_serve_rejected_total", {"model": "er/1"})],
                    total=[("mxtpu_serve_requests_total", {"model": "er/1"}),
                           ("mxtpu_serve_rejected_total", {"model": "er/1"})],
                    budget=0.01, fast_windows=[60.0], slow_window=3600.0)
    t0 = time.time()
    good.inc(100)
    good._roll(t0, 64)
    bad._roll(t0, 64)
    good.inc(90)
    bad.inc(10)
    v = slo._eval_objective(obj, t0 + 30)
    assert not v["healthy"]
    assert v["value"] == pytest.approx(0.1)          # 10 bad / 100 total
    assert v["burn_rate"] == pytest.approx(10.0)     # vs 1% budget
    # quiet period (rolls continue, no traffic) => no verdict, not a
    # breach — absent traffic must never read as burning
    good._roll(t0 + 60, 64)
    bad._roll(t0 + 60, 64)
    v2 = slo._eval_objective(obj, t0 + 10_000)
    assert v2["no_data"] and v2["healthy"]


def test_gauge_ceiling_and_floor_objectives():
    reg = core.get_registry()
    g = reg.gauge("mxtpu_serve_queue_depth", {"model": "gc/1"})
    ceiling = Objective("t-queue:gc/1", "gauge_ceiling",
                        metric="mxtpu_serve_queue_depth",
                        labels={"model": "gc/1"}, threshold=8.0,
                        budget=0.25, fast_windows=[60.0], slow_window=3600.0)
    t0 = time.time()
    g.set(2)
    g._roll(t0, 64)
    v = slo._eval_objective(ceiling, t0 + 1)
    assert v["healthy"] and not v["no_data"]
    # every sample over the ceiling: violation fraction 1.0 vs 0.25 budget
    for i in range(3):
        g.set(30)
        g._roll(t0 + 2 + i, 64)
    v = slo._eval_objective(ceiling, t0 + 6)
    assert v["page"] and v["burn_rate"] >= 2.0
    assert v["value"] == 30
    floor = Objective("t-floor:gc/1", "gauge_floor",
                      metric="mxtpu_serve_queue_depth",
                      labels={"model": "gc/1"}, threshold=100.0,
                      budget=0.25, fast_windows=[60.0], slow_window=3600.0)
    v = slo._eval_objective(floor, t0 + 6)  # all samples under the floor
    assert v["page"] and v["value"] == 2


def test_staleness_objective():
    reg = core.get_registry()
    c = reg.counter("mxtpu_steps_total", {"kind": "stale-test"})
    obj = Objective("t-stale", "staleness", metric="mxtpu_steps_total",
                    labels={"kind": "stale-test"}, threshold=30.0,
                    fast_windows=[60.0], slow_window=3600.0)
    t0 = time.time()
    c.inc()
    c._roll(t0, 64)
    assert slo._eval_objective(obj, t0 + 10)["healthy"]  # 10s < 30s
    v = slo._eval_objective(obj, t0 + 100)               # 100s stale
    assert v["page"] and v["value"] == pytest.approx(100.0, abs=1.0)
    assert v["burn_rate"] == pytest.approx(100.0 / 30.0, rel=0.05)


# ---------------------------------------------------------------------------
# spec parsing: malformed JSON / unknown kind / unknown metric are EAGER
# ---------------------------------------------------------------------------

def test_spec_malformed_json_is_typed_error(tmp_path):
    p = tmp_path / "bad.json"
    p.write_text("{not json")
    with pytest.raises(SLOSpecError, match="not valid JSON"):
        slo.load_spec(str(p))
    missing = tmp_path / "nope.json"
    with pytest.raises(SLOSpecError, match="cannot read"):
        slo.load_spec(str(missing))
    p2 = tmp_path / "shape.json"
    p2.write_text(json.dumps({"objectives": "not-a-list"}))
    with pytest.raises(SLOSpecError, match="objectives"):
        slo.load_spec(str(p2))


def test_spec_unknown_kind_and_metric_fail_eagerly():
    with pytest.raises(SLOSpecError, match="unknown kind"):
        Objective("x", "quantile_of_vibes",
                  metric="mxtpu_serve_request_seconds", threshold=1.0)
    with pytest.raises(SLOSpecError, match="unknown metric"):
        Objective("x", "latency_quantile",
                  metric="mxtpu_totally_made_up_seconds", threshold=1.0)
    with pytest.raises(SLOSpecError, match="not a valid mxtpu"):
        Objective("x", "latency_quantile", metric="http_requests_total",
                  threshold=1.0)
    # the escape hatch: bespoke instrumentation may opt out of the catalog
    obj = Objective("x", "latency_quantile",
                    metric="mxtpu_totally_made_up_seconds", threshold=1.0,
                    allow_unknown_metric=True)
    assert obj.metric == "mxtpu_totally_made_up_seconds"


def test_spec_field_validation():
    with pytest.raises(SLOSpecError, match="threshold"):
        Objective("x", "latency_quantile",
                  metric="mxtpu_serve_request_seconds")
    with pytest.raises(SLOSpecError, match="quantile"):
        Objective("x", "latency_quantile",
                  metric="mxtpu_serve_request_seconds", threshold=0.1,
                  quantile=1.5)
    with pytest.raises(SLOSpecError, match="budget"):
        Objective("x", "error_rate",
                  bad=["mxtpu_serve_rejected_total"],
                  total=["mxtpu_serve_requests_total"])
    with pytest.raises(SLOSpecError, match="unknown key"):
        Objective.from_spec({"name": "x", "kind": "latency_quantile",
                             "metric": "mxtpu_serve_request_seconds",
                             "treshold_ms": 100})
    with pytest.raises(SLOSpecError, match="threshold OR"):
        Objective.from_spec({"name": "x", "kind": "latency_quantile",
                             "metric": "mxtpu_serve_request_seconds",
                             "threshold": 0.1, "threshold_ms": 100})


def test_spec_roundtrip_registers_objectives(tmp_path):
    p = tmp_path / "slo.json"
    p.write_text(json.dumps({"objectives": [
        {"name": "spec-p99", "kind": "latency_quantile",
         "metric": "mxtpu_serve_request_seconds",
         "labels": {"model": "spec/1"}, "quantile": 0.95,
         "threshold_ms": 200},
        {"name": "spec-avail", "kind": "error_rate", "availability": 0.99,
         "bad": [{"metric": "mxtpu_serve_rejected_total",
                  "labels": {"model": "spec/1"}}],
         "total": [{"metric": "mxtpu_serve_requests_total",
                    "labels": {"model": "spec/1"}}]},
    ]}))
    try:
        objs = slo.load_spec(str(p))
        assert [o.name for o in objs] == ["spec-p99", "spec-avail"]
        assert objs[0].threshold == pytest.approx(0.2)
        assert objs[0].quantile == 0.95
        assert objs[1].budget == pytest.approx(0.01)
        names = {o.name for o in slo.objectives()}
        assert {"spec-p99", "spec-avail"} <= names
    finally:
        slo.unregister("spec-p99")
        slo.unregister("spec-avail")


# ---------------------------------------------------------------------------
# evaluator: gauges, transition events, the alerts ring, flight recorder
# ---------------------------------------------------------------------------

def test_evaluator_publishes_gauges_events_and_alerts():
    reg = core.get_registry()
    h = reg.histogram("mxtpu_serve_request_seconds", {"model": "pub/1"})
    obj = _mk_latency_obj("pub/1", fast=(60.0,), slow=3600.0)
    slo.register(obj)
    slo.stop()  # drive transitions manually: single-writer, deterministic
    try:
        t0 = time.time()
        h._roll(t0, 256)
        for _ in range(10):
            h.observe(0.4, exemplar="deadbeef00000001")
        slo._evaluate_and_publish(t0 + 30)
        snap = telemetry.snapshot()
        assert snap['mxtpu_slo_healthy{slo="t-p99:pub/1"}']["value"] == 0
        assert snap['mxtpu_slo_burn_rate{slo="t-p99:pub/1"}']["value"] \
            >= 1.0
        breaches = [e for e in telemetry.events()
                    if e["event"] == "slo_breach"
                    and e["fields"].get("slo") == "t-p99:pub/1"]
        assert breaches, "breach transition must land in the event ring"
        assert breaches[-1]["fields"]["exemplar_trace"] == \
            "deadbeef00000001"
        # re-evaluating while still breaching must NOT re-emit the event
        slo._evaluate_and_publish(t0 + 31)
        assert len([e for e in telemetry.events()
                    if e["event"] == "slo_breach"
                    and e["fields"].get("slo") == "t-p99:pub/1"]) == \
            len(breaches)
        # recovery: quiet epoch rolls by, fresh traffic is fast
        h._roll(t0 + 60, 256)
        for _ in range(10):
            h.observe(0.01)
        slo._evaluate_and_publish(t0 + 90)
        snap = telemetry.snapshot()
        assert snap['mxtpu_slo_healthy{slo="t-p99:pub/1"}']["value"] == 1
        recovered = [e for e in telemetry.events()
                     if e["event"] == "slo_recovered"
                     and e["fields"].get("slo") == "t-p99:pub/1"]
        assert recovered and recovered[-1]["fields"]["burned_for_s"] > 0
        # both transitions in the bounded alerts ring, oldest first
        kinds = [a["event"] for a in recorder.alerts()
                 if a["fields"].get("slo") == "t-p99:pub/1"]
        assert kinds[-2:] == ["slo_breach", "slo_recovered"]
    finally:
        slo.unregister(obj.name)


def test_unregister_retires_published_gauges():
    """A model unloaded while breaching must not export a permanently
    breaching mxtpu_slo_healthy series forever."""
    reg = core.get_registry()
    h = reg.histogram("mxtpu_serve_request_seconds", {"model": "gone/1"})
    obj = _mk_latency_obj("gone/1")
    slo.register(obj)
    slo.stop()
    t0 = time.time()
    h._roll(t0, 64)
    for _ in range(5):
        h.observe(0.4)
    slo._evaluate_and_publish(t0 + 30)
    key = 'mxtpu_slo_healthy{slo="%s"}' % obj.name
    assert telemetry.snapshot()[key]["value"] == 0  # breaching
    slo.unregister_model("gone/1")
    snap = telemetry.snapshot()
    assert key not in snap
    assert 'mxtpu_slo_burn_rate{slo="%s"}' % obj.name not in snap
    assert not any(o.labels.get("model") == "gone/1"
                   for o in slo.objectives())


def test_spec_objective_survives_model_unload_reload(tmp_path):
    """An operator's spec objective scoped to a model must come back on
    reload — not silently revert to the env-default built-in."""
    p = tmp_path / "spec.json"
    p.write_text(json.dumps({"objectives": [
        {"name": "serve-p99:reload/1", "kind": "latency_quantile",
         "metric": "mxtpu_serve_request_seconds",
         "labels": {"model": "reload/1"}, "threshold_ms": 123}]}))
    try:
        slo.load_spec(str(p))
        slo.wire_serving_objectives("reload/1", queue_depth=8)
        by_name = {o.name: o for o in slo.objectives()}
        assert by_name["serve-p99:reload/1"].threshold == \
            pytest.approx(0.123)  # spec beats the built-in default
        slo.unregister_model("reload/1")  # the model unloads
        assert "serve-p99:reload/1" not in {o.name
                                            for o in slo.objectives()}
        slo.wire_serving_objectives("reload/1", queue_depth=8)  # reload
        by_name = {o.name: o for o in slo.objectives()}
        assert by_name["serve-p99:reload/1"].threshold == \
            pytest.approx(0.123), "spec objective lost on reload"
    finally:
        slo.unregister_model("reload/1")
        with slo._REG_LOCK:
            slo._STATE.spec_objectives.pop("serve-p99:reload/1", None)


def test_spec_load_failure_is_not_latched(tmp_path, monkeypatch):
    """A typo'd MXTPU_SLO_SPEC fails the triggering load EAGERLY — and a
    corrected file must be retried by the next load, not silently skipped
    for the process lifetime."""
    p = tmp_path / "spec.json"
    p.write_text("{broken")
    monkeypatch.setenv("MXTPU_SLO_SPEC", str(p))
    saved = dict(slo._STATE.objectives)
    slo.clear()  # resets the spec_loaded latch for this test
    try:
        with pytest.raises(SLOSpecError):
            slo._ensure_spec()
        # operator fixes the file; the SAME process retries and registers
        p.write_text(json.dumps({"objectives": [
            {"name": "latched-p99", "kind": "latency_quantile",
             "metric": "mxtpu_serve_request_seconds",
             "threshold_ms": 100}]}))
        slo._ensure_spec()
        assert any(o.name == "latched-p99" for o in slo.objectives())
    finally:
        slo.clear()
        with slo._REG_LOCK:
            slo._STATE.objectives.update(saved)


def test_flightrec_dump_carries_alerts_ring(tmp_path):
    recorder.record_alert("slo_breach", {"slo": "dump-test",
                                         "burn_rate": 9.9})
    path = recorder.dump("test-alerts", path=str(tmp_path / "fr.json"))
    assert path is not None
    doc = json.loads((tmp_path / "fr.json").read_text())
    assert "alerts" in doc
    mine = [a for a in doc["alerts"]
            if a["fields"].get("slo") == "dump-test"]
    assert mine and mine[-1]["event"] == "slo_breach"
    assert mine[-1]["fields"]["burn_rate"] == 9.9


# ---------------------------------------------------------------------------
# /statusz
# ---------------------------------------------------------------------------

def test_statusz_payload_sections():
    reg = core.get_registry()
    h = reg.histogram("mxtpu_serve_request_seconds", {"model": "szp/1"})
    for _ in range(5):
        h.observe(0.02, exemplar="0123456789abcdef")
    core.roll_windows(force=True)
    obj = _mk_latency_obj("szp/1")
    slo.register(obj)
    try:
        p = slo.statusz_payload(extra={"server": {"port": 1}})
        for key in ("slo", "rates", "pools", "compile_cache", "memory",
                    "slowest_exemplars", "server"):
            assert key in p, key
        assert any(v["slo"] == obj.name for v in p["slo"]["verdicts"])
        assert "szp/1" in p["rates"]["serving"]
        row = p["rates"]["serving"]["szp/1"]
        assert row["p99_ms"] is None or row["p99_ms"] >= 0
        assert any(e["trace"] == "0123456789abcdef"
                   for e in p["slowest_exemplars"])
        # text rendering covers the same document without raising
        text = slo._render_text(p)
        assert "statusz @" in text and obj.name in text
    finally:
        slo.unregister(obj.name)


def test_statusz_on_telemetry_exporter():
    port = telemetry.start_http_server(port=0)
    assert port
    with urllib.request.urlopen(
            "http://127.0.0.1:%d/statusz" % port, timeout=10) as r:
        assert r.status == 200
        assert r.headers["Content-Type"].startswith("application/json")
        doc = json.loads(r.read())
    assert doc["version"] == 1 and "slo" in doc and "rates" in doc
    with urllib.request.urlopen(
            "http://127.0.0.1:%d/statusz?format=text" % port,
            timeout=10) as r:
        assert r.status == 200
        assert r.read().startswith(b"statusz @")


# ---------------------------------------------------------------------------
# overhead: the hot path must not notice the SLO engine (PR-3 bar)
# ---------------------------------------------------------------------------

def test_slo_enabled_vs_disabled_step_overhead_under_2pct():
    """Same shape as the PR-3 acceptance: per-call observe_step cost,
    enabled minus disabled, as a fraction of a realistic ~1ms step — but
    measured WITH the SLO engine armed (objectives registered, rings
    rolled, evaluator running). The dispatch hot path is unchanged by
    design; this pins it."""
    reg = core.get_registry()
    h = reg.histogram("mxtpu_serve_request_seconds", {"model": "ovh/1"})
    h.observe(0.001)
    obj = _mk_latency_obj("ovh/1")
    slo.register(obj)  # starts the evaluator
    core.roll_windows(force=True)
    assert slo.running()

    def per_call_cost(chunks=40, inner=500):
        best = float("inf")
        for _ in range(chunks):
            t0 = time.perf_counter()
            for i in range(inner):
                telemetry.observe_step(0.001, examples=32, step=i,
                                       kind="slo-bench")
            best = min(best, (time.perf_counter() - t0) / inner)
        return best

    try:
        telemetry.observe_step(0.001, examples=32, step=0,
                               kind="slo-bench")  # warm
        cost_on = per_call_cost()
        telemetry.set_enabled(False)
        try:
            cost_off = per_call_cost()
        finally:
            telemetry.set_enabled(True)
        cost = max(0.0, cost_on - cost_off)
        a = np.random.rand(384, 384).astype(np.float32)
        a.dot(a)
        step = min((lambda t0=time.perf_counter(): (
            [a.dot(a) for _ in range(10)],
            (time.perf_counter() - t0) / 10)[1])() for _ in range(20))
        overhead = cost / step
        assert overhead < 0.02, \
            "SLO-armed per-step overhead %.3f%% (cost %.2fus vs step " \
            "%.0fus)" % (overhead * 100.0, cost * 1e6, step * 1e6)
    finally:
        slo.unregister(obj.name)


# ---------------------------------------------------------------------------
# bench_history --check regression gate
# ---------------------------------------------------------------------------

def _traj_row(rnd, metric, value, file=None, stale=False, mfu=None,
              row="serve"):
    return {"file": file or "BENCH_local_r%02d_%s.json" % (rnd, row),
            "round": rnd, "row": row, "stale": stale, "metric": metric,
            "value": value, "unit": "", "device": "cpu", "mfu": mfu,
            "detail": "", "utc": ""}


def test_bench_history_check_gate(tmp_path):
    import tools.bench_history as bh

    # >15% regression on the newest round vs the best prior row
    rows = [_traj_row(6, "serve_batched_rps", 100.0),
            _traj_row(12, "serve_batched_rps", 80.0)]
    regs = bh.check(rows)
    assert len(regs) == 1
    assert regs[0]["metric"] == "serve_batched_rps"
    assert regs[0]["regression_pct"] == pytest.approx(20.0)
    # within tolerance passes; stale prior rows are never the baseline
    assert bh.check([_traj_row(6, "serve_batched_rps", 100.0),
                     _traj_row(12, "serve_batched_rps", 90.0)]) == []
    assert bh.check([_traj_row(6, "serve_batched_rps", 1000.0, stale=True),
                     _traj_row(12, "serve_batched_rps", 90.0)]) == []
    # lower-is-better family: cold-start time-to-ready
    regs = bh.check([_traj_row(8, "coldstart_resnet18_mb8", 5.0,
                               row="coldstart"),
                     _traj_row(12, "coldstart_resnet18_mb8", 9.0,
                               row="coldstart")])
    assert len(regs) == 1 and regs[0]["direction"] == "lower"
    # coldstart gates per metric name: a NEW slower-to-load model's first
    # row must not be compared against a different model's history
    assert bh.check([_traj_row(8, "coldstart_resnet18_mb8", 5.0,
                               row="coldstart"),
                     _traj_row(12, "coldstart_bert_mb8", 20.0,
                               row="coldstart_bert")]) == []
    # MFU regression gates per (metric, row) family
    regs = bh.check([_traj_row(3, "resnet50_train_bs32_imgs_per_sec",
                               500.0, mfu=0.15, row="train"),
                     _traj_row(12, "resnet50_train_bs32_imgs_per_sec",
                               520.0, mfu=0.10, row="train")])
    assert len(regs) == 1 and regs[0]["metric"].startswith("mfu:")
    # run_check over a fabricated trajectory file: exit 2 on regression
    (tmp_path / "BENCH_TRAJECTORY.json").write_text(json.dumps({
        "rows": [_traj_row(6, "serve_batched_rps", 100.0),
                 _traj_row(12, "serve_batched_rps", 50.0)]}))
    assert bh.run_check(str(tmp_path), 0.15, quiet=True) == 2
    # and the COMMITTED trajectory passes (the acceptance criterion)
    assert bh.main(["--check", "--quiet"]) == 0


# ---------------------------------------------------------------------------
# THE acceptance e2e: slow_reply fault -> latency verdict flips ->
# /statusz reports burn rate + exemplar trace -> recovery after clear
# ---------------------------------------------------------------------------

def _get_statusz(port):
    with urllib.request.urlopen(
            "http://127.0.0.1:%d/statusz" % port, timeout=10) as r:
        assert r.status == 200
        return json.loads(r.read())


def _post_predict(port, name, x, timeout_ms):
    body = json.dumps({"inputs": {"x": [[x]]},
                       "timeout_ms": timeout_ms}).encode()
    req = urllib.request.Request(
        "http://127.0.0.1:%d/v1/models/%s:predict" % (port, name),
        data=body, headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as r:
        return r.status, json.loads(r.read())


def test_statusz_e2e_slow_reply_breach_and_recovery(monkeypatch):
    """ISSUE 14 acceptance: a pooled serving run with injected
    `slow_reply` faults flips the latency SLO verdict to breaching
    within one fast window, /statusz reports it with a burn rate and an
    exemplar trace id, and the verdict recovers after the fault clears."""
    from mxnet_tpu.serving import ModelRepository, ServedModel, \
        ServingServer

    # tiny windows so breach AND recovery fit in seconds, not minutes
    monkeypatch.setenv("MXTPU_SLO_WINDOW_MS", "200")
    monkeypatch.setenv("MXTPU_SLO_EVAL_MS", "150")
    monkeypatch.setenv("MXTPU_SLO_FAST_WINDOWS", "3")
    monkeypatch.setenv("MXTPU_SLO_SLOW_WINDOW_S", "30")
    monkeypatch.setenv("MXTPU_SLO_SERVE_P99_MS", "1000")
    slo.stop()  # fresh evaluator picks up the test cadence

    tracing = telemetry.tracing
    tracing.configure(sample=1.0)  # exemplars need recorded traces
    faults = " ".join("slow_reply@batch=%d,ms=1500" % b
                      for b in range(1, 5))
    model = ServedModel.pooled(
        "sloe2e", 1, None, 2,
        worker_args=["--stub", "echo", "--input", "x=1", "--max-batch", "2"],
        heartbeat_ms=500, backoff_ms=50, teardown_grace=1.0,
        spawn_timeout_s=90, max_delay_ms=1, queue_depth=64,
        extra_env={"MXTPU_FAULT_INJECT": faults})
    repo = ModelRepository()
    repo.add(model)
    srv = ServingServer(repo, port=0, addr="127.0.0.1").start()
    objective = "serve-p99:sloe2e/1"
    try:
        assert any(o.name == objective for o in slo.objectives())
        assert slo.running()

        def verdict_of(doc):
            for v in doc["slo"]["verdicts"]:
                if v["slo"] == objective:
                    return v
            return None

        # phase 1: slow replies (1.5s >> the 1s p99 objective) until the
        # evaluator pages. Each request is its own batch (max_delay 1ms,
        # sequential sends), so the per-replica batch counter walks
        # through the injected range deterministically.
        t_first_slow = time.monotonic()
        breach = None
        for i in range(8):
            code, _ = _post_predict(srv.port, "sloe2e", float(i),
                                    timeout_ms=20000)
            assert code == 200
            deadline = time.monotonic() + 2.0
            while breach is None and time.monotonic() < deadline:
                v = verdict_of(_get_statusz(srv.port))
                if v is not None and v["page"]:
                    breach = v
                    break
                time.sleep(0.05)
            if breach is not None:
                break
        assert breach is not None, \
            "latency verdict never flipped to breaching"
        # flipped within one fast window of the slow traffic (+ slack for
        # a loaded box — the window itself is 3s)
        assert time.monotonic() - t_first_slow < 30.0
        assert breach["burn_rate"] >= 1.0
        assert breach["value"] is not None and breach["value"] > 1.0
        assert re.fullmatch(r"[0-9a-f]{16}", breach["exemplar_trace"] or \
                            ""), breach["exemplar_trace"]
        # the breach transition reached the alerts ring and /statusz
        doc = _get_statusz(srv.port)
        alerts = [a for a in doc["slo"]["alerts"]
                  if a["fields"].get("slo") == objective]
        assert alerts and alerts[-1]["event"] == "slo_breach"
        assert doc["server"]["port"] == srv.port
        # pool health generations ride the lock-free gauge table
        assert doc["pools"].get("sloe2e/1", {}).get("size") == 2

        # phase 2: the fault range is exhausted — fast traffic only, and
        # the verdict recovers once the bad epoch slides out of the fast
        # window
        recovered = None
        deadline = time.monotonic() + 30.0
        while recovered is None and time.monotonic() < deadline:
            code, _ = _post_predict(srv.port, "sloe2e", 1.0,
                                    timeout_ms=20000)
            assert code == 200
            v = verdict_of(_get_statusz(srv.port))
            if v is not None and v["healthy"] and not v["no_data"]:
                recovered = v
                break
            time.sleep(0.1)
        assert recovered is not None, "verdict never recovered"
        assert not recovered["page"]
        doc = _get_statusz(srv.port)
        alerts = [a for a in doc["slo"]["alerts"]
                  if a["fields"].get("slo") == objective]
        assert alerts[-1]["event"] == "slo_recovered"
    finally:
        tracing.configure()
        srv.shutdown()
        model.close(drain=False, timeout=0)
        slo.stop()
