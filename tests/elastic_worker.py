"""Worker body for the preemption / elastic world-size resume tests
(tests/test_preempt_elastic.py — the ISSUE 17 acceptance path).

Trains a deterministic linear regression with gluon.Trainer over a
dist_sync kvstore, writing PER-RANK SHARDED checkpoints through
parallel.resilience.CheckpointManager.save_sharded_async every
MXTPU_TEST_CKPT_EVERY steps and auto-resuming via restore_sharded at
startup — the fast path when the manifest matches this run's world size,
the elastic path (all shards read, state reassembled) when it does not.
On SIGTERM (MXTPU_FAULT_INJECT preempt action, or a real scheduler) the
in-flight step finishes, a SOLO emergency checkpoint lands inside the
grace window, and the process exits MXTPU_PREEMPT_EXIT_CODE so
tools/launch.py restarts it for free.

Cross-world-size exactness trick: EVERY rank computes the FULL global
batch, so each rank's local gradient is identical and the dist_sync
allreduce-sum divided by (batch × world) is bit-exact for power-of-two
world sizes — a 2-rank trajectory equals a 1-rank trajectory to the last
ulp, which lets the parent test assert exact final-weight matches across
preempt→resume at the same AND at a different world size."""
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")  # axon sitecustomize override

from mxnet_tpu.parallel import collectives  # noqa: E402

collectives.init_process_group()

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, gluon  # noqa: E402
from mxnet_tpu.gluon import nn  # noqa: E402
from mxnet_tpu.parallel import resilience  # noqa: E402
from mxnet_tpu.parallel.resilience import (CheckpointManager,  # noqa: E402
                                           restart_generation)

TOTAL_STEPS = int(os.environ.get("MXTPU_TEST_TOTAL_STEPS", "12"))
CKPT_EVERY = int(os.environ.get("MXTPU_TEST_CKPT_EVERY", "2"))
BATCH = 16
DIM = 8


def batch_for(step):
    """The FULL deterministic global batch for a (1-based) step — the same
    on every rank and at every world size (see module docstring)."""
    rng = np.random.RandomState(10_000 + step)
    x = rng.normal(size=(BATCH, DIM)).astype(np.float32)
    w = np.arange(1, DIM + 1, dtype=np.float32).reshape(DIM, 1) / DIM
    return x, x @ w


def main():
    kv = mx.kv.create("dist_sync")
    r, n = kv.rank, kv.num_workers
    topology = {"world_size": n}

    np.random.seed(77)  # same init draw on every rank
    net = nn.Dense(1, in_units=DIM, use_bias=False)
    net.initialize(mx.init.Normal(0.5))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05, "momentum": 0.9},
                            kvstore=kv)
    mgr = CheckpointManager(os.environ["MXTPU_CKPT_DIR"],
                            keep_last=3, save_every=CKPT_EVERY)
    resilience.install_preemption_handler()

    def payload():
        """This rank's shard: replicated params + the trainer-states blob
        (opaque bytes via the public save_states API, so the optimizer
        cursor and momentum ride along)."""
        fd, tmp = tempfile.mkstemp(prefix="trainer-states-")
        os.close(fd)
        try:
            trainer.save_states(tmp)
            with open(tmp, "rb") as f:
                blob = f.read()
        finally:
            os.unlink(tmp)
        return {"params": {k: v.data().asnumpy()
                           for k, v in net.collect_params().items()},
                "states_blob": blob, "step": trainer.step_count}

    def load_shards(payloads, header):
        # params are fully replicated, so ANY shard reassembles the whole
        # model — exactly why a solo emergency checkpoint (1 shard) can
        # elastically resume at any world size
        p = payloads[min(payloads)]
        for k, v in net.collect_params().items():
            v.set_data(mx.nd.array(p["params"][k]))
        fd, tmp = tempfile.mkstemp(prefix="trainer-states-")
        os.close(fd)
        try:
            with open(tmp, "wb") as f:
                f.write(p["states_blob"])
            trainer.load_states(tmp)
        finally:
            os.unlink(tmp)

    header = mgr.restore_sharded(load_shards, rank=r, world_size=n,
                                 topology=topology)
    start = trainer.step_count
    if header is not None:
        elastic = not (header.get("topology") == topology
                       and int(header.get("shards") or 0) == n)
        print("ELASTIC_RESUMED rank=%d/%d gen=%d from_step=%d elastic=%d "
              "shards=%d" % (r, n, restart_generation(), start, int(elastic),
                             int(header.get("shards") or 0)), flush=True)

    def emergency():
        mgr.flush()  # let any in-flight periodic shard publish first
        mgr.save_sharded(trainer.step_count, payload(), rank=0, world_size=1,
                         topology={"world_size": 1}, meta={"preempt": True})

    l2 = gluon.loss.L2Loss()
    for step in range(start + 1, TOTAL_STEPS + 1):
        xb, yb = batch_for(step)
        with autograd.record():
            loss = l2(net(mx.nd.array(xb)), mx.nd.array(yb))
        loss.backward()
        # the MXTPU_FAULT_INJECT hook fires inside step() at the boundary;
        # the preempt action SIGTERMs this very process there
        trainer.step(BATCH * n)
        if step % CKPT_EVERY == 0:
            mgr.save_sharded_async(step, payload(), rank=r, world_size=n,
                                   topology=topology,
                                   meta={"kind": "elastic-test"})
        resilience.maybe_preempt_exit(emergency_save=emergency, rank=r)

    mgr.close()  # drain the async writer so the final manifest publishes
    w = net.weight.data().asnumpy()
    print("ELASTIC_OK rank=%d/%d gen=%d steps=%d wsum=%.8f"
          % (r, n, restart_generation(), trainer.step_count, float(w.sum())),
          flush=True)


if __name__ == "__main__":
    main()
