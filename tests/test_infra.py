"""Tests for infra modules: test_utils oracles, attribute/name scopes,
runtime features, profiler, monitor, visualization.

Mirrors the reference's test strategy (SURVEY §4): numeric-gradient checking,
naive-vs-jit consistency, seeded RNG.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import test_utils as tu
from mxnet_tpu.base import MXNetError


def test_assert_almost_equal():
    a = np.array([1.0, 2.0])
    tu.assert_almost_equal(a, a + 1e-9)
    with pytest.raises(AssertionError):
        tu.assert_almost_equal(a, a + 1.0)


def test_same_array():
    x = mx.nd.array([1, 2, 3])
    y = x
    assert tu.same_array(x, y)
    assert not tu.same_array(x, x.copy())


@tu.with_seed(42)
def test_check_numeric_gradient_fc():
    data = mx.sym.var("data")
    w = mx.sym.var("w")
    out = mx.sym.FullyConnected(data=data, weight=w, no_bias=True, num_hidden=3)
    out = mx.sym.tanh(out)
    loc = {"data": np.random.uniform(-1, 1, (2, 4)),
           "w": np.random.uniform(-1, 1, (3, 4))}
    tu.check_numeric_gradient(out, loc, rtol=1e-2, atol=1e-2)


def test_check_symbolic_forward_backward():
    x = mx.sym.var("x")
    y = mx.sym.square(x)
    loc = {"x": np.array([[1.0, 2.0], [3.0, 4.0]], dtype=np.float32)}
    tu.check_symbolic_forward(y, loc, [loc["x"] ** 2])
    tu.check_symbolic_backward(y, loc, [np.ones((2, 2), dtype=np.float32)],
                               {"x": 2 * loc["x"]})


def test_check_consistency():
    x = mx.sym.var("x")
    y = mx.sym.exp(x) + mx.sym.sqrt(mx.sym.abs(x))
    tu.check_consistency(y, {"x": np.random.uniform(0.5, 2, (3, 3))})


def test_rand_ndarray_dense():
    arr = tu.rand_ndarray((4, 5))
    assert arr.shape == (4, 5)


def test_attr_scope():
    with mx.AttrScope(ctx_group="dev1", lr_mult="0.5"):
        w = mx.sym.var("w")
    assert w.attr("ctx_group") == "dev1"
    assert w.attr("lr_mult") == "0.5"
    v = mx.sym.var("v")
    assert v.attr("ctx_group") is None
    # nested scopes merge, inner wins
    with mx.AttrScope(a="1"):
        with mx.AttrScope(a="2", b="3"):
            u = mx.sym.var("u")
    assert u.attr("a") == "2" and u.attr("b") == "3"


def test_attr_scope_on_ops_doesnt_break_eval():
    with mx.AttrScope(ctx_group="stage1"):
        x = mx.sym.var("x")
        y = mx.sym.relu(x)
    out = y.eval_with({"x": np.array([-1.0, 2.0], dtype=np.float32)})
    np.testing.assert_allclose(out.asnumpy(), [0.0, 2.0])


def test_name_manager_prefix():
    from mxnet_tpu import name as name_mod

    with name_mod.Prefix("stage1_"):
        s = mx.sym.relu(mx.sym.var("x"))
    assert s.name.startswith("stage1_")


def test_runtime_features():
    feats = mx.runtime.Features()
    assert feats.is_enabled("CPU")
    names = [f.name for f in mx.runtime.feature_list()]
    assert "TPU" in names and "BF16" in names


def test_profiler_trace(tmp_path):
    from mxnet_tpu import profiler

    fname = str(tmp_path / "trace.json")
    profiler.set_config(filename=fname, aggregate_stats=True)
    profiler.set_state("run")
    x = mx.nd.array([1.0, 2.0, 3.0])
    y = (x * 2 + 1).sum()
    y.wait_to_read()
    with profiler.Task("custom_task"):
        _ = x + 1
    profiler.set_state("stop")
    profiler.dump()
    import json

    with open(fname) as f:
        data = json.load(f)
    names = [e["name"] for e in data["traceEvents"]]
    assert any("mul" in n or "plus" in n or "sum" in n for n in names), names
    assert "custom_task" in names
    summary = profiler.dumps()
    assert "Total(ms)" in summary


def test_monitor():
    from mxnet_tpu.monitor import Monitor

    mon = Monitor(interval=1, pattern=".*")
    x = mx.sym.var("x")
    y = mx.sym.relu(x)
    exe = y.bind(mx.cpu(), args={"x": mx.nd.array([[-1.0, 3.0]])})
    mon.install(exe)
    mon.tic()
    exe.forward()
    rows = mon.toc()
    assert rows and rows[0][1] in y.list_outputs()


def test_print_summary(capsys):
    data = mx.sym.var("data")
    fc = mx.sym.FullyConnected(data=data, num_hidden=8, name="fc1")
    act = mx.sym.relu(fc, name="act1")
    mx.viz.print_summary(act, shape={"data": (2, 16)})
    out = capsys.readouterr().out
    assert "fc1" in out and "Total params" in out


def test_with_seed_reproducible():
    @tu.with_seed(7)
    def draw():
        return mx.nd.random_uniform(shape=(4,)).asnumpy()

    a = draw()
    b = draw()
    np.testing.assert_allclose(a, b)


def test_registry_module():
    """mx.registry factory surface (reference: python/mxnet/registry.py)."""
    from mxnet_tpu import registry

    class Base:
        def __init__(self, x=1):
            self.x = x

    reg = registry.get_register_func(Base, "thing")
    alias = registry.get_alias_func(Base, "thing")
    create = registry.get_create_func(Base, "thing")

    @alias("myalias")
    class Impl(Base):
        pass

    reg(Impl)
    assert isinstance(create("impl"), Impl)
    assert isinstance(create("myalias", x=5), Impl)
    assert create("myalias", x=5).x == 5
    # (name, kwargs) spec, JSON spec, instance pass-through
    assert create(("impl", {"x": 3})).x == 3
    assert create('["impl", {"x": 4}]').x == 4
    inst = Impl()
    assert create(inst) is inst
    assert "impl" in registry.get_registry(Base)
    with pytest.raises(MXNetError):
        reg(int)  # not a subclass


def test_log_module(tmp_path, capsys):
    from mxnet_tpu import log

    logger = log.get_logger("mxtpu_test_logger", level=log.INFO)
    logger.info("hello-from-test")
    f = str(tmp_path / "x.log")
    flog = log.get_logger("mxtpu_file_logger", filename=f, level=log.DEBUG)
    flog.debug("to-file")
    for h in flog.handlers:
        h.flush()
    assert "to-file" in open(f).read()


def test_registry_shares_subsystem_storage():
    """mx.registry resolves onto the SAME registries the subsystems use
    (regression: a parallel empty store made create('adam') fail)."""
    from mxnet_tpu import optimizer, registry

    create = registry.get_create_func(optimizer.Optimizer, "optimizer")
    o = create("adam", learning_rate=1e-3)
    assert type(o).__name__ == "Adam" and o.lr == 1e-3
    # reference keyword-name form
    o2 = create(optimizer="sgd", learning_rate=0.5)
    assert type(o2).__name__ == "SGD"
    assert "adam" in registry.get_registry(optimizer.Optimizer)


def test_libinfo_util_kvstore_server(tmp_path):
    """Small reference-module shims: libinfo paths, util helpers,
    kvstore_server role handling (reference: libinfo.py/util.py/
    kvstore_server.py)."""
    import os

    from mxnet_tpu import kvstore_server, libinfo, util

    paths = libinfo.find_lib_path()
    assert paths and all(os.path.exists(p) for p in paths)
    inc = libinfo.find_include_path()
    assert os.path.exists(os.path.join(inc, "mxtpu_c_predict_api.h"))
    assert util.get_gpu_count() >= 0
    d = str(tmp_path / "a" / "b")
    util.makedirs(d)
    assert os.path.isdir(d)
    # worker role: no server loop
    assert kvstore_server._init_kvstore_server_module() is False


def test_registry_third_party_isolation():
    """A third-party base class sharing a subsystem nickname must get its
    own registry (regression: it claimed/polluted the optimizer store)."""
    from mxnet_tpu import optimizer, registry

    class MyBase:
        pass

    create = registry.get_create_func(MyBase, "optimizer")
    with pytest.raises(MXNetError):
        create("adam")  # NOT resolved onto the real optimizer registry
    reg = registry.get_register_func(MyBase, "optimizer")

    class Thing(MyBase):
        pass

    reg(Thing)
    assert isinstance(create("thing"), Thing)
    # and the real optimizer registry is untouched
    assert "thing" not in registry.get_registry(optimizer.Optimizer)
    assert isinstance(optimizer.create("adam"), optimizer.Adam)


def test_kvstore_server_roles(monkeypatch):
    from mxnet_tpu import kvstore_server

    monkeypatch.setenv("DMLC_ROLE", "scheduler")
    assert kvstore_server._init_kvstore_server_module() is True


def test_kvstore_server_role_exits_cleanly(monkeypatch):
    from mxnet_tpu import kvstore_server

    monkeypatch.setenv("DMLC_ROLE", "server")
    assert kvstore_server._init_kvstore_server_module() is True


def test_get_registry_does_not_poison(monkeypatch):
    """get_registry on a framework base whose name differs from the kind
    (EvalMetric vs 'metric') resolves the subsystem store and must not
    cache an isolated registry (regression)."""
    from mxnet_tpu import metric, registry

    m = registry.get_registry(metric.EvalMetric)
    assert "accuracy" in m
    # and registration after the read still lands in the real store
    reg = registry.get_register_func(metric.EvalMetric, "metric")

    class _ProbeMetric(metric.EvalMetric):
        def __init__(self):
            super().__init__("probe")

    reg(_ProbeMetric, "probe_metric_xyz")
    assert "probe_metric_xyz" in registry.get_registry(metric.EvalMetric)


def test_image_record_iter_unindexed_sequential(tmp_path):
    """A .rec without its .idx must stream sequentially (reference
    image.py ImageIter: plain MXRecordIO, seq=None) — it previously opened
    an empty index and silently yielded zero batches; shuffle requires the
    index and must say so."""
    import io as _io

    import pytest
    from PIL import Image

    import mxnet_tpu as mx
    from mxnet_tpu import recordio

    rec = str(tmp_path / "plain.rec")
    w = recordio.MXRecordIO(rec, "w")
    rng = np.random.RandomState(0)
    for i in range(6):
        img = Image.fromarray(rng.randint(0, 255, (20, 20, 3), np.uint8))
        buf = _io.BytesIO()
        img.save(buf, format="JPEG")
        w.write(recordio.pack(recordio.IRHeader(0, float(i), i, 0),
                              buf.getvalue()))
    w.close()

    it = mx.io.ImageRecordIter(path_imgrec=rec, data_shape=(3, 20, 20),
                               batch_size=3)
    assert sum(1 for _ in it) == 2
    it.reset()
    assert sum(1 for _ in it) == 2  # reset rewinds the stream

    with pytest.raises(Exception, match="index"):
        mx.io.ImageRecordIter(path_imgrec=rec, data_shape=(3, 20, 20),
                              batch_size=3, shuffle=True)


def test_image_det_iter_unindexed_sequential(tmp_path):
    """ImageDetIter over an un-indexed .rec: the label-shape scan streams
    the headers and rewinds, then batches iterate from record 0."""
    import io as _io

    from PIL import Image

    import mxnet_tpu as mx
    from mxnet_tpu import recordio

    rec = str(tmp_path / "det.rec")
    w = recordio.MXRecordIO(rec, "w")
    rng = np.random.RandomState(0)
    for i in range(6):
        img = Image.fromarray(rng.randint(0, 255, (40, 40, 3), np.uint8))
        buf = _io.BytesIO()
        img.save(buf, format="JPEG")
        label = np.array([2, 5, 0, 0.1, 0.1, 0.6, 0.6], np.float32)
        w.write(recordio.pack(recordio.IRHeader(0, label, i, 0),
                              buf.getvalue()))
    w.close()

    it = mx.image.ImageDetIter(batch_size=2, data_shape=(3, 32, 32),
                               path_imgrec=rec)
    assert sum(1 for _ in it) == 3
    it.reset()
    assert sum(1 for _ in it) == 3


def test_notebook_pandas_logger():
    """notebook.callback.PandasLogger (reference python/mxnet/notebook/):
    metrics land in train/eval/epoch DataFrames via the fit() callback
    slots; the bokeh-backed live charts raise with direction."""
    import pytest as _pytest

    from mxnet_tpu import metric as mmetric
    from mxnet_tpu.module.base_module import BatchEndParam
    from mxnet_tpu.notebook.callback import LiveLearningCurve, PandasLogger

    lg = PandasLogger(batch_size=4, frequent=1)
    m = mmetric.Accuracy()
    m.update([mx.nd.array([0.0, 1.0])],
             [mx.nd.array(np.array([[0.9, 0.1], [0.2, 0.8]], np.float32))])
    lg.train_cb(BatchEndParam(epoch=0, nbatch=1, eval_metric=m,
                              locals=None))
    lg.epoch_cb()
    assert len(lg.train_df) == 1 and "accuracy" in lg.train_df.columns
    assert len(lg.epoch_df) == 1
    assert set(lg.callback_args()) == {"batch_end_callback",
                                       "eval_end_callback",
                                       "epoch_end_callback"}
    with _pytest.raises(ImportError, match="bokeh"):
        LiveLearningCurve()


def test_no_bare_print_in_library(tmp_path):
    """CI lint (ci/lint_print.py): library output goes through mxnet_tpu.log
    / telemetry, never bare print — enforced in-suite so a violation fails
    tier-1, not just a side CI job. Also proves the linter still CATCHES a
    violation (a silently broken linter would pass vacuously)."""
    import os
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    lint = os.path.join(root, "ci", "lint_print.py")
    r = subprocess.run([sys.executable, lint], capture_output=True,
                       text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr

    bad_pkg = tmp_path / "mxnet_tpu"
    bad_pkg.mkdir()
    (bad_pkg / "bad.py").write_text(
        'x = 1\nprint("no")\ny = 2  # print("in comment") is fine\n'
        's = "print(also fine)"\npprint(1)\nobj.print(2)\n'
        'print("ok")  # allow-print\n')
    r = subprocess.run([sys.executable, lint, str(tmp_path)],
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 1, r.stdout
    assert "bad.py:2" in r.stdout, r.stdout
    assert r.stdout.count("bad.py:") == 1, r.stdout  # only the real one


def test_conftest_leaked_thread_report(tmp_path, monkeypatch):
    """The end-of-suite report records non-daemon threads still alive next
    to the walltime/peak-RSS row (MXTPU_WALLTIME_FILE), and FAIL-ANNOTATEs
    when the count grew vs the previous run — the runtime shadow of
    mxlint's thread-hygiene rule."""
    import json
    import threading
    import time

    import conftest

    stop = threading.Event()
    t = threading.Thread(target=stop.wait, name="mxtpu-test-leak",
                         daemon=False)
    t.start()
    try:
        assert "mxtpu-test-leak" in conftest._leaked_threads()

        out = tmp_path / "walltime.jsonl"
        out.write_text(json.dumps({"wall_s": 1.0,
                                   "leaked_threads": []}) + "\n")
        monkeypatch.setenv("MXTPU_WALLTIME_FILE", str(out))

        lines = []

        class _Reporter:
            def write_line(self, line, **kw):
                lines.append(line)

        class _Config:
            _mxtpu_suite_t0 = time.time()

        conftest.pytest_terminal_summary(_Reporter(), 0, _Config())
        report = "\n".join(lines)
        assert "leaked non-daemon threads: " in report
        assert "FAIL-ANNOTATE" in report and "GREW from 0" in report
        rows = [json.loads(ln) for ln in out.read_text().splitlines()]
        assert "mxtpu-test-leak" in rows[-1]["leaked_threads"]

        # same count on the next run: reported, but no growth annotation
        lines.clear()
        conftest.pytest_terminal_summary(_Reporter(), 0, _Config())
        assert "FAIL-ANNOTATE" not in "\n".join(lines)
    finally:
        stop.set()
        t.join(timeout=5)


def test_mxlint_clean():
    """CI static analysis (ci/mxlint, docs/static_analysis.md): the tree has
    ZERO findings across all fourteen checkers (host-sync, signal-safety,
    env-registry, registry-parity, metric-registry, compile-registry,
    bare-print, the concurrency suite: lock-discipline, lock-order,
    thread-hygiene, and the trace-discipline suite: tracer-leak,
    trace-purity, retrace-hazard, donation-discipline) modulo the committed
    baseline — enforced in-suite so a new violation fails tier-1, not just
    a side CI job. Checker efficacy (each rule still catches a planted
    violation) is proven separately in test_mxlint.py's fixture tests."""
    import os
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run([sys.executable, "-m", "ci.mxlint"], cwd=root,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "0 finding(s) across 14 rule(s)" in r.stdout, r.stdout
