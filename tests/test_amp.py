"""AMP (bf16 mixed-precision) coverage — the flagship TPU training precision.

Round-1 lesson: the default bench path (bf16 conv train) shipped broken
because no test exercised a conv BACKWARD in bf16 (forward-only AMP tests
missed a dtype mismatch in the conv transpose rule). These tests pin:

  * bf16 forward+backward for the whole nn op family (conv/dense/BN/pool/
    softmax/layernorm/... — mirrors the reference's fp16 coverage,
    tests/python/train/test_dtype.py + test_operator.py fp16 runs);
  * bf16 end-to-end training convergence through BOTH trainers
    (parallel.DistributedTrainer amp_dtype path and gluon.Trainer with a
    bf16-cast net + multi_precision optimizer);
  * master-weight dtype invariants (params/optimizer state stay fp32 while
    compute runs bf16 — reference analogue: multi-precision SGD,
    python/mxnet/optimizer/optimizer.py fp32 master weights).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn, loss as gloss


BF16 = "bfloat16"


def _bf16(arr):
    return mx.nd.array(arr).astype(BF16)


# ---------------------------------------------------------------------------
# op-family bf16 forward + backward sweep
# ---------------------------------------------------------------------------

def _grad_through(net_fn, *inputs):
    """Run fwd+bwd under autograd; return (out, grads). All bf16 in/out."""
    nds = [x.copy() for x in inputs]
    for nd_ in nds:
        nd_.attach_grad()
    with autograd.record():
        out = net_fn(*nds)
        loss = out.astype("float32").sum()
    loss.backward()
    return out, [nd_.grad for nd_ in nds]


@pytest.mark.parametrize("case", [
    "convolution", "deconvolution", "fully_connected", "batchnorm",
    "layernorm", "pooling", "global_pool", "activation", "softmax",
    "log_softmax", "dropout", "embedding_out", "leaky_relu",
])
def test_bf16_nn_family_fwd_bwd(case):
    rng = np.random.RandomState(0)
    x = _bf16(rng.uniform(-1, 1, (4, 3, 8, 8)).astype(np.float32))
    if case == "convolution":
        w = _bf16(rng.uniform(-1, 1, (6, 3, 3, 3)).astype(np.float32))
        out, grads = _grad_through(
            lambda a, b: mx.nd.Convolution(a, b, kernel=(3, 3), num_filter=6,
                                           no_bias=True, pad=(1, 1)), x, w)
        assert out.shape == (4, 6, 8, 8)
    elif case == "deconvolution":
        w = _bf16(rng.uniform(-1, 1, (3, 6, 3, 3)).astype(np.float32))
        out, grads = _grad_through(
            lambda a, b: mx.nd.Deconvolution(a, b, kernel=(3, 3),
                                             num_filter=6, no_bias=True), x, w)
    elif case == "fully_connected":
        xf = _bf16(rng.uniform(-1, 1, (4, 12)).astype(np.float32))
        w = _bf16(rng.uniform(-1, 1, (5, 12)).astype(np.float32))
        b = _bf16(np.zeros(5, np.float32))
        out, grads = _grad_through(
            lambda a, ww, bb: mx.nd.FullyConnected(a, ww, bb, num_hidden=5),
            xf, w, b)
    elif case == "batchnorm":
        g = _bf16(np.ones(3, np.float32))
        bt = _bf16(np.zeros(3, np.float32))
        mean = mx.nd.zeros((3,)).astype(BF16)
        var = mx.nd.ones((3,)).astype(BF16)
        with autograd.record():
            xx = x.copy()
            xx.attach_grad()
            out = mx.nd.BatchNorm(xx, g, bt, mean, var)
            out.astype("float32").sum().backward()
        grads = [xx.grad]
    elif case == "layernorm":
        g = _bf16(np.ones(8, np.float32))
        bt = _bf16(np.zeros(8, np.float32))
        out, grads = _grad_through(
            lambda a, gg, bb: mx.nd.LayerNorm(a, gg, bb, axis=-1), x, g, bt)
    elif case == "pooling":
        out, grads = _grad_through(
            lambda a: mx.nd.Pooling(a, kernel=(2, 2), stride=(2, 2),
                                    pool_type="max"), x)
    elif case == "global_pool":
        out, grads = _grad_through(
            lambda a: mx.nd.Pooling(a, global_pool=True, pool_type="avg"), x)
    elif case == "activation":
        out, grads = _grad_through(
            lambda a: mx.nd.Activation(a, act_type="relu"), x)
    elif case == "softmax":
        out, grads = _grad_through(lambda a: mx.nd.softmax(a, axis=-1), x)
    elif case == "log_softmax":
        out, grads = _grad_through(lambda a: mx.nd.log_softmax(a, axis=-1), x)
    elif case == "dropout":
        with autograd.record(train_mode=True):
            xx = x.copy()
            xx.attach_grad()
            out = mx.nd.Dropout(xx, p=0.5)
            out.astype("float32").sum().backward()
        grads = [xx.grad]
    elif case == "embedding_out":
        idx = mx.nd.array(np.array([[0, 1], [2, 1]], np.float32))
        w = _bf16(rng.uniform(-1, 1, (4, 6)).astype(np.float32))
        with autograd.record():
            ww = w.copy()
            ww.attach_grad()
            out = mx.nd.Embedding(idx, ww, input_dim=4, output_dim=6)
            out.astype("float32").sum().backward()
        grads = [ww.grad]
    elif case == "leaky_relu":
        out, grads = _grad_through(
            lambda a: mx.nd.LeakyReLU(a, act_type="leaky", slope=0.1), x)
    else:  # pragma: no cover
        raise AssertionError(case)

    assert str(np.dtype(out.dtype)) == BF16, f"{case}: out dtype {out.dtype}"
    for g_ in grads:
        assert g_ is not None, f"{case}: missing grad"
        assert str(np.dtype(g_.dtype)) == BF16, f"{case}: grad dtype {g_.dtype}"
        assert np.isfinite(g_.astype("float32").asnumpy()).all(), \
            f"{case}: non-finite grad"


# ---------------------------------------------------------------------------
# DistributedTrainer amp_dtype=bfloat16 (the bench.py default path)
# ---------------------------------------------------------------------------

def _conv_net(prefix):
    net = nn.HybridSequential(prefix=prefix)
    with net.name_scope():
        net.add(nn.Conv2D(8, 3, padding=1, prefix="c1_"),
                nn.BatchNorm(prefix="bn1_"),
                nn.Activation("relu", prefix="a1_"),
                nn.GlobalAvgPool2D(prefix="p1_"),
                nn.Dense(4, prefix="d1_"))
    net.initialize()
    return net


def test_distributed_trainer_bf16_convergence():
    import jax

    from mxnet_tpu.parallel import DistributedTrainer, make_mesh

    rng = np.random.RandomState(0)
    xs = rng.uniform(-1, 1, (16, 3, 8, 8)).astype(np.float32)
    ys = (np.arange(16) % 4).astype(np.float32)
    # class-dependent channel shift → linearly separable through GAP features
    for i, c in enumerate(ys.astype(int)):
        xs[i, c % 3] += 2.0 * (1 + c // 3)
    x, y = mx.nd.array(xs), mx.nd.array(ys)
    net = _conv_net("ampconv_")
    net(x)

    mesh = make_mesh([("dp", 2)], devices=jax.devices()[:2])
    tr = DistributedTrainer(net, "sgd",
                            {"learning_rate": 0.2, "momentum": 0.9},
                            loss=gloss.SoftmaxCrossEntropyLoss(),
                            mesh=mesh, amp_dtype=BF16)
    losses = [float(tr.step(x, y).asnumpy()) for _ in range(12)]
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0] * 0.8, f"bf16 training did not learn: {losses}"
    # master weights + optimizer state stay fp32 (bf16 is compute-only)
    for arr in (tr._arrays[i] for i in tr._trainable):
        assert str(arr.dtype) == "float32"
    import jax as _jax
    for st in tr._states:
        for leaf in _jax.tree_util.tree_leaves(st):
            assert str(leaf.dtype) == "float32"


def test_distributed_trainer_bf16_matches_fp32_direction():
    """One bf16 step moves the loss the same direction as fp32 (sanity that
    the cast-inside-grad AMP wiring computes real gradients)."""
    import jax

    from mxnet_tpu.parallel import DistributedTrainer, make_mesh

    rng = np.random.RandomState(1)
    x = mx.nd.array(rng.uniform(-1, 1, (8, 10)).astype(np.float32))
    y = mx.nd.array((np.arange(8) % 3).astype(np.float32))

    results = {}
    for tag, amp in [("fp32", None), ("bf16", BF16)]:
        mx.random.seed(3)
        net = nn.HybridSequential(prefix=f"ampdir{tag}_")
        with net.name_scope():
            net.add(nn.Dense(16, activation="relu", prefix="d1_"),
                    nn.Dense(3, prefix="d2_"))
        net.initialize()
        net(x)
        mesh = make_mesh([("dp", 1)], devices=jax.devices()[:1])
        tr = DistributedTrainer(net, "sgd", {"learning_rate": 0.5},
                                loss=gloss.SoftmaxCrossEntropyLoss(),
                                mesh=mesh, amp_dtype=amp)
        results[tag] = [float(tr.step(x, y).asnumpy()) for _ in range(6)]
    # both learn, and bf16 tracks fp32 loss within coarse tolerance
    for tag in results:
        assert results[tag][-1] < results[tag][0]
    assert abs(results["bf16"][-1] - results["fp32"][-1]) < 0.35, results


# ---------------------------------------------------------------------------
# gluon.Trainer path: bf16-cast net + multi_precision master weights
# ---------------------------------------------------------------------------

def test_gluon_trainer_bf16_multi_precision():
    rng = np.random.RandomState(2)
    x = _bf16(rng.uniform(-1, 1, (16, 10)).astype(np.float32))
    y = mx.nd.array((np.arange(16) % 3).astype(np.float32))

    net = nn.HybridSequential(prefix="gtbf16_")
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu", prefix="d1_"),
                nn.Dense(3, prefix="d2_"))
    net.initialize()
    net.cast(BF16)
    net(x)  # deferred init in bf16

    for p in net.collect_params().values():
        assert str(np.dtype(p.dtype)) == BF16

    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.5, "momentum": 0.9,
                             "multi_precision": True})
    lfn = gloss.SoftmaxCrossEntropyLoss()
    losses = []
    for _ in range(12):
        with autograd.record():
            out = net(x)
            l = lfn(out.astype("float32"), y)
        l.backward()
        trainer.step(16)
        losses.append(float(l.mean().asnumpy()))
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0] * 0.9, f"bf16 gluon training stuck: {losses}"
    # weights remain bf16; the updater holds fp32 master copies
    for p in net.collect_params().values():
        assert str(np.dtype(p.data().dtype)) == BF16
    states = trainer._updaters[0].states if hasattr(trainer, "_updaters") \
        else {}
    saw_master = False
    for st in states.values():
        if isinstance(st, tuple) and len(st) == 2:
            _, w32 = st
            if hasattr(w32, "dtype"):
                assert str(np.dtype(w32.dtype)) == "float32"
                saw_master = True
    assert saw_master, "multi_precision updater kept no fp32 master weights"
