"""Deployment-path tests: HybridBlock.export -> symbol json + params ->
SymbolBlock.imports and Predictor (c_predict_api parity), plus the
im2rec/rec2idx tools (reference strategy: model_backwards_compatibility +
predict API smoke)."""
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.predict import Predictor
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import nn


def _make_net():
    net = gluon.nn.HybridSequential(prefix="exp_")
    with net.name_scope():
        net.add(gluon.nn.Dense(16, activation="relu", prefix="d1_"))
        net.add(gluon.nn.Dense(4, prefix="d2_"))
    net.initialize(ctx=mx.cpu())
    return net


def test_export_symbolblock_roundtrip(tmp_path):
    net = _make_net()
    x = mx.nd.array(np.random.uniform(-1, 1, (2, 8)).astype(np.float32))
    ref = net(x).asnumpy()
    prefix = str(tmp_path / "model")
    net.export(prefix)
    assert os.path.exists(prefix + "-symbol.json")
    assert os.path.exists(prefix + "-0000.params")

    sb = gluon.SymbolBlock.imports(prefix + "-symbol.json", ["data"],
                                   prefix + "-0000.params", ctx=mx.cpu())
    out = sb(x).asnumpy()
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


def test_predictor(tmp_path):
    net = _make_net()
    x = np.random.uniform(-1, 1, (2, 8)).astype(np.float32)
    ref = net(mx.nd.array(x)).asnumpy()
    prefix = str(tmp_path / "model")
    net.export(prefix)

    pred = Predictor(prefix + "-symbol.json", prefix + "-0000.params",
                     input_shapes={"data": (2, 8)})
    pred.forward(data=x)
    out = pred.get_output(0).asnumpy()
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)
    assert pred.get_output_shape(0) == (2, 4)

    # reshape rebinds for a new batch geometry
    x2 = np.random.uniform(-1, 1, (5, 8)).astype(np.float32)
    pred.reshape({"data": (5, 8)})
    pred.forward(data=x2)
    assert pred.get_output(0).shape == (5, 4)


def test_predictor_partial_out(tmp_path):
    net = _make_net()
    net(mx.nd.zeros((2, 8)))  # materialize params (export requires it,
    #                           like the reference's hybridize-then-export)
    prefix = str(tmp_path / "model")
    net.export(prefix)
    sym = mx.sym.load(prefix + "-symbol.json")
    internal = sym.get_internals().list_outputs()
    # op outputs carry the _output suffix; vars (weights) don't
    relu_outs = [n for n in internal
                 if n.endswith("_output") and "activation" in n]
    assert relu_outs, internal
    pred = Predictor(prefix + "-symbol.json", prefix + "-0000.params",
                     input_shapes={"data": (2, 8)},
                     output_names=[relu_outs[-1]])
    pred.forward(data=np.zeros((2, 8), np.float32))
    assert pred.get_output(0).shape[0] == 2


def _write_png(path, arr):
    from PIL import Image

    Image.fromarray(arr).save(path)


def test_im2rec_and_rec2idx_tools(tmp_path):
    np.random.seed(0)
    root = tmp_path / "imgs"
    for cls in ("cat", "dog"):
        (root / cls).mkdir(parents=True)
        for i in range(3):
            _write_png(str(root / cls / ("%d.png" % i)),
                       (np.random.rand(12, 12, 3) * 255).astype(np.uint8))
    prefix = str(tmp_path / "ds")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PYTHONPATH=repo, JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, os.path.join(repo, "tools/im2rec.py"),
                        prefix, str(root), "--encoding", "png"], env=env,
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    assert os.path.exists(prefix + ".rec") and os.path.exists(prefix + ".idx")

    # records decode back through the reader
    from mxnet_tpu import recordio

    reader = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "r")
    assert len(reader.keys) == 6
    header, img = recordio.unpack_img(reader.read_idx(reader.keys[0]))
    assert img.shape[2] == 3
    reader.close()

    # rec2idx reproduces the idx file
    r2 = subprocess.run([sys.executable, os.path.join(repo, "tools/rec2idx.py"),
                         prefix + ".rec", prefix + ".idx2"], env=env,
                        capture_output=True, text=True, timeout=120)
    assert r2.returncode == 0, r2.stderr
    with open(prefix + ".idx2") as f:
        assert len(f.readlines()) == 6


def test_im2rec_native_multithreaded_pack(tmp_path):
    """The C++ fast path (--num-thread > 1, reference tools/im2rec.cc)
    produces byte-identical .rec/.idx to the Python packer and reads back
    through MXIndexedRecordIO."""
    from mxnet_tpu.lib import native

    if native.get() is None:
        pytest.skip("native library unavailable")
    np.random.seed(1)
    root = tmp_path / "imgs"
    for cls in ("a", "b"):
        (root / cls).mkdir(parents=True)
        for i in range(4):
            _write_png(str(root / cls / ("%d.png" % i)),
                       (np.random.rand(10, 10, 3) * 255).astype(np.uint8))
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PYTHONPATH=repo, JAX_PLATFORMS="cpu")
    ppy = str(tmp_path / "py")
    pcc = str(tmp_path / "cc")
    for prefix, extra in ((ppy, []), (pcc, ["--num-thread", "4"])):
        r = subprocess.run(
            [sys.executable, os.path.join(repo, "tools/im2rec.py"),
             prefix, str(root)] + extra,
            env=env, capture_output=True, text=True, timeout=120)
        assert r.returncode == 0, r.stderr
    # same listing (seeded shuffle) -> byte-identical pack
    with open(ppy + ".rec", "rb") as f1, open(pcc + ".rec", "rb") as f2:
        assert f1.read() == f2.read()
    with open(ppy + ".idx") as f1, open(pcc + ".idx") as f2:
        assert f1.read() == f2.read()

    from mxnet_tpu import recordio

    reader = recordio.MXIndexedRecordIO(pcc + ".idx", pcc + ".rec", "r")
    assert len(reader.keys) == 8
    header, img = recordio.unpack_img(reader.read_idx(reader.keys[3]))
    assert img.shape == (10, 10, 3)
    reader.close()


def test_aot_compiled_predictor_roundtrip(tmp_path):
    """TensorRT-analogue AOT artifact (jax.export StableHLO, params frozen
    in): export_compiled -> CompiledPredictor.load -> forward matches the
    live Predictor; geometry is frozen like a TRT engine."""
    from mxnet_tpu.predict import CompiledPredictor, Predictor

    net = nn.HybridSequential(prefix="aot_")
    with net.name_scope():
        net.add(nn.Dense(8, activation="relu"), nn.Dense(3))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    x = mx.nd.array(np.random.RandomState(0)
                    .uniform(-1, 1, (4, 6)).astype(np.float32))
    net(x)
    prefix = str(tmp_path / "aot")
    net.export(prefix, epoch=0)

    pred = Predictor(prefix + "-symbol.json", prefix + "-0000.params",
                     input_shapes={"data": (4, 6)})
    ref = pred.forward(data=x).get_output(0).asnumpy()

    path = str(tmp_path / "model.mxaot")
    blob = pred.export_compiled(path)
    assert blob.startswith(b"MXTPUAOT1")

    comp = CompiledPredictor.load(path)
    assert "cpu" in comp.platforms and "tpu" in comp.platforms
    assert comp.get_output_shape(0) == (4, 3)
    got = comp.forward(data=x).get_output(0).asnumpy()
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)

    # frozen geometry: wrong shape must raise (TRT-engine semantics)
    with pytest.raises(MXNetError, match="frozen"):
        comp.set_input("data", np.zeros((2, 6), np.float32))

    # artifact is self-contained: raw jax.export can run it too
    import jax.export as je

    hlen = int.from_bytes(blob[10:18], "little")
    raw = je.deserialize(bytearray(blob[18 + hlen:]))
    np.testing.assert_allclose(np.asarray(raw.call(x.asnumpy())[0]), ref,
                               rtol=1e-5, atol=1e-6)


def test_multithread_clone_shares_weight_buffers(tmp_path):
    """ADVICE r2: per-thread predictors share the prototype's device weight
    buffers (no N-fold weight memory); only input buffers are private."""
    from mxnet_tpu.predict import _capi_clone_shared

    net = _make_net()
    net.hybridize()
    x = mx.nd.array(np.random.uniform(-1, 1, (2, 8)).astype(np.float32))
    net(x)
    prefix = str(tmp_path / "mt")
    net.export(prefix, epoch=0)
    proto = Predictor(prefix + "-symbol.json", prefix + "-0000.params",
                      input_shapes={"data": (2, 8)})
    clone = _capi_clone_shared(proto)
    for name, buf in proto._args.items():
        if name == "data":
            assert clone._args[name] is not buf
        else:
            assert clone._args[name] is buf
    ref = proto.forward(data=x).get_output(0).asnumpy()
    got = clone.forward(data=x).get_output(0).asnumpy()
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_compiled_predictor_load_accepts_pathlike(tmp_path):
    """Regression: `CompiledPredictor.load` with a pathlib.Path used to fall
    through to the bad-magic branch (only `str` hit the open() path)."""
    from pathlib import Path

    from mxnet_tpu.predict import CompiledPredictor, Predictor

    net = _make_net()
    net.hybridize()
    x = mx.nd.array(np.random.uniform(-1, 1, (2, 8)).astype(np.float32))
    net(x)
    prefix = str(tmp_path / "plike")
    net.export(prefix, epoch=0)
    pred = Predictor(prefix + "-symbol.json", prefix + "-0000.params",
                     input_shapes={"data": (2, 8)})
    ref = pred.forward(data=x).get_output(0).asnumpy()
    artifact = tmp_path / "plike.mxc"  # a Path, never str()'d
    pred.export_compiled(str(artifact))

    comp = CompiledPredictor.load(artifact)
    assert isinstance(artifact, Path)
    got = comp.forward(data=x).get_output(0).asnumpy()
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
    # real bytes (not a path) still load; garbage still raises bad-magic
    comp2 = CompiledPredictor.load(artifact.read_bytes())
    assert comp2.get_output_shape(0) == comp.get_output_shape(0)
    with pytest.raises(MXNetError, match="bad magic"):
        CompiledPredictor.load(b"not an artifact")


def test_predictor_clones_concurrent_no_buffer_bleed(tmp_path):
    """N client threads driving per-thread Predictor clones (the shared-
    weights/private-IO mechanism, predict._capi_clone_shared): concurrent
    forwards must never bleed inputs/outputs across threads."""
    import threading

    from mxnet_tpu.predict import _capi_clone_shared

    net = _make_net()
    net.hybridize()
    warm = mx.nd.array(np.zeros((2, 8), np.float32))
    net(warm)
    prefix = str(tmp_path / "mtc")
    net.export(prefix, epoch=0)
    proto = Predictor(prefix + "-symbol.json", prefix + "-0000.params",
                      input_shapes={"data": (2, 8)})
    proto.forward(data=warm)  # compile the signature once, before the race

    n_threads, iters = 4, 20
    rng = np.random.RandomState(7)
    inputs = [rng.uniform(-1, 1, (2, 8)).astype(np.float32)
              for _ in range(n_threads)]
    expected = [proto.forward(data=x).get_output(0).asnumpy().copy()
                for x in inputs]
    clones = [_capi_clone_shared(proto) for _ in range(n_threads)]

    errors = []
    barrier = threading.Barrier(n_threads)

    def worker(tid):
        try:
            clone, x, want = clones[tid], inputs[tid], expected[tid]
            barrier.wait(timeout=30)
            for it in range(iters):
                got = clone.forward(data=x).get_output(0).asnumpy()
                np.testing.assert_allclose(
                    got, want, rtol=1e-5, atol=1e-6,
                    err_msg="thread %d iter %d: cross-request bleed"
                            % (tid, it))
        except Exception as e:  # pragma: no cover - failure reporting
            errors.append((tid, e))

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors[0]


def test_export_compiled_preserves_input_dtype(tmp_path):
    """ADVICE r2: AOT export traces inputs at their live dtype (int32
    token ids for embedding models), not a blanket float32."""
    from mxnet_tpu.predict import CompiledPredictor, Predictor

    net = nn.HybridSequential(prefix="emb_")
    with net.name_scope():
        net.add(nn.Embedding(11, 6), nn.Dense(3, flatten=True))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    tok = mx.nd.array(np.array([[1, 4, 9], [0, 2, 7]], np.int32),
                      dtype=np.int32)
    net(tok)
    prefix = str(tmp_path / "emb")
    net.export(prefix, epoch=0)

    pred = Predictor(prefix + "-symbol.json", prefix + "-0000.params",
                     input_shapes={"data": (2, 3)},
                     input_dtypes={"data": np.int32})
    ref = pred.forward(data=tok.asnumpy()).get_output(0).asnumpy()

    path = str(tmp_path / "emb.mxaot")
    pred.export_compiled(path)
    comp = CompiledPredictor.load(path)
    assert comp._input_dtypes["data"] == np.dtype(np.int32)
    got = comp.forward(data=tok.asnumpy()).get_output(0).asnumpy()
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
