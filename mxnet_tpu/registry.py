"""Generic object registry factories (reference: python/mxnet/registry.py
— get_register_func / get_alias_func / get_create_func, the machinery
behind `@mx.optimizer.register`, metric lookup, initializer strings).

The per-subsystem registries here are `base._Registry` instances; this
module provides the reference's functional surface over the same storage,
so third-party code written against `mx.registry` works unchanged —
including string-spec creation ("adam", ("adam", {"learning_rate": 1e-3}),
or a JSON '["adam", {...}]' spec, matching the reference's create())."""
from __future__ import annotations

import json

from .base import MXNetError, _Registry

__all__ = ["get_registry", "get_register_func", "get_alias_func",
           "get_create_func", "register", "alias", "create"]

_REGISTRY = {}  # base_class -> _Registry


def get_registry(base_class):
    """The (class-keyed) registry dict for `base_class` (reference:
    registry.py:32 — returns a copy of the name->class map)."""
    reg = _reg_for(base_class, base_class.__name__.lower(),
                   create_if_missing=False)
    return dict(reg._map) if reg is not None else {}


def _reg_for(base_class, nickname, create_if_missing=True):
    from .base import _ALL_REGISTRIES

    reg = _REGISTRY.get(base_class)
    if reg is None:
        # resolve onto an existing subsystem registry by nickname (the
        # reference keys by base class; our subsystem registries are
        # kind-named _Registry instances — optimizer/metric/initializer).
        # ONLY framework base classes may claim a subsystem registry:
        # a third-party class that happens to share a nickname gets its
        # own isolated store (under a non-colliding kind, so it can't
        # claim a subsystem slot in _ALL_REGISTRIES either)
        if (base_class.__module__ or "").startswith("mxnet_tpu"):
            cls_lower = base_class.__name__.lower()
            for cand in (nickname, cls_lower):
                reg = _ALL_REGISTRIES.get(cand)
                if reg is not None:
                    break
            else:
                # suffix match: EvalMetric -> 'metric' (the subsystem
                # kinds are the trailing word of the base-class name)
                for kind, r in _ALL_REGISTRIES.items():
                    if cls_lower.endswith(kind):
                        reg = r
                        break
        else:
            reg = None
        if reg is None:
            if not create_if_missing:
                return None
            reg = _Registry("%s(%s)" % (nickname, base_class.__name__))
        _REGISTRY[base_class] = reg
    return reg


def get_register_func(base_class, nickname):
    """reference: registry.py:49."""
    reg = _reg_for(base_class, nickname)

    def register(klass, name=None):
        if not issubclass(klass, base_class):
            raise MXNetError("can only register subclass of %s"
                             % base_class.__name__)
        reg.register(klass, name)
        return klass

    register.__doc__ = "Register %s to the %s factory" % (
        base_class.__name__, nickname)
    return register


def get_alias_func(base_class, nickname):
    """reference: registry.py:88."""
    register_fn = get_register_func(base_class, nickname)

    def alias(*aliases):
        def reg(klass):
            for name in aliases:
                register_fn(klass, name)
            return klass

        return reg

    return alias


def get_create_func(base_class, nickname):
    """reference: registry.py:115 — create from a name, a (name, kwargs)
    pair, a JSON spec string, or pass through an existing instance."""
    reg = _reg_for(base_class, nickname)

    def create(*args, **kwargs):
        if args and isinstance(args[0], base_class):
            if len(args) > 1 or kwargs:
                raise MXNetError(
                    "%s is already an instance; additional arguments are "
                    "invalid" % nickname)
            return args[0]
        if args and isinstance(args[0], (list, tuple)):
            spec = args[0]
            return create(spec[0], **(spec[1] if len(spec) > 1 else {}))
        if not args and nickname in kwargs:
            # reference form: create(optimizer='adam', learning_rate=0.1)
            name = kwargs.pop(nickname)
            return create(name, **kwargs)
        if not args or not isinstance(args[0], str):
            raise MXNetError("%s.create needs a name string, (name, kwargs) "
                             "pair, or an instance" % nickname)
        name = args[0]
        if name.startswith("[") or name.startswith("{"):
            spec = json.loads(name)
            if isinstance(spec, dict):
                return create(spec["name"], **spec.get("params", {}))
            return create(spec[0], **(spec[1] if len(spec) > 1 else {}))
        return reg.create(name, *args[1:], **kwargs)

    create.__doc__ = "Create a %s instance from config" % nickname
    return create


# convenience single-registry aliases matching common reference usage
def register(base_class, nickname="object"):
    return get_register_func(base_class, nickname)


def alias(base_class, nickname="object"):
    return get_alias_func(base_class, nickname)


def create(base_class, nickname="object"):
    return get_create_func(base_class, nickname)
