"""Execution engine facade.

The reference's dependency engine (src/engine/threaded_engine.cc, SURVEY §2.1
N1) topologically orders ops by read/write variable dependencies and runs them
on per-device worker threads. On TPU the equivalent machinery is XLA/PJRT's
async dispatch: every jax op/executable launch is enqueued onto the device
stream and Python returns immediately; data dependencies are carried by the
arrays themselves, and transfers/computation overlap automatically. What
remains for us is the *control* surface the reference exposes:

- ``WaitForAll`` / per-array ``wait_to_read`` barriers,
- a sync "naive engine" debug mode (disable per-op jit, run op-by-op),
- bulking hints (`set_bulk_size`) — a no-op, XLA fuses within a jit scope.

Async exceptions: like threaded_engine.cc:418-503, device-side errors (e.g.
NaN-checking, OOM) surface at the next blocking read; jax raises them from
``block_until_ready``/``__array__`` which our NDArray sync points call.
"""
from __future__ import annotations

import contextlib
import threading

_local = threading.local()


def is_naive():
    """True when running in sync, per-op-uncompiled debug mode
    (reference env MXNET_ENGINE_TYPE=NaiveEngine, src/engine/engine.cc:33)."""
    import os

    if getattr(_local, "naive", None) is not None:
        return _local.naive
    return os.environ.get("MXNET_ENGINE_TYPE", "") == "NaiveEngine"


@contextlib.contextmanager
def naive_engine(enable=True):
    """Scoped sync/debug scheduler mode (SURVEY §5.2 item b)."""
    prev = getattr(_local, "naive", None)
    _local.naive = enable
    try:
        yield
    finally:
        _local.naive = prev


def wait_all():
    """Block until all pending device work is done
    (reference: Engine::WaitForAll include/mxnet/engine.h:234)."""
    import jax

    (jax.device_put(0) + 0).block_until_ready()
    try:
        jax.effects_barrier()
    except Exception:  # pragma: no cover - older jax
        pass


def set_bulk_size(size):
    """Reference: python/mxnet/engine.py:26 — engine op bulking. XLA fuses
    everything inside a jit scope, so this is an accepted no-op; returns the
    previous value for API parity."""
    prev = getattr(_local, "bulk", 15)
    _local.bulk = size
    return prev


@contextlib.contextmanager
def bulk(size):
    prev = set_bulk_size(size)
    try:
        yield
    finally:
        set_bulk_size(prev)
