"""Docstring-enhancement registry for generated NDArray functions
(reference: python/mxnet/ndarray_doc.py). Subclass `NDArrayDoc` with a
class named `<op>Doc` whose docstring is appended to the generated op's
help(); `_build_doc` assembles the reference's docstring layout from the
registry metadata (here: the signature-derived arg lists the C ABI's
MXSymbolGetAtomicSymbolInfo reports)."""
from __future__ import annotations

__all__ = ["NDArrayDoc", "_build_doc"]


class NDArrayDoc:
    """Base class: subclasses named `<op>Doc` contribute extra doc."""


def _build_param_doc(arg_names, arg_types, arg_descs):
    lines = ["Parameters", "----------"]
    for n, t, d in zip(arg_names, arg_types, arg_descs):
        lines.append("%s : %s" % (n, t or "NDArray"))
        if d:
            lines.append("    %s" % d)
    return "\n".join(lines) + "\n"


def _build_doc(func_name, desc, arg_names, arg_types, arg_desc,
               key_var_num_args=None, ret_type=None):
    """reference: ndarray_doc.py:132 — assemble the standard doc layout
    plus any registered `<op>Doc` extension."""
    doc = "%s\n\n%s\nout : NDArray, optional\n" \
          "    The output NDArray to hold the result.\n\n" \
          "Returns\n-------\n" \
          "out : NDArray or list of NDArrays\n" \
          "    The output of this function.\n" \
          % (desc, _build_param_doc(arg_names, arg_types, arg_desc))
    extras = [cls.__doc__ for cls in type.__subclasses__(NDArrayDoc)
              if cls.__name__ == "%sDoc" % func_name and cls.__doc__]
    if extras:
        doc += "\n" + "\n".join(extras)
    return doc
