"""Executor — a bound, compiled symbolic graph.

Reference: include/mxnet/executor.h + src/executor/graph_executor.cc
(Bind :1726 / SimpleBind :1694, Forward :65, Backward :78) and the Python
wrapper python/mxnet/executor.py.

TPU-native design: binding does NOT run a pass pipeline — `forward` jits the
whole-graph interpreter (one XLA executable per (shape, is_train) signature;
XLA performs memory planning/fusion/placement, SURVEY §3.5), and `backward`
jits the jax.vjp of the same interpreted graph (recomputing the forward
inside the backward executable — XLA's rematerialization model — instead of
the reference's retained fwd+bwd graph). Aux states (BatchNorm moving
stats) come back as extra functional outputs and are written into
`aux_arrays` after the call, mirroring the reference's in-place mutation.

An optional `mesh` shards the leading (batch) dim of data arguments over
the mesh's data axes — the Module multi-context path (the reference's
DataParallelExecutorGroup batch slicing, executor_group.py:281) expressed
as GSPMD sharding.
"""
from __future__ import annotations

import numpy as _np

from .base import MXNetError
from .context import current_context
from .ndarray import NDArray
from . import ndarray as nd

__all__ = ["Executor"]


class Executor:
    def __init__(self, symbol, ctx, args, args_grad=None, grad_req="write",
                 aux_states=None, mesh=None, data_arg_names=None):
        self._symbol = symbol
        self._ctx = ctx if not isinstance(ctx, (list, tuple)) else ctx[0]
        self._mesh = mesh
        self._arg_names = symbol.list_arguments()
        self._aux_names = symbol.list_auxiliary_states()
        self._output_names = symbol.list_outputs()
        self._data_arg_names = set(data_arg_names or ())

        self.arg_arrays = self._as_list(args, self._arg_names, "args")
        self.aux_arrays = self._as_list(aux_states or [], self._aux_names,
                                        "aux_states", allow_missing=True)
        self.grad_req = self._req_dict(grad_req)
        self.grad_arrays = self._grad_list(args_grad)

        self.outputs = []
        self._monitor_callback = None
        self._graph_meta_cache = None  # (content fingerprint, no_persist)
        self._last = None

    # -- construction helpers ---------------------------------------------
    def _as_list(self, arrays, names, what, allow_missing=False):
        if isinstance(arrays, dict):
            missing = [n for n in names if n not in arrays]
            if missing and not allow_missing:
                raise MXNetError("%s missing arrays for %s" % (what, missing))
            return [arrays.get(n) for n in names]
        arrays = list(arrays)
        if len(arrays) != len(names):
            if allow_missing and not arrays:
                return [None] * len(names)
            raise MXNetError("%s: expected %d arrays (%s), got %d"
                             % (what, len(names), names, len(arrays)))
        return arrays

    def _req_dict(self, grad_req):
        if isinstance(grad_req, str):
            return {n: grad_req for n in self._arg_names}
        if isinstance(grad_req, (list, tuple)):
            return dict(zip(self._arg_names, grad_req))
        out = {n: "null" for n in self._arg_names}
        out.update(grad_req or {})
        return out

    def _grad_list(self, args_grad):
        if args_grad is None:
            return [None] * len(self._arg_names)
        if isinstance(args_grad, dict):
            return [args_grad.get(n) for n in self._arg_names]
        grads = list(args_grad)
        if len(grads) != len(self._arg_names):
            raise MXNetError("args_grad length mismatch")
        return grads

    # -- dict views --------------------------------------------------------
    @property
    def arg_dict(self):
        return dict(zip(self._arg_names, self.arg_arrays))

    @property
    def grad_dict(self):
        return dict(zip(self._arg_names, self.grad_arrays))

    @property
    def aux_dict(self):
        return dict(zip(self._aux_names, self.aux_arrays))

    @property
    def output_dict(self):
        return dict(zip(self._output_names, self.outputs))

    # -- sharding ----------------------------------------------------------
    def _shardings(self):
        from jax.sharding import PartitionSpec

        from .parallel.sharding import batch_spec, named_sharding

        repl = named_sharding(self._mesh, PartitionSpec())
        arg_sh = []
        for n, a in zip(self._arg_names, self.arg_arrays):
            if n in self._data_arg_names and a is not None and a.ndim > 0:
                arg_sh.append(named_sharding(
                    self._mesh, batch_spec(self._mesh, a.ndim)))
            else:
                arg_sh.append(repl)
        return repl, arg_sh

    def _place_inputs(self):
        """device_put data args onto their mesh sharding (no-op when already
        placed, e.g. when the input pipeline produced sharded batches)."""
        import jax

        if self._mesh is None:
            return
        _, arg_sh = self._shardings()
        for i, (a, sh) in enumerate(zip(self.arg_arrays, arg_sh)):
            if a is not None:
                self.arg_arrays[i]._data = jax.device_put(a._data, sh)

    # -- the unified executable cache --------------------------------------
    def _graph_meta(self):
        """(content fingerprint, no_persist) of the bound graph — the
        stable half of the `mxnet_tpu.compile` key, so two Executors over
        the same exported graph (serving's per-bucket predictor clones, a
        restarted replica) share ONE executable per signature, in memory
        and across processes via the persistent tier. ``no_persist`` marks
        graphs staging host callbacks (Custom/host ops): their serialized
        executables would carry dangling process-local references."""
        if self._graph_meta_cache is None:
            import hashlib
            import json as _json

            from . import ops as _ops_mod

            js = self._symbol.tojson()
            fingerprint = hashlib.sha256(js.encode()).hexdigest()[:40]
            no_persist = False
            try:
                for node in _json.loads(js).get("nodes", []):
                    opname = node.get("op")
                    if opname in (None, "null"):
                        continue
                    opdef = _ops_mod._REGISTRY.get(opname)
                    if opname == "Custom" or (opdef is not None
                                              and opdef.host):
                        no_persist = True
                        break
            except Exception:  # unparseable graph json: cache in memory only
                no_persist = True
            self._graph_meta_cache = (fingerprint, no_persist)
        return self._graph_meta_cache

    def _mesh_desc(self):
        if self._mesh is None:
            return None
        return (tuple(str(a) for a in self._mesh.axis_names),
                tuple(int(d) for d in self._mesh.devices.shape))

    def _cache_key(self, kind, sig, static):
        from . import compile as _compile

        fingerprint, no_persist = self._graph_meta()
        aux_sig = tuple(tuple(a.shape) + (str(a.dtype),)
                        for a in self.aux_arrays)
        topology = None
        if self._mesh is not None:
            # the topology fingerprint lets the MESH-sharded executor
            # executables reach the persistent tier (registry._dir)
            from .parallel.mesh import mesh_fingerprint

            topology = mesh_fingerprint(self._mesh)
        return _compile.ExecutableKey(
            kind, fingerprint, shapes=(sig[0], aux_sig),
            static=static + (self._mesh_desc(),
                             tuple(sorted(self._data_arg_names))),
            sharded=self._mesh is not None, no_persist=no_persist,
            topology=topology)

    # -- execution ---------------------------------------------------------
    def forward(self, is_train=False, **kwargs):
        """reference: executor.py forward / GraphExecutor::Forward."""
        import jax

        from . import random as _random

        for name, val in kwargs.items():
            if name not in self._arg_names:
                raise MXNetError("unknown argument '%s'" % name)
            i = self._arg_names.index(name)
            if isinstance(val, NDArray):
                self.arg_arrays[i] = val
            else:
                self.arg_arrays[i] = nd.array(val, ctx=self._ctx)
        self._place_inputs()

        sig = (tuple(tuple(a.shape) + (str(a.dtype),) for a in self.arg_arrays),
               bool(is_train))
        key = _random.next_key()
        arg_arrays = tuple(a._data for a in self.arg_arrays)
        aux_arrays = tuple(a._data for a in self.aux_arrays)
        from . import compile as _compile
        from .telemetry import core as _tm_core

        fn = _compile.get_or_build(
            self._cache_key("executor_fwd", sig, (bool(is_train),)),
            lambda: self._build_forward(bool(is_train)),
            label="executor_forward",
            example_args=(key, arg_arrays, aux_arrays),
            on_fill=lambda: _tm_core.counter(
                "mxtpu_executor_build_total", {"what": "forward"}).inc(),
            event_fields={"is_train": bool(is_train)})
        from . import profiler as _profiler

        outs, new_aux = _profiler.timed_call(
            "ExecutorForward", fn, (key, arg_arrays, aux_arrays), cat="symbolic")
        for dst, src in zip(self.aux_arrays, new_aux):
            dst._set_data(src)
        self.outputs = [NDArray(o, ctx=self._ctx) for o in outs]
        self._last = (sig, key, arg_arrays, aux_arrays)
        if self._monitor_callback is not None:
            for name, o in zip(self._output_names, self.outputs):
                self._monitor_callback(name, o)
        return self.outputs

    def _build_forward(self, is_train):
        import jax

        arg_names, aux_names = self._arg_names, self._aux_names
        symbol = self._symbol

        def run(key, arg_arrays, aux_arrays):
            values = dict(zip(arg_names, arg_arrays))
            values.update(zip(aux_names, aux_arrays))
            outs, aux_up = symbol._interpret(values, is_train=is_train,
                                             rng_key=key)
            new_aux = tuple(aux_up.get(n, values[n]) for n in aux_names)
            return tuple(outs), new_aux

        # FLOP accounting + persistence happen at the registry fill hook
        # (mxnet_tpu.compile.registry), not here
        if self._mesh is None:
            return jax.jit(run)
        repl, arg_sh = self._shardings()
        return jax.jit(run, in_shardings=(repl, tuple(arg_sh),
                                          tuple(repl for _ in aux_names)))

    def backward(self, out_grads=None, is_train=True):
        """Gradients via jax.vjp of the graph (reference:
        GraphExecutor::Backward graph_executor.cc:78; loss-head ops carry
        their own cotangent-independent custom_vjp, so no out_grads means
        ones — identical to the reference's head-gradient convention)."""
        import jax
        import jax.numpy as jnp

        if self._last is None:
            raise MXNetError("backward called before forward")
        sig, key, arg_arrays, aux_arrays = self._last
        wrt = [i for i, n in enumerate(self._arg_names)
               if self.grad_req.get(n, "null") != "null"]
        if not wrt:
            return
        if out_grads is None:
            cots = tuple(jnp.ones(tuple(o.shape), o.dtype) for o in self.outputs)
        else:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            cots = tuple(g._data if isinstance(g, NDArray) else jnp.asarray(g)
                         for g in out_grads)
        from . import compile as _compile
        from .telemetry import core as _tm_core

        fn = _compile.get_or_build(
            self._cache_key("executor_bwd", sig,
                            (bool(sig[1]), tuple(wrt))),
            lambda: self._build_backward(sig[1], wrt),
            label="executor_backward",
            example_args=(key, arg_arrays, aux_arrays, cots),
            on_fill=lambda: _tm_core.counter(
                "mxtpu_executor_build_total", {"what": "backward"}).inc())
        from . import profiler as _profiler

        grads = _profiler.timed_call(
            "ExecutorBackward", fn, (key, arg_arrays, aux_arrays, cots),
            cat="symbolic")
        for k, i in enumerate(wrt):
            name = self._arg_names[i]
            req = self.grad_req.get(name, "null")
            dst = self.grad_arrays[i]
            if dst is None:
                dst = NDArray(grads[k], ctx=self._ctx)
                self.grad_arrays[i] = dst
            elif req == "add":
                dst._set_data(dst._data + grads[k])
            else:
                dst._set_data(grads[k])

    def _build_backward(self, is_train, wrt):
        import jax

        arg_names, aux_names = self._arg_names, self._aux_names
        symbol = self._symbol

        def bwd(key, arg_arrays, aux_arrays, cots):
            def pure(wrt_arrays):
                full = list(arg_arrays)
                for k, i in enumerate(wrt):
                    full[i] = wrt_arrays[k]
                values = dict(zip(arg_names, full))
                values.update(zip(aux_names, aux_arrays))
                outs, _ = symbol._interpret(values, is_train=is_train,
                                            rng_key=key)
                return tuple(outs)

            _, pull = jax.vjp(pure, tuple(arg_arrays[i] for i in wrt))
            return pull(tuple(cots))[0]

        return jax.jit(bwd)

    # -- misc API parity ---------------------------------------------------
    def set_monitor_callback(self, callback, monitor_all=False):
        self._monitor_callback = callback

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        """reference: executor.py copy_params_from."""
        for name, arr in (arg_params or {}).items():
            if name in self._arg_names:
                self.arg_arrays[self._arg_names.index(name)]._set_data(
                    arr._data if isinstance(arr, NDArray)
                    else nd.array(arr, ctx=self._ctx)._data)
            elif not allow_extra_params:
                raise MXNetError("unknown parameter '%s'" % name)
        for name, arr in (aux_params or {}).items():
            if name in self._aux_names:
                self.aux_arrays[self._aux_names.index(name)]._set_data(
                    arr._data if isinstance(arr, NDArray)
                    else nd.array(arr, ctx=self._ctx)._data)
            elif not allow_extra_params:
                raise MXNetError("unknown aux state '%s'" % name)

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """Re-bind with new shapes (executable cache handles the rest —
        the reference rebuilt memory plans; XLA just compiles per shape)."""
        new_args = {}
        for n, a in zip(self._arg_names, self.arg_arrays):
            if n in kwargs:
                new_args[n] = nd.zeros(kwargs[n], ctx=self._ctx)
            else:
                new_args[n] = a
        ex = Executor(self._symbol, self._ctx, new_args,
                      {n: g for n, g in zip(self._arg_names, self.grad_arrays)
                       if g is not None} or None,
                      dict(self.grad_req),
                      list(self.aux_arrays), mesh=self._mesh,
                      data_arg_names=self._data_arg_names)
        return ex

    @property
    def symbol(self):
        return self._symbol

    def debug_str(self):
        return self._symbol.debug_str()
