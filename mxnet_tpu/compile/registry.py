"""The unified executable registry: one cache every jit factory resolves
through.

Two tiers:

  * **memory** — an LRU table of live executables, capacity
    ``MXTPU_COMPILE_CACHE_ENTRIES``. A hit is a dict lookup; eviction
    drops the oldest-touched entry (its per-shape XLA executables go with
    it).
  * **persistent** (opt-in via ``MXTPU_COMPILE_CACHE``, persist.py) —
    serialized compiled executables on disk. A memory miss checks the
    disk tier before compiling: a hit deserializes the executable and
    NEVER traces or compiles (no ``jit_compile`` event), which is what
    lets a restarted serving replica or elastic-restart generation reach
    ready with zero recompiles.

Fill telemetry (the single hook that replaced per-site wrappers):

  * ``mxtpu_jit_cache_lookup_total`` — one per registry lookup;
  * ``mxtpu_compile_cache_hit_total`` — memory hits;
  * ``mxtpu_jit_cache_miss_total`` + a ``jit_compile`` flight-recorder
    event + a ``compile.fill`` span — true fills (trace + compile);
  * ``mxtpu_compile_cache_persist_hit_total`` / ``_store_total`` /
    ``_bad_total`` — disk-tier traffic (bad = present but corrupt/stale);
  * ``mxtpu_compile_cache_evict_total`` + ``mxtpu_compile_cache_entries``
    — capacity behavior.

FLOP accounting also rides the fill hook: concrete fills capture
`Lowered.cost_analysis()` once at compile (or read it back from the
artifact header), lazy fills wrap the jitted callable in the per-shape
memo (`telemetry.flops.instrument`) exactly as the call sites used to.
"""
from __future__ import annotations

import collections
import itertools
import os
import threading

from .. import env as _env
from ..telemetry import core as _tm_core
from ..telemetry import flops as _tm_flops
from ..telemetry import memory as _tm_memory
from ..telemetry import recorder as _tm_rec
from ..telemetry import tracing as _tracing
from . import persist as _persist

__all__ = ["Registry", "registry", "get_or_build", "lookup", "invalidate_tag",
           "reset", "stats", "mark", "keys_since", "prefetch_paths",
           "clear_staged", "instance_token", "begin_touch_log",
           "end_touch_log"]


# lazily-resolved counters: a process that starts MXTPU_TELEMETRY=0 and
# enables telemetry later must record real counts (never cache the null
# metric) — the ops-dispatch pattern, now in one place
_TM = {}


def _counter(name):
    c = _TM.get(name)
    if c is None:
        if not _tm_core._STATE.enabled:
            return _tm_core._NULL
        c = _tm_core.counter(name)
        _TM[name] = c
    return c


def _entries_gauge():
    return _counter_gauge("mxtpu_compile_cache_entries")


def _counter_gauge(name):
    g = _TM.get(name)
    if g is None:
        if not _tm_core._STATE.enabled:
            return _tm_core._NULL
        g = _tm_core.gauge(name)
        _TM[name] = g
    return g


class _FixedFlops:
    """AOT-compiled executable wrapper: every execution accumulates the
    compile-time cost-analysis FLOPs (no per-call lowering). Carries a
    one-shot ``rebuild`` escape hatch: if the compiled executable rejects
    a call (a deserialized artifact this process can't drive — device
    placement/layout skew the key can't see), the wrapper recompiles
    through the plain jit path, COUNTS the fill honestly (miss +
    ``jit_compile`` event), swaps itself over, and retries — a stale
    artifact costs one recompile, it never bricks the entry."""

    __slots__ = ("_fn", "_flops", "_rebuild")
    _mxtpu_aot = True

    def __init__(self, fn, flops, rebuild=None):
        self._fn = fn
        self._flops = flops
        self._rebuild = rebuild

    def __call__(self, *args):
        if self._rebuild is None:
            if self._flops:
                _tm_flops.accumulate(self._flops)
            return self._fn(*args)
        try:
            if self._flops:
                _tm_flops.accumulate(self._flops)
            return self._fn(*args)
        except Exception:
            # executables are pure: a retry through a fresh compile is
            # safe, and a real input error will re-raise from it
            self._fn = self._rebuild()
            self._flops = None  # the instrumented fallback prices itself
            self._rebuild = None
            return self._fn(*args)


class _LazyPerShape:
    """Per-shape wrapper stored under a LAZY key when the persistent tier
    is armed: each NEW shape signature resolves through the concrete-fill
    path (disk hit or AOT compile + store), so eager-op and autograd
    executables persist per shape. When an AOT-loaded executable rejects
    a call (device/weak-type skew the shape signature can't see), the
    signature falls back to the plain jitted callable permanently."""

    __slots__ = ("_registry", "_key", "_jitted", "_label", "_by_sig",
                 "_fallback")

    def __init__(self, registry, key, jitted, label):
        self._registry = registry
        self._key = key
        self._jitted = jitted
        self._label = label
        self._by_sig = {}
        self._fallback = None

    def _fallback_fn(self):
        """The plain jitted path for a signature the AOT route can't
        serve. Counted as a true fill — the jax.jit beneath really will
        trace+compile this signature, and the zero-compile acceptance
        signals must not be blind to the degraded path."""
        self._registry._count_fill(self._label, None, None)
        if self._fallback is None:
            self._fallback = _tm_flops.instrument(self._jitted)
        return self._fallback

    def __call__(self, *args):
        sig = _tm_flops._shape_sig(args)
        fn = self._by_sig.get(sig)
        if fn is None:
            try:
                fn = self._registry._fill_concrete(
                    self._key.with_shapes(sig), lambda: self._jitted, args,
                    self._label, None, None)
            except Exception:
                fn = self._fallback_fn()
            self._by_sig[sig] = fn
        try:
            return fn(*args)
        except Exception:
            if getattr(fn, "_mxtpu_aot", False):
                # a deserialized executable this process can't drive:
                # recompile through the normal jit path and remember that
                fn = self._fallback_fn()
                self._by_sig[sig] = fn
                return fn(*args)
            raise


class Registry:
    """LRU executable table + persistent-tier front end (one process-wide
    instance via `registry()`; tests build private ones)."""

    def __init__(self, capacity=None, persist_dir=None):
        self._lock = threading.Lock()   # guards insert/evict/invalidate;
        #                                 the HIT path is lock-free (below)
        self._table = {}     # ExecutableKey -> value (plain dict: GIL-
        #                      atomic get keeps per-op dispatch lock-free)
        self._stamps = {}    # ExecutableKey -> recency stamp (LRU order)
        self._clock = itertools.count(1)
        self._capacity = capacity
        self._persist_dir = persist_dir  # None = resolve from env per miss
        self._staged = {}    # digest -> (callable, flops, memory_figures)
        #                      manifest prefetch staging
        # per-THREAD fill log: loads/warms bracket their own thread's
        # fills with mark()/keys_since(), so concurrent model loads (and
        # live traffic on batcher threads) never pollute each other's
        # warmup manifests
        self._fill_local = threading.local()
        # per-THREAD touch log (armed only between begin_touch_log/
        # end_touch_log): which keys a warm LOOKED UP, hit or miss — the
        # memory-attribution bracket needs this because a reload of an
        # already-resident model fills nothing (telemetry.memory)
        self._touch_local = threading.local()

    # -- config ------------------------------------------------------------
    def capacity(self):
        if self._capacity is not None:
            return self._capacity
        return max(1, _env.get("MXTPU_COMPILE_CACHE_ENTRIES"))

    def _dir(self, key):
        """Persistent-tier directory for this key, or None (tier off, or
        the key cannot persist: process-local fingerprints/callbacks, and
        sharded executables that carry NO topology fingerprint — without
        one, a serialized sharded step could resurrect onto a different
        mesh geometry; keys that declare their topology (the
        ShardedTrainer promoted path) persist like any other)."""
        if key.no_persist or (key.sharded and key.topology is None):
            return None
        if self._persist_dir is not None:
            return self._persist_dir or None
        return _persist.cache_dir()

    # -- core --------------------------------------------------------------
    def _fill_log(self):
        log = getattr(self._fill_local, "entries", None)
        if log is None:
            log = self._fill_local.entries = []
        return log

    def _log_fill(self, key, digest):
        self._fill_log().append((key, digest))

    def lookup(self, key):
        """Memory-tier probe (counts a lookup; None on miss). LOCK-FREE:
        dict get + a recency-stamp store, both GIL-atomic — eager-op
        dispatch from N serving/predictor threads never contends on a
        mutex (the eviction path under the lock tolerates the benign
        stamp races this allows)."""
        _counter("mxtpu_jit_cache_lookup_total").inc()
        touches = getattr(self._touch_local, "log", None)
        if touches is not None:  # armed only inside a warm bracket
            touches.append(key)
        value = self._table.get(key)
        if value is not None:
            # lock-free hit path by design: a torn/raced stamp only skews
            # LRU recency by one touch, never correctness
            self._stamps[key] = next(self._clock)  # mxlint: gil-atomic — LRU stamp
            _counter("mxtpu_compile_cache_hit_total").inc()
        return value

    def get_or_build(self, key, build, label=None, example_args=None,
                     on_fill=None, event_fields=None):
        """THE factory entry point. ``build()`` returns a jax.jit callable
        (never called on a hit). With ``example_args`` the key is filled
        as ONE concrete executable (AOT + persistent tier when armed);
        without, the entry is a per-shape callable (plain jitted wrapper,
        or the per-shape persist wrapper when armed). ``example_args``
        may be a zero-arg THUNK returning the tuple — evaluated only on
        a true fill, so hot call sites (the trainers' per-step
        resolution) pay nothing on a hit. ``on_fill`` runs only on a
        true fill (site-specific build counters); ``event_fields`` joins
        the ``jit_compile`` event."""
        value = self.lookup(key)
        if value is not None:
            return value
        # the whole miss path — persistent-tier loads and true fills alike —
        # is compile time the training step did not spend on the device;
        # the goodput accountant attributes it whether or not a step
        # bracket is open (warmup compiles land on the cumulative counter)
        from ..telemetry import goodput as _goodput
        import time as _time

        t0 = _time.perf_counter()
        try:
            label = label or key.fingerprint
            if callable(example_args):
                example_args = example_args()
            if key.concrete and example_args is not None:
                value = self._fill_concrete(key, build, example_args, label,
                                            on_fill, event_fields)
            else:
                value = self._fill_lazy(key, build, label, on_fill,
                                        event_fields)
            return self._insert(key, value)
        finally:
            _goodput.add("compile", _time.perf_counter() - t0)

    def _insert(self, key, value):
        with self._lock:
            existing = self._table.get(key)
            if existing is not None:   # racing fill: first one wins
                self._stamps[key] = next(self._clock)
                return existing
            self._table[key] = value
            self._stamps[key] = next(self._clock)
            cap = self.capacity()
            while len(self._table) > cap:
                old_key = min(self._table,
                              key=lambda k: self._stamps.get(k, 0))
                del self._table[old_key]
                self._stamps.pop(old_key, None)
                _counter("mxtpu_compile_cache_evict_total").inc()
                _tm_rec.record_event("compile_evict", key_kind=old_key.kind,
                                     fingerprint=old_key.fingerprint[:32])
            if len(self._stamps) > 2 * len(self._table):
                # prune stamps orphaned by lock-free hit races
                for k in list(self._stamps):
                    if k not in self._table:
                        del self._stamps[k]
            _entries_gauge().set(len(self._table))
        return value

    def _fill_lazy(self, key, build, label, on_fill, event_fields):
        """Fill a lazy (shapes-unknown) entry: the jitted callable keeps
        its internal per-shape cache; armed persistence upgrades it to the
        per-shape AOT wrapper. The jit_compile event fires here (one per
        signature family, matching the historical per-(op, attrs) event)
        unless the armed wrapper will emit per-shape events instead."""
        jitted = build()
        if self._dir(key) is not None:
            # per-shape wrapper: fills (and their events) happen per shape
            return _LazyPerShape(self, key, jitted, label)
        self._count_fill(label, on_fill, event_fields)
        return _tm_flops.instrument(jitted)

    def _fill_concrete(self, key, build, args, label, on_fill, event_fields):
        """Fill ONE executable for pinned shapes: disk hit (no compile) or
        AOT trace+compile (+ store when armed). Sharded/donating keys the
        persistent tier refuses (topology-less sharded steps) still take
        the AOT path when memory accounting is on, so their memory figures
        — and the donation verifier — come from the compile the fill pays
        anyway."""
        directory = self._dir(key)
        if directory is not None:
            loaded = self._load_persisted(directory, key, label, build)
            if loaded is not None:
                return loaded
        with _tracing.span("compile.fill",
                           attrs={"kind": key.kind, "label": label}):
            jitted = build()
            value = None
            if directory is not None:
                value = self._aot_store(directory, key, jitted, args, label)
            elif (key.sharded or key.donation) and _tm_memory.enabled():
                value = self._aot_capture(key, jitted, args, label)
            if value is None:
                value = _tm_flops.instrument(jitted)
        self._count_fill(label, on_fill, event_fields)
        return value

    def _count_fill(self, label, on_fill, event_fields):
        _counter("mxtpu_jit_cache_miss_total").inc()
        _tm_rec.record_event("jit_compile", op=label, **(event_fields or {}))
        if on_fill is not None:
            on_fill()

    def _rebuilder(self, build, label):
        """The execution-failure escape hatch handed to `_FixedFlops`:
        rebuild through plain jit, counting the fill honestly."""
        def rebuild():
            self._count_fill(label, None, None)
            return _tm_flops.instrument(build())

        return rebuild

    def _compile_aot(self, key, jitted, args, label):
        """Shared AOT front half: lower + compile, price FLOPs from the
        lowering and memory figures from the compile (recorded into the
        attribution table; donating keys run the donation verifier).
        Returns (compiled, flops, mem) or None when this executable
        can't take the AOT path."""
        try:
            lowered = jitted.lower(*args)
            flops = None
            if _tm_flops.enabled():
                try:
                    flops = _tm_flops.cost_analysis_flops(
                        lowered.cost_analysis())
                except Exception:
                    flops = None
            compiled = lowered.compile()
        except Exception:
            return None
        mem = _tm_memory.from_compiled(compiled)
        if key.donation:
            _tm_memory.verify_donation(key, args, mem)
        return compiled, flops, mem

    def _aot_capture(self, key, jitted, args, label):
        """Memory-tier-only AOT fill (sharded/donating keys): same
        compile the jit would pay on first call, but through `lower()`+
        `compile()` so `memory_analysis()` is attributable. The compiled
        executable is used directly (no second compile), with the
        standard rebuild escape hatch."""
        res = self._compile_aot(key, jitted, args, label)
        if res is None:
            return None
        compiled, flops, mem = res
        _tm_memory.record_executable(key.kind, label, None, mem, key=key)
        return _FixedFlops(compiled, flops,
                           rebuild=self._rebuilder(lambda: jitted, label))

    def _aot_store(self, directory, key, jitted, args, label):
        """Lower+compile ahead of time, capture cost-analysis FLOPs +
        memory figures, and serialize into the persistent tier (figures
        ride the artifact header, so a zero-compile cold start still
        knows its footprint). None when this executable can't take the
        AOT path (caller falls back to plain jit)."""
        res = self._compile_aot(key, jitted, args, label)
        if res is None:
            return None
        compiled, flops, mem = res
        digest = _persist.store(directory, key, compiled, label=label,
                                flops=flops, memory=mem)
        if digest is not None:
            _counter("mxtpu_compile_cache_persist_store_total").inc()
            self._log_fill(key, digest)
        _tm_memory.record_executable(key.kind, label, digest, mem, key=key)
        return _FixedFlops(compiled, flops,
                           rebuild=self._rebuilder(lambda: jitted, label))

    def _load_persisted(self, directory, key, label, build):
        """Disk/staged probe for a concrete key. A hit deserializes the
        executable — no trace, no compile, no ``jit_compile`` event."""
        import jax

        digest = key.digest(jax.default_backend(), jax.__version__)
        with self._lock:
            staged = self._staged.pop(digest, None)
        if staged is not None:
            fn, flops, mem = staged
        else:
            path = _persist.artifact_path(directory, digest)
            if not os.path.exists(path):
                return None
            fn, flops, mem = _persist.load_path(path)
            if fn is None:
                _counter("mxtpu_compile_cache_persist_bad_total").inc()
                _tm_rec.record_event("compile_persist_bad", op=label)
                return None
        _counter("mxtpu_compile_cache_persist_hit_total").inc()
        _tm_rec.record_event("compile_persist_hit", op=label)
        self._log_fill(key, digest)
        # the header figures keep attribution alive across a zero-compile
        # cold start (the memory_analysis ran in the process that stored)
        _tm_memory.record_executable(key.kind, label, digest, mem, key=key)
        return _FixedFlops(fn, flops, rebuild=self._rebuilder(build, label))

    # -- invalidation ------------------------------------------------------
    def invalidate_tag(self, tag):
        """Drop every memory entry whose key carries ``tag`` (custom-op
        re-registration). Returns how many entries were dropped."""
        with self._lock:
            doomed = [k for k in self._table if tag in k.tags]
            for k in doomed:
                del self._table[k]
                self._stamps.pop(k, None)
            _entries_gauge().set(len(self._table))
        return len(doomed)

    def reset(self):
        """Clear the memory tier + staging (tests, fork children). The
        persistent tier is untouched. (Fill logs are per-thread; this
        clears the calling thread's.)"""
        with self._lock:
            self._table.clear()
            self._stamps.clear()
            self._staged.clear()
            self._fill_local.entries = []
            self._touch_local.log = None
            _entries_gauge().set(0)

    # -- touch bracketing (memory attribution) -----------------------------
    def begin_touch_log(self):
        """Arm this thread's touch log: every registry lookup (hit or
        miss) records its key until `end_touch_log`. The serving warm
        brackets each bucket with it so memory attribution survives the
        all-hits reload path (docs/observability.md §Memory)."""
        self._touch_local.log = []

    def end_touch_log(self):
        """Disarm and return this thread's touched keys (in order)."""
        log = getattr(self._touch_local, "log", None)
        self._touch_local.log = None
        return log or []

    # -- warmup manifests --------------------------------------------------
    def mark(self):
        """Cursor into THIS THREAD's persistable-fill log (bracket a
        load+warm with mark()/keys_since() to learn a model's executable
        key-set; fills on other threads — a concurrent load, live
        traffic — never leak into the bracket)."""
        return len(self._fill_log())

    def keys_since(self, cursor):
        """This thread's (key, digest) pairs persisted/loaded since
        ``cursor``."""
        return list(self._fill_log()[cursor:])

    def clear_staged(self):
        """Drop staged prefetch entries the warm never claimed (stale
        manifest rows — shrunk geometry, changed dtypes): a long-lived
        worker must not pin deserialized executables forever. Returns
        how many were dropped; call after warm completes."""
        with self._lock:
            n = len(self._staged)
            self._staged.clear()
        return n

    def prefetch_paths(self, paths):
        """Deserialize artifact files into the staging table BEFORE the
        executables are requested (replica warmup-manifest prefetch).
        Returns how many loaded; unreadable entries are skipped."""
        n = 0
        for path in paths:
            header = _persist.read_header(path)
            if header is None or not header.get("digest"):
                _counter("mxtpu_compile_cache_persist_bad_total").inc()
                continue
            fn, flops, mem = _persist.load_path(path)
            if fn is None:
                _counter("mxtpu_compile_cache_persist_bad_total").inc()
                continue
            with self._lock:
                self._staged[header["digest"]] = (fn, flops, mem)
            n += 1
        return n

    # -- introspection -----------------------------------------------------
    def stats(self):
        with self._lock:
            return {
                "entries": len(self._table),
                "capacity": self.capacity(),
                "staged": len(self._staged),
                "kinds": collections.Counter(k.kind for k in self._table),
            }


_REGISTRY = None
_REGISTRY_LOCK = threading.Lock()


def registry():
    """The process-wide registry singleton."""
    global _REGISTRY
    if _REGISTRY is None:
        with _REGISTRY_LOCK:
            if _REGISTRY is None:
                _REGISTRY = Registry()
    return _REGISTRY


def _reset_after_fork():
    # forked children must not call into jax executables compiled by the
    # parent (the jax runtime is not fork-safe); drop every live entry so
    # first use rebuilds in the child (DataLoader workers never get here —
    # HOST_ARRAY_MODE keeps them off the jit path entirely)
    if _REGISTRY is not None:
        _REGISTRY.reset()


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_reset_after_fork)


# module-level conveniences (the call-site surface)

def get_or_build(key, build, label=None, example_args=None, on_fill=None,
                 event_fields=None):
    return registry().get_or_build(key, build, label=label,
                                   example_args=example_args,
                                   on_fill=on_fill,
                                   event_fields=event_fields)


def lookup(key):
    return registry().lookup(key)


def invalidate_tag(tag):
    return registry().invalidate_tag(tag)


def reset():
    registry().reset()


def stats():
    return registry().stats()


def mark():
    return registry().mark()


def keys_since(cursor):
    return registry().keys_since(cursor)


def prefetch_paths(paths):
    return registry().prefetch_paths(paths)


def clear_staged():
    return registry().clear_staged()


def begin_touch_log():
    registry().begin_touch_log()


def end_touch_log():
    return registry().end_touch_log()


_TOKENS = itertools.count()


def instance_token(prefix):
    """A process-unique fingerprint for executables keyed to a LIVE
    python object (gluon CachedOp, the sharded trainers): stable for the
    object's lifetime, never reused (unlike ``id()``), and obviously
    process-local — such keys must also set ``no_persist``."""
    return "%s#%d" % (prefix, next(_TOKENS))
