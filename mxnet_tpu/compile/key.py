"""Executable cache keys: one schema for every compiled-artifact factory.

The reference framework's dependency engine amortized kernel setup behind
one shared execution layer (PAPER.md layer 1); this rebuild had grown five
independent signature-keyed executable caches (per-op jit, autograd
backward, Executor builds, gluon CachedOp, serving's per-bucket
predictors). `ExecutableKey` is the one key those factories now share:

    (kind, graph/op fingerprint) x input shapes x dtypes x static attrs
    x sharding x donation

Keys are immutable, hashable (the in-memory table key) and canonically
JSON-able; the persistent tier names its artifact files by
``digest(backend=..., jax_version=...)`` — a sha256 over the canonical
JSON plus the jax version and XLA backend, so an upgraded jax or a
different platform can never resurrect a stale executable.

``tags`` carry invalidation labels (e.g. ``custom-op:<op_type>``): the
registry drops every entry carrying a tag when that tag is invalidated
(the custom-op re-registration path, operator.py).

``no_persist`` marks executables that embed process-local state — today,
anything staging a `jax.pure_callback` into the program (custom ops, host
ops): the serialized executable would carry a dangling host-callback
reference into the next process. Those keys live in the memory tier only.

``topology`` is the device-topology fingerprint (mesh axis names x mesh
shape x device kinds x process count — parallel.mesh.mesh_fingerprint)
that makes a SHARDED executable's identity honest across processes: a
serialized sharded step is only valid on the same mesh geometry it was
compiled for, so sharded keys reach the persistent tier only when they
carry one (registry._dir), and a different mesh resolves to a different
digest — an honest miss, never a wrong load. The component joins the
canonical JSON only when set, so every pre-existing unsharded key keeps
its on-disk digest.
"""
from __future__ import annotations

import hashlib
import json

__all__ = ["ExecutableKey"]


def _freeze(v):
    """Canonicalize a key component: lists/tuples -> tuples, dicts ->
    sorted (k, v) tuples, JSON primitives kept, anything else -> repr."""
    if isinstance(v, (list, tuple)):
        return tuple(_freeze(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((str(k), _freeze(x)) for k, x in v.items()))
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return repr(v)


def _jsonable(v):
    """The canonical-JSON rendering of a frozen component (tuples become
    lists; bools stay bools)."""
    if isinstance(v, tuple):
        return [_jsonable(x) for x in v]
    return v


class ExecutableKey:
    """One executable's identity across the memory and persistent tiers."""

    __slots__ = ("kind", "fingerprint", "shapes", "static", "sharded",
                 "donation", "tags", "no_persist", "topology", "_hash")

    def __init__(self, kind, fingerprint, shapes=None, static=(),
                 sharded=False, donation=(), tags=(), no_persist=False,
                 topology=None):
        self.kind = str(kind)
        self.fingerprint = str(fingerprint)
        self.shapes = _freeze(shapes) if shapes is not None else None
        self.static = _freeze(static)
        self.sharded = bool(sharded)
        self.donation = _freeze(tuple(donation))
        self.tags = tuple(str(t) for t in tags)
        self.no_persist = bool(no_persist)
        self.topology = str(topology) if topology else None
        self._hash = hash((self.kind, self.fingerprint, self.shapes,
                           self.static, self.sharded, self.donation,
                           self.topology))

    # -- identity ----------------------------------------------------------
    def _ident(self):
        return (self.kind, self.fingerprint, self.shapes, self.static,
                self.sharded, self.donation, self.topology)

    def __hash__(self):
        return self._hash

    def __eq__(self, other):
        return isinstance(other, ExecutableKey) and \
            self._ident() == other._ident()

    def __repr__(self):
        return "ExecutableKey(kind=%r, fingerprint=%r, shapes=%r)" % (
            self.kind, self.fingerprint, self.shapes)

    @property
    def concrete(self):
        """Shapes are pinned: the key names ONE executable (eligible for
        AOT compile + the persistent tier). Lazy keys (shapes None) hold a
        per-shape wrapper instead."""
        return self.shapes is not None

    def with_static_extra(self, extra):
        """A derived key with ``extra`` joined onto the static component
        (autograd's has_rng/x64 axes on top of the shared op key)."""
        return ExecutableKey(self.kind, self.fingerprint, shapes=self.shapes,
                            static=(self.static, _freeze(extra)),
                            sharded=self.sharded, donation=self.donation,
                            tags=self.tags, no_persist=self.no_persist,
                            topology=self.topology)

    def with_shapes(self, shapes):
        """The concrete per-shape key derived from a lazy base key (the
        eager-op / autograd per-shape persistence path)."""
        return ExecutableKey(self.kind, self.fingerprint, shapes=shapes,
                            static=self.static, sharded=self.sharded,
                            donation=self.donation, tags=self.tags,
                            no_persist=self.no_persist,
                            topology=self.topology)

    # -- persistence -------------------------------------------------------
    def to_json(self):
        """Canonical JSON-able rendering (stable across processes — the
        digest input and the artifact-header record)."""
        doc = {
            "kind": self.kind,
            "fingerprint": self.fingerprint,
            "shapes": _jsonable(self.shapes),
            "static": _jsonable(self.static),
            "sharded": self.sharded,
            "donation": _jsonable(self.donation),
        }
        # only when set: pre-topology keys keep their on-disk digests
        if self.topology is not None:
            doc["topology"] = self.topology
        return doc

    def digest(self, backend, jax_version):
        """Artifact name in the persistent tier: sha256 over the canonical
        key JSON + backend + jax version (version/platform mismatches
        resolve to different files, never to a wrong load)."""
        blob = json.dumps({"key": self.to_json(), "backend": str(backend),
                           "jax": str(jax_version)},
                          sort_keys=True, separators=(",", ":"))
        return hashlib.sha256(blob.encode()).hexdigest()[:40]
