"""CLI for the persistent compile cache: list / inspect / prune / verify.

    python -m mxnet_tpu.compile list   [--dir D]
    python -m mxnet_tpu.compile inspect <digest-prefix> [--dir D]
    python -m mxnet_tpu.compile prune  [--all | --bad | --jax-mismatch |
                                        --older-than SECONDS] [--dir D]
    python -m mxnet_tpu.compile verify [--dir D]

``--dir`` overrides ``MXTPU_COMPILE_CACHE``. ``list``/``inspect``/
``prune --all/--bad/--older-than`` read only headers and never import
jax; ``verify`` crc-checks payloads; ``prune --jax-mismatch`` needs jax
to know the live version/backend.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

from . import manifest as _manifest
from . import persist as _persist


def _resolve_dir(args):
    d = args.dir or _persist.cache_dir()
    if not d:
        sys.stderr.write("compile-cache: no directory (set "
                         "MXTPU_COMPILE_CACHE or pass --dir)\n")
        sys.exit(2)
    return d


def _fmt_age(created):
    if not created:
        return "?"
    s = max(0, time.time() - created)
    for unit, div in (("d", 86400), ("h", 3600), ("m", 60)):
        if s >= div:
            return "%.1f%s" % (s / div, unit)
    return "%ds" % s


def _fmt_mem(memory):
    """Compact argument/output/temp rendering of a header's compile-time
    memory_analysis figures (docs/observability.md §Memory)."""
    if not memory:
        return "-"
    return "a%s+o%s+t%s" % tuple(
        _fmt_bytes(memory.get(k)) for k in ("arguments", "outputs", "temp"))


def _fmt_bytes(v):
    if v is None:
        return "?"
    for unit, div in (("G", 1 << 30), ("M", 1 << 20), ("K", 1 << 10)):
        if v >= div:
            return "%.1f%s" % (v / div, unit)
    return "%d" % v


def cmd_list(args):
    d = _resolve_dir(args)
    rows, bad, total = [], 0, 0
    for path, header in _persist.scan(d):
        size = os.path.getsize(path)
        total += size
        if header is None:
            bad += 1
            rows.append(("<corrupt>", "-", "-", size, "-", "-", "-",
                         os.path.basename(path)))
            continue
        key = header.get("key") or {}
        rows.append((header.get("digest", "?")[:12], key.get("kind", "?"),
                     header.get("label") or key.get("fingerprint", "?")[:24],
                     size, _fmt_mem(header.get("memory")),
                     _fmt_age(header.get("created")),
                     "%s/%s" % (header.get("backend", "?"),
                                header.get("jax", "?")),
                     ""))
    print("%-14s %-14s %-26s %10s %-20s %6s %-16s" %  # allow-print: CLI display surface
          ("DIGEST", "KIND", "LABEL", "BYTES", "MEM(arg+out+tmp)", "AGE",
           "BACKEND/JAX"))
    for r in rows:
        print("%-14s %-14s %-26s %10d %-20s %6s %-16s %s" % r)  # allow-print: CLI display surface
    manifests = list(_manifest.list_manifests(d))
    print("-- %d artifact(s), %d bad, %.1f KiB total, %d manifest(s) in %s"  # allow-print: CLI display surface
          % (len(rows), bad, total / 1024.0, len(manifests), d))
    for doc in manifests:
        print("   manifest %s  model=%s/%s  %d entries" %  # allow-print: CLI display surface
              (doc.get("manifest"), doc.get("model"), doc.get("version"),
               len(doc.get("entries", []))))
    return 0


def cmd_inspect(args):
    d = _resolve_dir(args)
    for path, header in _persist.scan(d):
        if header is not None and \
                header.get("digest", "").startswith(args.digest):
            doc = dict(header)
            doc["path"] = path
            doc["bytes"] = os.path.getsize(path)
            json.dump(doc, sys.stdout, indent=2, sort_keys=True)
            sys.stdout.write("\n")
            return 0
    sys.stderr.write("compile-cache: no artifact matching %r\n" % args.digest)
    return 1


def cmd_prune(args):
    d = _resolve_dir(args)
    removed = _persist.prune(
        d,
        older_than_s=args.older_than,
        bad_only=args.bad,
        jax_mismatch=args.jax_mismatch,
    )
    for path in removed:
        print("pruned %s" % path)  # allow-print: CLI display surface
    print("-- pruned %d artifact(s)" % len(removed))  # allow-print: CLI display surface
    return 0


def cmd_verify(args):
    d = _resolve_dir(args)
    ok = bad = 0
    for path, header in _persist.scan(d):
        # full-payload read: crc + length verified by the loader contract
        full, payload = _persist._read(path, want_payload=True)
        if header is None or full is None or payload is None:
            bad += 1
            print("BAD  %s" % path)  # allow-print: CLI display surface
        else:
            ok += 1
    print("-- %d ok, %d bad" % (ok, bad))  # allow-print: CLI display surface
    return 1 if bad else 0


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.compile",
        description="persistent compile-cache maintenance "
                    "(docs/compile_cache.md)")
    parser.add_argument("--dir", default=None,
                        help="cache directory (default: MXTPU_COMPILE_CACHE)")
    sub = parser.add_subparsers(dest="cmd", required=True)
    sub.add_parser("list", help="table of artifacts + manifests")
    p_inspect = sub.add_parser("inspect", help="full header of one artifact")
    p_inspect.add_argument("digest", help="digest prefix")
    p_prune = sub.add_parser("prune", help="delete artifacts")
    group = p_prune.add_mutually_exclusive_group()
    group.add_argument("--all", action="store_true",
                       help="everything (the default)")
    group.add_argument("--bad", action="store_true",
                       help="only unreadable/corrupt artifacts")
    group.add_argument("--jax-mismatch", action="store_true",
                       help="only artifacts from another jax/backend")
    group.add_argument("--older-than", type=float, default=None,
                       metavar="SECONDS")
    sub.add_parser("verify", help="crc-check every artifact payload")
    args = parser.parse_args(argv)
    return {"list": cmd_list, "inspect": cmd_inspect, "prune": cmd_prune,
            "verify": cmd_verify}[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
