"""Persistent executable artifacts: the on-disk tier of the compile cache.

Each artifact is ONE compiled XLA executable, serialized with
`jax.experimental.serialize_executable` and written crash-consistently
(`base.atomic_writer` — same-directory temp + fsync + one atomic rename,
the `CheckpointManager` discipline), so a reader only ever sees a complete
previous file or a complete new file. Layout under the cache directory
(``MXTPU_COMPILE_CACHE``):

    <dir>/objects/<digest>.mxe      one executable per file
    <dir>/manifests/<model>.json    warmup manifests (see manifest.py)

Artifact format (``MXTPUEXE1``): magic, 8-byte little-endian header
length, a JSON header (format version, the canonical key JSON, label, jax
version, backend, FLOPs-per-execution from compile-time cost analysis,
payload length + crc32), then the pickled ``(payload, in_tree, out_tree)``
triple from ``serialize_executable.serialize``.

Every read re-verifies magic, format, jax version, backend and the
payload crc; ANY mismatch or decode error is a miss, never a fatal error
— a corrupt/truncated/stale artifact costs one recompile, nothing else.

Trust model: loading an artifact unpickles it, so the cache directory
must be exactly as trusted as a checkpoint directory or jax's own
persistent compilation cache — writable only by the deployment. The
serving wire protocol's pickle paranoia (supervisor.py) does NOT apply
here: these are local files under an operator-chosen path, not a socket
any local user can dial.
"""
from __future__ import annotations

import json
import os
import pickle
import time
import zlib

from .. import env as _env
from ..base import atomic_writer

__all__ = ["cache_dir", "artifact_path", "store", "load", "scan",
           "read_header", "prune", "MAGIC", "FORMAT"]

MAGIC = b"MXTPUEXE1\n"
FORMAT = 1
_FALSY = ("0", "off", "none", "disable", "false", "no")


def cache_dir(create=False):
    """The persistent tier's directory from ``MXTPU_COMPILE_CACHE``
    (``1``/``on`` -> the repo-local ``.mxtpu_compile_cache`` default), or
    None when the tier is disabled. Read per call — arming the cache after
    import (bench.py's post-dial pattern) just works."""
    choice = _env.raw("MXTPU_COMPILE_CACHE") or ""
    if not choice or choice.lower() in _FALSY:
        return None
    if choice.lower() in ("1", "on", "true", "yes"):
        d = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), ".mxtpu_compile_cache")
    else:
        d = choice
    if create:
        os.makedirs(os.path.join(d, "objects"), exist_ok=True)
    return d


def artifact_path(directory, digest):
    return os.path.join(directory, "objects", digest + ".mxe")


def _backend():
    import jax

    return jax.default_backend()


def _jax_version():
    import jax

    return jax.__version__


def store(directory, key, compiled, label=None, flops=None, memory=None):
    """Serialize ``compiled`` (a jax Compiled) under ``key``; returns the
    digest, or None when this executable/backend cannot serialize (a
    cache store is always best-effort). ``memory`` is the compile-time
    `memory_analysis()` figures dict (argument/output/temp/generated-
    code/alias bytes) — persisted in the header so a zero-compile cold
    start still knows the executable's footprint
    (docs/compile_cache.md)."""
    from jax.experimental import serialize_executable as _se

    backend, jaxver = _backend(), _jax_version()
    digest = key.digest(backend, jaxver)
    try:
        payload = pickle.dumps(_se.serialize(compiled),
                               protocol=pickle.HIGHEST_PROTOCOL)
    except Exception:
        return None
    header = json.dumps({
        "format": FORMAT,
        "digest": digest,
        "key": key.to_json(),
        "label": label,
        "jax": jaxver,
        "backend": backend,
        "flops": flops,
        "memory": memory,
        "created": time.time(),
        "payload_len": len(payload),
        "payload_crc32": zlib.crc32(payload) & 0xFFFFFFFF,
    }, sort_keys=True).encode()
    path = artifact_path(directory, digest)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    try:
        with atomic_writer(path, "wb") as f:
            f.write(MAGIC)
            f.write(len(header).to_bytes(8, "little"))
            f.write(header)
            f.write(payload)
    except OSError:
        return None  # full/read-only cache disk never breaks compilation
    return digest


def _read(path, want_payload):
    """(header, payload|None) for a verified artifact, or (None, None) on
    ANY problem — corrupt, truncated, foreign, stale-versioned."""
    try:
        with open(path, "rb") as f:
            if f.read(len(MAGIC)) != MAGIC:
                return None, None
            hlen = int.from_bytes(f.read(8), "little")
            if not 0 < hlen < (1 << 24):
                return None, None
            header = json.loads(f.read(hlen).decode())
            if header.get("format") != FORMAT:
                return None, None
            if not want_payload:
                return header, None
            payload = f.read()
    except (OSError, ValueError, UnicodeDecodeError):
        return None, None
    if len(payload) != header.get("payload_len") or \
            (zlib.crc32(payload) & 0xFFFFFFFF) != header.get("payload_crc32"):
        return None, None
    return header, payload


def read_header(path):
    """Verified header of one artifact file (no payload/crc check), or
    None. The CLI's list/inspect read."""
    return _read(path, want_payload=False)[0]


def load(directory, key):
    """Deserialize the executable stored under ``key``. Returns
    ``(callable, flops, memory)`` or ``(None, None, None)`` on miss/
    corruption/version skew — loading NEVER raises."""
    path = artifact_path(directory, key.digest(_backend(), _jax_version()))
    return load_path(path)


def load_path(path):
    """`load` by explicit artifact path (manifest prefetch)."""
    header, payload = _read(path, want_payload=True)
    if header is None:
        return None, None, None
    # version/backend double-check: the digest already encodes both, but a
    # renamed/copied file must not smuggle a foreign executable in
    if header.get("jax") != _jax_version() or \
            header.get("backend") != _backend():
        return None, None, None
    try:
        from jax.experimental import serialize_executable as _se

        payload_bytes, in_tree, out_tree = pickle.loads(payload)
        fn = _se.deserialize_and_load(payload_bytes, in_tree, out_tree)
    except Exception:
        return None, None, None
    return fn, header.get("flops"), header.get("memory")


def scan(directory):
    """Yield ``(path, header_or_None)`` for every ``*.mxe`` object file
    (header None = unreadable/corrupt/foreign — prune targets)."""
    objects = os.path.join(directory, "objects")
    try:
        names = sorted(os.listdir(objects))
    except OSError:
        return
    for name in names:
        if not name.endswith(".mxe"):
            continue
        path = os.path.join(objects, name)
        yield path, read_header(path)


def prune(directory, older_than_s=None, bad_only=False, jax_mismatch=False):
    """Delete artifacts: all (default), only unreadable/corrupt ones
    (``bad_only``), only other-jax/backend ones (``jax_mismatch``), or
    those older than ``older_than_s`` seconds. Returns paths removed."""
    now = time.time()
    removed = []
    for path, header in scan(directory):
        if bad_only:
            drop = header is None
        elif jax_mismatch:
            drop = header is not None and (
                header.get("jax") != _jax_version()
                or header.get("backend") != _backend())
        elif older_than_s is not None:
            created = (header or {}).get("created") or 0
            drop = (now - created) > older_than_s
        else:
            drop = True
        if drop:
            try:
                os.unlink(path)
                removed.append(path)
            except OSError:
                pass
    return removed
