"""Warmup manifests: a served model's executable key-set, on disk.

When a model is published (`ModelRepository.add` after warm) with the
persistent tier armed, the repository records which compile-cache
artifacts the warm filled or loaded — one JSON manifest per model under
``<cache>/manifests/``. A freshly spawned replica worker reads its
artifact's manifest BEFORE accepting traffic and prefetches every listed
executable into the registry's staging table, so the warm pass (and the
first real request on any bucket) deserializes instead of compiling:
cold start with a warm cache reaches ready with zero ``jit_compile``
events.

Manifests are keyed by a stable *artifact id* (sha256 of the resolved
artifact path + the serving geometry), so the worker — which knows only
its ``--artifact`` argv — finds the same manifest the repository wrote.
Writes are atomic-rename (`base.atomic_writer`); a missing/corrupt
manifest is a no-op, never fatal.
"""
from __future__ import annotations

import hashlib
import json
import os
import time

from ..base import atomic_writer
from . import persist as _persist

__all__ = ["model_manifest_id", "manifest_path", "write_manifest",
           "read_manifest", "prefetch", "list_manifests"]


def model_manifest_id(artifact_path, max_batch=None, input_shapes=None):
    """Stable id tying a serving artifact + geometry to its manifest.
    Path is resolved absolute so repository and replica worker agree."""
    blob = json.dumps({
        "path": os.path.abspath(os.fspath(artifact_path)),
        "max_batch": max_batch,
        "input_shapes": {str(k): list(v)
                         for k, v in sorted((input_shapes or {}).items())},
    }, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:24]


def manifest_path(directory, manifest_id):
    return os.path.join(directory, "manifests", manifest_id + ".json")


def write_manifest(directory, manifest_id, entries, model=None,
                   version=None):
    """Record a model's key-set: ``entries`` is the registry's
    ``keys_since`` result — (ExecutableKey, digest) pairs. Returns the
    manifest path, or None when there is nothing to record."""
    digests = []
    seen = set()
    for key, digest in entries:
        if digest in seen:
            continue
        seen.add(digest)
        digests.append({"digest": digest, "kind": key.kind,
                        "fingerprint": key.fingerprint[:40]})
    if not digests:
        return None
    path = manifest_path(directory, manifest_id)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    doc = {
        "format": 1,
        "manifest": manifest_id,
        "model": model,
        "version": version,
        "created": time.time(),
        "entries": digests,
    }
    try:
        with atomic_writer(path, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
    except OSError:
        return None
    return path


def read_manifest(directory, manifest_id):
    """The manifest document, or None (missing/corrupt are misses)."""
    try:
        with open(manifest_path(directory, manifest_id)) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if doc.get("format") != 1 or not isinstance(doc.get("entries"), list):
        return None
    return doc


def prefetch(manifest_id, directory=None, registry=None):
    """Load every artifact a manifest names into the registry staging
    table (replica pre-traffic warm). Returns how many executables
    loaded (0 when the tier is off or the manifest is absent)."""
    directory = directory or _persist.cache_dir()
    if directory is None:
        return 0
    doc = read_manifest(directory, manifest_id)
    if doc is None:
        return 0
    if registry is None:
        from .registry import registry as _singleton

        registry = _singleton()
    paths = [_persist.artifact_path(directory, e.get("digest", ""))
             for e in doc["entries"] if e.get("digest")]
    return registry.prefetch_paths(paths)


def list_manifests(directory):
    """Yield every readable manifest document under the cache dir."""
    mdir = os.path.join(directory, "manifests")
    try:
        names = sorted(os.listdir(mdir))
    except OSError:
        return
    for name in names:
        if not name.endswith(".json"):
            continue
        doc = read_manifest(directory, name[:-len(".json")])
        if doc is not None:
            yield doc
