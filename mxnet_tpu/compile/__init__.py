"""`mxnet_tpu.compile` — the unified executable cache.

One registry every executable factory resolves through (the reference's
shared dependency-engine execution layer, PAPER.md layer 1, rebuilt for
XLA): per-op eager jit (`ops.invoke_jax`), autograd backward
(`autograd._bwd_jitted`), symbolic Executor forward/backward, gluon
CachedOp, the sharded trainers' fused steps, and — through the Executor —
the serving layer's per-bucket predictors.

Key = (kind, graph/op fingerprint) x input shapes x dtypes x static
attrs x sharding x donation (key.py); the persistent tier additionally
keys on jax version + XLA backend. Tiers, counters and the fill hook are
documented in registry.py; on-disk artifacts in persist.py; serving
warmup manifests in manifest.py. `python -m mxnet_tpu.compile` lists,
inspects and prunes the persistent tier. docs/compile_cache.md is the
operator-facing writeup.
"""
from __future__ import annotations

from .key import ExecutableKey
from .manifest import (list_manifests, model_manifest_id, prefetch,
                       read_manifest, write_manifest)
from .persist import cache_dir
from .registry import (Registry, begin_touch_log, clear_staged, end_touch_log,
                       get_or_build, instance_token, invalidate_tag,
                       keys_since, lookup, mark, prefetch_paths, registry,
                       reset, stats)

__all__ = [
    "ExecutableKey", "Registry", "registry", "get_or_build", "lookup",
    "invalidate_tag", "reset", "stats", "mark", "keys_since",
    "prefetch_paths", "clear_staged", "instance_token", "cache_dir",
    "begin_touch_log", "end_touch_log",
    "model_manifest_id", "write_manifest", "read_manifest", "prefetch",
    "list_manifests",
]
