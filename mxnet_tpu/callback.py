"""Training callbacks (reference: python/mxnet/callback.py — Speedometer :120,
do_checkpoint :55, log_train_metric, ProgressBar).

Progress output goes through `mxnet_tpu.log.get_logger` (the framework
formatter, level INFO so progress is visible by default) and every number a
callback prints is ALSO published as a telemetry metric — the human log and
the machine-readable JSONL/Prometheus views stay in lockstep
(docs/observability.md)."""
from __future__ import annotations

import time

from . import log as _log
from . import telemetry

__all__ = ["Speedometer", "do_checkpoint", "log_train_metric", "ProgressBar",
           "module_checkpoint"]

_LOG = _log.get_logger("mxnet_tpu.callback", level=_log.INFO)


def do_checkpoint(prefix, period=1):
    """Epoch-end checkpoint callback (reference: callback.py:55)."""
    period = int(max(1, period))

    def _callback(iter_no, sym, arg, aux):
        if (iter_no + 1) % period == 0:
            from . import model

            model.save_checkpoint(prefix, iter_no + 1, sym, arg, aux)

    return _callback


def module_checkpoint(mod, prefix, period=1, save_optimizer_states=False):
    """reference: callback.py module_checkpoint"""
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            mod.save_checkpoint(prefix, iter_no + 1, save_optimizer_states)

    return _callback


def log_train_metric(period, auto_reset=False):
    """reference: callback.py log_train_metric"""

    def _callback(param):
        if param.nbatch % period == 0 and param.eval_metric is not None:
            name_value = param.eval_metric.get_name_value()
            for name, value in name_value:
                _LOG.info("Iter[%d] Batch[%d] Train-%s=%f",
                          param.epoch, param.nbatch, name, value)
                telemetry.gauge("mxtpu_train_metric",
                                {"metric": name}).set(float(value))
            if auto_reset:
                param.eval_metric.reset()

    return _callback


class Speedometer:
    """Samples/sec logger (reference: callback.py:120)."""

    def __init__(self, batch_size, frequent=50, auto_reset=True):
        self.batch_size = batch_size
        self.frequent = frequent
        self.init = False
        self.tic = 0
        self.last_count = 0
        self.auto_reset = auto_reset

    def __call__(self, param):
        count = param.nbatch
        if self.last_count > count:
            self.init = False
        self.last_count = count
        if self.init:
            if count % self.frequent == 0:
                speed = self.frequent * self.batch_size / (time.time() - self.tic)
                telemetry.gauge("mxtpu_speedometer_samples_per_sec").set(speed)
                if param.eval_metric is not None:
                    name_value = param.eval_metric.get_name_value()
                    for name, value in name_value:
                        telemetry.gauge("mxtpu_train_metric",
                                        {"metric": name}).set(float(value))
                    if self.auto_reset:
                        param.eval_metric.reset()
                    msg = "Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec" \
                        % (param.epoch, count, speed)
                    msg += "".join("\t%s=%f" % kv for kv in name_value)
                    _LOG.info(msg)
                else:
                    _LOG.info("Iter[%d] Batch [%d]\tSpeed: %.2f samples/sec",
                              param.epoch, count, speed)
                self.tic = time.time()
        else:
            self.init = True
            self.tic = time.time()


class ProgressBar:
    """reference: callback.py ProgressBar"""

    def __init__(self, total, length=80):
        self.bar_len = length
        self.total = total

    def __call__(self, param):
        count = param.nbatch
        filled_len = int(round(self.bar_len * count / float(self.total)))
        percents = int(round(100.0 * count / float(self.total)))
        prog_bar = "=" * filled_len + "-" * (self.bar_len - filled_len)
        _LOG.info("[%s] %s%s\r", prog_bar, percents, "%")
