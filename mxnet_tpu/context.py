"""Device contexts.

TPU-native equivalent of the reference's `python/mxnet/context.py` (Context
class + ctx stack, context.py:23-309). Devices map onto JAX/PJRT devices:

- ``cpu()``    -> host CPU PJRT device
- ``tpu(i)``   -> i-th TPU chip
- ``gpu(i)``   -> alias for the i-th *accelerator* device; kept so reference
  scripts written against ``mx.gpu()`` run unmodified on TPU machines.
- ``cpu_pinned()`` -> host CPU (XLA manages pinned staging buffers itself).

Unlike the reference there is no device-id-indexed cuda runtime behind this;
a Context is a thin, hashable handle resolving to a `jax.Device`.
"""
from __future__ import annotations

import threading

from .base import MXNetError

__all__ = ["Context", "cpu", "gpu", "tpu", "cpu_pinned", "current_context",
           "num_gpus", "num_tpus", "gpu_memory_info"]


class Context:
    """Device context (reference: python/mxnet/context.py:23).

    Parameters
    ----------
    device_type : {'cpu', 'gpu', 'tpu', 'cpu_pinned', 'cpu_shared'}
    device_id : int
    """

    _stack = threading.local()

    devtype2id = {"cpu": 1, "gpu": 2, "cpu_pinned": 3, "cpu_shared": 5, "tpu": 6}
    devid2type = {v: k for k, v in devtype2id.items()}

    def __init__(self, device_type, device_id=0):
        if isinstance(device_type, Context):
            device_type, device_id = device_type.device_type, device_type.device_id
        if device_type not in self.devtype2id:
            raise MXNetError("unknown device type %s" % device_type)
        self.device_type = device_type
        self.device_id = int(device_id)

    # -- identity ---------------------------------------------------------
    @property
    def device_typeid(self):
        return self.devtype2id[self.device_type]

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
        )

    def __repr__(self):
        return "%s(%d)" % (self.device_type, self.device_id)

    __str__ = __repr__

    # -- JAX resolution ---------------------------------------------------
    def jax_device(self):
        """Resolve to a concrete `jax.Device`. Always a process-LOCAL device:
        under jax.distributed the global list contains other hosts'
        non-addressable devices, which a Context must never resolve to (the
        reference's Context is likewise host-local; cross-host placement
        goes through the mesh/sharding layer)."""
        import jax

        if self.device_type in ("cpu", "cpu_pinned", "cpu_shared"):
            try:
                devs = jax.local_devices(backend="cpu")
            except RuntimeError:
                devs = jax.devices("cpu")
            return devs[min(self.device_id, len(devs) - 1)]
        # 'gpu' and 'tpu' both mean "accelerator": prefer the default backend's
        # devices (TPU when present), fall back to cpu so CPU-only test runs work.
        devs = jax.local_devices()
        if devs[0].platform == "cpu" and self.device_type in ("gpu", "tpu"):
            return devs[min(self.device_id, len(devs) - 1)]
        if self.device_id >= len(devs):
            raise MXNetError(
                "device %s out of range: %d accelerator device(s) visible"
                % (self, len(devs))
            )
        return devs[self.device_id]

    # -- stack ------------------------------------------------------------
    def __enter__(self):
        if not hasattr(Context._stack, "ctxs"):
            Context._stack.ctxs = []
        Context._stack.ctxs.append(self)
        return self

    def __exit__(self, *args):
        Context._stack.ctxs.pop()

    @classmethod
    def default_ctx(cls):
        stack = getattr(cls._stack, "ctxs", None)
        if stack:
            return stack[-1]
        return _DEFAULT

    def empty_cache(self):
        """Release cached device memory (reference: context.py:292). XLA owns
        the allocator; this is a best-effort no-op hook."""

    def memory_info(self):
        """(free_bytes, total_bytes) for this context's device (reference:
        context.py gpu_memory_info / cudaMemGetInfo). Sourced from PJRT
        device memory stats; CPU backends report (0, 0) — the host allocator
        has no device pool (SURVEY N2: PJRT owns device memory)."""
        stats = self.jax_device().memory_stats() or {}
        total = stats.get("bytes_limit", 0)
        used = stats.get("bytes_in_use", 0)
        return (max(total - used, 0), total)


def cpu(device_id=0):
    return Context("cpu", device_id)


def cpu_pinned(device_id=0):
    return Context("cpu_pinned", device_id)


def gpu(device_id=0):
    """Accelerator context; on TPU machines this is the TPU chip (kept for
    source compatibility with reference scripts using mx.gpu())."""
    return Context("gpu", device_id)


def tpu(device_id=0):
    return Context("tpu", device_id)


def num_gpus():
    """Number of accelerator devices (reference: context.py:258 num_gpus)."""
    import jax

    devs = jax.devices()
    return 0 if devs[0].platform == "cpu" else len(devs)


def num_tpus():
    return num_gpus()


def gpu_memory_info(device_id=0):
    """reference: python/mxnet/context.py gpu_memory_info — (free, total)
    bytes on the accelerator device."""
    return Context("gpu", device_id).memory_info()


_DEFAULT = Context("cpu", 0)


def _set_default(ctx):
    global _DEFAULT
    _DEFAULT = ctx


def current_context():
    """The context on top of the with-stack (reference: context.py:301)."""
    return Context.default_ctx()
