"""Legacy alias: contrib symbol functions under mx.contrib.symbol
(reference: python/mxnet/contrib/symbol.py; the same functions live on
mx.sym.contrib)."""


def __getattr__(name):
    from .. import symbol as _sym

    return getattr(_sym.contrib, name)


def __dir__():
    from .. import symbol as _sym

    return sorted(set(dir(_sym.contrib)))
