"""Legacy contrib autograd API (reference: python/mxnet/contrib/autograd.py
— the deprecated precursor of mx.autograd; reference scripts from the era
import these names). Thin adapters over the modern tape."""
from __future__ import annotations

import functools

from .. import autograd as _ag

__all__ = ["set_is_training", "TrainingStateScope", "train_section",
           "test_section", "mark_variables", "backward", "compute_gradient",
           "grad_and_loss", "grad"]


class _PrevState(tuple):
    """Restore token returned by set_is_training. Truth-tests like the
    reference's previous-bool return (legacy code branches on the result),
    while carrying (recording, training) as a pair so the
    `set_is_training(prev)` round-trip restores a diverged
    train_mode()/pause() scope exactly."""

    __slots__ = ()

    def __bool__(self):
        return bool(self[0] or self[1])


def set_is_training(is_train):
    """reference: contrib/autograd.py:32 — returns the previous state.
    The legacy flag conflated recording with train mode; here both flags
    follow, and the returned value is a bool-compatible restore token
    capturing them as a pair."""
    if isinstance(is_train, tuple):
        rec, train = is_train
    else:
        rec = train = bool(is_train)
    return _PrevState((_ag.set_recording(rec), _ag.set_training(train)))


class TrainingStateScope:
    """reference: contrib/autograd.py:54. The legacy API had one flag;
    the modern tape has two (recording, training) that can diverge, so the
    scope saves and restores them as a pair — feeding one flag's previous
    value into both would corrupt an enclosing train_mode()/pause()."""

    def __init__(self, enter_state):
        self._enter_state = enter_state

    def __enter__(self):
        self._prev_rec = _ag.set_recording(self._enter_state)
        self._prev_train = _ag.set_training(self._enter_state)

    def __exit__(self, *exc):
        _ag.set_recording(self._prev_rec)
        _ag.set_training(self._prev_train)


def train_section():
    """reference: contrib/autograd.py:74."""
    return TrainingStateScope(True)


def test_section():
    """reference: contrib/autograd.py:88."""
    return TrainingStateScope(False)


def mark_variables(variables, gradients, grad_reqs="write"):
    """reference: contrib/autograd.py:102."""
    _ag.mark_variables(variables, gradients, grad_reqs)


def backward(outputs, out_grads=None, retain_graph=False):
    """reference: contrib/autograd.py:123."""
    _ag.backward(outputs, head_grads=out_grads, retain_graph=retain_graph)


def compute_gradient(outputs):
    """reference: contrib/autograd.py:158."""
    backward(outputs)


def grad_and_loss(func, argnum=None):
    """reference: contrib/autograd.py:163 — returns a function computing
    both gradient wrt the (selected) args and the loss."""
    from ..ndarray import ndarray as _nd

    @functools.wraps(func)
    def wrapped(*args):
        variables = list(args)
        if argnum is not None:
            argnums = [argnum] if isinstance(argnum, int) else list(argnum)
            variables = [args[i] for i in argnums]
        for v in variables:
            if not isinstance(v, _nd.NDArray):
                raise TypeError("type %s not supported" % type(v))
        grads = [_nd.zeros(v.shape, ctx=v._ctx, dtype=str(v.dtype))
                 for v in variables]
        mark_variables(variables, grads)
        with train_section():
            outputs = func(*args)
        compute_gradient([outputs] if isinstance(outputs, _nd.NDArray)
                         else outputs)
        return grads, outputs

    return wrapped


def grad(func, argnum=None):
    """reference: contrib/autograd.py:195."""
    gl = grad_and_loss(func, argnum)

    @functools.wraps(func)
    def wrapped(*args):
        return gl(*args)[0]

    return wrapped
