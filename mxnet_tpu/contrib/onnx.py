"""ONNX import/export.

Reference: python/mxnet/contrib/onnx/ (onnx2mx/import_model.py:24,
mx2onnx/export_model.py:35 + per-op translation tables). Like the
reference, this module requires the `onnx` package at call time; the
translation tables cover the common CNN/MLP subset (Gemm/Conv/BN/Relu/
Pool/Reshape/Softmax and elementwise) and raise clearly on anything else.
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError

__all__ = ["import_model", "export_model", "get_model_metadata"]


def _require_onnx():
    try:
        import onnx  # noqa: F401

        return onnx
    except ImportError:
        raise ImportError(
            "ONNX support requires the `onnx` package (reference gates the "
            "same way, contrib/onnx/__init__.py); it is not installed in "
            "this environment")


# -- import ---------------------------------------------------------------

_IMPORT_OPS = {}


def _imports(name):
    def deco(fn):
        _IMPORT_OPS[name] = fn
        return fn

    return deco


def _symmetric_pads(attrs, what):
    """ONNX pads = (h_begin, w_begin, h_end, w_end); only symmetric padding
    maps onto the framework's `pad` attr — raise on the rest instead of
    silently importing wrong geometry."""
    pads = tuple(attrs.get("pads", (0, 0, 0, 0)))
    if len(pads) == 2:
        return pads
    if len(pads) == 4:
        if pads[0] != pads[2] or pads[1] != pads[3]:
            raise MXNetError("%s: asymmetric ONNX pads %s are not supported"
                             % (what, (pads,)))
        return pads[:2]
    raise MXNetError("%s: unsupported pads rank %d" % (what, len(pads)))


@_imports("Gemm")
def _gemm(sym_mod, inputs, attrs, params):
    if attrs.get("transA", 0) != 0:
        raise MXNetError("Gemm with transA=1 is not supported")
    if attrs.get("alpha", 1.0) != 1.0 or attrs.get("beta", 1.0) != 1.0:
        raise MXNetError("Gemm with alpha/beta != 1 is not supported")
    data, w, b = inputs[0], inputs[1], inputs[2] if len(inputs) > 2 else None
    wshape = params[w.name].shape
    if not attrs.get("transB", 0):
        # ONNX default stores weight (K, N); FullyConnected wants (N, K) —
        # transpose the initializer once at import
        params[w.name] = _np.ascontiguousarray(params[w.name].T)
        wshape = params[w.name].shape
    return sym_mod.FullyConnected(data=data, weight=w, bias=b,
                                  num_hidden=wshape[0], no_bias=b is None)


@_imports("Conv")
def _conv(sym_mod, inputs, attrs, params):
    kernel = tuple(attrs.get("kernel_shape", ()))
    strides = tuple(attrs.get("strides", (1, 1)))
    pads = _symmetric_pads(attrs, "Conv")
    if tuple(attrs.get("dilations", (1, 1))) not in ((), (1, 1)):
        raise MXNetError("Conv with dilations != 1 is not supported")
    w = inputs[1]
    return sym_mod.Convolution(data=inputs[0], weight=w,
                               bias=inputs[2] if len(inputs) > 2 else None,
                               kernel=kernel, stride=strides, pad=pads,
                               num_filter=params[w.name].shape[0],
                               no_bias=len(inputs) <= 2)


@_imports("Relu")
def _relu(sym_mod, inputs, attrs, params):
    return sym_mod.relu(inputs[0])


@_imports("MaxPool")
def _maxpool(sym_mod, inputs, attrs, params):
    return sym_mod.Pooling(inputs[0], kernel=tuple(attrs["kernel_shape"]),
                           stride=tuple(attrs.get("strides", (1, 1))),
                           pad=_symmetric_pads(attrs, "MaxPool"),
                           pool_type="max")


@_imports("AveragePool")
def _avgpool(sym_mod, inputs, attrs, params):
    return sym_mod.Pooling(inputs[0], kernel=tuple(attrs["kernel_shape"]),
                           stride=tuple(attrs.get("strides", (1, 1))),
                           pad=_symmetric_pads(attrs, "AveragePool"),
                           pool_type="avg")


@_imports("GlobalAveragePool")
def _gavgpool(sym_mod, inputs, attrs, params):
    return sym_mod.Pooling(inputs[0], kernel=(1, 1), global_pool=True,
                           pool_type="avg")


@_imports("Softmax")
def _softmax(sym_mod, inputs, attrs, params):
    return sym_mod.softmax(inputs[0], axis=attrs.get("axis", -1))


@_imports("Flatten")
def _flatten(sym_mod, inputs, attrs, params):
    return sym_mod.Flatten(inputs[0])


@_imports("Reshape")
def _reshape(sym_mod, inputs, attrs, params):
    shape = attrs.get("shape")
    if shape is None:
        # opset >= 5: shape arrives as the 2nd input tensor (an initializer);
        # resolve it through params like the reference's onnx2mx reshape
        # translation (reference: onnx2mx/_op_translations.py reshape)
        if len(inputs) < 2 or inputs[1].name not in params:
            raise MXNetError("Reshape: no shape attribute and the shape "
                             "input is not a constant initializer")
        shape = params[inputs[1].name]
    return sym_mod.Reshape(inputs[0], shape=tuple(int(s) for s in shape))


@_imports("Add")
def _add(sym_mod, inputs, attrs, params):
    return inputs[0] + inputs[1]


@_imports("Mul")
def _mul(sym_mod, inputs, attrs, params):
    return inputs[0] * inputs[1]


@_imports("BatchNormalization")
def _bn(sym_mod, inputs, attrs, params):
    return sym_mod.BatchNorm(data=inputs[0], gamma=inputs[1], beta=inputs[2],
                             moving_mean=inputs[3], moving_var=inputs[4],
                             eps=attrs.get("epsilon", 1e-5),
                             momentum=attrs.get("momentum", 0.9))


def import_model(model_file):
    """ONNX file -> (sym, arg_params, aux_params) (reference:
    onnx2mx/import_model.py:24)."""
    onnx = _require_onnx()
    from onnx import numpy_helper

    from .. import ndarray as nd
    from .. import symbol as sym_mod

    model = onnx.load(model_file)
    graph = model.graph
    params = {init.name: _np.asarray(numpy_helper.to_array(init))
              for init in graph.initializer}
    tensors = {}
    for inp in graph.input:
        if inp.name not in params:
            tensors[inp.name] = sym_mod.var(inp.name)
    for name in params:
        tensors[name] = sym_mod.var(name)

    def get_attrs(node):
        out = {}
        for a in node.attribute:
            out[a.name] = onnx.helper.get_attribute_value(a)
        return out

    for node in graph.node:
        if node.op_type not in _IMPORT_OPS:
            raise MXNetError("ONNX op '%s' is not supported by the importer"
                             % node.op_type)
        ins = [tensors[i] for i in node.input if i]
        out = _IMPORT_OPS[node.op_type](sym_mod, ins, get_attrs(node), params)
        outs = [out] if not isinstance(out, (list, tuple)) else out
        for name, o in zip(node.output, outs):
            tensors[name] = o
    final = tensors[graph.output[0].name]
    arg_names = set(final.list_arguments())
    aux_names = set(final.list_auxiliary_states())
    arg_params = {k: nd.array(v) for k, v in params.items() if k in arg_names}
    aux_params = {k: nd.array(v) for k, v in params.items() if k in aux_names}
    return final, arg_params, aux_params


def get_model_metadata(model_file):
    onnx = _require_onnx()

    model = onnx.load(model_file)
    init = {i.name for i in model.graph.initializer}
    return {
        "input_tensor_data": [(i.name, tuple(d.dim_value for d in
                                             i.type.tensor_type.shape.dim))
                              for i in model.graph.input if i.name not in init],
        "output_tensor_data": [(o.name, tuple(d.dim_value for d in
                                              o.type.tensor_type.shape.dim))
                               for o in model.graph.output],
    }


# -- export ---------------------------------------------------------------

def export_model(sym, params, input_shape, input_type=_np.float32,
                 onnx_file_path="model.onnx", verbose=False):
    """Symbol + params -> ONNX file (reference: mx2onnx/export_model.py:35).
    Covers the same CNN/MLP op subset as the importer."""
    onnx = _require_onnx()
    from onnx import TensorProto, helper, numpy_helper

    params = {k: (v.asnumpy() if hasattr(v, "asnumpy") else _np.asarray(v))
              for k, v in params.items()}
    nodes, initializers = [], []
    name_of = {}

    def edge_name(node, idx):
        base = name_of[id(node)]
        return base if idx == 0 else "%s_out%d" % (base, idx)

    topo = list(sym._topo())
    inputs_proto = []
    for node in topo:
        if node.is_var:
            name_of[id(node)] = node.name
            if node.name in params:
                initializers.append(
                    numpy_helper.from_array(
                        params[node.name].astype(_np.float32), node.name))
            else:
                shape = list(input_shape) if not isinstance(input_shape, dict) \
                    else list(input_shape[node.name])
                inputs_proto.append(helper.make_tensor_value_info(
                    node.name, TensorProto.FLOAT, shape))
            continue
        name_of[id(node)] = node.name
        ins = [edge_name(s, i) for s, i in node.inputs]
        a = node.attrs
        if node.op == "FullyConnected":
            nodes.append(helper.make_node("Gemm", ins[:3], [node.name],
                                          transB=1))
        elif node.op == "Convolution":
            nodes.append(helper.make_node(
                "Conv", ins[:3] if not a.get("no_bias") else ins[:2],
                [node.name], kernel_shape=list(a.get("kernel", ())),
                strides=list(a.get("stride", (1, 1)) or (1, 1)),
                pads=list(a.get("pad", (0, 0)) or (0, 0)) * 2))
        elif node.op in ("relu", "Activation") and \
                a.get("act_type", "relu") == "relu":
            nodes.append(helper.make_node("Relu", ins[:1], [node.name]))
        elif node.op == "Pooling":
            kind = "MaxPool" if a.get("pool_type", "max") == "max" \
                else "AveragePool"
            if a.get("global_pool"):
                nodes.append(helper.make_node("GlobalAveragePool", ins[:1],
                                              [node.name]))
            else:
                nodes.append(helper.make_node(
                    kind, ins[:1], [node.name],
                    kernel_shape=list(a.get("kernel", ())),
                    strides=list(a.get("stride", (1, 1)) or (1, 1)),
                    # like the Conv branch: padded pools must export their
                    # geometry, else the consumer sees implicit zero pad
                    pads=list(a.get("pad", (0, 0)) or (0, 0)) * 2))
        elif node.op == "Flatten":
            nodes.append(helper.make_node("Flatten", ins[:1], [node.name]))
        elif node.op in ("softmax", "SoftmaxOutput"):
            nodes.append(helper.make_node("Softmax", ins[:1], [node.name]))
        elif node.op == "elemwise_add":
            nodes.append(helper.make_node("Add", ins[:2], [node.name]))
        elif node.op == "elemwise_mul":
            nodes.append(helper.make_node("Mul", ins[:2], [node.name]))
        elif node.op == "BatchNorm":
            nodes.append(helper.make_node(
                "BatchNormalization", ins[:5], [node.name],
                epsilon=float(a.get("eps", 1e-5)),
                momentum=float(a.get("momentum", 0.9))))
        elif node.op == "Reshape":
            shape_name = node.name + "_shape"
            initializers.append(numpy_helper.from_array(
                _np.asarray(a.get("shape", ()), dtype=_np.int64), shape_name))
            nodes.append(helper.make_node("Reshape", [ins[0], shape_name],
                                          [node.name]))
        else:
            raise MXNetError("ONNX export: op '%s' not supported" % node.op)

    out_node, out_idx = sym._outputs[0]
    graph = helper.make_graph(
        nodes, "mxnet_tpu_model", inputs_proto,
        [helper.make_tensor_value_info(edge_name(out_node, out_idx),
                                       TensorProto.FLOAT, None)],
        initializer=initializers)
    model = helper.make_model(graph)
    onnx.save(model, onnx_file_path)
    return onnx_file_path
