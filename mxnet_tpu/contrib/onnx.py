"""ONNX import/export.

Reference: python/mxnet/contrib/onnx/ (onnx2mx/import_model.py:24,
mx2onnx/export_model.py:35 + per-op translation tables covering ~90 import
/ ~75 export ops). This module mirrors those tables over the TPU-native
symbol layer: CNN ops (Conv incl. groups/dilation, pooling variants,
BatchNorm, Concat, Dropout, clip/relu6), the BERT/transformer subset
(LayerNormalization, Erf/GELU, MatMul/batch_dot, Gather/Embedding,
Transpose/Unsqueeze/Squeeze/Slice, Where, reductions), elementwise/scalar
/broadcast families, and the classic extras (LRN, InstanceNorm,
L2Normalization, Deconvolution/ConvTranspose, Pad, Split, argmax/argmin,
Cast, Expand/Tile).

Uses the `onnx` pip package when importable (reference behavior,
contrib/onnx/__init__.py); otherwise falls back to the in-tree pure-Python
protobuf shim (onnx_proto.py) so interchange works without external
dependencies — the artifacts are standard .onnx protobufs either way.

Known model-level divergences (documented, reference-equivalent):
- SSD's MultiBox*/nms contrib ops have no ONNX mapping (the reference's
  tables don't cover them either); SSD deploys via StableHLO AOT
  (predict.py export_compiled).
- Fused RNN layers (word_lm LSTM) are not exported (no RNN/LSTM rows in
  the reference mx2onnx table either); use the AOT path.
- BERTModel's hybrid_forward is shape-specialized (reads concrete input
  shapes), so the full model cannot be traced to a Symbol for export; its
  building-block ops all translate (tested op-level) and whole-model
  deployment goes through export_compiled.
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError

__all__ = ["import_model", "export_model", "get_model_metadata"]


def _onnx_impl():
    """(onnx_like, helper, numpy_helper, TensorProto): the real package if
    installed, else the in-tree protobuf shim."""
    try:
        import onnx
        from onnx import TensorProto, helper, numpy_helper

        return onnx, helper, numpy_helper, TensorProto
    except ImportError:
        from . import onnx_proto

        return (onnx_proto, onnx_proto.helper, onnx_proto.numpy_helper,
                onnx_proto.TensorProto)


# ===========================================================================
# import: ONNX graph -> Symbol
# ===========================================================================

_IMPORT_OPS = {}


def _imports(*names):
    def deco(fn):
        for n in names:
            _IMPORT_OPS[n] = fn
        return fn

    return deco


class _ImportCtx:
    """Carries the graph-wide state each import handler may need: the
    initializer dict (mutable — Constant nodes add to it) and the symbol
    module."""

    def __init__(self, sym_mod, params, opset):
        self.sym = sym_mod
        self.params = params
        self.opset = opset

    def const_value(self, sym_or_name):
        """Resolve an input that must be a constant initializer (shape /
        axes / pads arguments of opset>=10 ops)."""
        name = getattr(sym_or_name, "name", sym_or_name)
        if name not in self.params:
            raise MXNetError(
                "input '%s' must be a constant initializer (data-dependent "
                "dynamic values are not importable onto a static-shape "
                "compiler)" % name)
        return self.params[name]


def _symmetric_pads(attrs, what, spatial=2):
    """ONNX pads = (x1_begin.. xn_begin, x1_end.. xn_end); only symmetric
    padding maps onto the framework's `pad` attr — raise on the rest
    instead of silently importing wrong geometry."""
    pads = tuple(attrs.get("pads", (0,) * (2 * spatial)))
    if len(pads) == spatial:
        return pads
    if len(pads) == 2 * spatial:
        beg, end = pads[:spatial], pads[spatial:]
        if beg != end:
            raise MXNetError("%s: asymmetric ONNX pads %s are not supported"
                             % (what, (pads,)))
        return beg
    raise MXNetError("%s: unsupported pads rank %d" % (what, len(pads)))


@_imports("Gemm")
def _in_gemm(ctx, inputs, attrs):
    if attrs.get("transA", 0) != 0:
        raise MXNetError("Gemm with transA=1 is not supported")
    data, w = inputs[0], inputs[1]
    b = inputs[2] if len(inputs) > 2 else None
    params = ctx.params
    if w.name not in params:
        raise MXNetError("Gemm: weight '%s' must be a constant initializer"
                         % w.name)
    alpha, beta = attrs.get("alpha", 1.0), attrs.get("beta", 1.0)
    if alpha != 1.0:
        params[w.name] = params[w.name] * _np.float32(alpha)
    if beta != 1.0 and b is not None:
        if b.name not in params:
            raise MXNetError("Gemm with beta=%s needs a constant-"
                             "initializer bias (got computed tensor '%s')"
                             % (beta, b.name))
        params[b.name] = params[b.name] * _np.float32(beta)
    wshape = params[w.name].shape
    if not attrs.get("transB", 0):
        # ONNX default stores weight (K, N); FullyConnected wants (N, K) —
        # transpose the initializer once at import
        params[w.name] = _np.ascontiguousarray(params[w.name].T)
        wshape = params[w.name].shape
    return ctx.sym.FullyConnected(data=data, weight=w, bias=b,
                                  num_hidden=wshape[0], no_bias=b is None)


@_imports("Conv")
def _in_conv(ctx, inputs, attrs):
    kernel = tuple(attrs.get("kernel_shape", ()))
    nsp = len(kernel) or 2
    w = inputs[1]
    b = inputs[2] if len(inputs) > 2 else None
    return ctx.sym.Convolution(
        data=inputs[0], weight=w, bias=b,
        kernel=kernel, stride=tuple(attrs.get("strides", (1,) * nsp)),
        pad=_symmetric_pads(attrs, "Conv", nsp),
        dilate=tuple(attrs.get("dilations", (1,) * nsp)),
        num_group=int(attrs.get("group", 1)),
        num_filter=ctx.params[w.name].shape[0],
        no_bias=b is None)


@_imports("ConvTranspose")
def _in_convtranspose(ctx, inputs, attrs):
    kernel = tuple(attrs.get("kernel_shape", ()))
    nsp = len(kernel) or 2
    if attrs.get("output_padding") or attrs.get("output_shape"):
        raise MXNetError("ConvTranspose with output_padding/output_shape "
                         "is not supported")
    w = inputs[1]
    b = inputs[2] if len(inputs) > 2 else None
    return ctx.sym.Deconvolution(
        data=inputs[0], weight=w, bias=b,
        kernel=kernel, stride=tuple(attrs.get("strides", (1,) * nsp)),
        pad=_symmetric_pads(attrs, "ConvTranspose", nsp),
        dilate=tuple(attrs.get("dilations", (1,) * nsp)),
        num_group=int(attrs.get("group", 1)),
        num_filter=ctx.params[w.name].shape[1] * int(attrs.get("group", 1)),
        no_bias=b is None)


def _pool(ctx, inputs, attrs, pool_type, global_pool=False):
    if global_pool:
        return ctx.sym.Pooling(inputs[0], kernel=(1, 1), global_pool=True,
                               pool_type=pool_type)
    kernel = tuple(attrs["kernel_shape"])
    nsp = len(kernel)
    return ctx.sym.Pooling(
        inputs[0], kernel=kernel,
        stride=tuple(attrs.get("strides", (1,) * nsp)),
        pad=_symmetric_pads(attrs, "Pool", nsp), pool_type=pool_type,
        pooling_convention="full" if attrs.get("ceil_mode") else "valid",
        count_include_pad=bool(attrs.get("count_include_pad", 0)))


@_imports("MaxPool")
def _in_maxpool(ctx, inputs, attrs):
    return _pool(ctx, inputs, attrs, "max")


@_imports("AveragePool")
def _in_avgpool(ctx, inputs, attrs):
    return _pool(ctx, inputs, attrs, "avg")


@_imports("GlobalAveragePool")
def _in_gavgpool(ctx, inputs, attrs):
    return _pool(ctx, inputs, attrs, "avg", global_pool=True)


@_imports("GlobalMaxPool")
def _in_gmaxpool(ctx, inputs, attrs):
    return _pool(ctx, inputs, attrs, "max", global_pool=True)


@_imports("BatchNormalization", "SpatialBN")
def _in_bn(ctx, inputs, attrs):
    # fix_gamma=False is essential: the mx op DEFAULT (True) would silently
    # replace the imported scale tensor with ones — correct only for
    # untrained nets, which is exactly why a test on fresh weights can't
    # catch it (found by the trained-model drive)
    return ctx.sym.BatchNorm(data=inputs[0], gamma=inputs[1], beta=inputs[2],
                             moving_mean=inputs[3], moving_var=inputs[4],
                             eps=attrs.get("epsilon", 1e-5),
                             momentum=attrs.get("momentum", 0.9),
                             fix_gamma=False)


@_imports("LayerNormalization")
def _in_layernorm(ctx, inputs, attrs):
    return ctx.sym.LayerNorm(data=inputs[0], gamma=inputs[1], beta=inputs[2],
                             axis=int(attrs.get("axis", -1)),
                             eps=attrs.get("epsilon", 1e-5))


@_imports("InstanceNormalization")
def _in_instancenorm(ctx, inputs, attrs):
    return ctx.sym.InstanceNorm(data=inputs[0], gamma=inputs[1],
                                beta=inputs[2],
                                eps=attrs.get("epsilon", 1e-5))


@_imports("LRN")
def _in_lrn(ctx, inputs, attrs):
    return ctx.sym.LRN(inputs[0], nsize=int(attrs.get("size", 5)),
                       alpha=attrs.get("alpha", 1e-4),
                       beta=attrs.get("beta", 0.75),
                       knorm=attrs.get("bias", 1.0))


@_imports("LpNormalization")
def _in_lpnorm(ctx, inputs, attrs):
    if int(attrs.get("p", 2)) != 2:
        raise MXNetError("LpNormalization: only p=2 maps to "
                         "L2Normalization")
    # axis=1 is mx 'channel'; axis=-1 round-trips mx 'instance' (exact for
    # 2D inputs — the only rank where instance mode is a single-axis norm)
    axis = int(attrs.get("axis", -1))
    return ctx.sym.L2Normalization(
        inputs[0], mode="channel" if axis == 1 else "instance")


# -- activations / unary ----------------------------------------------------

_UNARY = {"Relu": "relu", "Sigmoid": "sigmoid", "Tanh": "tanh",
          "Exp": "exp", "Log": "log", "Sqrt": "sqrt", "Abs": "abs",
          "Neg": "negative", "Erf": "erf", "Ceil": "ceil", "Floor": "floor",
          "Round": "round", "Reciprocal": "reciprocal", "Sin": "sin",
          "Cos": "cos", "Tan": "tan", "Asin": "arcsin", "Acos": "arccos",
          "Atan": "arctan", "Identity": "identity", "Sign": "sign"}


def _register_unary():
    for onnx_name, mx_name in _UNARY.items():
        @_imports(onnx_name)
        def _fn(ctx, inputs, attrs, _mx=mx_name):
            return getattr(ctx.sym, _mx)(inputs[0])


_register_unary()


@_imports("Softplus")
def _in_softplus(ctx, inputs, attrs):
    return ctx.sym.Activation(inputs[0], act_type="softrelu")


@_imports("LeakyRelu")
def _in_leakyrelu(ctx, inputs, attrs):
    return ctx.sym.LeakyReLU(inputs[0], act_type="leaky",
                             slope=attrs.get("alpha", 0.01))


@_imports("Elu")
def _in_elu(ctx, inputs, attrs):
    return ctx.sym.LeakyReLU(inputs[0], act_type="elu",
                             slope=attrs.get("alpha", 1.0))


@_imports("PRelu")
def _in_prelu(ctx, inputs, attrs):
    return ctx.sym.LeakyReLU(inputs[0], gamma=inputs[1], act_type="prelu")


@_imports("Gelu")
def _in_gelu(ctx, inputs, attrs):
    return ctx.sym.LeakyReLU(inputs[0], act_type="gelu")


@_imports("HardSigmoid")
def _in_hardsigmoid(ctx, inputs, attrs):
    return ctx.sym.hard_sigmoid(inputs[0],
                                alpha=attrs.get("alpha", 0.2),
                                beta=attrs.get("beta", 0.5))


@_imports("Clip")
def _in_clip(ctx, inputs, attrs):
    if "min" in attrs or "max" in attrs:      # opset < 11: attributes
        lo, hi = attrs.get("min", -3.4e38), attrs.get("max", 3.4e38)
    else:                                     # opset >= 11: inputs
        lo = float(ctx.const_value(inputs[1])) \
            if len(inputs) > 1 and inputs[1] is not None else -3.4e38
        hi = float(ctx.const_value(inputs[2])) \
            if len(inputs) > 2 and inputs[2] is not None else 3.4e38
    return ctx.sym.clip(inputs[0], a_min=lo, a_max=hi)


@_imports("Softmax")
def _in_softmax(ctx, inputs, attrs):
    return ctx.sym.softmax(inputs[0], axis=attrs.get("axis", -1))


@_imports("LogSoftmax")
def _in_logsoftmax(ctx, inputs, attrs):
    return ctx.sym.log_softmax(inputs[0], axis=attrs.get("axis", -1))


# -- binary / variadic ------------------------------------------------------

_BINARY = {"Add": "broadcast_add", "Sub": "broadcast_sub",
           "Mul": "broadcast_mul", "Div": "broadcast_div",
           "Pow": "broadcast_power", "Max": "broadcast_maximum",
           "Min": "broadcast_minimum"}


def _register_binary():
    for onnx_name, mx_name in _BINARY.items():
        @_imports(onnx_name)
        def _fn(ctx, inputs, attrs, _mx=mx_name):
            out = inputs[0]
            for other in inputs[1:]:          # Max/Min/Sum are variadic
                out = getattr(ctx.sym, _mx)(out, other)
            return out


_register_binary()


@_imports("Sum")
def _in_sum(ctx, inputs, attrs):
    if len(inputs) == 1:
        return ctx.sym.identity(inputs[0])
    return ctx.sym.add_n(*inputs)


@_imports("MatMul")
def _in_matmul(ctx, inputs, attrs):
    return ctx.sym.linalg_gemm2(inputs[0], inputs[1])


@_imports("Where")
def _in_where(ctx, inputs, attrs):
    return ctx.sym.where(inputs[0], inputs[1], inputs[2])


# -- shape / movement -------------------------------------------------------

@_imports("Reshape")
def _in_reshape(ctx, inputs, attrs):
    shape = attrs.get("shape")
    if shape is None:
        # opset >= 5: shape arrives as the 2nd input tensor (initializer)
        if len(inputs) < 2:
            raise MXNetError("Reshape: no shape attribute and no shape "
                             "input")
        shape = ctx.const_value(inputs[1])
    return ctx.sym.Reshape(inputs[0], shape=tuple(int(s) for s in shape))


@_imports("Flatten")
def _in_flatten(ctx, inputs, attrs):
    axis = int(attrs.get("axis", 1))
    if axis == 1:
        return ctx.sym.Flatten(inputs[0])
    raise MXNetError("Flatten with axis=%d is not supported" % axis)


@_imports("Transpose")
def _in_transpose(ctx, inputs, attrs):
    perm = attrs.get("perm")
    return ctx.sym.transpose(inputs[0],
                             axes=tuple(perm) if perm is not None else ())


def _axes_arg(ctx, inputs, attrs, idx=1):
    axes = attrs.get("axes")
    if axes is None and len(inputs) > idx and inputs[idx] is not None:
        axes = [int(a) for a in ctx.const_value(inputs[idx])]
    return axes


@_imports("Unsqueeze")
def _in_unsqueeze(ctx, inputs, attrs):
    axes = _axes_arg(ctx, inputs, attrs)
    out = inputs[0]
    for ax in sorted(int(a) for a in axes):
        out = ctx.sym.expand_dims(out, axis=ax)
    return out


@_imports("Squeeze")
def _in_squeeze(ctx, inputs, attrs):
    axes = _axes_arg(ctx, inputs, attrs)
    return ctx.sym.squeeze(inputs[0],
                           axis=tuple(int(a) for a in axes) if axes else None)


@_imports("Slice")
def _in_slice(ctx, inputs, attrs):
    if "starts" in attrs:                      # opset < 10: attributes
        starts = list(attrs["starts"])
        ends = list(attrs["ends"])
        axes = list(attrs.get("axes", range(len(starts))))
        steps = [1] * len(starts)
    else:                                      # opset >= 10: inputs
        starts = [int(v) for v in ctx.const_value(inputs[1])]
        ends = [int(v) for v in ctx.const_value(inputs[2])]
        axes = [int(v) for v in ctx.const_value(inputs[3])] \
            if len(inputs) > 3 and inputs[3] is not None \
            else list(range(len(starts)))
        steps = [int(v) for v in ctx.const_value(inputs[4])] \
            if len(inputs) > 4 and inputs[4] is not None \
            else [1] * len(starts)
    if any(s != 1 for s in steps):
        raise MXNetError("Slice with steps != 1 is not supported")
    out = inputs[0]
    for ax, b, e in zip(axes, starts, ends):
        # ONNX clamps out-of-range ends (INT_MAX idiom) — slice_axis
        # understands None as "to the end"
        out = ctx.sym.slice_axis(out, axis=int(ax), begin=int(b),
                                 end=None if e >= 2 ** 31 - 1 else int(e))
    return out


@_imports("Split")
def _in_split(ctx, inputs, attrs):
    axis = int(attrs.get("axis", 0))
    split = attrs.get("split")
    if split is None and len(inputs) > 1 and inputs[1] is not None:
        split = [int(v) for v in ctx.const_value(inputs[1])]
    if split is not None and len(set(split)) != 1:
        raise MXNetError("Split with unequal parts %s is not supported"
                         % (split,))
    if split is not None:
        n = len(split)
    elif "num_outputs" in attrs:              # opset >= 18 attribute
        n = int(attrs["num_outputs"])
    else:                                     # opset < 18: equal split
        n = int(attrs["_n_outputs"])          # across the node's outputs
    return list(ctx.sym.SliceChannel(inputs[0], num_outputs=n, axis=axis))


@_imports("Concat")
def _in_concat(ctx, inputs, attrs):
    return ctx.sym.Concat(*inputs, dim=int(attrs.get("axis", 1)))


@_imports("Gather")
def _in_gather(ctx, inputs, attrs):
    return ctx.sym.take(inputs[0], inputs[1],
                        axis=int(attrs.get("axis", 0)))


@_imports("Expand")
def _in_expand(ctx, inputs, attrs):
    shape = tuple(int(s) for s in ctx.const_value(inputs[1]))
    return ctx.sym.broadcast_to(inputs[0], shape=shape)


@_imports("Tile")
def _in_tile(ctx, inputs, attrs):
    reps = tuple(int(r) for r in ctx.const_value(inputs[1]))
    return ctx.sym.tile(inputs[0], reps=reps)


@_imports("Pad")
def _in_pad(ctx, inputs, attrs):
    mode = attrs.get("mode", b"constant")
    mode = mode.decode() if isinstance(mode, bytes) else mode
    pads = attrs.get("pads")
    if pads is None:
        pads = [int(v) for v in ctx.const_value(inputs[1])]
    n = len(pads) // 2
    # ONNX layout (b1..bn, e1..en) -> mx pad_width (b1, e1, b2, e2, ...)
    pad_width = []
    for i in range(n):
        pad_width += [int(pads[i]), int(pads[i + n])]
    value = attrs.get("value", 0.0)
    if len(inputs) > 2 and inputs[2] is not None:
        value = float(ctx.const_value(inputs[2]))
    return ctx.sym.Pad(inputs[0], mode="edge" if mode == "edge" else mode,
                       pad_width=tuple(pad_width), constant_value=value)


@_imports("Cast")
def _in_cast(ctx, inputs, attrs):
    from .onnx_proto import _ONNX_TO_NP

    to = int(attrs["to"])
    if to not in _ONNX_TO_NP:
        raise MXNetError("Cast: unsupported ONNX dtype %d" % to)
    return ctx.sym.Cast(inputs[0], dtype=_ONNX_TO_NP[to].name)


@_imports("Constant")
def _in_constant(ctx, inputs, attrs, _counter=[0]):
    _, helper, numpy_helper, _TP = _onnx_impl()

    tensor = attrs.get("value")
    if tensor is None:
        raise MXNetError("Constant without a `value` tensor attribute is "
                         "not supported")
    arr = _np.asarray(numpy_helper.to_array(tensor))
    _counter[0] += 1
    name = "_onnx_const_%d" % _counter[0]
    ctx.params[name] = arr
    return ctx.sym.var(name)


# -- reductions -------------------------------------------------------------

_REDUCE = {"ReduceMean": "mean", "ReduceSum": "sum", "ReduceMax": "max",
           "ReduceMin": "min", "ReduceProd": "prod"}


def _register_reduce():
    for onnx_name, mx_name in _REDUCE.items():
        @_imports(onnx_name)
        def _fn(ctx, inputs, attrs, _mx=mx_name):
            axes = _axes_arg(ctx, inputs, attrs)
            return getattr(ctx.sym, _mx)(
                inputs[0],
                axis=tuple(int(a) for a in axes) if axes else None,
                keepdims=bool(attrs.get("keepdims", 1)))


_register_reduce()


@_imports("ArgMax")
def _in_argmax(ctx, inputs, attrs):
    return ctx.sym.argmax(inputs[0], axis=int(attrs.get("axis", 0)),
                          keepdims=bool(attrs.get("keepdims", 1)))


@_imports("ArgMin")
def _in_argmin(ctx, inputs, attrs):
    return ctx.sym.argmin(inputs[0], axis=int(attrs.get("axis", 0)),
                          keepdims=bool(attrs.get("keepdims", 1)))


@_imports("Dropout")
def _in_dropout(ctx, inputs, attrs):
    return ctx.sym.Dropout(inputs[0], p=attrs.get("ratio", 0.5))


def import_model(model_file):
    """ONNX file -> (sym, arg_params, aux_params) (reference:
    onnx2mx/import_model.py:24)."""
    onnx, helper, numpy_helper, _TP = _onnx_impl()

    from .. import ndarray as nd
    from .. import symbol as sym_mod

    model = onnx.load(model_file)
    graph = model.graph
    opset = max([o.version for o in model.opset_import] or [13])
    params = {init.name: _np.asarray(numpy_helper.to_array(init))
              for init in graph.initializer}
    ctx = _ImportCtx(sym_mod, params, opset)
    tensors = {}
    for inp in graph.input:
        if inp.name not in params:
            tensors[inp.name] = sym_mod.var(inp.name)

    def get_attrs(node):
        out = {}
        for a in node.attribute:
            out[a.name] = helper.get_attribute_value(a)
        return out

    for node in graph.node:
        if node.op_type not in _IMPORT_OPS:
            raise MXNetError("ONNX op '%s' is not supported by the importer"
                             % node.op_type)
        ins = []
        for i in node.input:
            if not i:
                # empty string = omitted optional input (ONNX idiom);
                # keep the positional slot as None so later inputs don't
                # shift into the wrong argument positions
                ins.append(None)
                continue
            if i not in tensors:
                tensors[i] = sym_mod.var(i)   # lazily materialize params
            ins.append(tensors[i])
        while ins and ins[-1] is None:
            ins.pop()
        attrs = get_attrs(node)
        attrs["_n_outputs"] = len(node.output)
        out = _IMPORT_OPS[node.op_type](ctx, ins, attrs)
        outs = [out] if not isinstance(out, (list, tuple)) else out
        for name, o in zip(node.output, outs):
            tensors[name] = o
    outputs = [tensors[o.name] for o in graph.output]
    final = outputs[0] if len(outputs) == 1 else sym_mod.Group(outputs)
    arg_names = set(final.list_arguments())
    aux_names = set(final.list_auxiliary_states())
    arg_params = {k: nd.array(v) for k, v in params.items() if k in arg_names}
    aux_params = {k: nd.array(v) for k, v in params.items() if k in aux_names}
    return final, arg_params, aux_params


def get_model_metadata(model_file):
    onnx, _h, _nh, _TP = _onnx_impl()

    model = onnx.load(model_file)
    init = {i.name for i in model.graph.initializer}
    return {
        "input_tensor_data": [(i.name, tuple(d.dim_value for d in
                                             i.type.tensor_type.shape.dim))
                              for i in model.graph.input if i.name not in init],
        "output_tensor_data": [(o.name, tuple(d.dim_value for d in
                                              o.type.tensor_type.shape.dim))
                               for o in model.graph.output],
    }


# ===========================================================================
# export: Symbol -> ONNX graph
# ===========================================================================

_EXPORT_OPS = {}


def _exports(*names):
    def deco(fn):
        for n in names:
            _EXPORT_OPS[n] = fn
        return fn

    return deco


class _ExportCtx:
    """Per-export state handed to each op converter: node emission,
    initializer registration, and fresh-name generation."""

    def __init__(self, helper, numpy_helper, TensorProto):
        self.helper = helper
        self.numpy_helper = numpy_helper
        self.TensorProto = TensorProto
        self.nodes = []
        self.initializers = []
        self._n = 0

    def add(self, op_type, ins, outs, **attrs):
        self.nodes.append(self.helper.make_node(op_type, ins, outs, **attrs))
        return outs[0]

    def init(self, base, arr):
        """Register a constant initializer, return its name."""
        self._n += 1
        name = "%s_c%d" % (base, self._n)
        self.initializers.append(
            self.numpy_helper.from_array(_np.asarray(arr), name))
        return name

    def tmp(self, base):
        self._n += 1
        return "%s_t%d" % (base, self._n)


def _t2(v, default=(1, 1)):
    return list(v) if v else list(default)


@_exports("FullyConnected")
def _ex_fc(ctx, name, ins, a):
    if a.get("flatten", True) in (True, "True", 1):
        gemm_ins = ins[:3] if not a.get("no_bias") else ins[:2]
        ctx.add("Gemm", gemm_ins, [name], transB=1)
    else:
        # 3D dense (transformer projections): MatMul against W^T (+ bias)
        wt = ctx.tmp(name)
        ctx.add("Transpose", [ins[1]], [wt], perm=[1, 0])
        if a.get("no_bias"):
            ctx.add("MatMul", [ins[0], wt], [name])
        else:
            mm = ctx.tmp(name)
            ctx.add("MatMul", [ins[0], wt], [mm])
            ctx.add("Add", [mm, ins[2]], [name])


@_exports("Convolution")
def _ex_conv(ctx, name, ins, a):
    ctx.add("Conv", ins[:3] if not a.get("no_bias") else ins[:2], [name],
            kernel_shape=list(a.get("kernel", ())),
            strides=_t2(a.get("stride")),
            pads=list(a.get("pad", (0, 0)) or (0, 0)) * 2,
            dilations=_t2(a.get("dilate")),
            group=int(a.get("num_group", 1) or 1))


@_exports("Deconvolution")
def _ex_deconv(ctx, name, ins, a):
    ctx.add("ConvTranspose", ins[:3] if not a.get("no_bias") else ins[:2],
            [name],
            kernel_shape=list(a.get("kernel", ())),
            strides=_t2(a.get("stride")),
            pads=list(a.get("pad", (0, 0)) or (0, 0)) * 2,
            dilations=_t2(a.get("dilate")),
            group=int(a.get("num_group", 1) or 1))


@_exports("Activation")
def _ex_activation(ctx, name, ins, a):
    kind = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
            "softrelu": "Softplus", "softsign": "Softsign"}.get(
                a.get("act_type", "relu"))
    if kind is None:
        raise MXNetError("ONNX export: Activation act_type=%r not supported"
                         % a.get("act_type"))
    ctx.add(kind, ins[:1], [name])


_EX_UNARY = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
             "exp": "Exp", "log": "Log", "sqrt": "Sqrt", "abs": "Abs",
             "negative": "Neg", "erf": "Erf", "ceil": "Ceil",
             "floor": "Floor", "round": "Round", "reciprocal": "Reciprocal",
             "sin": "Sin", "cos": "Cos", "tan": "Tan", "arcsin": "Asin",
             "arccos": "Acos", "arctan": "Atan", "identity": "Identity",
             "_copy": "Identity", "BlockGrad": "Identity",
             "stop_gradient": "Identity", "sign": "Sign"}


def _register_ex_unary():
    for mx_name, onnx_name in _EX_UNARY.items():
        @_exports(mx_name)
        def _fn(ctx, name, ins, a, _onnx=onnx_name):
            ctx.add(_onnx, ins[:1], [name])


_register_ex_unary()


@_exports("LeakyReLU")
def _ex_leakyrelu(ctx, name, ins, a):
    kind = a.get("act_type", "leaky")
    if kind == "leaky":
        ctx.add("LeakyRelu", ins[:1], [name],
                alpha=float(a.get("slope", 0.25)))
    elif kind == "elu":
        ctx.add("Elu", ins[:1], [name], alpha=float(a.get("slope", 1.0)))
    elif kind == "prelu":
        ctx.add("PRelu", ins[:2], [name])
    elif kind == "gelu":
        # exact GELU decomposition: 0.5 * x * (1 + erf(x / sqrt(2)))
        x = ins[0]
        div = ctx.add("Div", [x, ctx.init(name, _np.float32(_np.sqrt(2.0)))],
                      [ctx.tmp(name)])
        erf = ctx.add("Erf", [div], [ctx.tmp(name)])
        one = ctx.add("Add", [erf, ctx.init(name, _np.float32(1.0))],
                      [ctx.tmp(name)])
        half = ctx.add("Mul", [x, one], [ctx.tmp(name)])
        ctx.add("Mul", [half, ctx.init(name, _np.float32(0.5))], [name])
    else:
        raise MXNetError("ONNX export: LeakyReLU act_type=%r not supported"
                         % kind)


@_exports("square")
def _ex_square(ctx, name, ins, a):
    ctx.add("Mul", [ins[0], ins[0]], [name])


@_exports("clip")
def _ex_clip(ctx, name, ins, a):
    ctx.add("Clip",
            [ins[0], ctx.init(name, _np.float32(a.get("a_min", 0.0))),
             ctx.init(name, _np.float32(a.get("a_max", 1.0)))], [name])


@_exports("Pooling")
def _ex_pooling(ctx, name, ins, a):
    ptype = a.get("pool_type", "max")
    if ptype not in ("max", "avg"):
        raise MXNetError("ONNX export: pool_type=%r not supported" % ptype)
    if a.get("global_pool"):
        ctx.add("GlobalMaxPool" if ptype == "max" else "GlobalAveragePool",
                ins[:1], [name])
        return
    kw = dict(kernel_shape=list(a.get("kernel", ())),
              strides=_t2(a.get("stride")),
              pads=list(a.get("pad", (0, 0)) or (0, 0)) * 2)
    if a.get("pooling_convention") == "full":
        kw["ceil_mode"] = 1
    if ptype == "avg":
        kw["count_include_pad"] = 1 if a.get("count_include_pad", True) \
            else 0
    ctx.add("MaxPool" if ptype == "max" else "AveragePool", ins[:1],
            [name], **kw)


@_exports("BatchNorm")
def _ex_bn(ctx, name, ins, a):
    gamma = ins[1]
    if a.get("fix_gamma", True) in (True, "True", 1):
        # mx semantics: gamma forced to 1 regardless of the stored tensor;
        # ONNX has no such flag, so export a ones scale initializer
        gamma = ctx.add("Sub", [ins[1], ins[1]], [ctx.tmp(name)])
        gamma = ctx.add("Add",
                        [gamma, ctx.init(name, _np.float32(1.0))],
                        [ctx.tmp(name)])
    ctx.add("BatchNormalization", [ins[0], gamma] + ins[2:5], [name],
            # note: the mx BatchNorm op default eps is 1e-3 (reference
            # batch_norm.cc), not ONNX's 1e-5 — export must use the op's
            # default when the attr is absent
            epsilon=float(a.get("eps", 1e-3)),
            momentum=float(a.get("momentum", 0.9)))


@_exports("LayerNorm")
def _ex_layernorm(ctx, name, ins, a):
    ctx.add("LayerNormalization", ins[:3], [name],
            axis=int(a.get("axis", -1)), epsilon=float(a.get("eps", 1e-5)))


@_exports("InstanceNorm")
def _ex_instancenorm(ctx, name, ins, a):
    ctx.add("InstanceNormalization", ins[:3], [name],
            epsilon=float(a.get("eps", 1e-3)))


@_exports("LRN")
def _ex_lrn(ctx, name, ins, a):
    ctx.add("LRN", ins[:1], [name], size=int(a.get("nsize", 5)),
            alpha=float(a.get("alpha", 1e-4)),
            beta=float(a.get("beta", 0.75)),
            bias=float(a.get("knorm", 2.0)))


@_exports("L2Normalization")
def _ex_l2norm(ctx, name, ins, a):
    if a.get("mode", "instance") not in ("instance", "channel"):
        raise MXNetError("L2Normalization mode=%r not exportable"
                         % a.get("mode"))
    ctx.add("LpNormalization", ins[:1], [name], p=2,
            axis=1 if a.get("mode") == "channel" else -1)


@_exports("Flatten", "flatten")
def _ex_flatten(ctx, name, ins, a):
    ctx.add("Flatten", ins[:1], [name])


@_exports("softmax", "SoftmaxOutput", "SoftmaxActivation")
def _ex_softmax(ctx, name, ins, a):
    ctx.add("Softmax", ins[:1], [name], axis=int(a.get("axis", -1)))


@_exports("log_softmax")
def _ex_logsoftmax(ctx, name, ins, a):
    ctx.add("LogSoftmax", ins[:1], [name], axis=int(a.get("axis", -1)))


_EX_BINARY = {"elemwise_add": "Add", "elemwise_sub": "Sub",
              "elemwise_mul": "Mul", "elemwise_div": "Div",
              "broadcast_add": "Add", "broadcast_sub": "Sub",
              "broadcast_mul": "Mul", "broadcast_div": "Div",
              "broadcast_power": "Pow", "_power": "Pow",
              "broadcast_maximum": "Max", "broadcast_minimum": "Min",
              "maximum": "Max", "minimum": "Min", "dot": "MatMul"}


def _register_ex_binary():
    for mx_name, onnx_name in _EX_BINARY.items():
        @_exports(mx_name)
        def _fn(ctx, name, ins, a, _onnx=onnx_name):
            ctx.add(_onnx, ins[:2], [name])


_register_ex_binary()


@_exports("add_n")
def _ex_addn(ctx, name, ins, a):
    ctx.add("Sum", ins, [name])


_EX_SCALAR = {"_plus_scalar": ("Add", False), "_minus_scalar": ("Sub", False),
              "_rminus_scalar": ("Sub", True), "_mul_scalar": ("Mul", False),
              "_div_scalar": ("Div", False), "_rdiv_scalar": ("Div", True),
              "_power_scalar": ("Pow", False)}


def _register_ex_scalar():
    for mx_name, (onnx_name, rev) in _EX_SCALAR.items():
        @_exports(mx_name)
        def _fn(ctx, name, ins, a, _onnx=onnx_name, _rev=rev):
            c = ctx.init(name, _np.float32(a.get("scalar", 0.0)))
            pair = [c, ins[0]] if _rev else [ins[0], c]
            ctx.add(_onnx, pair, [name])


_register_ex_scalar()


@_exports("batch_dot")
def _ex_batchdot(ctx, name, ins, a):
    lhs, rhs = ins[0], ins[1]
    if a.get("transpose_a"):
        raise MXNetError("batch_dot transpose_a export is not supported")
    if a.get("transpose_b"):
        # rank known to be 3 for batch_dot
        rt = ctx.tmp(name)
        ctx.add("Transpose", [rhs], [rt], perm=[0, 2, 1])
        rhs = rt
    ctx.add("MatMul", [lhs, rhs], [name])


@_exports("linalg_gemm2", "_linalg_gemm2")
def _ex_gemm2(ctx, name, ins, a):
    if a.get("transpose_a") or a.get("transpose_b") or \
            a.get("alpha", 1.0) != 1.0:
        raise MXNetError("linalg_gemm2 with transpose/alpha is not "
                         "exportable")
    ctx.add("MatMul", ins[:2], [name])


@_exports("where")
def _ex_where(ctx, name, ins, a):
    cond = ctx.tmp(name)
    ctx.add("Cast", [ins[0]], [cond], to=9)   # BOOL
    ctx.add("Where", [cond, ins[1], ins[2]], [name])


@_exports("Reshape", "reshape")
def _ex_reshape(ctx, name, ins, a):
    # mx 0/-1 special values match ONNX Reshape semantics (allowzero=0)
    shape = ctx.init(name, _np.asarray(a.get("shape", ()), _np.int64))
    ctx.add("Reshape", [ins[0], shape], [name])


@_exports("transpose")
def _ex_transpose(ctx, name, ins, a):
    axes = a.get("axes")
    if axes:
        ctx.add("Transpose", ins[:1], [name], perm=list(axes))
    else:
        ctx.add("Transpose", ins[:1], [name])


@_exports("expand_dims")
def _ex_expanddims(ctx, name, ins, a):
    axes = ctx.init(name, _np.asarray([int(a.get("axis", 0))], _np.int64))
    ctx.add("Unsqueeze", [ins[0], axes], [name])


@_exports("squeeze")
def _ex_squeeze(ctx, name, ins, a):
    ax = a.get("axis")
    if ax is None:
        ctx.add("Squeeze", ins[:1], [name])
    else:
        ax = [ax] if isinstance(ax, int) else list(ax)
        axes = ctx.init(name, _np.asarray(ax, _np.int64))
        ctx.add("Squeeze", [ins[0], axes], [name])


@_exports("slice_axis")
def _ex_sliceaxis(ctx, name, ins, a):
    end = a.get("end")
    ctx.add("Slice",
            [ins[0],
             ctx.init(name, _np.asarray([int(a.get("begin", 0))], _np.int64)),
             ctx.init(name, _np.asarray(
                 [2 ** 31 - 1 if end is None else int(end)], _np.int64)),
             ctx.init(name, _np.asarray([int(a.get("axis", 0))], _np.int64))],
            [name])


@_exports("SliceChannel", "split")
def _ex_split(ctx, name, ins, a, outs=None):
    n = int(a.get("num_outputs", 1))
    outs = outs or [name] + ["%s_out%d" % (name, i) for i in range(1, n)]
    if a.get("squeeze_axis"):
        raise MXNetError("SliceChannel squeeze_axis export not supported")
    ctx.nodes.append(ctx.helper.make_node(
        "Split", [ins[0]], outs, axis=int(a.get("axis", 1))))


@_exports("Concat", "concat")
def _ex_concat(ctx, name, ins, a):
    ctx.add("Concat", ins, [name], axis=int(a.get("dim", 1)))


@_exports("Embedding")
def _ex_embedding(ctx, name, ins, a):
    # Gather(weight, indices): data-first argument order flips
    ctx.add("Gather", [ins[1], ins[0]], [name], axis=0)


@_exports("take")
def _ex_take(ctx, name, ins, a):
    if a.get("mode", "clip") not in ("clip", "raise"):
        raise MXNetError("take mode=%r not exportable" % a.get("mode"))
    ctx.add("Gather", ins[:2], [name], axis=int(a.get("axis", 0)))


@_exports("broadcast_to")
def _ex_broadcastto(ctx, name, ins, a):
    shape = ctx.init(name, _np.asarray(a.get("shape", ()), _np.int64))
    ctx.add("Expand", [ins[0], shape], [name])


@_exports("tile")
def _ex_tile(ctx, name, ins, a):
    reps = ctx.init(name, _np.asarray(a.get("reps", ()), _np.int64))
    ctx.add("Tile", [ins[0], reps], [name])


@_exports("Pad", "pad")
def _ex_pad(ctx, name, ins, a):
    pw = list(a.get("pad_width", ()))
    n = len(pw) // 2
    # mx (b1, e1, b2, e2, ...) -> ONNX (b1..bn, e1..en)
    pads = [pw[2 * i] for i in range(n)] + [pw[2 * i + 1] for i in range(n)]
    mode = a.get("mode", "constant")
    ctx.add("Pad",
            [ins[0], ctx.init(name, _np.asarray(pads, _np.int64)),
             ctx.init(name, _np.float32(a.get("constant_value", 0.0)))],
            [name], mode="edge" if mode == "edge" else mode)


@_exports("Cast")
def _ex_cast(ctx, name, ins, a):
    from .onnx_proto import _NP_TO_ONNX

    dt = _np.dtype(a.get("dtype", "float32"))
    if dt not in _NP_TO_ONNX:
        raise MXNetError("Cast dtype %s not exportable" % dt)
    ctx.add("Cast", ins[:1], [name], to=int(_NP_TO_ONNX[dt]))


@_exports("Dropout")
def _ex_dropout(ctx, name, ins, a):
    ctx.add("Dropout", ins[:1], [name])


def _register_ex_reduce():
    for mx_name, onnx_name in [("mean", "ReduceMean"), ("sum", "ReduceSum"),
                               ("max", "ReduceMax"), ("min", "ReduceMin"),
                               ("prod", "ReduceProd")]:
        @_exports(mx_name)
        def _fn(ctx, name, ins, a, _onnx=onnx_name):
            ax = a.get("axis")
            kw = {"keepdims": 1 if a.get("keepdims") else 0}
            if _onnx == "ReduceSum":
                # opset 13 moved ReduceSum axes to an input
                extra = [] if ax is None else \
                    [ctx.init(name, _np.asarray(
                        [ax] if isinstance(ax, int) else list(ax),
                        _np.int64))]
                ctx.add(_onnx, ins[:1] + extra, [name], **kw)
            else:
                if ax is not None:
                    kw["axes"] = [ax] if isinstance(ax, int) else list(ax)
                ctx.add(_onnx, ins[:1], [name], **kw)


_register_ex_reduce()


@_exports("argmax")
def _ex_argmax(ctx, name, ins, a):
    ctx.add("ArgMax", ins[:1], [name], axis=int(a.get("axis", 0) or 0),
            keepdims=1 if a.get("keepdims") else 0)


@_exports("zeros_like")
def _ex_zeroslike(ctx, name, ins, a):
    ctx.add("Sub", [ins[0], ins[0]], [name])


@_exports("ones_like")
def _ex_oneslike(ctx, name, ins, a):
    z = ctx.add("Sub", [ins[0], ins[0]], [ctx.tmp(name)])
    ctx.add("Add", [z, ctx.init(name, _np.float32(1.0))], [name])


@_exports("argmin")
def _ex_argmin(ctx, name, ins, a):
    ctx.add("ArgMin", ins[:1], [name], axis=int(a.get("axis", 0) or 0),
            keepdims=1 if a.get("keepdims") else 0)


def export_model(sym, params, input_shape, input_type=_np.float32,
                 onnx_file_path="model.onnx", verbose=False):
    """Symbol + params -> ONNX file (reference: mx2onnx/export_model.py:35).
    Op coverage mirrors the reference mx2onnx table over the in-tree model
    zoo (see module docstring for the documented divergences)."""
    onnx, helper, numpy_helper, TensorProto = _onnx_impl()

    params = {k.split(":", 1)[-1]: (v.asnumpy() if hasattr(v, "asnumpy")
                                    else _np.asarray(v))
              for k, v in params.items()}
    ctx = _ExportCtx(helper, numpy_helper, TensorProto)
    name_of = {}

    def edge_name(node, idx):
        base = name_of[id(node)]
        return base if idx == 0 else "%s_out%d" % (base, idx)

    topo = list(sym._topo())
    inputs_proto = []
    for node in topo:
        if node.is_var:
            name_of[id(node)] = node.name
            if node.name in params:
                ctx.initializers.append(
                    numpy_helper.from_array(
                        _np.ascontiguousarray(params[node.name]), node.name))
            else:
                shape = list(input_shape) if not isinstance(input_shape, dict) \
                    else list(input_shape[node.name])
                from .onnx_proto import _NP_TO_ONNX

                elem = int(_NP_TO_ONNX.get(_np.dtype(input_type),
                                           TensorProto.FLOAT))
                inputs_proto.append(helper.make_tensor_value_info(
                    node.name, elem, shape))
            continue
        name_of[id(node)] = node.name
        ins = [edge_name(s, i) for s, i in node.inputs]
        fn = _EXPORT_OPS.get(node.op)
        if fn is None:
            raise MXNetError(
                "ONNX export: op '%s' not supported (covered ops: %d; "
                "MultiBox*/nms and fused RNN have no ONNX mapping — use "
                "Predictor.export_compiled for those models)"
                % (node.op, len(_EXPORT_OPS)))
        fn(ctx, node.name, ins, node.attrs)

    out_names = [edge_name(n, i) for n, i in sym._outputs]
    graph = helper.make_graph(
        ctx.nodes, "mxnet_tpu_model", inputs_proto,
        [helper.make_tensor_value_info(n, TensorProto.FLOAT, None)
         for n in out_names],
        initializer=ctx.initializers)
    if _is_shim(onnx):
        model = helper.make_model(graph, opset_version=17)
    else:
        model = helper.make_model(
            graph, opset_imports=[helper.make_opsetid("", 17)])
    onnx.save(model, onnx_file_path)
    return onnx_file_path


def _is_shim(onnx_mod):
    return getattr(onnx_mod, "__version__", "").startswith("shim")
