"""Legacy alias: contrib op functions under mx.contrib.ndarray
(reference: python/mxnet/contrib/ndarray.py — the registration namespace
old scripts import; the same functions live on mx.nd.contrib)."""


def __getattr__(name):
    from .. import ndarray as _nd

    return getattr(_nd.contrib, name)


def __dir__():
    from .. import ndarray as _nd

    return sorted(set(dir(_nd.contrib)))
