"""Contrib IO (reference: python/mxnet/contrib/io.py DataLoaderIter —
wraps a gluon DataLoader as a module-style DataIter)."""
from __future__ import annotations

from ..io import DataBatch, DataDesc, DataIter

__all__ = ["DataLoaderIter"]


class DataLoaderIter(DataIter):
    """reference: contrib/io.py DataLoaderIter."""

    def __init__(self, loader, data_name="data", label_name="softmax_label"):
        super().__init__()
        self._loader = loader
        self._iter = iter(loader)
        self._data_name = data_name
        self._label_name = label_name
        self._current = None

    @property
    def provide_data(self):
        batch = self._peek()
        if batch is None:
            return []
        data = batch[0] if isinstance(batch, (list, tuple)) else batch
        return [DataDesc(self._data_name, data.shape, data.dtype)]

    @property
    def provide_label(self):
        batch = self._peek()
        if batch is None or not isinstance(batch, (list, tuple)) \
                or len(batch) < 2:
            return []
        label = batch[1]
        return [DataDesc(self._label_name, label.shape, label.dtype)]

    def _peek(self):
        if self._current is None:
            try:
                self._current = next(self._iter)
            except StopIteration:
                return None
        return self._current

    def reset(self):
        self._iter = iter(self._loader)
        self._current = None

    def next(self):
        batch = self._peek()
        if batch is None:
            raise StopIteration
        self._current = None
        if isinstance(batch, (list, tuple)):
            data, label = [batch[0]], [batch[1]] if len(batch) > 1 else None
        else:
            data, label = [batch], None
        return DataBatch(data=data, label=label)
