"""Model quantization driver.

TPU-native equivalent of the reference's `python/mxnet/contrib/quantization.py`
(`quantize_model` :422 — graph pass src/operator/quantization/
quantize_graph_pass.cc + calibration). The pass rewrites FullyConnected /
Convolution nodes into quantize_v2 -> quantized_* (int8 MXU dot) ->
dequantize chains. Calibration modes:

- 'none'   — runtime min/max per batch (quantize_v2 without calib ranges)
- 'naive'  — exact min/max of each quantized input collected over the
             calibration set (reference: collect_layer_output_min_max)
- 'entropy'— KL-divergence threshold search over layer-output histograms
             (reference: contrib/quantization.py _get_optimal_threshold —
             minimize KL(P||Q) between the clipped fp32 distribution P and
             its 255-bin int8 quantization Q over candidate thresholds)
"""
from __future__ import annotations

import logging

import numpy as _np

from ..base import MXNetError
from ..symbol.symbol import Symbol, _Node

__all__ = ["quantize_model", "quantize_graph", "quantize_params",
           "fold_batch_norm"]


def fold_batch_norm(sym, arg_params, aux_params):
    """Fold inference-mode BatchNorm into the preceding Convolution /
    FullyConnected weights and bias (y = s*(Wx+b-mean)+beta with
    s = gamma/sqrt(var+eps) becomes W'=s*W, b'=s*(b-mean)+beta).

    Deployment pre-pass for int8: with BN folded, conv->relu->pool chains
    quantize into one int8 segment (the reference reaches the same effect
    via its MKLDNN subgraph fusion backend before quantize_graph_pass.cc
    runs). Returns (new_sym, new_arg_params, new_aux_params); the folded
    BN's parameters are dropped from the dicts."""
    params = dict(arg_params)
    auxs = dict(aux_params)

    def value(name):
        v = params.get(name, auxs.get(name))
        if v is None:
            return None
        return _np.asarray(v.asnumpy() if hasattr(v, "asnumpy") else v)

    counts = {}
    for node in sym._topo():
        for e in node.inputs:
            counts[id(e[0])] = counts.get(id(e[0]), 0) + 1
    for n, _ in sym._outputs:
        counts[id(n)] = counts.get(id(n), 0) + 1

    def _axis_matches(bn, conv):
        # folding scales weight dim 0 (output channels); only valid when
        # BN normalizes the conv/FC channel axis
        axis = int(bn.attrs.get("axis", 1))
        if conv.op == "FullyConnected":
            # flatten=True (the default) makes the output 2-D so axis 1 is
            # the hidden axis; with flatten=False only axis=-1 is safe
            if str(conv.attrs.get("flatten", True)) in ("True", "1"):
                return axis in (1, -1)
            return axis == -1
        layout = str(conv.attrs.get("layout") or "NCHW")
        return axis % len(layout) == layout.index("C")

    def _try_fold(node):
        """The folded replacement node, or None when folding is invalid —
        every guard funnels to the shared copy path."""
        prod_edge = node.inputs[0] if node.inputs else None
        prod = prod_edge[0] if prod_edge else None
        if not (node.op == "BatchNorm" and prod is not None
                and prod.op in ("Convolution", "FullyConnected")
                and prod_edge[1] == 0 and counts.get(id(prod)) == 1
                and not node.attrs.get("output_mean_var", False)
                and _axis_matches(node, prod)
                and all(e[0].is_var for e in node.inputs[1:])
                and prod.inputs[1][0].is_var):
            return None
        # every folded-into or dropped parameter var must have exactly ONE
        # consumer: scaling a tied weight or popping shared BN stats would
        # corrupt the other consumers
        if any(counts.get(id(e[0])) != 1
               for e in [prod.inputs[1]] + list(node.inputs[1:5])):
            return None
        g_n, b_n, m_n, v_n = (e[0].name for e in node.inputs[1:5])
        w_name = prod.inputs[1][0].name
        gamma, beta = value(g_n), value(b_n)
        mean, var = value(m_n), value(v_n)
        w = value(w_name)
        no_bias = str(prod.attrs.get("no_bias", False)) in ("True", "1")
        b_edge = None if no_bias or len(prod.inputs) < 3 else prod.inputs[2]
        if b_edge is not None and (not b_edge[0].is_var
                                   or counts.get(id(b_edge[0])) != 1):
            return None
        b_name = b_edge[0].name if b_edge is not None else None
        if any(x is None for x in (gamma, beta, mean, var, w)) or \
                (b_name is not None and value(b_name) is None):
            return None
        # attr defaults MUST mirror the op's execution defaults
        # (ops/nn.py batch_norm: eps=1e-3, fix_gamma=True), or a BN built
        # without explicit attrs folds to a different function
        eps = float(node.attrs.get("eps", 1e-3))
        if str(node.attrs.get("fix_gamma", True)) in ("True", "1"):
            gamma = _np.ones_like(gamma)
        s = gamma / _np.sqrt(var + eps)
        bias = value(b_name) if b_name is not None \
            else _np.zeros(w.shape[0], w.dtype)
        params[w_name] = w * s.reshape((-1,) + (1,) * (w.ndim - 1))
        new_b_name = b_name or (prod.name + "_folded_bias")
        params[new_b_name] = (bias - mean) * s + beta
        for p in (g_n, b_n, m_n, v_n):
            params.pop(p, None)
            auxs.pop(p, None)
        attrs = dict(prod.attrs)
        attrs["no_bias"] = False
        bias_var = _Node(None, new_b_name, {})
        return _Node(prod.op, prod.name, attrs,
                     [(mapping[id(prod.inputs[0][0])], prod.inputs[0][1]),
                      (mapping[id(prod.inputs[1][0])], prod.inputs[1][1]),
                      (bias_var, 0)])

    mapping = {}
    for node in sym._topo():
        if node.is_var:
            n = _Node(None, node.name, dict(node.attrs))
            n._shape, n._dtype = node._shape, node._dtype
            mapping[id(node)] = n
            continue
        folded = _try_fold(node)
        mapping[id(node)] = folded if folded is not None else _Node(
            node.op, node.name, dict(node.attrs),
            [(mapping[id(e[0])], e[1]) for e in node.inputs],
            node.aux_slots)
    new_sym = Symbol([(mapping[id(n)], i) for n, i in sym._outputs])
    return new_sym, params, auxs

_QUANTIZABLE = {"FullyConnected", "Convolution"}

# ops that run IN the int8 domain when fed by a quantized producer
# (reference: FQuantizedOp registrations in quantized_activation.cc,
# quantized_flatten.cc, quantized_pooling.cc, quantized_concat.cc). The
# pass consumes the producer's (int8, min, max) directly, so the graph
# stops dequantizing around relu/flatten/pool/concat nodes.
_INT8_PASSTHROUGH = {
    "Activation": "_contrib_quantized_act",
    "relu": "_contrib_quantized_act",
    "Flatten": "_contrib_quantized_flatten",
    "flatten": "_contrib_quantized_flatten",
    "Pooling": "_contrib_quantized_pooling",
    "Concat": "_contrib_quantized_concat",
    "concat": "_contrib_quantized_concat",
}

# the attrs each quantized passthrough kernel understands
_PASSTHROUGH_KEEP = {
    "_contrib_quantized_act": ("act_type",),
    "_contrib_quantized_flatten": (),
    "_contrib_quantized_pooling": ("kernel", "pool_type", "global_pool",
                                   "stride", "pad", "pooling_convention",
                                   "count_include_pad"),
    "_contrib_quantized_concat": ("dim", "num_args"),
}


def _can_passthrough(node, qop):
    if qop == "_contrib_quantized_act":
        return node.op == "relu" or node.attrs.get("act_type") == "relu"
    if qop == "_contrib_quantized_pooling":
        return node.attrs.get("pool_type", "max") in ("max", "avg")
    return True


def _can_quantize(node):
    """Conv variants the int8 kernel doesn't cover stay fp32 (reference
    skips them in quantize_graph_pass.cc the same way)."""
    if node.op == "Convolution":
        dil = tuple(node.attrs.get("dilate") or (1, 1))
        ng = int(node.attrs.get("num_group") or 1)
        if dil not in ((), (1, 1)) or ng != 1:
            return False
    return True


def _kl_divergence(p, q):
    """KL(P||Q), both unnormalized counts. Each is normalized by its FULL
    mass (not just P's support) so mass Q fails to place where P has it is
    charged — masking+renormalizing Q over P's support would score a
    single-spike P as a perfect match for any Q."""
    p = p.astype(_np.float64)
    q = q.astype(_np.float64)
    psum, qsum = p.sum(), q.sum()
    if psum == 0.0:
        return 0.0
    if qsum == 0.0:
        return _np.inf
    p = p / psum
    q = q / qsum
    mask = p > 0
    return float(_np.sum(p[mask] * _np.log(p[mask] /
                                           _np.maximum(q[mask], 1e-12))))


def _optimal_threshold(hist, amax, num_quantized_bins=255):
    """KL-minimizing symmetric clip threshold from an |value| histogram
    (reference: contrib/quantization.py _get_optimal_threshold — the
    TensorRT-style search: for each candidate bin count i, fold outliers
    into the edge bin to form P, quantize P's support into
    num_quantized_bins to form Q, keep the threshold with least KL)."""
    num_bins = hist.size
    if amax == 0.0 or hist.sum() == 0:
        return amax
    best_div, best_i = _np.inf, num_bins
    hist = hist.astype(_np.float64)
    tail = _np.concatenate([_np.cumsum(hist[::-1])[::-1][1:], [0.0]])
    for i in range(num_quantized_bins, num_bins + 1, 2):
        sliced = hist[:i]
        p = sliced.copy()
        p[i - 1] += tail[i - 1]          # clipped outliers -> edge bin
        idx = _np.arange(i) * num_quantized_bins // i
        # Q is built from the UNFOLDED slice (reference quantization.py
        # _get_optimal_threshold): P carries the clipped-outlier mass in its
        # edge bin but Q cannot represent it, so KL(P||Q) charges each
        # candidate threshold for what it clips. Folding the tail into Q too
        # would make Q==P at i==num_quantized_bins (identity bin map) and the
        # search would degenerate to always picking the smallest threshold.
        counts = _np.bincount(idx, weights=sliced, minlength=num_quantized_bins)
        nz = (p > 0).astype(_np.float64)
        denom = _np.bincount(idx, weights=nz, minlength=num_quantized_bins)
        # expand Q back over P's support: each nonzero source bin gets its
        # quantized bin's mass split evenly over that bin's nonzero sources
        q = _np.where(nz > 0, counts[idx] / _np.maximum(denom[idx], 1.0), 0.0)
        div = _kl_divergence(p, q)
        if div < best_div:
            best_div, best_i = div, i
    return (best_i + 0.5) * amax / num_bins


def _iter_calib(sym, arg_params, aux_params, calib_data, num_calib_examples):
    """Yield lists of per-internal-output numpy arrays per batch."""
    internals = sym.get_internals()
    seen = 0
    for batch in calib_data:
        values = {}
        for name, arr in zip(calib_data.provide_data, batch.data):
            values[name.name if hasattr(name, "name") else name[0]] = arr
        for name, arr in zip(getattr(calib_data, "provide_label", []) or [],
                             batch.label or []):
            values[name.name if hasattr(name, "name") else name[0]] = arr
        values.update(arg_params)
        values.update(aux_params)
        outs, _ = internals._interpret(
            {k: (v._data if hasattr(v, "_data") else v)
             for k, v in values.items()})
        yield [((node, idx), _np.asarray(out))
               for (node, idx), out in zip(internals._outputs, outs)]
        seen += batch.data[0].shape[0]
        if num_calib_examples is not None and seen >= num_calib_examples:
            break
    calib_data.reset()


def _collect_ranges(sym, arg_params, aux_params, calib_data,
                    num_calib_examples, mode, data_names=("data",),
                    label_names=("softmax_label",), num_bins=8001):
    """Run calibration batches through every internal output, returning
    {(node_id, out_idx): (min, max)} (reference:
    _LayerOutputMinMaxCollector / _LayerHistogramCollector)."""
    samples = {}
    if mode != "entropy":
        for batch_outs in _iter_calib(sym, arg_params, aux_params,
                                      calib_data, num_calib_examples):
            for (node, idx), a in batch_outs:
                key = (id(node), idx)
                mn, mx = float(a.min()), float(a.max())
                if key in samples:
                    omn, omx = samples[key]
                    samples[key] = (min(omn, mn), max(omx, mx))
                else:
                    samples[key] = (mn, mx)
        return samples
    # entropy: pass 1 finds each tensor's |max| (fixing its histogram
    # range), pass 2 accumulates histograms, then the KL search picks the
    # clip threshold per tensor
    amax = {}
    for batch_outs in _iter_calib(sym, arg_params, aux_params, calib_data,
                                  num_calib_examples):
        for (node, idx), a in batch_outs:
            key = (id(node), idx)
            m = float(_np.abs(a).max()) if a.size else 0.0
            amax[key] = max(amax.get(key, 0.0), m)
    hists = {k: _np.zeros(num_bins, _np.int64) for k in amax}
    for batch_outs in _iter_calib(sym, arg_params, aux_params, calib_data,
                                  num_calib_examples):
        for (node, idx), a in batch_outs:
            key = (id(node), idx)
            if amax[key] > 0 and a.size:
                h, _ = _np.histogram(_np.abs(a.reshape(-1)), bins=num_bins,
                                     range=(0.0, amax[key]))
                hists[key] += h
    for key in amax:
        thr = _optimal_threshold(hists[key], amax[key])
        samples[key] = (-thr, thr)
    return samples


def quantize_graph(sym, excluded_sym_names=(), calib_ranges=None,
                   weight_ranges=None, quantized_dtype="int8"):
    """Rewrite the graph, returning the quantized Symbol (reference:
    quantize_graph_pass.cc QuantizeGraph)."""
    if quantized_dtype != "int8":
        raise MXNetError("only int8 quantization is supported (reference "
                         "uint8 path is MKLDNN-specific)")
    excluded = set(excluded_sym_names)
    calib_ranges = calib_ranges or {}
    mapping = {}  # id(old node) -> new node
    offline_vars = {}  # weight name -> (qwv, mnv, mxv) var nodes, shared by
    #                    every quantized consumer of that weight (duplicate
    #                    same-named vars would corrupt list_arguments())

    def new_edge(old_node, idx):
        return (mapping[id(old_node)], idx)

    _INT32_PRODUCERS = {"_contrib_quantized_conv",
                        "_contrib_quantized_fully_connected"}

    def int8_sources(deq, name, cal=None):
        """(q, min, max) edges in int8 from a pass-inserted dequantize
        producer. Quantized conv/FC emit an int32 ACCUMULATOR — feeding it
        onward as int8 would wrap — so a requantize (int32 -> int8,
        reference requantize-inl.h) is inserted, calibrated when the
        original edge has a collected range."""
        q_e, mn_e, mx_e = deq.inputs
        if q_e[0].op in _INT32_PRODUCERS:
            attrs = {}
            if cal is not None:
                attrs = {"min_calib_range": cal[0], "max_calib_range": cal[1]}
            rq = _Node("_contrib_requantize", name + "_requantize", attrs,
                       [q_e, mn_e, mx_e])
            return ((rq, 0), (rq, 1), (rq, 2))
        return (q_e, mn_e, mx_e)

    for node in sym._topo():
        if node.is_var:
            n = _Node(None, node.name, dict(node.attrs))
            n._shape, n._dtype = node._shape, node._dtype
            mapping[id(node)] = n
            continue
        if node.op in _QUANTIZABLE and node.name not in excluded \
                and _can_quantize(node):
            data_edge = node.inputs[0]
            w_edge = node.inputs[1]
            no_bias = bool(node.attrs.get("no_bias", False))
            b_edge = None if (no_bias or len(node.inputs) < 3) else node.inputs[2]

            src = new_edge(*data_edge)
            if src[0].op == "_contrib_dequantize" and src[1] == 0:
                # the producer is a dequantize this pass inserted: consume
                # its int8 sources directly instead of paying a
                # dequantize->quantize_v2 round trip (reference: the
                # requantize/dequantize fusion in quantize_graph_pass.cc)
                d_edges = int8_sources(
                    src[0], node.name,
                    calib_ranges.get((id(data_edge[0]), data_edge[1])))
            else:
                cal = calib_ranges.get((id(data_edge[0]), data_edge[1]))
                qattrs = {}
                if cal is not None:
                    qattrs = {"min_calib_range": cal[0],
                              "max_calib_range": cal[1]}
                qdata = _Node("_contrib_quantize_v2",
                              node.name + "_quantize", qattrs, [src])
                d_edges = ((qdata, 0), (qdata, 1), (qdata, 2))
            if w_edge[0].is_var and w_edge[1] == 0:
                # weight is a parameter: quantize OFFLINE. The graph gets
                # `<name>_quantize{,_min,_max}` vars which quantize_params
                # fills from the fp32 weight once, so the compiled step
                # never re-reads fp32 weights or recomputes their ranges
                # (reference: quantize_graph_pass.cc renames the weight
                # entry and _quantize_params materializes it).
                base = w_edge[0].name + "_quantize"
                if base in offline_vars:
                    # weight shared by multiple quantized consumers: reuse
                    # the var triple created for the first one (reference
                    # renames a single shared entry; fresh same-named vars
                    # here would yield duplicate argument names)
                    qwv, mnv, mxv = offline_vars[base]
                else:
                    qwv = _Node(None, base, {})
                    if w_edge[0]._shape is not None:
                        qwv._shape = w_edge[0]._shape
                    qwv._dtype = _np.int8
                    mnv = _Node(None, base + "_min", {})
                    mxv = _Node(None, base + "_max", {})
                    mnv._shape = mxv._shape = (1,)
                    mnv._dtype = mxv._dtype = _np.float32
                    offline_vars[base] = (qwv, mnv, mxv)
                w_edges = ((qwv, 0), (mnv, 0), (mxv, 0))
            else:
                # computed weight (rare): quantize at runtime
                qweight = _Node("_contrib_quantize_v2",
                                node.name + "_qweight", {},
                                [new_edge(*w_edge)])
                w_edges = ((qweight, 0), (qweight, 1), (qweight, 2))
            qop = "_contrib_quantized_fully_connected" \
                if node.op == "FullyConnected" else "_contrib_quantized_conv"
            qin = [d_edges[0], w_edges[0]]
            # bias (fp32; quantized inside the op) or a zero placeholder
            if b_edge is not None:
                qin.append(new_edge(*b_edge))
            # only the attrs the quantized kernels understand survive
            # (reference filters the same way in quantize_graph_pass.cc)
            keep = ("num_hidden", "no_bias", "flatten") \
                if node.op == "FullyConnected" \
                else ("kernel", "stride", "pad", "num_filter", "no_bias")
            attrs = {k: v for k, v in node.attrs.items() if k in keep}
            attrs["no_bias"] = b_edge is None
            if b_edge is None:
                # quantized op signature has a bias slot; reuse weight as a
                # dummy — no_bias=True means it is never read
                qin.append(w_edges[0])
            qin += [d_edges[1], d_edges[2], w_edges[1], w_edges[2]]
            qnode = _Node(qop, node.name + "_quantized", attrs, qin)
            deq = _Node("_contrib_dequantize", node.name + "_dequantize", {},
                        [(qnode, 0), (qnode, 1), (qnode, 2)])
            mapping[id(node)] = deq
        elif (node.op in _INT8_PASSTHROUGH and node.name not in excluded
              and _can_passthrough(node, _INT8_PASSTHROUGH[node.op])
              and all(mapping[id(e[0])].op == "_contrib_dequantize"
                      and e[1] == 0 for e in node.inputs)):
            qop = _INT8_PASSTHROUGH[node.op]
            # every producer is a dequantize the pass itself inserted:
            # consume its int8 sources directly and re-wrap the result,
            # keeping the whole segment in the quantized domain (the
            # intermediate dequantize drops out at graph rebuild)
            srcs = [int8_sources(mapping[id(e[0])],
                                 "%s_in%d" % (node.name, i),
                                 calib_ranges.get((id(e[0]), e[1])))
                    for i, e in enumerate(node.inputs)]
            attrs = {k: v for k, v in node.attrs.items()
                     if k in _PASSTHROUGH_KEEP[qop]}
            if qop == "_contrib_quantized_act":
                attrs.setdefault("act_type", "relu")
            if qop == "_contrib_quantized_concat":
                attrs["num_args"] = len(srcs)
                qin = [s[0] for s in srcs]
                for s in srcs:
                    qin += [s[1], s[2]]
            else:
                qin = list(srcs[0])
            qnode = _Node(qop, node.name + "_quantized", attrs, qin)
            deq = _Node("_contrib_dequantize", node.name + "_dequantize", {},
                        [(qnode, 0), (qnode, 1), (qnode, 2)])
            mapping[id(node)] = deq
        else:
            n = _Node(node.op, node.name, dict(node.attrs),
                      [new_edge(*e) for e in node.inputs], node.aux_slots)
            mapping[id(node)] = n
    outs = [(mapping[id(n)], i) for n, i in sym._outputs]
    return Symbol(outs)


def quantize_params(qsym, arg_params):
    """Materialize the offline-quantized weight params a quantize_graph
    symbol expects: for every `<w>_quantize` var, the int8 tensor plus its
    `_min`/`_max` range scalars computed from the fp32 param `<w>`
    (reference: contrib/quantization.py _quantize_params). Params still
    consumed in fp32 (biases, excluded layers) pass through; fp32 weights
    whose only consumer was the quantized op are dropped."""
    from ..ndarray import array as _nd_array

    out = {}
    var_names = [n.name for n in qsym._topo() if n.is_var]
    for name in var_names:
        if name.endswith("_quantize"):
            orig = name[:-len("_quantize")]
            if orig not in arg_params:
                raise MXNetError(
                    "quantize_params: no fp32 source param %r for %r"
                    % (orig, name))
            v = arg_params[orig]
            w = _np.asarray(v.asnumpy() if hasattr(v, "asnumpy") else v,
                            _np.float32)
            mn, mx = float(w.min()), float(w.max())
            scale = 127.0 / max(abs(mn), abs(mx), 1e-20)
            qw = _np.clip(_np.round(w * scale), -127, 127).astype(_np.int8)
            out[name] = _nd_array(qw, dtype="int8")
            out[name + "_min"] = _nd_array(_np.array([mn], _np.float32))
            out[name + "_max"] = _nd_array(_np.array([mx], _np.float32))
        elif not name.endswith(("_quantize_min", "_quantize_max")) \
                and name in arg_params:
            out[name] = arg_params[name]
    return out


def quantize_model(sym, arg_params, aux_params, data_names=("data",),
                   label_names=("softmax_label",), ctx=None,
                   excluded_sym_names=(), calib_mode="none", calib_data=None,
                   num_calib_examples=None, quantized_dtype="int8",
                   logger=logging):
    """reference: contrib/quantization.py:422 quantize_model. Returns
    (quantized_sym, quantized_arg_params, aux_params) — weights are
    quantized OFFLINE into int8 `_quantize` params (+ range scalars) like
    the reference's _quantize_params, so the compiled step reads int8
    weights directly instead of re-quantizing fp32 weights every batch."""
    if calib_mode not in ("none", "naive", "entropy"):
        raise MXNetError("calib_mode must be none/naive/entropy")
    calib_ranges = {}
    if calib_mode != "none":
        if calib_data is None:
            raise MXNetError("calib_data required for calib_mode=%s" % calib_mode)
        arg_j = {k: (v._data if hasattr(v, "_data") else v)
                 for k, v in arg_params.items()}
        aux_j = {k: (v._data if hasattr(v, "_data") else v)
                 for k, v in aux_params.items()}
        calib_ranges = _collect_ranges(sym, arg_j, aux_j, calib_data,
                                       num_calib_examples, calib_mode,
                                       data_names, label_names)
        logger.info("calibrated %d tensors (%s mode)", len(calib_ranges),
                    calib_mode)
    qsym = quantize_graph(sym, excluded_sym_names, calib_ranges,
                          quantized_dtype=quantized_dtype)
    qargs = quantize_params(qsym, arg_params)
    return qsym, qargs, aux_params
