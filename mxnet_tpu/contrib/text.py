"""Text utilities: vocabulary + token embeddings.

TPU-native equivalent of the reference's `python/mxnet/contrib/text/`
(vocab.py Vocabulary, embedding.py TokenEmbedding/CustomEmbedding,
utils.py count_tokens_from_str). Pretrained-embedding downloads are out of
scope (zero egress); `CustomEmbedding` loads local files in the same
`token<space>vec` format.
"""
from __future__ import annotations

import collections

import numpy as _np

from ..base import MXNetError

__all__ = ["count_tokens_from_str", "Vocabulary", "CustomEmbedding"]


def count_tokens_from_str(source_str, token_delim=" ", seq_delim="\n",
                          to_lower=False, counter_to_update=None):
    """reference: contrib/text/utils.py count_tokens_from_str."""
    source_str = source_str.lower() if to_lower else source_str
    counter = counter_to_update if counter_to_update is not None \
        else collections.Counter()
    for seq in filter(None, source_str.split(seq_delim)):
        counter.update(filter(None, seq.split(token_delim)))
    return counter


class Vocabulary:
    """Indexed vocabulary (reference: contrib/text/vocab.py Vocabulary)."""

    def __init__(self, counter=None, most_freq_count=None, min_freq=1,
                 unknown_token="<unk>", reserved_tokens=None):
        if min_freq < 1:
            raise MXNetError("min_freq must be >= 1")
        reserved_tokens = list(reserved_tokens or [])
        if unknown_token in reserved_tokens:
            raise MXNetError("unknown_token cannot also be reserved")
        self._unknown_token = unknown_token
        self._reserved_tokens = reserved_tokens
        self._idx_to_token = [unknown_token] + reserved_tokens
        if counter is not None:
            pairs = sorted(counter.items(), key=lambda kv: (-kv[1], kv[0]))
            if most_freq_count is not None:
                pairs = pairs[:most_freq_count]
            for tok, freq in pairs:
                if freq >= min_freq and tok != unknown_token \
                        and tok not in reserved_tokens:
                    self._idx_to_token.append(tok)
        self._token_to_idx = {t: i for i, t in enumerate(self._idx_to_token)}

    def __len__(self):
        return len(self._idx_to_token)

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_token(self):
        return self._idx_to_token

    @property
    def unknown_token(self):
        return self._unknown_token

    @property
    def reserved_tokens(self):
        return self._reserved_tokens

    def to_indices(self, tokens):
        """reference: vocab.py to_indices."""
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        idx = [self._token_to_idx.get(t, 0) for t in toks]
        return idx[0] if single else idx

    def to_tokens(self, indices):
        single = isinstance(indices, int)
        idxs = [indices] if single else indices
        for i in idxs:
            if not 0 <= i < len(self):
                raise MXNetError("index %d out of vocabulary range" % i)
        toks = [self._idx_to_token[i] for i in idxs]
        return toks[0] if single else toks


class CustomEmbedding:
    """Embedding matrix from a local `token vec...` text file (reference:
    contrib/text/embedding.py CustomEmbedding)."""

    def __init__(self, pretrained_file_path, elem_delim=" ", encoding="utf8",
                 vocabulary=None, init_unknown_vec=None):
        from .. import ndarray as nd

        vectors = {}
        dim = None
        with open(pretrained_file_path, encoding=encoding) as f:
            for line in f:
                parts = line.rstrip().split(elem_delim)
                if len(parts) < 2:
                    continue
                vec = _np.asarray([float(x) for x in parts[1:]],
                                  dtype=_np.float32)
                dim = len(vec) if dim is None else dim
                if len(vec) != dim:
                    raise MXNetError("inconsistent embedding dims in %s"
                                     % pretrained_file_path)
                vectors[parts[0]] = vec
        self.vec_len = dim or 0
        if vocabulary is None:
            vocab = Vocabulary(collections.Counter(vectors.keys()), min_freq=1)
        else:
            vocab = vocabulary
        self.vocabulary = vocab
        table = _np.zeros((len(vocab), self.vec_len), dtype=_np.float32)
        if init_unknown_vec is not None:
            table[0] = _np.asarray(init_unknown_vec, dtype=_np.float32)
        for tok, vec in vectors.items():
            i = vocab.token_to_idx.get(tok)
            if i is not None:
                table[i] = vec
        self.idx_to_vec = nd.array(table)

    def get_vecs_by_tokens(self, tokens):
        from .. import ndarray as nd

        idx = self.vocabulary.to_indices(tokens)
        single = isinstance(idx, int)
        out = self.idx_to_vec[nd.array([idx] if single else idx, dtype="int32")]
        return out[0] if single else out
