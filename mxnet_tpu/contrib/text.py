"""Text utilities: vocabulary + token embeddings.

TPU-native equivalent of the reference's `python/mxnet/contrib/text/`
(vocab.py Vocabulary; embedding.py register/create/
get_pretrained_file_names, _TokenEmbedding, GloVe, FastText,
CustomEmbedding, CompositeEmbedding; utils.py count_tokens_from_str;
_constants.py pretrained-file registry).

Divergence (documented): this build has zero egress, so pretrained files
are never downloaded. `GloVe`/`FastText` resolve
`embedding_root/<embedding_name>/<pretrained_file_name>` on the local
filesystem and raise a clear error telling the user where to place the
file when it is absent (the reference downloads from the Apache repo,
embedding.py:200). File-name registries mirror the reference's
`_constants.py` lists so `get_pretrained_file_names()` returns the same
catalogue.
"""
from __future__ import annotations

import collections
import os
import warnings

import numpy as _np

from ..base import MXNetError

__all__ = ["count_tokens_from_str", "Vocabulary", "register", "create",
           "get_pretrained_file_names", "TokenEmbedding", "GloVe",
           "FastText", "CustomEmbedding", "CompositeEmbedding"]

UNKNOWN_IDX = 0  # reference: contrib/text/_constants.py UNKNOWN_IDX


def count_tokens_from_str(source_str, token_delim=" ", seq_delim="\n",
                          to_lower=False, counter_to_update=None):
    """reference: contrib/text/utils.py count_tokens_from_str."""
    source_str = source_str.lower() if to_lower else source_str
    counter = counter_to_update if counter_to_update is not None \
        else collections.Counter()
    for seq in filter(None, source_str.split(seq_delim)):
        counter.update(filter(None, seq.split(token_delim)))
    return counter


class Vocabulary:
    """Indexed vocabulary (reference: contrib/text/vocab.py Vocabulary)."""

    def __init__(self, counter=None, most_freq_count=None, min_freq=1,
                 unknown_token="<unk>", reserved_tokens=None):
        if min_freq < 1:
            raise MXNetError("min_freq must be >= 1")
        reserved_tokens = list(reserved_tokens or [])
        if unknown_token in reserved_tokens:
            raise MXNetError("unknown_token cannot also be reserved")
        self._unknown_token = unknown_token
        self._reserved_tokens = reserved_tokens
        self._idx_to_token = [unknown_token] + reserved_tokens
        if counter is not None:
            pairs = sorted(counter.items(), key=lambda kv: (-kv[1], kv[0]))
            if most_freq_count is not None:
                pairs = pairs[:most_freq_count]
            for tok, freq in pairs:
                if freq >= min_freq and tok != unknown_token \
                        and tok not in reserved_tokens:
                    self._idx_to_token.append(tok)
        self._token_to_idx = {t: i for i, t in enumerate(self._idx_to_token)}

    def __len__(self):
        return len(self._idx_to_token)

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_token(self):
        return self._idx_to_token

    @property
    def unknown_token(self):
        return self._unknown_token

    @property
    def reserved_tokens(self):
        return self._reserved_tokens

    def to_indices(self, tokens):
        """reference: vocab.py to_indices."""
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        idx = [self._token_to_idx.get(t, UNKNOWN_IDX) for t in toks]
        return idx[0] if single else idx

    def to_tokens(self, indices):
        single = isinstance(indices, int)
        idxs = [indices] if single else indices
        for i in idxs:
            if not 0 <= i < len(self):
                raise MXNetError("index %d out of vocabulary range" % i)
        toks = [self._idx_to_token[i] for i in idxs]
        return toks[0] if single else toks


# --------------------------------------------------------------------------
# Token-embedding registry (reference: embedding.py register/create/
# get_pretrained_file_names over mxnet.registry)
# --------------------------------------------------------------------------

_EMBEDDING_REGISTRY: dict = {}


def register(embedding_cls):
    """Register a TokenEmbedding subclass under its lower-cased class name
    (reference: embedding.py:40)."""
    if not (isinstance(embedding_cls, type)
            and issubclass(embedding_cls, TokenEmbedding)):
        raise MXNetError("register expects a TokenEmbedding subclass")
    _EMBEDDING_REGISTRY[embedding_cls.__name__.lower()] = embedding_cls
    return embedding_cls


def create(embedding_name, **kwargs):
    """Instantiate a registered embedding by (case-insensitive) name
    (reference: embedding.py:63)."""
    key = embedding_name.lower()
    if key not in _EMBEDDING_REGISTRY:
        raise KeyError(
            "Cannot find `embedding_name` %s. Valid embedding names: %s"
            % (embedding_name, ", ".join(sorted(_EMBEDDING_REGISTRY))))
    return _EMBEDDING_REGISTRY[key](**kwargs)


def get_pretrained_file_names(embedding_name=None):
    """Valid embedding names and their pretrained file names
    (reference: embedding.py:90)."""
    if embedding_name is not None:
        key = embedding_name.lower()
        if key not in _EMBEDDING_REGISTRY:
            raise KeyError(
                "Cannot find `embedding_name` %s. Use "
                "`get_pretrained_file_names(embedding_name=None).keys()` "
                "to get all the valid embedding names." % embedding_name)
        return list(_EMBEDDING_REGISTRY[key].pretrained_file_name_sha1)
    return {name: list(cls.pretrained_file_name_sha1)
            for name, cls in _EMBEDDING_REGISTRY.items()}


class TokenEmbedding(Vocabulary):
    """Token embedding base (reference: embedding.py:133 _TokenEmbedding).

    Indexes tokens (it IS a Vocabulary) and maps each index to a vector
    row of `idx_to_vec`. Tokens either come from the loaded pretrained
    file, or — when a `vocabulary` is given — from that vocabulary, with
    vectors looked up in the loaded file."""

    #: pretrained file name -> sha1 (sha1 values are not tracked in this
    #: build — files are user-supplied locally, never downloaded)
    pretrained_file_name_sha1: dict = {}

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._vec_len = 0
        self._idx_to_vec = None

    # -- local pretrained-file resolution (no-egress divergence) ----------
    @classmethod
    def _get_pretrained_file(cls, embedding_root, pretrained_file_name):
        embedding_dir = os.path.join(os.path.expanduser(embedding_root),
                                     cls.__name__.lower())
        path = os.path.join(embedding_dir, pretrained_file_name)
        if not os.path.isfile(path):
            raise MXNetError(
                "pretrained embedding file %r not found under %s. This "
                "build never downloads (zero egress); obtain the file "
                "(reference URL scheme: embedding.py:191) and place it at "
                "that path." % (pretrained_file_name, embedding_dir))
        return path

    @classmethod
    def _check_pretrained_file_names(cls, pretrained_file_name):
        if pretrained_file_name not in cls.pretrained_file_name_sha1:
            raise KeyError(
                "Cannot find pretrained file %s for token embedding %s. "
                "Valid pretrained files for embedding %s: %s"
                % (pretrained_file_name, cls.__name__.lower(),
                   cls.__name__.lower(),
                   ", ".join(cls.pretrained_file_name_sha1)))

    # -- loading ----------------------------------------------------------
    def _load_embedding(self, pretrained_file_path, elem_delim,
                        init_unknown_vec, encoding="utf8"):
        """Parse `token<delim>v1<delim>...` lines into the index + vector
        table (reference: embedding.py:232). First occurrence of a token
        wins; 1-element lines (fasttext headers) are skipped; a vector for
        `unknown_token` in the file seeds index 0, else init_unknown_vec."""
        pretrained_file_path = os.path.expanduser(pretrained_file_path)
        if not os.path.isfile(pretrained_file_path):
            raise ValueError("`pretrained_file_path` must be a valid path "
                             "to the pre-trained token embedding file.")
        vec_len = None
        rows = []
        loaded_unknown_vec = None
        with open(pretrained_file_path, encoding=encoding) as f:
            for line_num, line in enumerate(f, 1):
                elems = line.rstrip().split(elem_delim)
                if len(elems) < 2:
                    continue
                token, vec = elems[0], [float(x) for x in elems[1:]]
                if token == self.unknown_token and loaded_unknown_vec is None:
                    loaded_unknown_vec = vec
                elif token in self._token_to_idx:
                    warnings.warn(
                        "line %d: duplicate embedding for token %r skipped"
                        % (line_num, token))
                elif len(vec) == 1:
                    warnings.warn(
                        "line %d: token %r with 1-dimensional vector %s is "
                        "likely a header and is skipped"
                        % (line_num, token, vec))
                else:
                    if vec_len is None:
                        vec_len = len(vec)
                    elif len(vec) != vec_len:
                        raise MXNetError(
                            "line %d: dimension of token %r is %d but "
                            "previous tokens have %d"
                            % (line_num, token, len(vec), vec_len))
                    self._idx_to_token.append(token)
                    self._token_to_idx[token] = len(self._idx_to_token) - 1
                    rows.append(vec)
        from .. import ndarray as nd

        if loaded_unknown_vec is not None:
            if vec_len is None:
                vec_len = len(loaded_unknown_vec)
            elif len(loaded_unknown_vec) != vec_len:
                raise MXNetError(
                    "the %r vector in %s has dimension %d but other tokens "
                    "have %d" % (self.unknown_token, pretrained_file_path,
                                 len(loaded_unknown_vec), vec_len))
        self._vec_len = vec_len or 0
        table = _np.zeros((len(self._idx_to_token), self._vec_len),
                          dtype=_np.float32)
        if rows:
            # vocabulary row 0 (+ reserved rows) precede the file tokens
            table[len(self._idx_to_token) - len(rows):] = _np.asarray(
                rows, dtype=_np.float32)
        if loaded_unknown_vec is not None:
            table[UNKNOWN_IDX] = _np.asarray(loaded_unknown_vec,
                                             dtype=_np.float32)
        elif init_unknown_vec is not None:
            table[UNKNOWN_IDX] = init_unknown_vec(
                shape=self._vec_len).asnumpy() \
                if callable(init_unknown_vec) else init_unknown_vec
        self._idx_to_vec = nd.array(table)

    # -- vocabulary re-indexing (reference: embedding.py:305,314,345) -----
    def _index_tokens_from_vocabulary(self, vocabulary):
        self._token_to_idx = dict(vocabulary.token_to_idx)
        self._idx_to_token = list(vocabulary.idx_to_token)
        self._unknown_token = vocabulary.unknown_token
        self._reserved_tokens = list(vocabulary.reserved_tokens or [])

    def _set_idx_to_vec_by_embeddings(self, token_embeddings, vocab_len,
                                      vocab_idx_to_token):
        from .. import ndarray as nd

        new_vec_len = sum(e.vec_len for e in token_embeddings)
        table = _np.zeros((vocab_len, new_vec_len), dtype=_np.float32)
        col = 0
        for e in token_embeddings:
            end = col + e.vec_len
            table[0, col:end] = e.idx_to_vec[0].asnumpy()
            if vocab_len > 1:
                table[1:, col:end] = e.get_vecs_by_tokens(
                    vocab_idx_to_token[1:]).asnumpy()
            col = end
        self._vec_len = new_vec_len
        self._idx_to_vec = nd.array(table)

    def _build_embedding_for_vocabulary(self, vocabulary):
        if vocabulary is not None:
            if not isinstance(vocabulary, Vocabulary):
                raise MXNetError("`vocabulary` must be a "
                                 "contrib.text.Vocabulary instance")
            self._set_idx_to_vec_by_embeddings(
                [self], len(vocabulary), vocabulary.idx_to_token)
            self._index_tokens_from_vocabulary(vocabulary)

    # -- lookup / update --------------------------------------------------
    @property
    def vec_len(self):
        return self._vec_len

    @property
    def idx_to_vec(self):
        return self._idx_to_vec

    def get_vecs_by_tokens(self, tokens, lower_case_backup=False):
        """reference: embedding.py:366 — Embedding-op row gather."""
        from .. import ndarray as nd

        single = not isinstance(tokens, list)
        toks = [tokens] if single else tokens
        if lower_case_backup:
            indices = [self._token_to_idx.get(
                t, self._token_to_idx.get(t.lower(), UNKNOWN_IDX))
                for t in toks]
        else:
            indices = [self._token_to_idx.get(t, UNKNOWN_IDX) for t in toks]
        vecs = nd.Embedding(nd.array(indices),
                            self._idx_to_vec,
                            input_dim=self._idx_to_vec.shape[0],
                            output_dim=self._idx_to_vec.shape[1])
        return vecs[0] if single else vecs

    def update_token_vectors(self, tokens, new_vectors):
        """reference: embedding.py:405 — in-place row updates for KNOWN
        tokens only (unknown tokens must be updated via unknown_token
        explicitly, to avoid unintended updates)."""
        from .. import ndarray as nd

        if self._idx_to_vec is None:
            raise MXNetError("`idx_to_vec` has not been set")
        toks = [tokens] if not isinstance(tokens, list) else tokens
        arr = new_vectors.asnumpy() if hasattr(new_vectors, "asnumpy") \
            else _np.asarray(new_vectors, dtype=_np.float32)
        if arr.ndim == 1:
            arr = arr[None, :]
        if arr.shape != (len(toks), self.vec_len):
            raise MXNetError(
                "new_vectors must have shape (%d, %d), got %s"
                % (len(toks), self.vec_len, arr.shape))
        indices = []
        for t in toks:
            if t not in self._token_to_idx:
                raise ValueError(
                    "Token %s is unknown. To update the embedding vector "
                    "for an unknown token, please specify it explicitly "
                    "as the `unknown_token` %s in `tokens`."
                    % (t, self._idx_to_token[UNKNOWN_IDX]))
            indices.append(self._token_to_idx[t])
        # asnumpy() may hand back a read-only view of the device buffer
        table = _np.array(self._idx_to_vec.asnumpy())
        table[indices] = arr
        self._idx_to_vec = nd.array(table)


# reference code subclasses the underscored name (embedding.py:133)
_TokenEmbedding = TokenEmbedding


def _default_unknown(shape):
    from .. import ndarray as nd

    return nd.zeros((shape,) if isinstance(shape, int) else shape)


# file catalogues mirroring reference _constants.py (names only — sha1
# hashes are download-validation data this no-egress build doesn't use)
_GLOVE_FILES = tuple(
    ["glove.42B.300d.txt", "glove.840B.300d.txt"]
    + ["glove.6B.%dd.txt" % d for d in (50, 100, 200, 300)]
    + ["glove.twitter.27B.%dd.txt" % d for d in (25, 50, 100, 200)])

_FAST_TEXT_LANGS = (
    "aa ab ace ady af ak als am ang an arc ar arz ast as av ay azb az bar "
    "bat_smg ba bcl be bg bh bi bjn bm bn bo bpy br bs bug bxr ca cbk_zam "
    "cdo ceb ce cho chr ch chy ckb co crh cr csb cs cu cv cy da de diq dsb "
    "dv dz ee el eml en eo es et eu ext fa ff fiu_vro fi fj fo frp frr fr "
    "fur fy gag gan ga gd glk gl gn gom got gu gv hak ha haw he hif hi ho "
    "hr hsb ht hu hy hz ia id ie ig ii ik ilo io is it iu jam ja jbo jv "
    "kaa kab ka kbd kg ki kj kk kl km kn koi ko krc kr ksh ks ku kv kw ky "
    "lad la lbe lb lez lg lij li lmo ln lo lrc ltg lt lv mai map_bms mdf "
    "mg mhr mh min mi mk ml mn mo mrj mr ms mt multi.ar multi.bg multi.ca "
    "multi.cs multi.da multi.de multi.el multi.en multi.es multi.et "
    "multi.fi multi.fr multi.he multi.hr multi.hu multi.id multi.it "
    "multi.mk multi.nl multi.no multi.pl multi.pt multi.ro multi.ru "
    "multi.sk multi.sl multi.sv multi.tr multi.uk multi.vi mus mwl my myv "
    "mzn nah nap na nds_nl nds ne new ng nl nn no nov nrm nso nv ny oc "
    "olo om or os pag pam pap pa pcd pdc pfl pih pi pl pms pnb pnt ps pt "
    "qu rm rmy rn roa_rup roa_tara ro rue ru rw sah sa scn sco sc sd se "
    "sg sh simple si sk sl sm sn so sq srn sr ss stq st su sv sw szl ta "
    "tcy tet te tg th ti tk tl tn to tpi tr ts tt tum tw ty tyv udm ug uk "
    "ur uz vec vep ve vi vls vo war wa wo wuu xal xh xmf yi yo za zea "
    "zh_classical zh_min_nan zh zh_yue zu").split()

_FAST_TEXT_FILES = tuple(
    ["wiki.%s.vec" % lang for lang in _FAST_TEXT_LANGS]
    + ["wiki-news-300d-1M.vec", "wiki-news-300d-1M-subword.vec",
       "crawl-300d-2M.vec"])


@register
class GloVe(TokenEmbedding):
    """GloVe word embeddings (reference: embedding.py:469). Loads a local
    `glove.*.txt` file from `embedding_root/glove/` (see module
    docstring for the no-download divergence)."""

    pretrained_file_name_sha1 = {f: None for f in _GLOVE_FILES}

    def __init__(self, pretrained_file_name="glove.840B.300d.txt",
                 embedding_root=os.path.join(
                     os.environ.get("MXNET_HOME",
                                    os.path.join("~", ".mxnet")),
                     "embeddings"),
                 init_unknown_vec=_default_unknown, vocabulary=None,
                 **kwargs):
        GloVe._check_pretrained_file_names(pretrained_file_name)
        super().__init__(**kwargs)
        path = GloVe._get_pretrained_file(embedding_root,
                                          pretrained_file_name)
        self._load_embedding(path, " ", init_unknown_vec)
        if vocabulary is not None:
            self._build_embedding_for_vocabulary(vocabulary)


@register
class FastText(TokenEmbedding):
    """fastText word embeddings (reference: embedding.py:541). Loads a
    local `wiki.*.vec` file from `embedding_root/fasttext/`."""

    pretrained_file_name_sha1 = {f: None for f in _FAST_TEXT_FILES}

    def __init__(self, pretrained_file_name="wiki.simple.vec",
                 embedding_root=os.path.join(
                     os.environ.get("MXNET_HOME",
                                    os.path.join("~", ".mxnet")),
                     "embeddings"),
                 init_unknown_vec=_default_unknown, vocabulary=None,
                 **kwargs):
        FastText._check_pretrained_file_names(pretrained_file_name)
        super().__init__(**kwargs)
        path = FastText._get_pretrained_file(embedding_root,
                                             pretrained_file_name)
        self._load_embedding(path, " ", init_unknown_vec)
        if vocabulary is not None:
            self._build_embedding_for_vocabulary(vocabulary)


class CustomEmbedding(TokenEmbedding):
    """Embedding matrix from a local `token vec...` text file (reference:
    embedding.py:623)."""

    def __init__(self, pretrained_file_path, elem_delim=" ",
                 encoding="utf8", init_unknown_vec=_default_unknown,
                 vocabulary=None, **kwargs):
        if isinstance(init_unknown_vec, Vocabulary):
            # pre-r4 signature had (.., vocabulary, init_unknown_vec);
            # the reference order (embedding.py:656) now stands — rescue
            # old positional callers instead of failing opaquely
            warnings.warn("CustomEmbedding: a Vocabulary was passed where "
                          "init_unknown_vec goes; the signature follows "
                          "the reference order (path, elem_delim, "
                          "encoding, init_unknown_vec, vocabulary)")
            init_unknown_vec, vocabulary = _default_unknown, init_unknown_vec
        super().__init__(**kwargs)
        self._load_embedding(pretrained_file_path, elem_delim,
                             init_unknown_vec, encoding)
        if vocabulary is not None:
            self._build_embedding_for_vocabulary(vocabulary)

    @property
    def vocabulary(self):
        # pre-r4 compatibility: this class used to carry a separate
        # `vocabulary` attribute; it now IS the vocabulary
        return self


class CompositeEmbedding(TokenEmbedding):
    """Concatenate several embeddings per token of a vocabulary
    (reference: embedding.py:665)."""

    def __init__(self, vocabulary, token_embeddings):
        if not isinstance(vocabulary, Vocabulary):
            raise MXNetError("`vocabulary` must be a "
                             "contrib.text.Vocabulary instance")
        if not isinstance(token_embeddings, list):
            token_embeddings = [token_embeddings]
        for e in token_embeddings:
            if not isinstance(e, TokenEmbedding):
                raise MXNetError("`token_embeddings` must be TokenEmbedding "
                                 "instance(s)")
        super().__init__()
        self._index_tokens_from_vocabulary(vocabulary)
        self._set_idx_to_vec_by_embeddings(token_embeddings, len(self),
                                           self.idx_to_token)
