"""SVRG optimization (reference: python/mxnet/contrib/svrg_optimization/ —
SVRGModule + _SVRGOptimizer: variance-reduced SGD where a full-batch
gradient snapshot is taken every `update_freq` epochs and each step uses
g_i - g_i(w_snapshot) + g_full).

TPU-native shape: a Gluon-level trainer wrapper instead of a Module
subclass — snapshot params/grads are plain buffers and the corrected update
is one fused XLA step.
"""
from __future__ import annotations

from ..base import MXNetError

__all__ = ["SVRGTrainer"]


class SVRGTrainer:
    """Variance-reduced SGD trainer (reference: svrg_module.py semantics).

    usage per epoch:
        if epoch % update_freq == 0:
            trainer.take_snapshot(full_batch_grad_fn)   # MEAN grads, full set
        for batch:
            loss.backward()
            trainer.step(bs, batch_grad_fn)  # grads of THIS minibatch at the
                                             # snapshot params, same scale as
                                             # p.grad() (sum over batch)
    The update is (g_batch - g_batch@snapshot)/bs + g_full_mean — the SVRG
    variance-reduced direction (reference: svrg_optimizer.py _SVRGOptimizer).
    """

    def __init__(self, params, learning_rate=0.01, update_freq=2, wd=0.0):
        from ..gluon.parameter import ParameterDict

        if isinstance(params, ParameterDict):
            params = list(params.values())
        self._params = [p for p in params if p.grad_req != "null"]
        if not self._params:
            raise MXNetError("SVRGTrainer: no trainable parameters")
        self.learning_rate = learning_rate
        self.update_freq = update_freq
        self.wd = wd
        self._snapshot = None       # list of param value copies
        self._full_grads = None     # list of full-batch grads at snapshot

    def take_snapshot(self, full_grad_fn):
        """Record w_snapshot and the full-batch gradient at it (reference:
        SVRGModule.update_full_grads)."""
        self._snapshot = [p.data().copy() for p in self._params]
        self._full_grads = full_grad_fn(self._snapshot)
        if len(self._full_grads) != len(self._params):
            raise MXNetError("full_grad_fn must return one grad per param")

    def step(self, batch_size, snapshot_grad_fn=None):
        """SGD step with SVRG correction when a snapshot exists."""
        # capture live batch grads FIRST: snapshot_grad_fn runs its own
        # backward, which overwrites the parameters' grad buffers
        live_grads = [p.grad().copy() for p in self._params]
        corrections = None
        if self._snapshot is not None:
            if snapshot_grad_fn is None:
                raise MXNetError("snapshot_grad_fn required after take_snapshot")
            corrections = snapshot_grad_fn(self._snapshot)
        lr = self.learning_rate
        for i, p in enumerate(self._params):
            g = live_grads[i]
            if corrections is not None:
                upd = (g - corrections[i]) / batch_size + self._full_grads[i]
            else:
                upd = g / batch_size
            if self.wd:
                upd = upd + self.wd * p.data()
            p.data()._set_data((p.data() - lr * upd)._data)
            for d in p.list_data():
                d._fresh_grad = False
