"""Minimal pure-Python ONNX protobuf implementation.

The reference gates ONNX interchange on the `onnx` pip package
(python/mxnet/contrib/onnx/__init__.py); this environment does not ship
it, and a deployment-interchange path that cannot run is not a feature.
ONNX files are ordinary protobufs, and the subset of the schema the
translation tables need (ModelProto/GraphProto/NodeProto/AttributeProto/
TensorProto/ValueInfoProto) is small — so this module implements the
protobuf wire format for exactly those messages, plus the slivers of the
`onnx.helper` / `onnx.numpy_helper` API that contrib/onnx.py uses.

contrib/onnx.py prefers the real `onnx` package when importable and falls
back to this shim, so artifacts written here are standard .onnx files
readable by onnxruntime/netron/etc. Wire-format correctness is covered by
tests/test_onnx.py, including a `protoc --decode_raw` golden check (an
independent protobuf decoder validating field numbers and structure).

Field numbers follow the public onnx.proto3 schema (onnx/onnx.proto).
"""
from __future__ import annotations

import struct

import numpy as _np

# -- protobuf wire format ---------------------------------------------------

_VARINT, _I64, _LEN, _I32 = 0, 1, 2, 5


def _enc_varint(v):
    out = bytearray()
    if v < 0:
        v += 1 << 64
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _dec_varint(buf, pos):
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            if result >= 1 << 63:
                result -= 1 << 64
            return result, pos
        shift += 7


def _tag(field, wire):
    return _enc_varint((field << 3) | wire)


class Field:
    def __init__(self, num, kind, repeated=False, message=None):
        self.num = num
        self.kind = kind          # int|float|double|bytes|string|message
        self.repeated = repeated
        self.message = message


class Message:
    """Declarative protobuf message: subclasses define FIELDS =
    {py_name: Field}. Unknown fields are skipped on decode (forward
    compatibility with full onnx files)."""

    FIELDS = {}

    def __init__(self, **kwargs):
        for name, f in self.FIELDS.items():
            setattr(self, name, [] if f.repeated else _default(f))
        for k, v in kwargs.items():
            if k not in self.FIELDS:
                raise AttributeError("%s has no field %r"
                                     % (type(self).__name__, k))
            setattr(self, k, v)

    # -- encoding ----------------------------------------------------------
    def SerializeToString(self):
        out = bytearray()
        for name, f in self.FIELDS.items():
            val = getattr(self, name)
            if f.repeated:
                if not val:
                    continue
                if f.kind in ("int", "float", "double"):
                    # proto3 packs repeated scalars
                    payload = bytearray()
                    for v in val:
                        payload += _enc_scalar(f.kind, v)
                    out += _tag(f.num, _LEN) + _enc_varint(len(payload)) \
                        + payload
                else:
                    for v in val:
                        out += _enc_field(f, v)
            else:
                if _is_default(f, val):
                    continue
                out += _enc_field(f, val)
        return bytes(out)

    # -- decoding ----------------------------------------------------------
    @classmethod
    def FromString(cls, data):
        msg = cls()
        pos, end = 0, len(data)
        while pos < end:
            key, pos = _dec_varint(data, pos)
            field_num, wire = key >> 3, key & 7
            f = cls._by_num().get(field_num)
            if f is None:
                pos = _skip(data, pos, wire)
                continue
            name = f._name
            if wire == _LEN:
                ln, pos = _dec_varint(data, pos)
                chunk = data[pos:pos + ln]
                pos += ln
                if f.kind == "message":
                    v = f.message.FromString(chunk)
                elif f.kind == "bytes":
                    v = bytes(chunk)
                elif f.kind == "string":
                    v = chunk.decode("utf-8")
                elif f.kind in ("int", "float", "double"):
                    # packed repeated scalars
                    vs, p2 = [], 0
                    while p2 < len(chunk):
                        if f.kind == "int":
                            v2, p2 = _dec_varint(chunk, p2)
                        elif f.kind == "float":
                            v2 = struct.unpack_from("<f", chunk, p2)[0]
                            p2 += 4
                        else:
                            v2 = struct.unpack_from("<d", chunk, p2)[0]
                            p2 += 8
                        vs.append(v2)
                    if f.repeated:
                        getattr(msg, name).extend(vs)
                        continue
                    v = vs[-1] if vs else _default(f)
                else:
                    raise ValueError("bad LEN field %s" % name)
            elif wire == _VARINT:
                v, pos = _dec_varint(data, pos)
            elif wire == _I32:
                v = struct.unpack_from("<f", data, pos)[0]
                pos += 4
            elif wire == _I64:
                v = struct.unpack_from("<d", data, pos)[0]
                pos += 8
            else:
                raise ValueError("unsupported wire type %d" % wire)
            if f.repeated:
                getattr(msg, name).append(v)
            else:
                setattr(msg, name, v)
        return msg

    @classmethod
    def _by_num(cls):
        cached = cls.__dict__.get("_num_index")
        if cached is None:
            cached = {}
            for name, f in cls.FIELDS.items():
                f._name = name
                cached[f.num] = f
            cls._num_index = cached
        return cached

    def __repr__(self):
        parts = []
        for name, f in self.FIELDS.items():
            v = getattr(self, name)
            if (f.repeated and v) or (not f.repeated
                                      and not _is_default(f, v)):
                parts.append("%s=%r" % (name, v))
        return "%s(%s)" % (type(self).__name__, ", ".join(parts))


def _default(f):
    return {"int": 0, "float": 0.0, "double": 0.0, "bytes": b"",
            "string": "", "message": None}[f.kind]


def _is_default(f, v):
    if f.kind == "message":
        return v is None
    return v == _default(f)


def _enc_scalar(kind, v):
    if kind == "int":
        return _enc_varint(int(v))
    if kind == "float":
        return struct.pack("<f", float(v))
    return struct.pack("<d", float(v))


def _enc_field(f, v):
    if f.kind == "int":
        return _tag(f.num, _VARINT) + _enc_varint(int(v))
    if f.kind == "float":
        return _tag(f.num, _I32) + struct.pack("<f", float(v))
    if f.kind == "double":
        return _tag(f.num, _I64) + struct.pack("<d", float(v))
    if f.kind == "bytes":
        b = bytes(v)
        return _tag(f.num, _LEN) + _enc_varint(len(b)) + b
    if f.kind == "string":
        b = v.encode("utf-8")
        return _tag(f.num, _LEN) + _enc_varint(len(b)) + b
    if f.kind == "message":
        b = v.SerializeToString()
        return _tag(f.num, _LEN) + _enc_varint(len(b)) + b
    raise ValueError(f.kind)


def _skip(data, pos, wire):
    if wire == _VARINT:
        _, pos = _dec_varint(data, pos)
        return pos
    if wire == _I64:
        return pos + 8
    if wire == _I32:
        return pos + 4
    if wire == _LEN:
        ln, pos = _dec_varint(data, pos)
        return pos + ln
    raise ValueError("unsupported wire type %d" % wire)


# -- ONNX messages (field numbers from onnx/onnx.proto) ---------------------

class TensorShapeDim(Message):
    FIELDS = {"dim_value": Field(1, "int"),
              "dim_param": Field(2, "string")}


class TensorShapeProto(Message):
    FIELDS = {"dim": Field(1, "message", repeated=True,
                           message=TensorShapeDim)}


class TensorTypeProto(Message):
    FIELDS = {"elem_type": Field(1, "int"),
              "shape": Field(2, "message", message=TensorShapeProto)}


class TypeProto(Message):
    FIELDS = {"tensor_type": Field(1, "message", message=TensorTypeProto)}


class ValueInfoProto(Message):
    FIELDS = {"name": Field(1, "string"),
              "type": Field(2, "message", message=TypeProto),
              "doc_string": Field(3, "string")}


class TensorProto(Message):
    # DataType enum values (onnx.proto TensorProto.DataType)
    FLOAT, UINT8, INT8, UINT16, INT16, INT32, INT64, STRING, BOOL, \
        FLOAT16, DOUBLE, UINT32, UINT64 = range(1, 14)

    FIELDS = {"dims": Field(1, "int", repeated=True),
              "data_type": Field(2, "int"),
              "float_data": Field(4, "float", repeated=True),
              "int32_data": Field(5, "int", repeated=True),
              "string_data": Field(6, "bytes", repeated=True),
              "int64_data": Field(7, "int", repeated=True),
              "name": Field(8, "string"),
              "raw_data": Field(9, "bytes"),
              "double_data": Field(10, "double", repeated=True),
              "uint64_data": Field(11, "int", repeated=True),
              "doc_string": Field(12, "string")}


class AttributeProto(Message):
    # AttributeType enum
    UNDEFINED, FLOAT, INT, STRING, TENSOR, GRAPH, \
        FLOATS, INTS, STRINGS, TENSORS, GRAPHS = range(11)

    FIELDS = {"name": Field(1, "string"),
              "f": Field(2, "float"),
              "i": Field(3, "int"),
              "s": Field(4, "bytes"),
              "t": Field(5, "message", message=TensorProto),
              "floats": Field(7, "float", repeated=True),
              "ints": Field(8, "int", repeated=True),
              "strings": Field(9, "bytes", repeated=True),
              "tensors": Field(10, "message", repeated=True,
                               message=TensorProto),
              "doc_string": Field(13, "string"),
              "type": Field(20, "int")}


class NodeProto(Message):
    FIELDS = {"input": Field(1, "string", repeated=True),
              "output": Field(2, "string", repeated=True),
              "name": Field(3, "string"),
              "op_type": Field(4, "string"),
              "attribute": Field(5, "message", repeated=True,
                                 message=AttributeProto),
              "doc_string": Field(6, "string"),
              "domain": Field(7, "string")}


class GraphProto(Message):
    FIELDS = {"node": Field(1, "message", repeated=True, message=NodeProto),
              "name": Field(2, "string"),
              "initializer": Field(5, "message", repeated=True,
                                   message=TensorProto),
              "doc_string": Field(10, "string"),
              "input": Field(11, "message", repeated=True,
                             message=ValueInfoProto),
              "output": Field(12, "message", repeated=True,
                              message=ValueInfoProto),
              "value_info": Field(13, "message", repeated=True,
                                  message=ValueInfoProto)}


class OperatorSetIdProto(Message):
    FIELDS = {"domain": Field(1, "string"),
              "version": Field(2, "int")}


class ModelProto(Message):
    FIELDS = {"ir_version": Field(1, "int"),
              "producer_name": Field(2, "string"),
              "producer_version": Field(3, "string"),
              "domain": Field(4, "string"),
              "model_version": Field(5, "int"),
              "doc_string": Field(6, "string"),
              "graph": Field(7, "message", message=GraphProto),
              "opset_import": Field(8, "message", repeated=True,
                                    message=OperatorSetIdProto)}


# -- onnx-package-compatible API surface ------------------------------------

def load(path_or_bytes):
    raw = path_or_bytes
    if isinstance(raw, str):
        with open(raw, "rb") as f:
            raw = f.read()
    return ModelProto.FromString(raw)


def save(model, path):
    with open(path, "wb") as f:
        f.write(model.SerializeToString())


_NP_TO_ONNX = {
    _np.dtype(_np.float32): TensorProto.FLOAT,
    _np.dtype(_np.float64): TensorProto.DOUBLE,
    _np.dtype(_np.float16): TensorProto.FLOAT16,
    _np.dtype(_np.int32): TensorProto.INT32,
    _np.dtype(_np.int64): TensorProto.INT64,
    _np.dtype(_np.int8): TensorProto.INT8,
    _np.dtype(_np.uint8): TensorProto.UINT8,
    _np.dtype(_np.bool_): TensorProto.BOOL,
}
_ONNX_TO_NP = {v: k for k, v in _NP_TO_ONNX.items()}


class numpy_helper:
    @staticmethod
    def from_array(arr, name=""):
        arr = _np.asarray(arr)
        dt = _NP_TO_ONNX.get(arr.dtype)
        if dt is None:
            raise ValueError("unsupported dtype %s" % arr.dtype)
        return TensorProto(dims=list(arr.shape), data_type=dt, name=name,
                           raw_data=_np.ascontiguousarray(arr).tobytes())

    @staticmethod
    def to_array(tensor):
        dt = _ONNX_TO_NP.get(tensor.data_type)
        if dt is None:
            raise ValueError("unsupported TensorProto data_type %d"
                             % tensor.data_type)
        shape = tuple(tensor.dims)
        if tensor.raw_data:
            return _np.frombuffer(tensor.raw_data, dtype=dt).reshape(shape)
        if tensor.data_type == TensorProto.FLOAT:
            return _np.asarray(tensor.float_data, _np.float32).reshape(shape)
        if tensor.data_type == TensorProto.DOUBLE:
            return _np.asarray(tensor.double_data,
                               _np.float64).reshape(shape)
        if tensor.data_type == TensorProto.INT64:
            return _np.asarray(tensor.int64_data, _np.int64).reshape(shape)
        return _np.asarray(tensor.int32_data, dt).reshape(shape)


class helper:
    @staticmethod
    def make_node(op_type, inputs, outputs, name=None, domain=None,
                  **attrs):
        node = NodeProto(op_type=op_type, input=list(inputs),
                         output=list(outputs), name=name or "")
        if domain:
            node.domain = domain
        for k in sorted(attrs):
            node.attribute.append(helper.make_attribute(k, attrs[k]))
        return node

    @staticmethod
    def make_attribute(key, value):
        a = AttributeProto(name=key)
        if isinstance(value, bool):
            a.i, a.type = int(value), AttributeProto.INT
        elif isinstance(value, int):
            a.i, a.type = value, AttributeProto.INT
        elif isinstance(value, float):
            a.f, a.type = value, AttributeProto.FLOAT
        elif isinstance(value, str):
            a.s, a.type = value.encode("utf-8"), AttributeProto.STRING
        elif isinstance(value, bytes):
            a.s, a.type = value, AttributeProto.STRING
        elif isinstance(value, TensorProto):
            a.t, a.type = value, AttributeProto.TENSOR
        elif isinstance(value, (list, tuple)):
            if all(isinstance(v, (int, _np.integer)) for v in value):
                a.ints, a.type = [int(v) for v in value], AttributeProto.INTS
            elif all(isinstance(v, (int, float, _np.floating, _np.integer))
                     for v in value):
                a.floats = [float(v) for v in value]
                a.type = AttributeProto.FLOATS
            elif all(isinstance(v, (str, bytes)) for v in value):
                a.strings = [v.encode("utf-8") if isinstance(v, str) else v
                             for v in value]
                a.type = AttributeProto.STRINGS
            else:
                raise ValueError("mixed attribute list %r" % (value,))
        else:
            raise ValueError("unsupported attribute value %r" % (value,))
        return a

    @staticmethod
    def get_attribute_value(attr):
        t = attr.type
        if t == AttributeProto.FLOAT:
            return attr.f
        if t == AttributeProto.INT:
            return attr.i
        if t == AttributeProto.STRING:
            return attr.s
        if t == AttributeProto.TENSOR:
            return attr.t
        if t == AttributeProto.FLOATS:
            return list(attr.floats)
        if t == AttributeProto.INTS:
            return list(attr.ints)
        if t == AttributeProto.STRINGS:
            return list(attr.strings)
        raise ValueError("unsupported attribute type %d" % t)

    @staticmethod
    def make_tensor_value_info(name, elem_type, shape):
        tshape = TensorShapeProto()
        for d in (shape or ()):
            if d is None or (isinstance(d, str)):
                tshape.dim.append(TensorShapeDim(dim_param=str(d or "?")))
            else:
                tshape.dim.append(TensorShapeDim(dim_value=int(d)))
        return ValueInfoProto(
            name=name,
            type=TypeProto(tensor_type=TensorTypeProto(
                elem_type=elem_type, shape=tshape)))

    @staticmethod
    def make_graph(nodes, name, inputs, outputs, initializer=None):
        return GraphProto(node=list(nodes), name=name, input=list(inputs),
                          output=list(outputs),
                          initializer=list(initializer or []))

    @staticmethod
    def make_model(graph, opset_version=13, producer_name="mxnet_tpu"):
        return ModelProto(
            ir_version=8, producer_name=producer_name, graph=graph,
            opset_import=[OperatorSetIdProto(domain="",
                                             version=opset_version)])


__version__ = "shim-1.0"
