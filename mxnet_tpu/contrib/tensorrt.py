"""TensorRT integration surface (reference: python/mxnet/contrib/
tensorrt.py). TensorRT is CUDA-only and declared out of scope for the
TPU build (SURVEY §7); the TPU-native analogue of a TRT engine is the
StableHLO AOT artifact (`mxnet_tpu.predict.Predictor.export_compiled` /
`CompiledPredictor`). The reference names exist so ported scripts fail
with direction instead of AttributeError; the use_tensorrt flag is
accepted and always reports False."""
from __future__ import annotations

from ..base import MXNetError

__all__ = ["set_use_tensorrt", "get_use_tensorrt", "get_optimized_symbol",
           "tensorrt_bind"]

_MSG = ("TensorRT is CUDA-only and out of scope for the TPU build; use "
        "Predictor.export_compiled -> CompiledPredictor (StableHLO AOT) "
        "for the equivalent frozen-engine deployment path")


def set_use_tensorrt(status):
    """reference: tensorrt.py:30 — accepted for script compatibility;
    enabling it raises (there is no TRT runtime here)."""
    if status:
        raise MXNetError(_MSG)


def get_use_tensorrt():
    """reference: tensorrt.py:40 — always False on TPU."""
    return False


def get_optimized_symbol(executor):
    """reference: tensorrt.py:50."""
    raise MXNetError(_MSG)


def tensorrt_bind(symbol, ctx, all_params, **kwargs):
    """reference: tensorrt.py:76."""
    raise MXNetError(_MSG)
