"""Subgraph partition/fusion framework.

TPU-native equivalent of the reference's subgraph machinery
(src/operator/subgraph/subgraph_property.h:77 SubgraphSelector,
SubgraphProperty + MXNET_REGISTER_SUBGRAPH_PROPERTY; partitioner
build_subgraph.cc; MKLDNN conv+bn+relu / fc fusion properties).

On TPU the *performance* role of fusion belongs to XLA — everything inside
one jit is fused automatically. What remains valuable (and is reproduced
here) is the *structural* API: selecting a region of the graph and
replacing it with a single node, so backends can substitute custom
implementations (a Pallas kernel, a quantized block) for matched patterns.
The fused node's implementation is the captured sub-Symbol interpreted as
one unit — under jit it compiles as a single fused region.

Partition strategy: each property seeds at `select()` nodes and grows
backward through `select_input()` edges whose producer has exactly one
consumer (keeps regions convex — the conv+bn+relu chain shape the
reference's MKLDNN properties match).
"""
from __future__ import annotations

import itertools

from . import ops as _ops
from .base import MXNetError
from .symbol.symbol import Symbol, _Node

__all__ = ["SubgraphSelector", "SubgraphProperty", "register_subgraph_property",
           "partition", "DefaultSubgraphProperty", "list_subgraph_properties"]

_PROPERTIES = {}
_counter = itertools.count()


class SubgraphSelector:
    """Decides which nodes join a subgraph (reference:
    subgraph_property.h:77)."""

    def select(self, node):
        """Start a subgraph at this node?"""
        return False

    def select_input(self, node, input_node):
        """Grow the subgraph from `node` to its producer `input_node`?"""
        return False

    def select_output(self, node, output_node):
        """Grow from `node` to its consumer `output_node`?"""
        return False


class SubgraphProperty:
    """Creates replacement nodes for selected regions (reference:
    subgraph_property.h SubgraphProperty)."""

    def create_subgraph_selector(self):
        return SubgraphSelector()

    def subgraph_op_name(self, subgraph_id):
        return "_subgraph_%s_%d" % (type(self).__name__.lower(), subgraph_id)

    def create_subgraph_node(self, subgraph_sym, input_names, subgraph_id):
        """Register + return the op name implementing this subgraph. Override
        to substitute a custom implementation (Pallas kernel, int8 block)."""
        op_name = self.subgraph_op_name(subgraph_id)

        def fused(*arrays, **_ignored):
            values = dict(zip(input_names, arrays))
            outs, _ = subgraph_sym._interpret(values)
            return tuple(outs) if len(outs) > 1 else outs[0]

        fused.__doc__ = ("fused subgraph op (%d nodes) created by %s"
                         % (sum(1 for n in subgraph_sym._topo()
                                if not n.is_var), type(self).__name__))
        _ops.register(op_name,
                      num_outputs=len(subgraph_sym._outputs))(fused)
        return op_name


def register_subgraph_property(name):
    """reference: MXNET_REGISTER_SUBGRAPH_PROPERTY."""

    def deco(cls):
        _PROPERTIES[name] = cls
        return cls

    return deco


def list_subgraph_properties():
    return sorted(_PROPERTIES)


class DefaultSubgraphProperty(SubgraphProperty):
    """Wraps every op node into one whole-graph subgraph (reference: the
    default property used by build_subgraph.cc tests)."""

    def create_subgraph_selector(self):
        class _All(SubgraphSelector):
            def select(self, node):
                return True

            def select_input(self, node, input_node):
                return True

        return _All()


register_subgraph_property("default")(DefaultSubgraphProperty)


def _fusable(node):
    """Ops with hidden aux outputs (BatchNorm moving stats) or per-call RNG
    (Dropout) cannot be captured — their side effects would be silently
    dropped by the fused interpreter (the reference's selectors skip
    stateful ops the same way)."""
    opdef = _ops.get(node.op)
    if opdef.needs_rng:
        return False
    return opdef.visible_outputs == max(1, opdef.num_outputs)


def _find_groups(sym, prop):
    """Greedy convex grouping; returns list of sets of node ids."""
    consumers = {}
    nodes = list(sym._topo())
    for n in nodes:
        for src, _ in n.inputs:
            consumers.setdefault(id(src), []).append(n)
    out_ids = {id(n) for n, _ in sym._outputs}

    assigned = set()
    groups = []
    for node in reversed(nodes):  # grow from late nodes backward
        if node.is_var or id(node) in assigned:
            continue
        selector = prop.create_subgraph_selector()
        if not _fusable(node) or not selector.select(node):
            continue
        group = {id(node)}
        frontier = [node]
        while frontier:
            cur = frontier.pop()
            for src, _ in cur.inputs:
                if src.is_var or id(src) in assigned or id(src) in group \
                        or not _fusable(src):
                    continue
                # convexity: producer must feed only into the group, and not
                # be a graph output itself
                cons = consumers.get(id(src), [])
                if id(src) in out_ids or \
                        not all(id(c) in group for c in cons):
                    continue
                if selector.select_input(cur, src):
                    group.add(id(src))
                    frontier.append(src)
        assigned |= group
        groups.append(group)
    return groups


def partition(sym, prop="default"):
    """Replace matched regions with fused subgraph nodes, returning the new
    Symbol (reference: build_subgraph.cc partitioner; Python surface
    build_subgraph/optimize_for)."""
    if isinstance(prop, str):
        if prop not in _PROPERTIES:
            raise MXNetError("unknown subgraph property '%s' (known: %s)"
                             % (prop, list_subgraph_properties()))
        prop = _PROPERTIES[prop]()
    groups = _find_groups(sym, prop)
    if not groups:
        return sym
    group_of = {}
    for gi, g in enumerate(groups):
        for nid in g:
            group_of[nid] = gi

    nodes = list(sym._topo())
    mapping = {}          # old node id -> (new_node, base_out_idx_offset fn)
    fused_nodes = {}      # group idx -> (fused _Node, {(old_nid, idx): out_idx})

    def new_edge(src, idx):
        nid = id(src)
        if nid in group_of and nid in fused_mapped:
            fnode, out_map = fused_nodes[group_of[nid]]
            return (fnode, out_map[(nid, idx)])
        return (mapping[nid], idx)

    fused_mapped = set()
    for gi, g in enumerate(groups):
        members = [n for n in nodes if id(n) in g]
        member_ids = set(g)
        # external edges -> subgraph var inputs
        ext_inputs = []   # [(src_node, idx)]
        sub_clone = {}

        def sub_edge(src, idx):
            if id(src) in member_ids:
                return (sub_clone[id(src)], idx)
            key = (id(src), idx)
            for i, k in enumerate(ext_inputs):
                if k == key:
                    return (sub_vars[i], 0)
            ext_inputs.append(key)
            v = _Node(None, "sub_in%d" % (len(ext_inputs) - 1))
            sub_vars.append(v)
            return (v, 0)

        sub_vars = []
        for n in members:
            clone = _Node(n.op, n.name, dict(n.attrs), [], n.aux_slots)
            sub_clone[id(n)] = clone
        for n in members:
            for src, idx in n.inputs:
                sub_clone[id(n)].inputs.append(sub_edge(src, idx))
        # region outputs: member outputs consumed outside the group (or graph outputs)
        out_edges = []
        consumed_outside = set()
        for n in nodes:
            if id(n) in member_ids:
                continue
            for src, idx in n.inputs:
                if id(src) in member_ids:
                    consumed_outside.add((id(src), idx))
        for n, idx in sym._outputs:
            if id(n) in member_ids:
                consumed_outside.add((id(n), idx))
        for n in members:
            for idx in range(max(1, n.visible_outputs())):
                if (id(n), idx) in consumed_outside:
                    out_edges.append((id(n), idx))
        sub_sym = Symbol([(sub_clone[nid], idx) for nid, idx in out_edges])
        input_names = ["sub_in%d" % i for i in range(len(ext_inputs))]
        op_name = prop.create_subgraph_node(sub_sym, input_names,
                                            next(_counter))
        fused = _Node(op_name, op_name, {}, [])
        out_map = {edge: i for i, edge in enumerate(out_edges)}
        fused_nodes[gi] = (fused, out_map)

    # rebuild the full graph (topo order: producers are mapped before use).
    # A group is wired at its LAST member's topo position — only then are
    # all its external producers guaranteed to be mapped (a group member
    # late in the graph may consume vars that appear after the first member)
    remaining = {gi: len(g) for gi, g in enumerate(groups)}
    for node in nodes:
        nid = id(node)
        if node.is_var:
            nv = _Node(None, node.name, dict(node.attrs))
            nv._shape, nv._dtype = node._shape, node._dtype
            mapping[nid] = nv
            continue
        if nid in group_of:
            gi = group_of[nid]
            remaining[gi] -= 1
            if remaining[gi] == 0:
                # wire the fused node's inputs in the SAME first-encounter
                # order the sub-Symbol's sub_in%d vars were created in
                fused, _ = fused_nodes[gi]
                g = groups[gi]
                members = [n for n in nodes if id(n) in g]
                member_ids = set(g)
                seen = []
                for n in members:
                    for src, idx in n.inputs:
                        if id(src) not in member_ids and \
                                (id(src), idx) not in seen:
                            seen.append((id(src), idx))
                            fused.inputs.append(new_edge(src, idx))
                fused_mapped |= member_ids
            continue
        mapping[nid] = _Node(node.op, node.name, dict(node.attrs),
                             [new_edge(s, i) for s, i in node.inputs],
                             node.aux_slots)

    outs = []
    for n, idx in sym._outputs:
        outs.append(new_edge(n, idx))
    return Symbol(outs)
