"""Custom operators defined in Python.

TPU-native equivalent of the reference's custom-op plugin
(python/mxnet/operator.py: CustomOp :426, CustomOpProp :472, register :692;
C++ bridge src/operator/custom/custom.cc running user callbacks on dedicated
worker threads custom-inl.h:210-222).

Design: one framework op named ``Custom`` is registered whose jax
implementation is a `jax.custom_vjp`-wrapped `jax.pure_callback` — the
XLA-era version of the reference's engine-callback bridge. The host callback
materializes inputs as NDArrays, instantiates the user's CustomOp via
``CustomOpProp.create_operator`` and runs ``forward``/``backward`` exactly as
the reference does (same req/assign protocol). Because ``Custom`` is an
ordinary registry op, every consumer works unchanged: eager `nd.Custom`,
the autograd tape (vjp hits the custom_vjp rule), `sym.Custom`, and
hybridized blocks (pure_callback stages the host call out of the compiled
program).
"""
from __future__ import annotations

import numpy as _np

from .base import MXNetError

__all__ = ["CustomOp", "CustomOpProp", "register", "get_all_registered_operators"]

_CUSTOM_PROPS = {}


class CustomOp:
    """Base class for user ops (reference: operator.py:426)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst, req, src):
        """Write src into dst honoring req (reference: operator.py:447)."""
        if req in ("null",):
            return
        if req in ("write", "inplace"):
            dst[:] = src
        elif req == "add":
            dst[:] = dst + src
        else:
            raise MXNetError("unknown req '%s'" % req)


class CustomOpProp:
    """Op metadata provider (reference: operator.py:472)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def infer_type(self, in_type):
        return in_type, [in_type[0]] * len(self.list_outputs()), []

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def need_top_grad(self):
        return self.need_top_grad_

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return CustomOp()


def register(reg_name):
    """Register a CustomOpProp subclass under op_type `reg_name`
    (reference: operator.py:692)."""

    def deco(prop_cls):
        if not issubclass(prop_cls, CustomOpProp):
            raise MXNetError("register expects a CustomOpProp subclass")
        _CUSTOM_PROPS[reg_name] = prop_cls
        # drop every compiled trace that closes over a previous
        # registration of THIS op_type (re-registering is the notebook
        # cell-rerun workflow the reference supports): the custom_vjp
        # bridge functions per name, and — through the unified registry's
        # tag invalidation — the forward/backward executables keyed with
        # the `custom-op:<op_type>` tag. Other ops' warm executables stay
        # cached (the old blanket cache_clear threw them ALL away).
        _CUSTOM_FNS.pop(reg_name, None)
        from . import compile as _compile

        _compile.invalidate_tag("custom-op:%s" % reg_name)
        return prop_cls

    return deco


def get_all_registered_operators():
    return sorted(_CUSTOM_PROPS)


# --------------------------------------------------------------------------
# the bridge: one registry op "Custom" running user callbacks on host
# --------------------------------------------------------------------------

def _make_prop(op_type, attr_key):
    if op_type not in _CUSTOM_PROPS:
        raise MXNetError("custom op '%s' is not registered (known: %s)"
                         % (op_type, sorted(_CUSTOM_PROPS)))
    # reference passes user kwargs to the Prop as strings (custom.cc attrs)
    kwargs = {k: str(v) for k, v in attr_key}
    prop = _CUSTOM_PROPS[op_type](**kwargs)
    if prop.list_auxiliary_states():
        raise MXNetError("custom ops with auxiliary states are not supported")
    return prop


def _infer(prop, ins):
    in_shapes = [list(a.shape) for a in ins]
    in_shapes, out_shapes, _ = prop.infer_shape(in_shapes)
    in_types = [_np.dtype(a.dtype) for a in ins]
    _, out_types, _ = prop.infer_type(in_types)
    return ([tuple(s) for s in out_shapes],
            [_np.dtype(t) for t in out_types])


def _to_nd(arrays):
    """Wrap host callback arrays as CPU-backed NDArrays. Staying on the CPU
    XLA backend is load-bearing: the accelerator core is blocked waiting for
    the pure_callback result, so the callback must never enqueue work on the
    default (TPU) device or it deadlocks."""
    import jax

    from .context import cpu
    from .ndarray.ndarray import NDArray

    ctx = cpu()
    dev = ctx.jax_device()
    return [NDArray(jax.device_put(_np.asarray(a), dev), ctx=ctx)
            for a in arrays]


def _run_forward(prop, np_ins, is_train):
    """Shared forward-recompute used by both callbacks (one definition so
    the protocol can't diverge between forward and backward paths)."""
    from . import autograd
    from . import ndarray as nd
    from .context import cpu

    ctx = cpu()
    n_out = len(prop.list_outputs())
    in_nd = _to_nd(np_ins)
    out_shapes, out_types = _infer(prop, np_ins)
    out_nd = [nd.zeros(s, dtype=t, ctx=ctx)
              for s, t in zip(out_shapes, out_types)]
    op = prop.create_operator(None, [list(a.shape) for a in np_ins],
                              [a.dtype for a in np_ins])
    with autograd.pause():
        op.forward(is_train=is_train, req=["write"] * n_out,
                   in_data=in_nd, out_data=out_nd, aux=[])
    return in_nd, out_nd, out_types, op


# op_type -> {(attr_key, is_train): (custom_vjp fn, n_out)} — keyed by
# name FIRST so re-registration invalidates exactly one op_type's
# bridges (the lru_cache this replaced could only be cleared wholesale)
_CUSTOM_FNS = {}


def _make_custom_fn(op_type, attr_key, is_train):
    """Build (or fetch) the custom_vjp jax function for
    (op_type, attrs, is_train)."""
    by_sig = _CUSTOM_FNS.setdefault(op_type, {})
    hit = by_sig.get((attr_key, is_train))
    if hit is not None:
        return hit
    fn_out = _build_custom_fn(op_type, attr_key, is_train)
    by_sig[(attr_key, is_train)] = fn_out
    return fn_out


def _build_custom_fn(op_type, attr_key, is_train):
    import jax

    prop = _make_prop(op_type, attr_key)
    n_out = len(prop.list_outputs())

    def fwd_host(*np_ins):
        _, out_nd, out_types, _ = _run_forward(prop, np_ins, is_train)
        return tuple(_np.asarray(o.asnumpy(), dtype=t)
                     for o, t in zip(out_nd, out_types))

    def bwd_host(*np_args):
        """args = inputs + out_grads; recomputes forward for out_data
        (the tape-recompute formulation used framework-wide). Backward
        always runs in train mode, as in the reference."""
        from . import autograd
        from . import ndarray as nd
        from .context import cpu

        n_in = len(np_args) - n_out
        np_ins, np_cots = np_args[:n_in], np_args[n_in:]
        in_nd, out_nd, _, op = _run_forward(prop, np_ins, True)
        with autograd.pause():
            ograd_nd = _to_nd(np_cots)
            igrad_nd = [nd.zeros(a.shape, dtype=a.dtype, ctx=cpu())
                        for a in in_nd]
            op.backward(req=["write"] * n_in, out_grad=ograd_nd,
                        in_data=in_nd, out_data=out_nd, in_grad=igrad_nd,
                        aux=[])
        return tuple(_np.asarray(g.asnumpy(), dtype=a.dtype)
                     for g, a in zip(igrad_nd, np_ins))

    def primal(*ins):
        out_shapes, out_types = _infer(prop, ins)
        structs = tuple(jax.ShapeDtypeStruct(s, t)
                        for s, t in zip(out_shapes, out_types))
        return jax.pure_callback(fwd_host, structs, *ins, vmap_method="sequential")

    @jax.custom_vjp
    def f(*ins):
        return primal(*ins)

    def f_fwd(*ins):
        return primal(*ins), ins

    def f_bwd(ins, cots):
        structs = tuple(jax.ShapeDtypeStruct(a.shape, a.dtype) for a in ins)
        return jax.pure_callback(bwd_host, structs, *(tuple(ins) + tuple(cots)),
                                 vmap_method="sequential")

    f.defvjp(f_fwd, f_bwd)
    return f, n_out


def _custom_dispatch(*arrays, op_type=None, is_train=False, **kwargs):
    """The registry op function for 'Custom' (reference entry:
    nd.Custom(*data, op_type=...) -> custom.cc CustomOperator). `is_train`
    is injected by the dispatch layer from the autograd training flag,
    like the reference's CustomOperator ctx.is_train."""
    if op_type is None:
        raise MXNetError("Custom requires op_type=")
    attr_key = tuple(sorted((k, str(v)) for k, v in kwargs.items()))
    f, n_out = _make_custom_fn(op_type, attr_key, bool(is_train))
    out = f(*arrays)
    if n_out == 1:
        return out[0]
    return tuple(out)


from . import ops as _ops  # noqa: E402

_ops.register("Custom", num_outputs=-1)(_custom_dispatch)

# install the generated front-end functions (the registry was already
# populated when nd/sym imported, before this module ran)
from . import ndarray as _nd_mod  # noqa: E402
from .ndarray.register import _make_function  # noqa: E402

_nd_mod.Custom = _make_function(_ops.get("Custom"))

from . import symbol as _sym_mod  # noqa: E402
from .symbol.register import _make_symbol_function  # noqa: E402

_sym_mod.Custom = _make_symbol_function(_ops.get("Custom"))
