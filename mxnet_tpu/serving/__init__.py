"""mxnet_tpu.serving — dynamic-batching inference serving.

The serving layer that turns the single-request predict API
(`mxnet_tpu.predict`, the c_predict_api rebuild) into sustained
high-occupancy inference (docs/serving.md):

  * `DynamicBatcher` — coalesces concurrent requests into padded,
    power-of-two-bucketed batches so every bucket hits one cached XLA
    executable (batcher.py);
  * `ModelRepository` / `ServedModel` — versioned multi-model registry
    over export prefixes and compiled ``.mxc`` artifacts, bucket warmup
    at load, hot load/unload with in-flight draining
    (model_repository.py);
  * `ServingServer` — stdlib `ThreadingHTTPServer` frontend with
    deterministic admission control: 429 on queue overflow, 504 on
    deadline expiry, graceful SIGTERM drain (server.py).

Launch with ``python tools/serve.py``; load-test with
``python tools/serve_bench.py``. All knobs are typed ``MXTPU_SERVE_*``
variables in `mxnet_tpu.env` (docs/env_vars.md).
"""
from __future__ import annotations

from .batcher import (  # noqa: F401
    DeadlineExceededError, DrainingError, DynamicBatcher,
    ModelUnavailableError, QueueFullError, ServeRequest, ServingError,
    bucket_for, power_of_two_buckets,
)
from .model_repository import ModelRepository, ServedModel  # noqa: F401
from .server import ServingServer  # noqa: F401

__all__ = [
    "DynamicBatcher", "ServeRequest", "ModelRepository", "ServedModel",
    "ServingServer", "ServingError", "QueueFullError",
    "DeadlineExceededError", "ModelUnavailableError", "DrainingError",
    "power_of_two_buckets", "bucket_for",
]
