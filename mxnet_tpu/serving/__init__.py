"""mxnet_tpu.serving — dynamic-batching inference serving.

The serving layer that turns the single-request predict API
(`mxnet_tpu.predict`, the c_predict_api rebuild) into sustained
high-occupancy inference (docs/serving.md):

  * `DynamicBatcher` — coalesces concurrent requests into padded,
    power-of-two-bucketed batches so every bucket hits one cached XLA
    executable (batcher.py);
  * `ModelRepository` / `ServedModel` — versioned multi-model registry
    over export prefixes and compiled ``.mxc`` artifacts, bucket warmup
    at load, hot load/unload with in-flight draining
    (model_repository.py);
  * `ServingServer` — stdlib `ThreadingHTTPServer` frontend with
    deterministic admission control: 429 on queue overflow, 504 on
    deadline expiry, bounded graceful SIGTERM drain (server.py);
  * `ReplicaPool` + `supervisor` — the resilience layer: N supervised
    replica worker processes per model with heartbeat health checks,
    ejection + respawn (restart generations, exponential backoff,
    process-group teardown), exactly-once batch failover, deterministic
    load shedding (503 + Retry-After scaled to healthy replicas) and
    per-request deadline propagation (replica_pool.py / supervisor.py);
  * `generate` — continuous-batching autoregressive decode with a paged
    KV cache: `GenerateScheduler` (token-level join/leave),
    `KVPageAllocator`, the `TransformerLMEngine` incremental LM runner
    and `ServedLM` (``POST /v1/models/<name>:generate``) — Orca-style
    iteration scheduling + PagedAttention, TPU-native (generate.py);
  * `Autoscaler` — the elastic loop over all of the above: SLO-verdict
    driven in-place replica scale-up (admitted against the memory
    budget, warm via manifest prefetch), idle scale-down with drain,
    and budget-pressure bin-packing in the repository — shrink cold
    pools, evict idle models — instead of flat 507s (autoscaler.py,
    docs/serving.md §Autoscaling).

Launch with ``python tools/serve.py`` (``--replicas N`` for a pool,
``--autoscale`` for the elastic loop); load-test with ``python
tools/serve_bench.py`` (``--failover`` for the chaos row,
``--autoscale`` for the surge row). All knobs are typed
``MXTPU_SERVE_*`` / ``MXTPU_AUTOSCALE_*`` variables in `mxnet_tpu.env`
(docs/env_vars.md).
"""
from __future__ import annotations

from .autoscaler import Autoscaler  # noqa: F401
from .batcher import (  # noqa: F401
    DeadlineExceededError, DrainingError, DynamicBatcher,
    MemoryBudgetError, ModelUnavailableError, OverloadedError,
    QueueFullError, ServeRequest,
    ServingError, bucket_for, pad_batch, power_of_two_buckets,
)
from .generate import (  # noqa: F401
    GenerateScheduler, GenRequest, KVPageAllocator, ServedLM,
    TransformerLMEngine, load_lm, save_lm,
)
from .model_repository import (  # noqa: F401
    ModelRepository, ServedModel, build_runner,
)
from .replica_pool import ReplicaPool  # noqa: F401
from .server import ServingServer  # noqa: F401

__all__ = [
    "Autoscaler",
    "DynamicBatcher", "ServeRequest", "ModelRepository", "ServedModel",
    "ServingServer", "ReplicaPool", "ServingError", "QueueFullError",
    "DeadlineExceededError", "ModelUnavailableError", "DrainingError",
    "OverloadedError", "MemoryBudgetError", "power_of_two_buckets",
    "bucket_for", "pad_batch",
    "build_runner",
    "GenerateScheduler", "GenRequest", "KVPageAllocator", "ServedLM",
    "TransformerLMEngine", "save_lm", "load_lm",
]
