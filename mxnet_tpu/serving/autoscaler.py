"""Elastic autoscaling: the controller that closes the loop
load -> memory budget -> replica count (docs/serving.md §Autoscaling).

PRs 6+8+9+14 built every ingredient — supervised replica pools with
health-checked failover, ~1s warm worker starts via persistent compile
artifacts + warmup manifests, per-model footprint accounting with typed
budget admission, and an SLO engine whose `verdicts()` API is the
programmatic breach signal — but replica count stayed a static
``--replicas N``: a traffic surge ended in deterministic 429/503
shedding instead of recovery. This module is the missing loop:

  * **scale up** when any SLO objective scoped to a served model pages
    (p99 latency burn, queue-depth ceiling, availability — the windowed
    views of ``mxtpu_serve_request_seconds`` / queue depth from PR 14)
    for ``MXTPU_AUTOSCALE_UP_WINDOWS`` consecutive evaluation laps.
    The new replica is admitted against the ``MXTPU_SERVE_MEMORY_BUDGET``
    headroom (one more ``memory_bytes`` copy — every replica process
    holds a full copy) and spawns through the existing warmup-manifest
    prefetch, so scale-up is seconds, not minutes. Growth is IN PLACE
    (`ReplicaPool.add_replica`), never a reload.
  * **scale down + drain** on sustained idle (``MXTPU_AUTOSCALE_IDLE_S``
    since the model's request counters last moved), never below the
    model's ``min_replicas``. The drained member finishes its in-flight
    work (`ReplicaPool.remove_replica(drain=True)`); if it dies
    mid-drain the work rides the existing exactly-once failover
    re-enqueue — zero request loss either way.
  * **hysteresis**: consecutive-lap breach counting on the way up, an
    idle clock on the way down, and a shared ``MXTPU_AUTOSCALE_COOLDOWN_S``
    between any two scaling actions on one model, so the controller
    never flaps on a single noisy window.

Every decision is observable: ``mxtpu_autoscale_decisions_total{action=}``
counters, ``autoscale_{up,down,evict,blocked}`` flight-recorder events
(`record_decision`, shared with the repository's budget-pressure
bin-packing), the ``mxtpu_serve_replicas{model=}`` gauge, and a bounded
decision trail on ``/statusz`` (`ServingServer.attach_autoscaler`).

The controller is ONE named thread (PR-12 thread hygiene: named
``mxtpu-autoscaler``, daemon, joined by `stop`, stop-event captured as a
local). It consumes `slo.verdicts()` — the hook the SLO engine built for
exactly this caller — so it needs the SLO engine enabled (``MXTPU_SLO``)
to see breaches; with no objectives registered it only ever scales down
on idle.
"""
from __future__ import annotations

import collections
import threading
import time

from .. import env as _env
from .. import telemetry
from ..base import MXNetError
from ..telemetry import core as _tm_core
from ..telemetry import memory as _tm_memory
from ..telemetry import slo as _slo

__all__ = ["Autoscaler", "record_decision", "request_age_s",
           "min_replicas", "max_replicas"]

_ACTIONS = ("up", "down", "evict", "blocked")

# the "has this model seen traffic lately" signals: predict admissions
# and generated tokens (LM pools have no request counter on the router)
_IDLE_METRICS = ("mxtpu_serve_requests_total",
                 "mxtpu_serve_generated_tokens_total")


def record_decision(action, model, **fields):
    """Publish one autoscaling decision: the
    ``mxtpu_autoscale_decisions_total{action=}`` counter plus an
    ``autoscale_<action>`` flight-recorder event, so ``/statusz`` and
    every watchdog/SIGUSR1 dump can explain what the controller (or the
    repository's budget-pressure bin-packing) did and why."""
    if action not in _ACTIONS:
        raise MXNetError("unknown autoscale action %r (one of %s)"
                         % (action, "|".join(_ACTIONS)))
    telemetry.counter("mxtpu_autoscale_decisions_total",
                      {"action": action}).inc()
    telemetry.record_event("autoscale_%s" % action, model=model, **fields)


def request_age_s(model_label, now=None):
    """Seconds since the model's request counters last moved (the
    windowed-staleness view, PR 14) — the scale-down / eviction idle
    clock. None when no windowed signal exists yet (rings not rolled, or
    the model never saw a request)."""
    if now is None:
        now = time.time()
    _tm_core.roll_windows(now)  # throttled; staleness needs fresh rings
    age = None
    for m in _tm_core.get_registry().metrics():
        if m.name not in _IDLE_METRICS \
                or m.labels.get("model") != model_label:
            continue
        if not hasattr(m, "seconds_since_change"):
            continue
        s = m.seconds_since_change(now)
        if s is not None and (age is None or s < age):
            age = s  # ANY moving series keeps the model "hot"
    return age


def idle_age_s(model, now=None):
    """The effective idle age for scaling decisions: counter staleness
    when available, else time since load (a model that never served a
    request is as cold as its publish)."""
    if now is None:
        now = time.time()
    label = "%s/%d" % (model.name, model.version)
    age = request_age_s(label, now)
    if age is None:
        loaded = getattr(model, "loaded_at", None)
        age = max(0.0, now - loaded) if loaded else 0.0
    return age


def min_replicas(model):
    """The floor the autoscaler (and budget-pressure shrinking) honors
    for one served model: the model's declared ``min_replicas`` or the
    ``MXTPU_AUTOSCALE_MIN_REPLICAS`` default."""
    v = getattr(model, "min_replicas", None)
    if v is None:
        v = _env.get("MXTPU_AUTOSCALE_MIN_REPLICAS")
    return max(1, int(v))


def max_replicas(model):
    """The ceiling for scale-up: the model's declared ``max_replicas``
    or the ``MXTPU_AUTOSCALE_MAX_REPLICAS`` default (never below the
    floor)."""
    v = getattr(model, "max_replicas", None)
    if v is None:
        v = _env.get("MXTPU_AUTOSCALE_MAX_REPLICAS")
    return max(min_replicas(model), int(v))


class Autoscaler:
    """The per-server scaling controller over one `ModelRepository`.

    Parameters (all default to the ``MXTPU_AUTOSCALE_*`` registry):

    interval_ms : evaluation-lap period.
    up_windows : consecutive breached laps before a scale-up (the fast
        hysteresis — one noisy window never scales).
    idle_s : sustained idle (no request-counter movement) before a
        scale-down drain.
    cooldown_s : minimum seconds between two scaling actions on one
        model (up or down), so a decision's effect lands before the
        next one is taken.
    start : spawn the controller thread immediately (tests pass False
        and drive `evaluate_once` deterministically).
    """

    def __init__(self, repository, interval_ms=None, up_windows=None,
                 idle_s=None, cooldown_s=None, start=True):
        self.repository = repository
        if interval_ms is None:
            interval_ms = _env.get("MXTPU_AUTOSCALE_INTERVAL_MS")
        self.interval_s = max(0.05, float(interval_ms) / 1e3)
        if up_windows is None:
            up_windows = _env.get("MXTPU_AUTOSCALE_UP_WINDOWS")
        self.up_windows = max(1, int(up_windows))
        if idle_s is None:
            idle_s = _env.get("MXTPU_AUTOSCALE_IDLE_S")
        self.idle_s = max(0.0, float(idle_s))
        if cooldown_s is None:
            cooldown_s = _env.get("MXTPU_AUTOSCALE_COOLDOWN_S")
        self.cooldown_s = max(0.0, float(cooldown_s))
        self._state = {}  # model label -> {"breach_laps", "last_scale"}
        # bounded decision trail for /statusz (deque appends/snapshots
        # are GIL-atomic; single-writer = the evaluating thread)
        self._decisions = collections.deque(maxlen=64)
        self._thread = None
        self._stop_event = None
        if start:
            self.start()

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        """Start (or restart) the controller thread. Idempotent."""
        if self._thread is not None and self._thread.is_alive():
            return self
        ev = threading.Event()
        t = threading.Thread(target=self._loop, args=(ev,),
                             name="mxtpu-autoscaler", daemon=True)
        self._stop_event = ev
        self._thread = t
        t.start()
        return self

    def stop(self, join=True):
        """Stop (and join) the controller thread."""
        t = self._thread
        ev = self._stop_event
        self._thread = None
        self._stop_event = None
        if ev is not None:
            ev.set()
        if t is not None and join:
            t.join(timeout=30.0)

    def running(self):
        t = self._thread
        return t is not None and t.is_alive()

    def _loop(self, stop_event):
        # stop_event captured as a local (the PR-12 io.py lesson): a
        # stop()/start() cycle replaces the instance attribute and the
        # OLD thread must keep honoring the event it was started with
        while not stop_event.wait(self.interval_s):
            try:
                self.evaluate_once()
            except Exception as e:  # the controller must never die
                telemetry.record_event("autoscale_error", error=repr(e))

    # -- evaluation --------------------------------------------------------
    def evaluate_once(self, now=None, verdicts=None):
        """One controller lap over every pooled model. ``verdicts``
        injects a pre-computed verdict list (unit tests); the live path
        consumes `slo.verdicts()`. Returns the decisions taken."""
        if now is None:
            now = time.time()
        if verdicts is None:
            verdicts = _slo.verdicts()
        else:
            _tm_core.roll_windows(now)  # verdicts() would have rolled
        by_model = {}
        for v in verdicts:
            label = (v.get("labels") or {}).get("model")
            if label:
                by_model.setdefault(label, []).append(v)
        decisions = []
        for model in self.repository.models():
            pool = getattr(model, "pool", None)
            if pool is None:
                continue  # in-process models have no replica dimension
            try:
                d = self._evaluate_model(model, pool, by_model, now)
            except Exception as e:
                telemetry.record_event(
                    "autoscale_error", error=repr(e),
                    model="%s/%d" % (model.name, model.version))
                continue
            if d is not None:
                decisions.append(d)
        return decisions

    def _evaluate_model(self, model, pool, by_model, now):
        label = "%s/%d" % (model.name, model.version)
        st = self._state.setdefault(  # mxlint: gil-atomic — one evaluating thread at a time (the loop, or a test driving evaluate_once with the loop stopped); readers snapshot via dict copy
            label, {"breach_laps": 0, "last_scale": 0.0})
        paging = [v["slo"] for v in by_model.get(label, ())
                  if v.get("page")]
        if paging:
            st["breach_laps"] += 1
        else:
            st["breach_laps"] = 0
            st.pop("blocked_reason", None)  # episode over: re-arm blocked
        cooling = (now - st["last_scale"]) < self.cooldown_s
        if paging:
            if st["breach_laps"] < self.up_windows or cooling:
                return None  # hysteresis: breach must sustain
            return self._scale_up(model, pool, label, st, paging, now)
        if cooling or pool.size <= min_replicas(model):
            return None
        age = idle_age_s(model, now)
        if age < self.idle_s:
            return None
        return self._scale_down(model, pool, label, st, age, now)

    def _resident_bytes(self):
        return sum(getattr(m, "effective_memory_bytes", None) or 0
                   for m in self.repository.models())

    def _blocked(self, label, st, now, reason, **fields):
        """One blocked decision per sustained breach episode — a breach
        pinned at the ceiling must not re-fire the event every lap."""
        st["breach_laps"] = 0
        if st.get("blocked_reason") == reason:
            return None
        st["blocked_reason"] = reason
        return self._note("blocked", label, now, reason=reason, **fields)

    def _scale_up(self, model, pool, label, st, paging, now):
        size = pool.size
        if size >= max_replicas(model):
            return self._blocked(label, st, now, "max_replicas",
                                 size=size,
                                 max_replicas=max_replicas(model),
                                 slos=paging)
        # one more replica = one more full copy of the model resident
        # (docs/observability.md §Memory): admit it against the budget
        # headroom, reclaiming cold residency first when short
        needed = getattr(model, "memory_bytes", None)
        limit, warn_only = _tm_memory.serve_memory_budget()
        if needed and limit and not warn_only:
            headroom = limit - self._resident_bytes()
            if needed > headroom:
                reclaim = getattr(self.repository, "reclaim_memory", None)
                if reclaim is not None:
                    headroom += reclaim(needed - headroom, exclude=label,
                                        reason="scale_up")
            if needed > headroom:
                return self._blocked(label, st, now, "memory_budget",
                                     needed_bytes=needed,
                                     headroom_bytes=max(0, headroom),
                                     budget_bytes=limit, slos=paging)
        replica = pool.add_replica()
        st["last_scale"] = now
        st["breach_laps"] = 0
        st.pop("blocked_reason", None)
        self._publish_footprint(model)
        return self._note("up", label, now, replica=replica,
                          size=pool.size, slos=paging)

    def _scale_down(self, model, pool, label, st, age, now):
        try:
            # the floor re-checks ATOMICALLY inside remove_replica: a
            # concurrent budget-pressure reclaim may have shrunk the
            # pool since this lap's size read
            replica = pool.remove_replica(drain=True,
                                          floor=min_replicas(model))
        except MXNetError:
            return None  # lost the race to another remover: no-op lap
        st["last_scale"] = time.time()  # the drain itself took time
        self._publish_footprint(model)
        return self._note("down", label, now, reason="idle",
                          replica=replica, size=pool.size,
                          idle_s=round(age, 3))

    def _publish_footprint(self, model):
        """Refresh the model's effective-footprint gauge after a resize
        (every replica holds a full copy, so the budget-facing figure
        just changed)."""
        eff = getattr(model, "effective_memory_bytes", None)
        if eff:
            telemetry.gauge(
                "mxtpu_serve_model_memory_bytes",
                {"model": "%s/%d" % (model.name, model.version)}).set(eff)

    def _note(self, action, label, now, **fields):
        record_decision(action, label, **fields)
        d = dict(fields, action=action, model=label, ts=now)
        self._decisions.append(d)  # mxlint: gil-atomic — bounded deque append, one evaluating thread; describe() snapshots with list()
        return d

    # -- observability -----------------------------------------------------
    def describe(self):
        """Plain-dict controller state for ``/statusz`` (lock-free:
        GIL-atomic snapshot reads only — the page must answer even when
        a drain is in progress)."""
        return {
            "running": self.running(),
            "interval_s": self.interval_s,
            "up_windows": self.up_windows,
            "idle_s": self.idle_s,
            "cooldown_s": self.cooldown_s,
            "models": {label: dict(st)
                       for label, st in dict(self._state).items()},
            "decisions": list(self._decisions),
        }
