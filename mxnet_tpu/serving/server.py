"""HTTP frontend for the serving subsystem (docs/serving.md).

Same stdlib pattern as the telemetry Prometheus endpoint
(telemetry/core.py `start_http_server`): a `ThreadingHTTPServer`, one
handler thread per connection, zero new dependencies. Handler threads
block cheaply on their request's event while the per-model batcher
worker drives the accelerator.

Routes (triton/KServe-shaped):

  * ``POST /v1/models/<name>:predict``            (newest version)
  * ``POST /v1/models/<name>/versions/<v>:predict``
      body: ``{"inputs": {"<input>": <nested list>}, "timeout_ms": opt}``
      or ``{"instances": <nested list>}`` for single-input models;
      reply: ``{"outputs": [...], "model": ..., "version": ...}``.
  * ``POST /v1/models/<name>[:versions/<v>]:generate``  (LM models,
      docs/serving.md §Generation)
      body: ``{"tokens": [int...], "max_new_tokens": opt,
      "temperature": opt, "top_k": opt, "top_p": opt, "timeout_ms": opt}``
      reply: ``{"tokens": [generated ids], "num_generated": ...,
      "finish_reason": "eos"|"length", ...}`` (non-streaming; requests
      join the model's running decode batch at token granularity).
  * ``GET /v1/models``        repository listing (buckets, signatures,
      warm state, pending counts)
  * ``GET /v1/models/<name>`` one model (``?version=``)
  * ``GET /healthz``          200 ``ok`` / 503 ``draining``
  * ``GET|POST /drainz``      start draining (idempotent); reply shows
      remaining pending work — poll until 0

Admission control is deterministic: a full queue answers 429
(`MXTPU_SERVE_QUEUE_DEPTH`), an expired deadline answers 504
(`MXTPU_SERVE_TIMEOUT_MS`, per-request override via ``timeout_ms``),
draining answers 503, an unknown model 404, a malformed request 400.
SIGTERM (via `install_signal_handlers`) drains queued + in-flight
requests, then stops the server so the launcher sees exit 0.
"""
from __future__ import annotations

import json
import math
import signal
import threading
import time

import numpy as _np

from .. import env as _env
from .. import telemetry
from ..telemetry import slo as _slo
from ..telemetry import tracing as _tracing
from ..base import MXNetError
from .batcher import DrainingError, ServingError, drain_timeout_s

__all__ = ["ServingServer"]


def _int_version(raw):
    """URL version component -> int; malformed is the CLIENT's error
    (400), not a 500 from a bare ValueError."""
    try:
        return int(raw)
    except ValueError:
        raise MXNetError("version %r is not an integer" % (raw,))


class ServingServer:
    """The HTTP frontend over a `ModelRepository`."""

    def __init__(self, repository, port=None, addr="0.0.0.0"):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        self.repository = repository
        self._autoscaler = None
        self._draining = False
        self._drain_failed = False
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self._m_codes = {}
        # the drain WAITER is pre-started so the SIGTERM handler only has
        # to set an Event: the main thread spawns handler threads inside
        # `serve_forever` (ThreadingHTTPServer), so a handler that called
        # Thread.start() itself could deadlock on the threading module's
        # own locks if the signal landed mid-spawn. mxlint's signal-safety
        # checker walks `_on_signal` to keep it that trivial.
        self._closed = False
        self._drain_shutdown = False
        self._drain_event = threading.Event()
        self._drain_waiter = threading.Thread(
            target=self._drain_when_signaled, name="mxtpu-serve-drain",
            daemon=True)
        self._drain_waiter.start()
        if port is None:
            port = _env.get("MXTPU_SERVE_PORT")

        outer = self

        class _Handler(BaseHTTPRequestHandler):
            # HTTP/1.1 keep-alive: steady clients reuse their connection
            # (and its handler thread) instead of paying TCP setup + a
            # thread spawn per request; every reply carries Content-Length
            protocol_version = "HTTP/1.1"

            def do_GET(self):
                outer._route(self, "GET")

            def do_POST(self):
                outer._route(self, "POST")

            def log_message(self, fmt, *args):  # no per-request stderr spam
                pass

        class _Server(ThreadingHTTPServer):
            daemon_threads = True
            # stdlib default backlog is 5: a burst of concurrent clients
            # overflows the accept queue and eats 1-3s TCP SYN retransmits
            request_queue_size = 128

        self._http = _Server((addr, int(port)), _Handler)
        self.port = self._http.server_address[1]
        self._serve_thread = None

    # -- lifecycle ---------------------------------------------------------
    def serve_forever(self):
        """Block serving requests until `shutdown` (tools/serve.py)."""
        self._http.serve_forever(poll_interval=0.1)

    def start(self):
        """Serve on a daemon thread (tests, serve_bench). Returns self."""
        self._serve_thread = threading.Thread(
            target=self.serve_forever, name="mxtpu-serve-http", daemon=True)
        self._serve_thread.start()
        return self

    def attach_autoscaler(self, autoscaler):
        """Adopt this server's autoscaling controller (docs/serving.md
        §Autoscaling): its decision trail joins ``/statusz`` and
        `shutdown` stops (and joins) its thread — the PR-12 hygiene
        contract for the per-server controller. Returns the autoscaler."""
        self._autoscaler = autoscaler
        return autoscaler

    @property
    def autoscaler(self):
        return self._autoscaler

    def shutdown(self):
        # monotonic False->True flag (drain waiter + api callers race
        # benignly: both write the same value, readers poll)
        self._closed = True  # mxlint: gil-atomic — monotonic shutdown flag
        self._drain_event.set()  # release an idle drain waiter
        if self._autoscaler is not None:
            # scaling decisions must stop before models start dropping
            self._autoscaler.stop()
        self._http.shutdown()
        self._http.server_close()
        if self._serve_thread is not None:
            self._serve_thread.join(timeout=5.0)

    @property
    def draining(self):
        return self._draining

    def drain(self, timeout=None, shutdown=False):
        """Stop admitting work, wait for queued + in-flight requests (and
        their handler threads) to finish, optionally stop the server.
        Returns True when everything completed within ``timeout``.

        The wait is BOUNDED (`MXTPU_SERVE_DRAIN_TIMEOUT_MS`): a wedged
        executor must not wedge shutdown forever. On expiry every stranded
        request is force-completed with a deterministic 503 (the waiter
        gets an answer, not a connection reset), `drain_failed` is set, and
        the `tools/serve.py` process exits nonzero so the supervisor knows
        the drain was not clean."""
        # monotonic admission flag: the /drainz waiter thread and direct
        # api callers both only ever flip it False->True
        self._draining = True  # mxlint: gil-atomic — monotonic drain flag
        if self._autoscaler is not None:
            # scaling decisions stop BEFORE models drain: the controller
            # must not spawn (or drain) replicas into a server that is
            # shutting down — stop() joins its thread, so no lap is
            # mid-flight when drain_all starts; idempotent for the later
            # shutdown() call
            self._autoscaler.stop()
        if timeout is None:
            # drain_timeout_s honors the deprecated seconds-typed
            # MXTPU_SERVE_DRAIN_TIMEOUT_S with a one-time warning
            timeout = drain_timeout_s()
        telemetry.record_event("serve_drain_start",
                               pending=self.repository.pending())
        deadline = time.monotonic() + timeout
        ok = self.repository.drain_all(timeout)
        while self._inflight and time.monotonic() < deadline:
            time.sleep(0.01)  # let handler threads finish writing replies
        ok = ok and not self._inflight
        if not ok:
            aborted = self.repository.abort_pending()
            self._drain_failed = True  # mxlint: gil-atomic — monotonic flag
            telemetry.record_event("serve_drain_forced", aborted=aborted,
                                   timeout_s=timeout)
            # the 503s are resolved; give handler threads a moment to
            # write them out before the listener dies
            force_deadline = time.monotonic() + 2.0
            while self._inflight and time.monotonic() < force_deadline:
                time.sleep(0.01)
        telemetry.record_event("serve_drain_done", complete=ok)
        if shutdown:
            self.shutdown()
        return ok

    @property
    def drain_failed(self):
        """True when a drain timed out and force-completed requests (the
        process should exit nonzero)."""
        return self._drain_failed

    def _drain_when_signaled(self):
        """The pre-started drain waiter: parked on `_drain_event` until a
        signal handler or `/drainz` releases it, then runs the (bounded)
        drain — with shutdown when the trigger was a signal. Loops after a
        `/drainz` drain so a later SIGTERM still shuts the server down; a
        signal landing mid-drain re-sets the event and is picked up on the
        next lap."""
        while True:
            self._drain_event.wait()
            if self._closed:
                return  # plain shutdown(), nothing to drain
            self._drain_event.clear()
            shutdown = self._drain_shutdown
            telemetry.record_event("serve_drain_triggered",
                                   shutdown=shutdown)
            self.drain(shutdown=shutdown)
            if shutdown or self._closed:
                return

    def install_signal_handlers(self, signals=(signal.SIGTERM, signal.SIGINT)):
        """Graceful-drain on SIGTERM/SIGINT: the handler only flips a flag
        and sets the Event the pre-started waiter parks on — it is walked
        by the mxlint signal-safety checker, so it must stay free of
        locks, logging, allocation and thread starts (the interrupted
        main thread spawns HTTP handler threads, so Thread.start() here
        could deadlock on the threading module's internals).
        `serve_forever` returns once the drain finishes and the caller
        exits 0 (or nonzero when `drain_failed`)."""

        def _on_signal(signum, frame):
            self._drain_shutdown = True
            self._drain_event.set()

        for s in signals:
            signal.signal(s, _on_signal)

    # -- routing -----------------------------------------------------------
    def _route(self, handler, method):
        try:
            path = handler.path.split("?", 1)[0]
            query = handler.path[len(path) + 1:] if "?" in handler.path else ""
            if path.rstrip("/") == "/healthz" and method == "GET":
                if self._draining:
                    self._text(handler, 503, "draining\n")
                else:
                    self._text(handler, 200, "ok\n")
            elif path.rstrip("/") == "/statusz" and method == "GET":
                # the "what is wrong right now" page (docs/observability.md
                # §SLOs): SLO verdicts + windowed rates + pool/memory/
                # compile state. Reads lock-free snapshots only — it must
                # answer even when a model's batcher is wedged, so it
                # never touches repository/batcher locks (admission-free:
                # works while draining too)
                extra = {"server": {"port": self.port,
                                    "draining": self._draining,
                                    "drain_failed": self._drain_failed,
                                    "inflight": self._inflight}}
                if self._autoscaler is not None:
                    # the decision trail that explains every replica-count
                    # change (lock-free snapshot reads)
                    extra["autoscaler"] = self._autoscaler.describe()
                ctype, body = _slo.render_statusz(
                    "text" if "format=text" in query else "json",
                    extra=extra)
                self._count(200)
                handler.send_response(200)
                handler.send_header("Content-Type", ctype)
                handler.send_header("Content-Length", str(len(body)))
                handler.end_headers()
                handler.wfile.write(body)
            elif path.rstrip("/") == "/drainz":
                self._drain_event.set()  # idempotent: wakes the waiter
                self._json(handler, 200, {
                    "draining": True,
                    "pending": self.repository.pending(),
                    "inflight": self._inflight,
                })
            elif path == "/v1/models" and method == "GET":
                self._json(handler, 200, self.repository.describe())
            elif path.startswith("/v1/models/"):
                self._model_route(handler, method, path[len("/v1/models/"):],
                                  query)
            else:
                self._json(handler, 404, {"error": "no route %s %s"
                                          % (method, path)})
        except BrokenPipeError:
            pass  # client went away mid-reply
        except ServingError as e:
            payload = {"error": str(e)}
            details = getattr(e, "details", None)
            if details:
                # 507s carry the footprint breakdown (what to evict) —
                # docs/serving.md §Autoscaling
                payload["details"] = details
            self._json(handler, e.status, payload,
                       retry_after=e.retry_after)
        except MXNetError as e:
            self._json(handler, 400, {"error": str(e)})
        except Exception as e:  # the server must answer, never unwind
            self._json(handler, 500, {"error": "%s: %s"
                                      % (type(e).__name__, e)})

    def _model_route(self, handler, method, rest, query):
        version = None
        if ":" in rest:
            rest, verb = rest.split(":", 1)
        else:
            verb = None
        if "/versions/" in rest:
            rest, v = rest.split("/versions/", 1)
            version = _int_version(v)
        name = rest.strip("/")
        if version is None and query.startswith("version="):
            version = _int_version(query.split("=", 1)[1].split("&")[0])
        if verb == "predict" and method == "POST":
            self._predict(handler, name, version)
        elif verb == "generate" and method == "POST":
            self._generate(handler, name, version)
        elif verb is None and method == "GET":
            model = self.repository.get(name, version)
            self._json(handler, 200, model.describe())
        else:
            self._json(handler, 404, {"error": "no route %s /v1/models/%s%s"
                                      % (method, name,
                                         ":" + verb if verb else "")})

    # -- predict -----------------------------------------------------------
    def _predict(self, handler, name, version):
        # trace context is minted AT ADMISSION (or honored from an
        # incoming `x-mxtpu-trace` header — a proxy/client that already
        # traces keeps its ids); the reply always carries the header so
        # callers can link any outcome to its trace
        ref = _tracing.parse_header(
            handler.headers.get(_tracing.HEADER) or "")
        ref = _tracing.mint(ref)
        handler._mxtpu_trace = _tracing.header_value(ref)
        with _tracing.root("serve.request", component="server", ref=ref,
                           attrs={"model": name}):
            self._predict_traced(handler, name, version)

    def _predict_traced(self, handler, name, version):
        # consume the body FIRST: replying before the read would desync a
        # keep-alive connection (next request line = leftover body bytes)
        length = int(handler.headers.get("Content-Length") or 0)
        raw_body = handler.rfile.read(length) if length > 0 else b""
        if self._draining:
            raise DrainingError("server is draining")
        model = self.repository.get(name, version)
        if not hasattr(model, "predict"):
            raise MXNetError(
                "model %r is a generation model; use :generate" % name)
        if not raw_body:
            raise MXNetError("empty request body")
        try:
            body = json.loads(raw_body.decode())
        except (ValueError, UnicodeDecodeError) as e:
            raise MXNetError("request body is not JSON: %s" % e)
        if "inputs" in body:
            raw = body["inputs"]
            if not isinstance(raw, dict):
                raise MXNetError("'inputs' must be an object of "
                                 "input-name -> array")
        elif "instances" in body:
            names = sorted(model.example_shapes)
            if len(names) != 1:
                raise MXNetError(
                    "'instances' shorthand needs a single-input model; "
                    "%r has inputs %s — use 'inputs'" % (name, names))
            raw = {names[0]: body["instances"]}
        else:
            raise MXNetError("request needs 'inputs' or 'instances'")
        try:
            arrays = {k: _np.asarray(v, dtype=model.input_dtypes.get(k))
                      for k, v in raw.items()}
        except (ValueError, TypeError, KeyError) as e:
            raise MXNetError("malformed input array: %s" % e)
        timeout_ms = body.get("timeout_ms")
        if timeout_ms is not None:
            timeout_ms = float(timeout_ms)
        with self._inflight_lock:
            self._inflight += 1
        try:
            outputs = model.predict(arrays, timeout_ms=timeout_ms)
            self._json(handler, 200, {
                "model": model.name,
                "version": model.version,
                "outputs": [o.tolist() for o in outputs],
            })
        finally:
            with self._inflight_lock:
                self._inflight -= 1

    # -- generate ----------------------------------------------------------
    def _generate(self, handler, name, version):
        ref = _tracing.parse_header(
            handler.headers.get(_tracing.HEADER) or "")
        ref = _tracing.mint(ref)
        handler._mxtpu_trace = _tracing.header_value(ref)
        with _tracing.root("serve.request", component="server", ref=ref,
                           attrs={"model": name, "verb": "generate"}):
            self._generate_traced(handler, name, version)

    def _generate_traced(self, handler, name, version):
        # body FIRST (keep-alive desync, same as predict)
        length = int(handler.headers.get("Content-Length") or 0)
        raw_body = handler.rfile.read(length) if length > 0 else b""
        if self._draining:
            raise DrainingError("server is draining")
        model = self.repository.get(name, version)
        gen = getattr(model, "generate", None)
        if gen is None:
            raise MXNetError(
                "model %r does not serve :generate (it is a predict "
                "model; load an LM artifact with generate=True)" % name)
        if not raw_body:
            raise MXNetError("empty request body")
        try:
            body = json.loads(raw_body.decode())
        except (ValueError, UnicodeDecodeError) as e:
            raise MXNetError("request body is not JSON: %s" % e)
        tokens = body.get("tokens")
        if not isinstance(tokens, list) or not tokens \
                or not all(isinstance(t, int) and not isinstance(t, bool)
                           for t in tokens):
            raise MXNetError("'tokens' must be a non-empty list of int "
                             "token ids")
        kwargs = {}
        for field, cast in (("max_new_tokens", int), ("temperature", float),
                            ("top_k", int), ("top_p", float),
                            ("timeout_ms", float)):
            if body.get(field) is None:
                continue
            try:
                value = cast(body[field])
            except (TypeError, ValueError):
                value = None
            # json.loads accepts NaN/Infinity literals; a non-finite knob
            # would silently poison the sampling masks — it is the
            # CLIENT's error (400), never a garbage 200 or a 500
            if value is None or not math.isfinite(value):
                raise MXNetError("%r must be a finite number, got %r"
                                 % (field, body[field]))
            kwargs[field] = value
        with self._inflight_lock:
            self._inflight += 1
        try:
            result = gen(tokens, **kwargs)
            self._json(handler, 200, {
                "model": model.name,
                "version": model.version,
                "tokens": result["tokens"],
                "num_generated": len(result["tokens"] or ()),
                "finish_reason": result.get("finish_reason"),
            })
        finally:
            with self._inflight_lock:
                self._inflight -= 1

    # -- replies -----------------------------------------------------------
    def _count(self, code):
        m = self._m_codes.get(code)
        if m is None:
            m = telemetry.counter("mxtpu_serve_http_requests_total",
                                  {"code": str(code)})
            # racing handler threads both miss and both store the SAME
            # object (the telemetry registry is the point of truth), so
            # the last-wins dict store is harmless memoization
            self._m_codes[code] = m  # mxlint: gil-atomic — idempotent memo
        m.inc()

    def _text(self, handler, code, text):
        body = text.encode()
        self._count(code)
        handler.send_response(code)
        handler.send_header("Content-Type", "text/plain; charset=utf-8")
        handler.send_header("Content-Length", str(len(body)))
        handler.end_headers()
        handler.wfile.write(body)

    def _json(self, handler, code, payload, retry_after=None):
        body = (json.dumps(payload) + "\n").encode()
        self._count(code)
        if code >= 400:
            # error replies may precede a full body read on some routes;
            # closing keeps the keep-alive stream from desyncing
            handler.close_connection = True
        handler.send_response(code)
        handler.send_header("Content-Type", "application/json")
        handler.send_header("Content-Length", str(len(body)))
        trace = getattr(handler, "_mxtpu_trace", None)
        if trace is not None:
            # header contract: every predict reply (success or error)
            # names its trace so a slow/failed request is renderable
            handler.send_header(_tracing.HEADER, trace)
        if retry_after is None and code == 429:
            retry_after = 1
        if retry_after is not None:
            # load-shed contract: 503s carry a Retry-After scaled to the
            # healthy-replica count (OverloadedError.retry_after)
            handler.send_header("Retry-After", str(int(retry_after)))
        handler.end_headers()
        handler.wfile.write(body)
