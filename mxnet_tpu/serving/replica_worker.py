"""``python -m mxnet_tpu.serving.replica_worker`` — the replica worker
entry point, split from `supervisor` so runpy never re-executes a module
the serving package already imported (the sys.modules RuntimeWarning)."""
from __future__ import annotations

from .supervisor import worker_main

if __name__ == "__main__":
    import sys

    sys.exit(worker_main())
