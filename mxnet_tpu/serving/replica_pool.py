"""Supervised replica pool: the serving resilience router.

`DynamicBatcher` assembles batches; this pool routes them to N replica
worker processes (`supervisor.py`) and keeps the endpoint answering
through the failures PR 2's fault harness and PR 3's flight recorder were
built to expose (docs/serving.md §resilience):

  * **health**: every replica is watched on a heartbeat deadline
    (``MXTPU_SERVE_HEARTBEAT_MS``). An idle replica is ping/pong'd; a
    busy one is silent-bounded by its batch deadline plus the heartbeat
    grace. A dead (process exit) or wedged (deadline missed) replica is
    EJECTED — process-group teardown — and respawned with exponential
    backoff on a fresh generation.
  * **failover**: the ejected replica's in-flight batch is pushed back to
    the front of the queue EXACTLY ONCE per request
    (`DynamicBatcher.requeue`; predict is idempotent so one retry is
    safe — the duplicate-work bound is one forward per failed-over
    request). Expired members 504, twice-unlucky members get a
    retryable 503.
  * **load shedding**: the admission gate sheds deterministically when
    the pool is degraded — with h of N replicas healthy only
    ``h/N`` of the queue depth is admitted, and beyond it (or at h=0)
    clients get 503 + ``Retry-After`` scaled to the healthy count
    instead of queueing into a black hole.
  * **deadline propagation**: each dispatched batch carries its remaining
    deadline budget; the replica cancels (``expired``) instead of
    computing answers nobody is waiting for.

Pool state is wired through telemetry (healthy-replica gauge, failover /
restart / shed counters, per-replica in-flight gauge) and every ejection
emits a flight-recorder event (docs/observability.md).

Weight sharing note: WITHIN a replica the padding buckets share one copy
of the weights (`predict._clone_with`); ACROSS co-located replica
processes each loads its own copy — device-memory sharing across PJRT
client processes is not portable, an accepted divergence recorded in
docs/serving.md.
"""
from __future__ import annotations

import collections
import hmac
import math
import queue
import secrets
import socket
import threading
import time

from .. import env as _env
from .. import telemetry
from ..telemetry import tracing as _tracing
from ..base import MXNetError
from .batcher import (DeadlineExceededError, DrainingError, OverloadedError,
                      QueueFullError, ServingError, drain_timeout_s,
                      pad_batch)
from .supervisor import (TOKEN_LEN, ReplicaProcess, backoff_s, recv_msg,
                         send_msg)

__all__ = ["ReplicaPool"]

# replica slot states
_SPAWNING = "spawning"   # process launched, not yet ready
_READY = "ready"         # healthy, idle
_BUSY = "busy"           # healthy, running a batch
_DEAD = "dead"           # ejected, awaiting respawn backoff


class _Slot:
    """Mutable state for one replica slot (owned by its dispatch thread;
    `state`/`conn` transitions are published under the pool lock)."""

    def __init__(self, replica_id, proc, joining=False):
        self.id = replica_id
        self.proc = proc          # ReplicaProcess (generation counter)
        self.state = _DEAD
        self.conn = None
        self.conn_event = threading.Event()  # a connection arrived
        self.ready_info = None
        self.consecutive_restarts = 0
        self.msg_id = 0
        self.thread = None        # this slot's dispatch thread
        # resize protocol (docs/serving.md §Autoscaling): `stop` asks the
        # dispatch thread to finish its in-flight work and exit (set under
        # the pool lock; the thread polls it between batches); `joining`
        # marks a scale-up member that has not reported ready yet — the
        # degraded-admission gate must not shed while a NEW replica warms
        # (only when an ESTABLISHED one is lost)
        self.stop = False
        self.joining = joining
        # generate mode: stats round trips requested by the api thread,
        # serviced by this slot's dispatch loop (deque append/popleft are
        # GIL-atomic; waiter events close the handoff)
        self.stats_requests = collections.deque()
        self.stats_pending = {}   # msg id -> waiter (dispatch thread only)


class ReplicaPool:
    """Router + supervisor for one served model's replica processes.

    Parameters
    ----------
    model : str
        Telemetry/flight-recorder label (usually ``name/version``).
    worker_args : list of str
        Argv tail for ``python -m mxnet_tpu.serving.supervisor`` —
        what to serve (``--artifact``/``--input``/``--stub`` flags).
    replicas : int
        Pool size (>= 1).
    heartbeat_ms / backoff_ms / wedge_timeout_ms : float, optional
        Override ``MXTPU_SERVE_HEARTBEAT_MS`` /
        ``MXTPU_SERVE_RESTART_BACKOFF_MS`` /
        ``MXTPU_SERVE_WEDGE_TIMEOUT_MS``.
    extra_env : dict, optional
        Extra environment for replica processes only (tests inject
        ``MXTPU_FAULT_INJECT`` serving actions here so the router itself
        stays fault-free).
    spawn_timeout_s : float
        Budget for one replica spawn → ready (includes model load + full
        bucket warm; compiles can be slow).
    teardown_grace : float, optional
        Seconds between SIGTERM and SIGKILL at ejection (default
        ``MXTPU_TEARDOWN_GRACE``; tests shrink it).
    """

    def __init__(self, model, worker_args, replicas, heartbeat_ms=None,
                 backoff_ms=None, extra_env=None, spawn_timeout_s=120.0,
                 teardown_grace=None, wedge_timeout_ms=None, generate=False,
                 gen_queue_depth=None, gen_outstanding=None):
        if replicas < 1:
            raise MXNetError("replica pool needs >= 1 replicas, got %d"
                             % replicas)
        self.model = str(model)
        self.size = int(replicas)
        if heartbeat_ms is None:
            heartbeat_ms = _env.get("MXTPU_SERVE_HEARTBEAT_MS")
        self.heartbeat_s = max(0.01, float(heartbeat_ms) / 1e3)
        if wedge_timeout_ms is None:
            wedge_timeout_ms = _env.get("MXTPU_SERVE_WEDGE_TIMEOUT_MS")
        self.wedge_timeout_s = max(0.05, float(wedge_timeout_ms) / 1e3)
        self._backoff_ms = backoff_ms
        self._spawn_timeout_s = float(spawn_timeout_s)
        self._batcher = None
        self._stop = False
        self._lock = threading.Lock()
        # BOUNDED handoff (one buffered batch per replica): when every
        # replica is busy and the buffer is full, dispatch_batch blocks
        # the batcher worker, the request queue backs up, and the existing
        # 429/degraded-503 admission checks fire — an unbounded buffer
        # here would hide the backlog from admission control entirely
        self._work = queue.Queue(maxsize=max(1, self.size))
        # generate mode (docs/serving.md §Generation): requests route
        # individually — each replica worker runs its own continuous-
        # batching scheduler, so the router's job is request routing,
        # health and exactly-once failover, not batch assembly
        self._generate = bool(generate)
        if gen_queue_depth is None:
            gen_queue_depth = _env.get("MXTPU_SERVE_QUEUE_DEPTH")
        self._gen_queue_depth = max(1, int(gen_queue_depth))
        self._gen_outstanding = max(1, int(gen_outstanding)) \
            if gen_outstanding else 16
        self._gen_cv = threading.Condition()
        self._gen_pending = collections.deque()
        self._gen_live = set()    # admitted + unresolved (guarded: _gen_cv)

        labels = {"model": self.model}
        if self._generate:
            # router-side admission volume + end-to-end latency for
            # pooled GENERATE models (predict pools get these from their
            # DynamicBatcher; the LM scheduler's copies live in the
            # worker processes under per-replica labels) — without them
            # the autoscaler's idle clock and p99 objective would read a
            # busy LM pool as eternally cold (docs/serving.md
            # §Autoscaling)
            self._m_gen_reqs = telemetry.counter(
                "mxtpu_serve_requests_total", labels)
            self._m_gen_request_s = telemetry.histogram(
                "mxtpu_serve_request_seconds", labels)
            self._m_gen_shed = {
                reason: telemetry.counter(
                    "mxtpu_serve_rejected_total",
                    {"model": self.model, "reason": reason})
                for reason in ("queue_full", "shed")}
        self._m_healthy = telemetry.gauge("mxtpu_serve_pool_healthy", labels)
        self._m_size = telemetry.gauge("mxtpu_serve_pool_size", labels)
        # the autoscaler-facing replica-count gauge (same value as
        # pool_size, named for the scaling loop's dashboards — the series
        # a `mxtpu_autoscale_decisions_total` spike should move)
        self._m_replicas = telemetry.gauge("mxtpu_serve_replicas", labels)
        self._m_size.set(self.size)
        self._m_replicas.set(self.size)
        self._m_failover = telemetry.counter("mxtpu_serve_failover_total",
                                             labels)
        self._m_requeued = telemetry.counter(
            "mxtpu_serve_failover_requeued_total", labels)
        self._m_restarts = telemetry.counter(
            "mxtpu_serve_replica_restart_total", labels)
        self._m_inflight = {}  # replica id -> per-replica in-flight gauge
        self._m_generation = {}  # replica id -> restart-generation gauge

        # per-pool handshake secret: a connection must present it before
        # the accept loop will unpickle a single frame (localhost TCP is
        # reachable by every local user; pickle is not)
        self._token = secrets.token_hex(TOKEN_LEN // 2)

        # one listener for every replica generation; workers CONNECT to it
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("127.0.0.1", 0))
        self._listener.listen(max(8, replicas * 2))
        self._listener.settimeout(0.25)
        addr = self._listener.getsockname()

        # kept for in-place resize: add_replica spawns new slots with the
        # SAME serving spec the pool was built with
        self._addr = (addr[0], addr[1])
        self._worker_args = list(worker_args)
        self._extra_env = extra_env
        self._teardown_grace = teardown_grace
        self._next_id = 0

        # `_slots` is REPLACED wholesale (never mutated in place) under
        # the pool lock, so lock-free readers iterate a consistent
        # snapshot even while a resize is landing; slot/gauge creation
        # holds the lock for the same discipline add_replica follows
        self._slots = []
        with self._lock:
            for _ in range(replicas):
                self._slots = self._slots + [self._new_slot()]

        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name="mxtpu-pool-accept-%s" % self.model)
        self._accept_thread.start()
        self._threads = []
        for slot in self._slots:
            self._start_slot_thread(slot)

    def _new_slot(self, joining=False):
        """Build one slot + its telemetry gauges (caller publishes it into
        `_slots` and starts its dispatch thread)."""
        k = self._next_id
        self._next_id += 1
        proc = ReplicaProcess(self.model, k, self._addr, self._worker_args,
                              extra_env=self._extra_env,
                              teardown_grace=self._teardown_grace,
                              token=self._token)
        slot = _Slot(k, proc, joining=joining)
        self._m_inflight[k] = telemetry.gauge(
            "mxtpu_serve_replica_inflight",
            {"model": self.model, "replica": str(k)})
        # restart generation per replica, published as a gauge so the
        # lock-free /statusz page can show pool health generations
        # without touching the pool's own locked describe()
        self._m_generation[k] = telemetry.gauge(
            "mxtpu_serve_replica_generation",
            {"model": self.model, "replica": str(k)})
        return slot

    def _start_slot_thread(self, slot):
        t = threading.Thread(target=self._replica_loop, args=(slot,),
                             daemon=True,
                             name="mxtpu-pool-%s-r%d" % (self.model,
                                                         slot.id))
        slot.thread = t
        self._threads.append(t)  # mxlint: gil-atomic — append-only roster
        t.start()

    def _slot_by_id(self, replica_id):
        for s in self._slots:
            if s.id == replica_id:
                return s
        return None

    def _resize_work_queue(self):
        """Track the bounded dispatch handoff to the live pool size (one
        buffered batch per replica — the backpressure contract)."""
        with self._work.mutex:
            self._work.maxsize = max(1, self.size)
            self._work.not_full.notify_all()

    # -- batcher wiring ----------------------------------------------------
    def bind(self, batcher):
        """Attach the model's DynamicBatcher (its dispatcher hook feeds
        `dispatch_batch`; its admission gate is `admission_gate`)."""
        self._batcher = batcher

    def dispatch_batch(self, batch, total):
        """DynamicBatcher dispatcher hook (runs on the batcher worker
        thread): hand the assembled batch to the replica dispatch threads
        — N replicas run N batches concurrently. Blocks while the bounded
        handoff buffer is full so overload backpressure reaches the
        batcher's admission checks instead of piling up here; expired
        members are pruned replica-side at dispatch."""
        while not self._stop:
            try:
                self._work.put((batch, total), timeout=0.05)
                return
            except queue.Full:
                continue
        # pool shut down under the batch: resolve, don't strand
        self._batcher.fail_batch(batch, OverloadedError(
            "model %r replica pool shut down before dispatch" % self.model))

    def admission_gate(self, queued_len):
        """Deterministic load shedding, consulted under the batcher queue
        lock on every submit. Healthy pool: admit (the depth check still
        applies). Degraded pool: scale the admissible queue to the healthy
        fraction. Dead pool: shed everything, Retry-After = the respawn
        backoff horizon.

        `size`/`expected_count` are read LIVE on every call, so after an
        autoscaler resize the shed quota and the ``Retry-After =
        ceil(N/h)`` horizon are computed against the POST-resize pool —
        never a size captured before the resize landed. A scale-up member
        that has not warmed yet (`joining`) is excluded from `expected`:
        growing the pool must not trigger shedding while the new replica
        compiles."""
        with self._lock:  # ONE acquisition per admission (hot path)
            healthy = sum(1 for s in self._slots
                          if s.state in (_READY, _BUSY))
            expected = max(1, self.size - sum(1 for s in self._slots
                                              if s.joining))
        if healthy >= expected:
            return None
        if healthy == 0:
            slots = self._slots  # consistent snapshot (replaced wholesale)
            eta = max((backoff_s(s.consecutive_restarts, self._backoff_ms)
                       for s in slots), default=1.0)
            return OverloadedError(
                "model %r has no healthy replicas (respawn in progress)"
                % self.model, retry_after=max(1.0, eta))
        # max(1, ...): a degraded-but-alive pool must keep admitting —
        # small queue depths would otherwise floor the quota to 0 and turn
        # a single-replica loss into a total outage
        allowed = max(1, int(self._batcher.queue_depth * healthy
                             / expected)) \
            if self._batcher is not None else 0
        if queued_len >= allowed:
            return OverloadedError(
                "model %r is degraded (%d/%d replicas healthy; queue "
                "scaled to %d)" % (self.model, healthy, expected, allowed),
                retry_after=math.ceil(self.size / healthy))
        return None

    # -- in-place resize (docs/serving.md §Autoscaling) --------------------
    def add_replica(self):
        """Grow the pool by one replica IN PLACE: spawn a fresh worker
        (same serving spec, fresh id) and start its dispatch thread. The
        new member joins the rotation when its warm finishes (a warmup-
        manifest prefetch makes that seconds, docs/compile_cache.md);
        until then the admission gate treats the pool at its pre-grow
        capacity instead of shedding. Returns the new replica id."""
        with self._lock:
            if self._stop:
                raise MXNetError("replica pool %r is shut down" % self.model)
            slot = self._new_slot(joining=True)
            self._slots = self._slots + [slot]
            self.size += 1
            size = self.size
        self._resize_work_queue()
        self._m_size.set(size)
        self._m_replicas.set(size)
        self._start_slot_thread(slot)
        telemetry.record_event("serve_replica_add", model=self.model,
                               replica=slot.id, size=size)
        return slot.id

    def remove_replica(self, replica_id=None, drain=True, timeout=None,
                       floor=1):
        """Shrink the pool by one replica IN PLACE with zero request
        loss: the victim (default: the newest slot) stops taking new work
        immediately, finishes what it has in flight, and is then torn
        down. If the worker dies mid-drain its unresolved work rides the
        existing exactly-once failover re-enqueue instead of being lost.
        ``drain=False`` (or a drain past ``timeout``) forces teardown —
        in-flight work then fails over. ``floor`` is checked UNDER the
        pool lock, so concurrent removers (the autoscaler's idle drain
        racing a load's budget-pressure reclaim) cannot both pass a
        caller-side check and shrink below a model's ``min_replicas``.
        Returns the removed replica id."""
        if timeout is None:
            timeout = drain_timeout_s()
        floor = max(1, int(floor))
        with self._lock:
            if self.size <= floor:
                raise MXNetError(
                    "replica pool %r cannot shrink below %d replica(s)"
                    % (self.model, floor))
            slots = self._slots
            if replica_id is None:
                slot = slots[-1]
            else:
                slot = next((s for s in slots if s.id == replica_id), None)
                if slot is None:
                    raise MXNetError("replica pool %r has no replica %r"
                                     % (self.model, replica_id))
            # published BEFORE the drain: admission/quota math and the
            # healthy gauge see the post-resize pool immediately
            slot.stop = True
            self._slots = [s for s in slots if s is not slot]
            self.size -= 1
            size = self.size
        self._resize_work_queue()
        self._m_size.set(size)
        self._m_replicas.set(size)
        self._set_healthy_gauge()
        with self._gen_cv:
            self._gen_cv.notify_all()  # wake an idle generate dispatch wait
        t = slot.thread
        if not drain:
            # no-drain removal: tear the worker down now; the dispatch
            # thread ejects on the dead socket and fails in-flight work
            # over exactly once
            slot.proc.teardown()
        if t is not None:
            t.join(timeout=max(0.1, timeout))
            if t.is_alive():
                # drain overran its budget: force the worker out — the
                # dispatch thread sees the dead socket, ejects, and fails
                # any in-flight work over exactly once
                slot.proc.teardown()
                t.join(timeout=10.0)
        conn = slot.conn
        if conn is not None:
            try:
                send_msg(conn, {"kind": "shutdown"})
            except OSError:
                pass
        slot.proc.teardown()
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass
        # retire the removed slot's per-replica series — a gauge for a
        # replica that no longer exists would read as a ghost on /statusz
        reg = telemetry.get_registry()
        for name in ("mxtpu_serve_replica_inflight",
                     "mxtpu_serve_replica_generation"):
            reg.remove(name, {"model": self.model, "replica": str(slot.id)})
        with self._lock:
            self._m_inflight.pop(slot.id, None)
            self._m_generation.pop(slot.id, None)
        telemetry.record_event("serve_replica_remove", model=self.model,
                               replica=slot.id, size=size,
                               drained=not (t is not None and t.is_alive()))
        return slot.id

    # -- generate-mode routing (docs/serving.md §Generation) ---------------
    def submit_generate(self, req):
        """Admit one `GenRequest` into the pool's routing queue. Healthy
        replicas' dispatch threads pull from it; admission sheds
        deterministically like predict (dead pool: 503 + backoff ETA,
        full queue: 429). `healthy_count` is read BEFORE the queue lock —
        the pool lock and the generate lock never nest."""
        healthy = self.healthy_count
        with self._gen_cv:
            if self._stop:
                raise DrainingError("model %r replica pool is shut down"
                                    % self.model)
            if healthy == 0:
                eta = max((backoff_s(s.consecutive_restarts,
                                     self._backoff_ms)
                           for s in self._slots), default=1.0)
                self._m_gen_shed["shed"].inc()
                raise OverloadedError(
                    "model %r has no healthy replicas (respawn in "
                    "progress)" % self.model, retry_after=max(1.0, eta))
            if len(self._gen_pending) >= self._gen_queue_depth:
                self._m_gen_shed["queue_full"].inc()
                raise QueueFullError(
                    "generation queue for %r is full (%d requests; "
                    "MXTPU_SERVE_QUEUE_DEPTH)"
                    % (self.model, self._gen_queue_depth))
            self._gen_pending.append(req)
            self._gen_live.add(req)
            self._m_gen_reqs.inc()
            self._gen_cv.notify()
        return req

    def generate_pending(self):
        """Admitted-and-unresolved generation requests (drain progress)."""
        with self._gen_cv:
            return len(self._gen_live)

    def drain_generate(self, timeout=None):
        if timeout is None:
            timeout = drain_timeout_s()
        deadline = time.monotonic() + timeout
        while self.generate_pending():
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.01)
        return True

    def abort_generate(self, error=None):
        """Force-resolve every admitted generation request (bounded-drain
        escape hatch; first-resolution-wins makes the race with live
        replies benign). Returns how many were force-resolved."""
        if error is None:
            error = DrainingError(
                "model %r shut down before this generation completed"
                % self.model)
        with self._gen_cv:
            victims = list(self._gen_live)
            self._gen_pending.clear()
        n = 0
        for req in victims:
            if not req.done():
                req._resolve(error=error)
                n += 1
        with self._gen_cv:
            self._gen_live.difference_update(victims)
        return n

    def replica_stats(self, replica_id, timeout=5.0):
        """One stats round trip to a replica worker (KV-page occupancy,
        post-warm jit count — the serve_bench/test evidence hooks).
        Returns the worker's stats dict, or None on timeout/eject."""
        slot = self._slot_by_id(replica_id)
        if slot is None:
            return None
        waiter = {"event": threading.Event(), "result": None}
        slot.stats_requests.append(waiter)
        with self._gen_cv:
            self._gen_cv.notify_all()   # nudge an idle dispatch loop
        if not waiter["event"].wait(timeout):
            return None
        return waiter["result"]

    def _gen_wire_error(self, msg):
        """Map a worker ``gen_error`` frame back to the typed admission
        error the HTTP layer knows how to answer."""
        status = msg.get("status")
        text = str(msg.get("error") or "replica generation error")
        if status == 429:
            return QueueFullError(text)
        if status == 504:
            return DeadlineExceededError(text)
        if status == 503:
            return OverloadedError(text)
        if status == 400:
            return MXNetError(text)
        return ServingError(text)

    def _requeue_generate(self, reqs):
        """Failover: push a dead replica's unresolved generation requests
        back to the routing queue's front, EXACTLY ONCE per request (the
        decode prefix is recomputed on the new replica — generation from
        a fixed prompt is idempotent for greedy and harmlessly re-drawn
        for sampled requests). Expired members 504; twice-unlucky get a
        retryable 503."""
        now = time.monotonic()
        requeued = 0
        taken = set()
        with self._gen_cv:
            accept = not self._stop
            for req in reversed(reqs):
                if req.done():
                    continue
                if req.deadline is not None and now >= req.deadline:
                    continue   # resolved below, outside the lock
                if req.retried or not accept:
                    continue
                req.retried = True
                req.tag = None
                taken.add(req)
                self._gen_pending.appendleft(req)
                requeued += 1
            if requeued:
                self._gen_cv.notify_all()
        for req in reqs:
            if req in taken:
                continue
            # even already-resolved requests (router-side expiry fired
            # while the batch was in flight) must leave _gen_live, or a
            # dead replica's phantom entries pin generate_pending() > 0
            # and every later drain spins to its timeout
            with self._gen_cv:
                self._gen_live.discard(req)
            if req.done():
                continue
            if req.deadline is not None and now >= req.deadline:
                req._resolve(error=DeadlineExceededError(
                    "deadline expired during replica failover"))
            elif req.retried:
                req._resolve(error=OverloadedError(
                    "generation already failed over once on model %r"
                    % self.model))
            else:
                req._resolve(error=OverloadedError(
                    "model %r is draining; generation not retried"
                    % self.model))
        return requeued

    def _serve_generate(self, slot):
        """Generate-mode dispatch loop for one replica: pull requests
        from the routing queue (bounded outstanding window), ship them as
        ``generate`` frames, and resolve ``gen_result``/``gen_error``
        replies as they arrive — OUT OF ORDER, matched by id, because the
        worker's scheduler finishes sequences at different lengths. The
        worker's receive thread answers pings while its scheduler
        decodes, so liveness stays on the heartbeat clock even under
        long generations. Returns (reason, unresolved) for ejection, or
        None on clean shutdown."""
        conn = slot.conn
        outstanding = {}   # msg id -> (req, dispatch ref, t0, t0_wall)
        last_frame = time.monotonic()
        ping_pending = False

        def unresolved():
            return [e[0] for e in outstanding.values()]

        try:
            while not self._stop:
                # drain the routing queue up to the outstanding window
                # BEFORE blocking on the socket: a burst of admissions
                # must not pay one recv timeout per dispatched request.
                # A draining slot (removal in progress) admits nothing
                # new but keeps servicing replies for what it dispatched.
                while len(outstanding) < self._gen_outstanding \
                        and not slot.stop:
                    req = None
                    with self._gen_cv:
                        if self._gen_pending:
                            req = self._gen_pending.popleft()
                        elif not outstanding and not slot.stats_requests:
                            self._gen_cv.wait(0.05)
                    if req is None:
                        break
                    now = time.monotonic()
                    if req.done():
                        with self._gen_cv:
                            self._gen_live.discard(req)
                        continue
                    if req.deadline is not None and now >= req.deadline:
                        with self._gen_cv:
                            self._gen_live.discard(req)
                        req._resolve(error=DeadlineExceededError(
                            "deadline expired before dispatch"))
                        continue
                    slot.msg_id += 1
                    req.tag = slot.msg_id
                    ref = _tracing.child_ref(req.trace)
                    frame = {
                        "kind": "generate", "id": slot.msg_id,
                        "tokens": req.tokens,
                        "max_new_tokens": req.max_new_tokens,
                        "temperature": req.temperature,
                        "top_k": req.top_k, "top_p": req.top_p,
                        "remaining": None if req.deadline is None
                        else max(0.0, req.deadline - now),
                        "trace": _tracing.to_wire(ref)
                        if ref is not None and ref.sampled else None,
                    }
                    try:
                        send_msg(conn, frame)
                    except OSError:
                        return ("died_mid_batch", [req] + unresolved())
                    outstanding[slot.msg_id] = (req, ref, now, time.time())
                    self._m_inflight[slot.id].set(len(outstanding))
                while slot.stats_requests:
                    waiter = slot.stats_requests.popleft()
                    slot.msg_id += 1
                    slot.stats_pending[slot.msg_id] = waiter
                    try:
                        send_msg(conn, {"kind": "stats",
                                        "id": slot.msg_id})
                    except OSError:
                        return ("died_mid_batch", unresolved())
                if slot.stop and not outstanding:
                    return None  # removal drain complete: nothing in flight
                try:
                    msg = recv_msg(
                        conn,
                        first_timeout=0.01 if outstanding else 0.05,
                        rest_timeout=max(1.0, self.heartbeat_s))
                except socket.timeout:
                    now = time.monotonic()
                    if not slot.proc.alive():
                        return ("died", unresolved())
                    if now - last_frame > 2 * self.heartbeat_s \
                            and ping_pending:
                        return ("heartbeat_missed", unresolved())
                    if now - last_frame > self.heartbeat_s \
                            and not ping_pending:
                        slot.msg_id += 1
                        try:
                            send_msg(conn, {"kind": "ping",
                                            "id": slot.msg_id})
                        except OSError:
                            return ("died_mid_batch", unresolved())
                        ping_pending = True
                    # router-side expiry backstop (grace past the
                    # deadline: the worker's own expiry normally answers
                    # first; first-resolution-wins absorbs the race)
                    for r, _, _, _ in list(outstanding.values()):
                        if r.deadline is not None \
                                and now >= r.deadline + 1.0 \
                                and not r.done():
                            r._resolve(error=DeadlineExceededError(
                                "generation deadline expired"))
                    continue
                except OSError:
                    return ("died_mid_batch", unresolved())
                if msg is None:
                    return ("died", unresolved())
                last_frame = time.monotonic()
                kind = msg.get("kind")
                if kind == "pong":
                    ping_pending = False
                elif kind in ("gen_result", "gen_error"):
                    entry = outstanding.pop(msg.get("id"), None)
                    self._m_inflight[slot.id].set(len(outstanding))
                    if entry is None:
                        continue   # late reply for a resolved request
                    r, ref, t0, t0_wall = entry
                    with self._gen_cv:
                        self._gen_live.discard(r)
                    if kind == "gen_result":
                        if ref is not None:
                            _tracing.emit_span(
                                "serve.dispatch", t0_wall,
                                time.monotonic() - t0, r.trace,
                                component="router", span_id=ref.span_id,
                                attrs={"replica": slot.id,
                                       "tokens":
                                       len(msg.get("tokens") or ())})
                        # router-side end-to-end latency (admission →
                        # resolution): the series the pooled-LM p99
                        # objective and the autoscaler read
                        self._m_gen_request_s.observe(
                            max(0.0, time.monotonic() - r._t_submit),
                            exemplar=r.trace.trace_id
                            if r.trace is not None and r.trace.recorded
                            else None)
                        r._resolve(outputs=list(msg.get("tokens") or []),
                                   finish_reason=msg.get("finish_reason"))
                        # the generation proved itself: reset backoff
                        if slot.consecutive_restarts:
                            with self._lock:
                                slot.consecutive_restarts = 0
                    else:
                        r._resolve(error=self._gen_wire_error(msg))
                elif kind == "stats_result":
                    waiter = slot.stats_pending.pop(msg.get("id"), None)
                    if waiter is not None:
                        waiter["result"] = msg.get("stats")
                        waiter["event"].set()
                else:
                    return ("protocol_desync", unresolved())
            return None
        finally:
            self._m_inflight[slot.id].set(0)
            for waiter in slot.stats_pending.values():
                waiter["event"].set()   # never park replica_stats callers
            slot.stats_pending.clear()

    # -- state -------------------------------------------------------------
    @property
    def healthy_count(self):
        with self._lock:
            return sum(1 for s in self._slots
                       if s.state in (_READY, _BUSY))

    @property
    def expected_count(self):
        """How many replicas the pool is SUPPOSED to have serving right
        now: the live size minus scale-up members still warming. The
        degraded-admission denominator — a joining replica must not
        count as a loss."""
        with self._lock:
            return max(1, self.size - sum(1 for s in self._slots
                                          if s.joining))

    def wait_ready(self, timeout=None):
        """Block until every replica reported ready once (load + warm).
        Returns the first replica's ready info (buckets, shapes, dtypes).
        Raises MXNetError on timeout."""
        if timeout is None:
            timeout = self._spawn_timeout_s
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                infos = [s.ready_info for s in self._slots]
            if all(i is not None for i in infos):
                return infos[0]
            time.sleep(0.01)
        raise MXNetError(
            "replica pool %r: %d/%d replicas ready within %.0fs"
            % (self.model, self.healthy_count, self.size, timeout))

    def describe(self):
        with self._lock:
            return {
                "replicas": self.size,
                "mode": "generate" if self._generate else "predict",
                "healthy": sum(1 for s in self._slots
                               if s.state in (_READY, _BUSY)),
                "states": {s.id: s.state for s in self._slots},
                "generations": {s.id: s.proc.generation
                                for s in self._slots},
            }

    def replica_pid(self, replica_id):
        """Pid of a replica's current process (serve_bench chaos hook)."""
        slot = self._slot_by_id(replica_id)
        return slot.proc.pid if slot is not None else None

    def replica_ids(self):
        """Live replica ids (sparse after resizes — ids never recycle)."""
        with self._lock:
            return [s.id for s in self._slots]

    # -- lifecycle ---------------------------------------------------------
    def close(self, timeout=5.0):
        """Stop dispatching, shut every replica down (shutdown message,
        then escalating teardown) and join the pool threads."""
        self._stop = True
        for _ in self._slots:
            try:
                self._work.put_nowait(None)  # wake idle dispatch threads
            except queue.Full:
                break  # full buffer: threads notice _stop on get timeout
        with self._gen_cv:
            self._gen_cv.notify_all()        # wake generate dispatch waits
        for t in self._threads:
            t.join(timeout=timeout)
        if self._generate:
            # anything still unresolved gets a deterministic answer, not
            # a stranded waiter
            self.abort_generate()
        for slot in self._slots:
            conn = slot.conn
            if conn is not None:
                try:
                    send_msg(conn, {"kind": "shutdown"})
                except OSError:
                    pass
            slot.proc.teardown()
            if conn is not None:
                try:
                    conn.close()
                except OSError:
                    pass
        try:
            self._listener.close()
        except OSError:
            pass
        self._set_healthy_gauge()

    # -- accept loop -------------------------------------------------------
    def _read_token(self, conn, timeout=5.0):
        """Read the fixed-length handshake secret — raw bytes, never
        pickled — and constant-time compare it to the pool's. False on
        short read, timeout, or mismatch."""
        conn.settimeout(timeout)
        buf = bytearray()
        try:
            while len(buf) < TOKEN_LEN:
                chunk = conn.recv(TOKEN_LEN - len(buf))
                if not chunk:
                    return False
                buf.extend(chunk)
        except (OSError, socket.timeout):
            return False
        return hmac.compare_digest(bytes(buf), self._token.encode("ascii"))

    def _accept_loop(self):
        """Accept replica connections, require the pool handshake secret
        BEFORE unpickling anything, match the hello to a slot and the
        slot's CURRENT generation (a zombie from a torn-down generation is
        refused), then hand the socket to the slot's dispatch thread."""
        while not self._stop:
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                if not self._read_token(conn):
                    conn.close()
                    continue
                hello = recv_msg(conn, first_timeout=5.0)
            except (OSError, socket.timeout):
                conn.close()
                continue
            if not isinstance(hello, dict) or hello.get("kind") != "hello":
                conn.close()
                continue
            k = hello.get("replica")
            gen = hello.get("generation")
            with self._lock:
                # slots are found BY ID, not index: after resizes the id
                # space is sparse (removed ids are never reused)
                slot = self._slot_by_id(k) if isinstance(k, int) else None
                if slot is None or gen != slot.proc.generation \
                        or slot.conn is not None:
                    slot = None
                else:
                    slot.conn = conn
                    slot.conn_event.set()
            if slot is None:
                conn.close()

    # -- per-replica dispatch / health loop --------------------------------
    def _replica_loop(self, slot):
        """One thread per replica slot: spawn → wait ready → serve batches
        (with idle heartbeats) → on death/wedge: eject, fail over, respawn
        with backoff. The loop body is guarded: this thread IS the slot's
        supervision — an escaped exception would silently shrink the pool
        forever (no eject event, no respawn), so any surprise ejects and
        respawns like a replica death."""
        while not self._stop and not slot.stop:
            try:
                # spawn the next generation
                slot.conn_event.clear()
                with self._lock:
                    slot.conn = None
                    slot.state = _SPAWNING
                gen = slot.proc.spawn()
                telemetry.record_event(
                    "serve_replica_spawn", model=self.model,
                    replica=slot.id, generation=gen, pid=slot.proc.pid)
                if not self._await_ready(slot):
                    if self._stop or slot.stop:
                        return
                    self._eject(slot, "spawn_failed", batch=None)
                    continue
                # serve until ejection, removal drain, or shutdown
                reason = self._serve_generate(slot) if self._generate \
                    else self._serve_generation(slot)
                if self._stop or reason is None:
                    return
                # a removed slot's failure still fails its in-flight work
                # over (exactly-once), but never respawns
                self._eject(slot, reason[0], batch=reason[1])
                if slot.stop:
                    return
            except Exception as e:
                if self._stop:
                    return
                telemetry.record_event(
                    "serve_replica_loop_error", model=self.model,
                    replica=slot.id, error=repr(e))
                try:
                    self._eject(slot, "internal_error", batch=None)
                except Exception:
                    pass  # keep supervising even when ejection misfires

    def _await_ready(self, slot):
        """Wait for this generation's connection + ready message (load +
        warm happen replica-side first). True on success."""
        deadline = time.monotonic() + self._spawn_timeout_s
        while time.monotonic() < deadline and not self._stop \
                and not slot.stop:
            if slot.conn_event.wait(timeout=0.1):
                break
            if not slot.proc.alive():
                return False  # died before connecting (bad artifact, OOM)
        if self._stop or slot.stop or slot.conn is None:
            return False
        try:
            msg = recv_msg(slot.conn,
                           first_timeout=max(0.1,
                                             deadline - time.monotonic()),
                           rest_timeout=30.0)
        except (OSError, socket.timeout):
            return False
        if not isinstance(msg, dict) or msg.get("kind") != "ready":
            return False
        with self._lock:
            slot.ready_info = msg
            slot.state = _READY
            # a scale-up member is established from its first ready: it
            # now counts toward the degraded-admission denominator
            slot.joining = False
            # consecutive_restarts is NOT reset here: an artifact that
            # warms on zeros but crashes on real input would otherwise
            # respawn at the constant initial backoff forever — the reset
            # waits until the generation serves a batch cleanly
        self._set_healthy_gauge()
        self._m_generation[slot.id].set(slot.proc.generation)
        telemetry.record_event(
            "serve_replica_ready", model=self.model, replica=slot.id,
            generation=slot.proc.generation,
            warm_seconds=round(msg.get("warm_seconds") or 0.0, 3))
        return True

    def _serve_generation(self, slot):
        """Dispatch batches on this replica until it dies or wedges.
        Returns (reason, batch_or_None) for ejection, or None on clean
        pool shutdown — or on a removal drain (`slot.stop`): the slot
        finishes the batch it holds, takes nothing new, and exits with
        zero request loss."""
        while not self._stop and not slot.stop:
            try:
                item = self._work.get(timeout=self.heartbeat_s / 2)
            except queue.Empty:
                # idle: liveness first (cheap), then a ping/pong round trip
                # bounded by the heartbeat deadline
                if not slot.proc.alive():
                    return ("died", None)
                if not self._ping(slot):
                    return ("heartbeat_missed", None)
                continue
            if item is None:
                return None  # close() sentinel
            batch, total = item
            # the batch may have aged in the work queue while every
            # replica was busy — do not ship expired members
            batch = self._batcher._prune_expired(batch)
            total = sum(r.n for r in batch)
            if not batch:
                continue
            try:
                outcome = self._run_batch(slot, batch, total)
            except Exception as e:
                # unexpected (bad output shapes in resolve_batch, a
                # pad_batch surprise): eject WITH the batch so its live
                # members ride the exactly-once failover instead of
                # hanging until their own deadlines
                telemetry.record_event(
                    "serve_replica_error", model=self.model,
                    replica=slot.id, error=repr(e))
                return ("internal_error", batch)
            if outcome is not None:
                return (outcome, batch)
        return None

    def _ping(self, slot):
        slot.msg_id += 1
        try:
            send_msg(slot.conn, {"kind": "ping", "id": slot.msg_id})
            msg = recv_msg(slot.conn, first_timeout=self.heartbeat_s,
                           rest_timeout=self.heartbeat_s)
        except (OSError, socket.timeout):
            return False
        return isinstance(msg, dict) and msg.get("kind") == "pong"

    def _run_batch(self, slot, batch, total):
        """Ship one batch to the replica and wait (bounded) for the
        answer. Returns None when the batch resolved (success, expiry or
        model error), or an ejection reason string when the replica died
        or went silent past its deadline."""
        padded, bucket = pad_batch(batch, total, self._batcher.buckets)
        # remaining budget: the LATEST member deadline (a replica only
        # cancels when nobody is waiting anymore); None if any member has
        # no deadline at all
        now = time.monotonic()
        remaining = None
        deadlines = [r.deadline for r in batch]
        if all(d is not None for d in deadlines):
            remaining = max(0.0, max(deadlines) - now)
        slot.msg_id += 1
        msg_id = slot.msg_id
        # per-request dispatch spans: ids are minted BEFORE the send so
        # the replica's compute span can parent under them on the far side
        # of the wire ((trace_id, span_id, sampled) tuples on the frame)
        dispatch_refs = [(req, _tracing.child_ref(req.trace))
                         for req in batch]
        wire_traces = [_tracing.to_wire(ref) for _, ref in dispatch_refs
                       if ref is not None and ref.sampled]
        with self._lock:
            slot.state = _BUSY
        self._m_inflight[slot.id].set(total)
        t0 = time.monotonic()
        t0_wall = time.time()
        # silence bound: max(batch deadline budget, the wedge floor) plus
        # the heartbeat grace. The floor (`MXTPU_SERVE_WEDGE_TIMEOUT_MS`)
        # decouples wedge detection from client deadlines — a forward that
        # legitimately outlasts a request budget must not be SIGKILLed
        # mid-compute; deadline-less batches use the floor alone
        budget = self.wedge_timeout_s if remaining is None \
            else max(remaining, self.wedge_timeout_s)
        silence_deadline = t0 + budget + self.heartbeat_s
        try:
            send_msg(slot.conn, {
                "kind": "predict", "id": msg_id, "arrays": padded,
                "bucket": bucket, "n": total, "remaining": remaining,
                "traces": wire_traces})
            while True:
                try:
                    msg = recv_msg(slot.conn, first_timeout=0.1,
                                   rest_timeout=max(1.0, self.heartbeat_s))
                except socket.timeout:
                    if not slot.proc.alive():
                        return "died_mid_batch"
                    if time.monotonic() >= silence_deadline:
                        return "wedged"
                    continue
                if msg is None:
                    return "died_mid_batch"  # EOF under an in-flight batch
                break
        except OSError:
            return "died_mid_batch"
        finally:
            self._m_inflight[slot.id].set(0)
            with self._lock:
                if slot.state == _BUSY:
                    slot.state = _READY
        kind = msg.get("kind")
        if kind == "result" and msg.get("id") == msg_id:
            # dispatch span per traced request: the router-side window
            # around the wire round trip; `wire_s` (window minus the
            # replica's own compute) is the serialization + hop cost
            dispatch_s = time.monotonic() - t0
            compute_s = msg.get("seconds") or dispatch_s
            for req, ref in dispatch_refs:
                if ref is not None:
                    _tracing.emit_span(
                        "serve.dispatch", t0_wall, dispatch_s, req.trace,
                        component="router", span_id=ref.span_id,
                        attrs={"replica": slot.id,
                               "wire_s": max(0.0, dispatch_s - compute_s),
                               "compute_s": compute_s})
            self._batcher.resolve_batch(batch, msg["outputs"], bucket,
                                        total, compute_s)
            # the generation proved itself on real input: the exponential
            # respawn backoff resets only now, so a warm-but-crash-on-input
            # artifact still escalates toward the 60s cap
            if slot.consecutive_restarts:
                with self._lock:
                    slot.consecutive_restarts = 0
            return None
        if kind == "expired" and msg.get("id") == msg_id:
            # replica cancelled past-deadline work; expire what's expired,
            # anything still live gets a retryable 503 (clock skew) — a
            # 504 would blame a deadline that never actually passed
            live = self._batcher._prune_expired(batch)
            if live:
                self._batcher.fail_batch(live, OverloadedError(
                    "replica %d cancelled the batch as past-deadline but "
                    "%d member(s) are still live; retry"
                    % (slot.id, len(live)), retry_after=1.0))
            return None
        if kind == "error":
            self._batcher.fail_batch(batch, ServingError(
                "model %r replica %d failed: %s"
                % (self.model, slot.id, msg.get("error"))))
            return None
        # protocol desync (stale pong, wrong id): the socket's framing
        # can no longer be trusted — eject and fail over
        return "protocol_desync"

    # -- ejection / failover ----------------------------------------------
    def _eject(self, slot, reason, batch=None):
        """Tear the replica's process group down, fail its in-flight batch
        over (exactly-once re-enqueue), publish telemetry + the
        flight-recorder event, and back off before the next spawn."""
        with self._lock:
            slot.state = _DEAD
            slot.ready_info = None
            conn, slot.conn = slot.conn, None
            slot.conn_event.clear()
            slot.consecutive_restarts += 1
            restarts = slot.consecutive_restarts
        self._set_healthy_gauge()
        exit_code = slot.proc.exit_code()
        slot.proc.teardown()
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass
        requeued = 0
        if batch:
            requeued = self._requeue_generate(batch) if self._generate \
                else self._batcher.requeue(batch)
            self._m_failover.inc()
            self._m_requeued.inc(requeued)
        self._m_restarts.inc()
        delay = backoff_s(restarts, self._backoff_ms)
        # the flight-recorder event every ejection must leave behind
        telemetry.record_event(
            "serve_replica_eject", model=self.model, replica=slot.id,
            generation=slot.proc.generation, reason=reason,
            exit_code=exit_code, requeued=requeued,
            backoff_s=round(delay, 3))
        if batch:
            telemetry.record_event(
                "serve_failover", model=self.model, replica=slot.id,
                requeued=requeued, dropped=len(batch) - requeued)
        deadline = time.monotonic() + delay
        while time.monotonic() < deadline and not self._stop \
                and not slot.stop:
            time.sleep(0.02)

    def _set_healthy_gauge(self):
        self._m_healthy.set(self.healthy_count)
