"""Replica process supervision + the replica worker entry point.

The serving resilience layer (docs/serving.md §resilience) runs each
served model as N *replica worker processes* so a wedged executor, a
poisoned request, or an OOM kills one process, not the endpoint. This
module is the process half of that design; the routing half is
`replica_pool.ReplicaPool`.

It deliberately reuses the `tools/launch.py` supervision machinery's
shape (docs/fault_tolerance.md): workers are spawned as session leaders
so teardown can signal the whole process GROUP (grandchildren die too),
teardown escalates SIGTERM → SIGKILL over `MXTPU_TEARDOWN_GRACE`, every
respawn bumps a per-replica restart *generation* exported as
`MXTPU_RESTART_GENERATION` (the same variable the elastic launcher uses,
so `MXTPU_FAULT_INJECT`'s ``gen=`` condition gates replica faults exactly
like trainer faults — a respawned replica does NOT re-fire its fault),
and respawns back off exponentially (`MXTPU_SERVE_RESTART_BACKOFF_MS`,
doubling, capped at 60s).

Worker side (``python -m mxnet_tpu.serving.supervisor``): loads an
artifact (or a test stub), warms every padding bucket, CONNECTS to the
pool's localhost listener, and serves length-prefixed pickled messages:

    router -> replica   {kind: predict, id, arrays, bucket, n, remaining}
                        {kind: generate, id, tokens, max_new_tokens,
                         temperature, top_k, top_p, remaining, trace}
                        {kind: ping, id} | {kind: stats, id}
                        {kind: shutdown}
    replica -> router   {kind: hello, replica, generation, pid}
                        {kind: ready, warm_seconds, bucket_flops,
                         bucket_memory, compile_digests, generate, ...}
                        {kind: result, id, outputs, seconds}
                        {kind: gen_result, id, tokens, finish_reason}
                        {kind: gen_error, id, status, error}
                        {kind: expired, id} | {kind: error, id, error}
                        {kind: pong, id} | {kind: stats_result, id, stats}

Generation workers (``--generate PREFIX``, docs/serving.md §Generation)
run their own continuous-batching scheduler: ``generate`` frames enqueue
into it and the receive loop keeps answering pings while the scheduler
thread decodes, so liveness stays on the heartbeat clock under long
generations; ``gen_result`` replies are pushed OUT OF ORDER as sequences
finish (the completion hook owns a send lock).

``remaining`` is the batch deadline budget in seconds (per-request
deadlines are process-local monotonic times, so the ROUTER converts to a
remaining budget before the wire): a replica that wakes up past it —
e.g. after a ``slow_reply`` injection — answers ``expired`` and never
runs the forward, so a slow replica cancels work instead of computing
answers nobody is waiting for.

SIGTERM asks the worker to finish its current batch and exit 0; the
handler (`_on_term`) only flips a flag — it is walked by the mxlint
signal-safety checker, so it must stay free of locks/logging/allocation
beyond a list-slot store.
"""
from __future__ import annotations

import logging
import os
import pickle
import signal
import socket
import struct
import subprocess
import sys
import threading
import time

from .. import env as _env

_LOG = logging.getLogger("mxnet_tpu.serving.supervisor")

_HDR = struct.Struct("!I")
_MAX_MSG = 1 << 30  # 1 GiB framing sanity bound
TOKEN_LEN = 32      # hex chars of the per-pool handshake secret


# ---------------------------------------------------------------------------
# wire protocol (shared by router and worker)
# ---------------------------------------------------------------------------

def send_msg(sock, obj):
    """One length-prefixed pickle frame. Pickle over a TCP socket is only
    safe because the router refuses to unpickle ANYTHING from a connection
    that has not first presented the pool's per-process handshake secret
    (`MXTPU_SERVE_POOL_TOKEN`, random per pool, handed to workers via
    their environment — the moral equivalent of multiprocessing's
    authkey); without it, any local user who found the 127.0.0.1 port
    could run code in the serving process via a crafted frame."""
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_HDR.pack(len(payload)) + payload)


def recv_msg(sock, first_timeout=None, rest_timeout=30.0):
    """Receive one frame. ``first_timeout`` bounds the wait for the FIRST
    byte (None = block); once a message has started, ``rest_timeout``
    bounds each subsequent chunk so a half-written frame from a dying peer
    cannot park us forever. Returns None on clean EOF before a frame
    starts. socket.timeout is raised ONLY before a frame starts (the
    stream is intact and a retry is safe); once bytes of a frame were
    consumed, a stall raises plain OSError — the framing can no longer be
    trusted, so callers that retry socket.timeout (the router's poll loop)
    must never resume reading mid-frame garbage."""
    sock.settimeout(first_timeout)
    try:
        first = sock.recv(_HDR.size)
    except socket.timeout:
        raise
    if not first:
        return None
    sock.settimeout(rest_timeout)
    buf = bytearray(first)
    try:
        while len(buf) < _HDR.size:
            chunk = sock.recv(_HDR.size - len(buf))
            if not chunk:
                raise OSError("peer closed mid-header")
            buf.extend(chunk)
        (length,) = _HDR.unpack(bytes(buf))
        if length > _MAX_MSG:
            raise OSError("oversized frame (%d bytes)" % length)
        data = bytearray()
        while len(data) < length:
            chunk = sock.recv(min(1 << 20, length - len(data)))
            if not chunk:
                raise OSError("peer closed mid-message")
            data.extend(chunk)
    except socket.timeout:
        raise OSError("peer stalled mid-frame (rest_timeout %.1fs)"
                      % rest_timeout) from None
    return pickle.loads(bytes(data))


# ---------------------------------------------------------------------------
# router side: one supervised replica process
# ---------------------------------------------------------------------------

def _signal_pg(proc, sig):
    """Signal the worker's whole process group (it was spawned a session
    leader), falling back to the single pid."""
    if proc.poll() is not None:
        return
    try:
        os.killpg(proc.pid, sig)
    except (ProcessLookupError, PermissionError, OSError):
        try:
            proc.send_signal(sig)
        except OSError:
            pass


def teardown(proc, grace=None):
    """Escalating SIGTERM → SIGKILL process-group teardown (the
    tools/launch.py `_teardown` contract for a single worker): give the
    group `grace` seconds (`MXTPU_TEARDOWN_GRACE`) to exit cleanly, then
    SIGKILL the survivors — a replica wedged in a forward ignores nothing
    after SIGKILL, so ejection can never hang the router."""
    if proc.poll() is not None:
        return
    if grace is None:
        grace = _env.get("MXTPU_TEARDOWN_GRACE")
    _signal_pg(proc, signal.SIGTERM)
    deadline = time.monotonic() + max(0.0, grace)
    while time.monotonic() < deadline and proc.poll() is None:
        time.sleep(0.02)
    if proc.poll() is None:
        _signal_pg(proc, signal.SIGKILL)
    try:
        proc.wait(timeout=10)
    except (subprocess.TimeoutExpired, OSError):
        pass


def _pump(stream, label):
    """Prefix a replica's merged stdout/stderr per line (the launch.py
    rank-prefix pattern) so a multi-replica post-mortem stays readable."""
    prefix = ("[%s] " % label).encode()
    out = getattr(sys.stderr, "buffer", None)
    for line in iter(stream.readline, b""):
        if out is not None:
            out.write(prefix + line)
            out.flush()
        else:
            sys.stderr.write((prefix + line).decode("utf-8", "replace"))
            sys.stderr.flush()
    stream.close()


class ReplicaProcess:
    """Spawn/teardown state for one replica slot.

    ``worker_args`` is the argv tail describing WHAT to serve (artifact or
    stub flags); this class owns generation counting, the env protocol and
    the process-group lifecycle. A fresh `spawn()` after `teardown()`
    starts the next generation.
    """

    def __init__(self, model, replica_id, connect_addr, worker_args,
                 extra_env=None, teardown_grace=None, token=None):
        self.model = str(model)
        self.replica_id = int(replica_id)
        self.connect_addr = connect_addr
        self.worker_args = list(worker_args)
        self.extra_env = dict(extra_env or {})
        self.teardown_grace = teardown_grace
        self.token = token
        self.generation = -1  # no spawn yet
        self.proc = None
        self._pump_thread = None

    @property
    def pid(self):
        return self.proc.pid if self.proc is not None else None

    def alive(self):
        return self.proc is not None and self.proc.poll() is None

    def spawn(self):
        """Start the next generation of this replica (session leader, own
        process group, line-prefixed output). Returns the generation."""
        self.generation += 1
        env = dict(os.environ)
        env.update(self.extra_env)
        # the launcher env protocol: generation gates fault injection and
        # labels flight-recorder events in the worker
        env["MXTPU_RESTART_GENERATION"] = str(self.generation)
        if self.token:
            # handshake secret via the environment (same-UID readable
            # only — argv would leak it to every user via /proc)
            env["MXTPU_SERVE_POOL_TOKEN"] = self.token
        # a replica must never inherit the parent's serving port/telemetry
        # HTTP endpoint (port collisions across respawns)
        env.pop("MXTPU_TELEMETRY_PORT", None)
        repo_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = repo_root + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
        argv = [sys.executable, "-m", "mxnet_tpu.serving.replica_worker",
                "--connect", "%s:%d" % self.connect_addr,
                "--replica", str(self.replica_id),
                "--generation", str(self.generation)] + self.worker_args
        self.proc = subprocess.Popen(
            argv, env=env, start_new_session=True,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        self._pump_thread = threading.Thread(
            target=_pump, args=(self.proc.stdout,
                                "%s/r%d.g%d" % (self.model, self.replica_id,
                                                self.generation)),
            daemon=True,
            name="mxtpu-replica-pump-r%d" % self.replica_id)
        self._pump_thread.start()
        return self.generation

    def teardown(self):
        if self.proc is not None:
            teardown(self.proc, self.teardown_grace)

    def exit_code(self):
        return self.proc.poll() if self.proc is not None else None


def backoff_s(consecutive_restarts, initial_ms=None):
    """Exponential respawn backoff: initial * 2^(n-1), capped at 60s."""
    if initial_ms is None:
        initial_ms = _env.get("MXTPU_SERVE_RESTART_BACKOFF_MS")
    if consecutive_restarts <= 0:
        return 0.0
    return min(60.0, (initial_ms / 1e3) * (2 ** (consecutive_restarts - 1)))


# ---------------------------------------------------------------------------
# worker side
# ---------------------------------------------------------------------------

# SIGTERM flag: a one-slot list the handler stores into. The handler is an
# mxlint signal-safety entry point — no locks, no logging, no Event.set().
_STOP = [False]


def _on_term(signum, frame):
    _STOP[0] = True


def _build_stub_runner(args):
    """Test stubs (numpy-only, no artifact): `echo` answers x*2; a
    positive --stub-delay-ms sleeps per batch (holds batches in flight so
    tests can land faults deterministically)."""
    import numpy as np

    delay = max(0.0, args.stub_delay_ms) / 1e3

    def runner(arrays, bucket, n):
        if delay:
            time.sleep(delay)
        name = sorted(arrays)[0]
        return [np.asarray(arrays[name]) * 2.0]

    return runner


def _parse_inputs(specs):
    shapes, dtypes = {}, {}
    for spec in specs or ():
        name, _, dims = spec.partition("=")
        if ":" in dims:
            dims, dtype = dims.split(":", 1)
            dtypes[name] = dtype
        shapes[name] = tuple(int(d) for d in dims.split("x") if d)
    return shapes, (dtypes or None)


def _connect_and_hello(args):
    """Dial the pool's listener, present the handshake secret and the
    hello frame; returns the connected socket (shared by the predict and
    generate worker paths)."""
    host, _, port = args.connect.rpartition(":")
    sock = socket.create_connection((host, int(port)), timeout=30)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    # authenticate BEFORE the first pickled frame: the router unpickles
    # nothing from a connection that has not presented the pool secret
    token = (_env.raw("MXTPU_SERVE_POOL_TOKEN") or "").encode("ascii")
    sock.sendall(token.ljust(TOKEN_LEN, b"\0")[:TOKEN_LEN])
    send_msg(sock, {"kind": "hello", "replica": args.replica,
                    "generation": args.generation, "pid": os.getpid()})
    return sock


def _generate_worker_main(args):
    """Generation replica (docs/serving.md §Generation): build the LM
    decode engine, warm every prefill/decode bucket, report ready with
    the KV geometry, then serve ``generate`` frames by feeding the local
    continuous-batching scheduler — replies are pushed as sequences
    finish, out of order, while this receive loop keeps answering
    pings/stats."""
    from .. import compile as _compile
    from .. import telemetry
    from ..telemetry import tracing
    from .batcher import ServingError
    from .generate import GenerateScheduler, TransformerLMEngine, load_lm

    compile_cursor = _compile.mark()
    engine = TransformerLMEngine(
        lm=load_lm(args.generate), num_pages=args.kv_pages,
        page_size=args.kv_page_size, max_prompt=args.max_prompt,
        max_new_tokens=args.max_new_tokens, max_batch=args.max_batch)
    sched = GenerateScheduler(engine, name="replica%d" % args.replica,
                              warm=not args.no_warm)
    compile_entries = _compile.keys_since(compile_cursor)

    sock = _connect_and_hello(args)
    send_lock = threading.Lock()

    def _send(obj):
        with send_lock:     # scheduler completion hook + this loop share
            send_msg(sock, obj)

    misses = telemetry.get_registry().counter("mxtpu_jit_cache_miss_total")
    base_miss = misses.value

    def stats():
        # the acceptance evidence: zero-compile steady state + KV pages
        # reclaimed, observable from the router (pool.replica_stats)
        return {"kv_pages_total": sched.allocator.num_pages,
                "kv_pages_used": sched.allocator.used_pages,
                "jit_after_warm": misses.value - base_miss,
                "pending": sched.pending()}

    _send({"kind": "ready", "replica": args.replica,
           "generation": args.generation,
           "warm_seconds": sched.warm_seconds,
           "buckets": list(engine.buckets),
           "example_shapes": {}, "input_dtypes": None,
           "bucket_flops": None, "bucket_memory": None,
           "generate": engine.geometry(),
           "compile_digests":
               sorted({d for _, d in compile_entries}) or None,
           "compile_prefetched": 0})
    _LOG.info("generate replica %d gen %d ready (warm %.2fs, buckets %s)",
              args.replica, args.generation, sched.warm_seconds or 0.0,
              list(engine.buckets))

    def on_complete(req):
        if req.tag is None:
            return
        if req.error is not None:
            _send({"kind": "gen_error", "id": req.tag,
                   "status": getattr(req.error, "status", 500),
                   "error": str(req.error)})
        else:
            _send({"kind": "gen_result", "id": req.tag,
                   "tokens": list(req.outputs or []),
                   "finish_reason": req.finish_reason})

    served = 0
    try:
        while not _STOP[0]:
            try:
                msg = recv_msg(sock, first_timeout=0.25)
            except socket.timeout:
                continue
            except OSError:
                break
            if msg is None or msg.get("kind") == "shutdown":
                break
            kind = msg.get("kind")
            if kind == "ping":
                _send({"kind": "pong", "id": msg.get("id")})
                continue
            if kind == "stats":
                _send({"kind": "stats_result", "id": msg.get("id"),
                       "stats": stats()})
                continue
            if kind != "generate":
                _LOG.warning("generate replica %d: unknown message "
                             "kind %r", args.replica, kind)
                continue
            served += 1
            deadline = None if msg.get("remaining") is None \
                else time.monotonic() + float(msg["remaining"])
            ref = tracing.from_wire(msg["trace"]) \
                if msg.get("trace") else None
            try:
                req = sched.submit(
                    msg["tokens"],
                    max_new_tokens=msg.get("max_new_tokens"),
                    temperature=msg.get("temperature") or 0.0,
                    top_k=msg.get("top_k") or 0,
                    top_p=msg.get("top_p") if msg.get("top_p") is not None
                    else 1.0,
                    deadline=deadline, trace=ref, on_complete=on_complete)
                req.tag = msg["id"]
                if req.done():   # resolved before the tag landed
                    on_complete(req)
            except ServingError as e:
                _send({"kind": "gen_error", "id": msg["id"],
                       "status": e.status, "error": str(e)})
            except Exception as e:   # malformed request: 400, never die
                _send({"kind": "gen_error", "id": msg["id"],
                       "status": 400,
                       "error": "%s: %s" % (type(e).__name__, e)})
    finally:
        sched.close(drain=False, timeout=0)
        try:
            sock.close()
        except OSError:
            pass
    _LOG.info("generate replica %d gen %d exiting after %d requests",
              args.replica, args.generation, served)
    return 0


def worker_main(argv=None):
    import argparse

    p = argparse.ArgumentParser(
        description="serving replica worker (spawned by ReplicaPool)")
    p.add_argument("--connect", required=True, metavar="HOST:PORT")
    p.add_argument("--replica", type=int, required=True)
    p.add_argument("--generation", type=int, default=0)
    p.add_argument("--artifact", default=None,
                   help="export prefix or .mxc path (tools/serve.py spec)")
    p.add_argument("--input", action="append", default=[],
                   metavar="NAME=DIMS[:DTYPE]")
    p.add_argument("--max-batch", type=int, default=None)
    p.add_argument("--stub", choices=("echo",), default=None,
                   help="serve a numpy stub instead of an artifact (tests)")
    p.add_argument("--stub-delay-ms", type=float, default=0.0)
    p.add_argument("--no-warm", action="store_true")
    p.add_argument("--generate", default=None, metavar="PREFIX",
                   help="serve a generation LM artifact (save_lm prefix) "
                        "through the continuous-batching scheduler")
    p.add_argument("--kv-pages", type=int, default=None)
    p.add_argument("--kv-page-size", type=int, default=None)
    p.add_argument("--max-prompt", type=int, default=None)
    p.add_argument("--max-new-tokens", type=int, default=None)
    args = p.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(message)s", stream=sys.stderr)
    signal.signal(signal.SIGTERM, _on_term)

    if args.generate:
        return _generate_worker_main(args)

    from .. import compile as _compile
    from ..parallel.resilience import maybe_inject_serving_fault
    from ..telemetry import tracing
    from .batcher import power_of_two_buckets

    max_batch = args.max_batch
    if max_batch is None:
        max_batch = _env.get("MXTPU_SERVE_MAX_BATCH")
    manifest_id = None
    prefetched = 0
    compile_cursor = _compile.mark()
    if args.stub:
        runner = _build_stub_runner(args)
        example_shapes, input_dtypes = _parse_inputs(args.input)
        buckets = power_of_two_buckets(max_batch)
    elif args.artifact:
        from .model_repository import build_runner

        example_shapes, input_dtypes = _parse_inputs(args.input)
        # warmup-manifest prefetch BEFORE the artifact binds: with the
        # persistent tier armed and a manifest from a previous publish of
        # this artifact+geometry, every executable the warm needs
        # deserializes up front — ready with zero jit_compile events
        # (docs/compile_cache.md cold-start playbook). The id keys on the
        # RESOLVED max_batch (the same resolution the bucket set uses and
        # the repository applies), so an MXTPU_SERVE_MAX_BATCH change
        # cleanly partitions manifests instead of reusing a stale one.
        manifest_id = _compile.model_manifest_id(
            args.artifact, max_batch, example_shapes or None)
        prefetched = _compile.prefetch(manifest_id)
        if prefetched:
            _LOG.info("replica %d: prefetched %d cached executable(s) "
                      "from warmup manifest %s", args.replica, prefetched,
                      manifest_id)
        runner, buckets, example_shapes, input_dtypes, _meta = build_runner(
            args.artifact, input_shapes=example_shapes or None,
            input_dtypes=input_dtypes, max_batch=max_batch)
    else:
        p.error("need --artifact or --stub")

    sock = _connect_and_hello(args)

    # warm every bucket BEFORE ready: a replica never joins the pool with a
    # cold executable cache (the same publish-after-warm rule as in-process
    # models, docs/serving.md)
    warm_s = 0.0
    bucket_flops = {}
    bucket_memory = {}
    if not args.no_warm:
        import numpy as np

        from ..telemetry import flops as _tm_flops
        from ..telemetry import memory as _tm_memory

        t0 = time.monotonic()
        for b in buckets:
            zeros = {k: np.zeros((b,) + tuple(s),
                                 dtype=(input_dtypes or {}).get(k, "float32"))
                     for k, s in example_shapes.items()}
            f0 = _tm_flops.total()
            m0 = _tm_memory.recorded_mark()
            _compile.begin_touch_log()
            try:
                runner(zeros, b, b)
            finally:
                touched = _compile.end_touch_log()
            f = _tm_flops.total() - f0
            if f:
                bucket_flops[int(b)] = f
            # memory figures the bucket's warm filled/deserialized/touched
            # — the router prices the pool's footprint from the ready frame
            mem = _tm_memory.bucket_figures(touched,
                                            _tm_memory.recorded_since(m0))
            if mem:
                bucket_memory[int(b)] = mem
        warm_s = time.monotonic() - t0
    # record this replica's executable key-set and (re)write the warmup
    # manifest so the NEXT cold start — a respawned generation or a fresh
    # deployment — prefetches these executables instead of compiling
    compile_entries = _compile.keys_since(compile_cursor)
    cache_dir = _compile.cache_dir()
    if cache_dir and manifest_id and compile_entries:
        _compile.write_manifest(cache_dir, manifest_id, compile_entries,
                                model="replica", version=args.generation)
    # staged prefetch entries the warm never claimed (stale manifest rows)
    # must not stay pinned for the worker's lifetime
    unclaimed = _compile.clear_staged()
    if unclaimed:
        _LOG.info("replica %d: dropped %d unclaimed prefetched "
                  "executable(s) (stale manifest rows)", args.replica,
                  unclaimed)
    send_msg(sock, {"kind": "ready", "replica": args.replica,
                    "generation": args.generation, "warm_seconds": warm_s,
                    "bucket_flops": bucket_flops or None,
                    "bucket_memory": bucket_memory or None,
                    "buckets": list(buckets),
                    "example_shapes": {k: tuple(v)
                                       for k, v in example_shapes.items()},
                    "input_dtypes": {k: str(v) for k, v in
                                     (input_dtypes or {}).items()} or None,
                    "compile_digests":
                        sorted({d for _, d in compile_entries}) or None,
                    "compile_prefetched": prefetched})
    _LOG.info("replica %d gen %d ready (warm %.2fs, buckets %s)",
              args.replica, args.generation, warm_s, list(buckets))

    seq = 0
    while not _STOP[0]:
        try:
            msg = recv_msg(sock, first_timeout=0.25)
        except socket.timeout:
            continue
        except OSError:
            break  # router went away: nothing to serve into
        if msg is None:
            break  # clean EOF
        kind = msg.get("kind")
        if kind == "shutdown":
            break
        if kind == "ping":
            send_msg(sock, {"kind": "pong", "id": msg.get("id")})
            continue
        if kind != "predict":
            _LOG.warning("replica %d: unknown message kind %r",
                         args.replica, kind)
            continue
        seq += 1
        t_batch = time.monotonic()
        deadline = None if msg.get("remaining") is None \
            else t_batch + float(msg["remaining"])
        # fault hook at the batch boundary (kill_replica / wedge_replica /
        # slow_reply — docs/fault_tolerance.md §5)
        maybe_inject_serving_fault(seq, args.replica)
        # deadline propagation: a replica that wakes up past the batch
        # budget (slow_reply, GC pause, CPU contention) cancels instead of
        # computing an answer nobody is waiting for
        if deadline is not None and time.monotonic() >= deadline:
            send_msg(sock, {"kind": "expired", "id": msg["id"]})
            continue
        t_run_wall = time.time()
        try:
            outs = runner(msg["arrays"], msg["bucket"], msg["n"])
        except Exception as e:  # model failure (incl. OSError from the
            try:                # runner itself): answer, never die
                send_msg(sock, {"kind": "error", "id": msg["id"],
                                "error": "%s: %s" % (type(e).__name__, e)})
            except OSError:
                break  # router went away mid-reply
            continue
        compute_s = time.monotonic() - t_batch
        # cross-process trace: one compute span per traced request in the
        # batch, parented under the router's dispatch span shipped on the
        # frame — this process's JSONL carries the worker lane of the
        # merged timeline (tools/trace_merge.py)
        for wire_ctx in msg.get("traces") or ():
            ref = tracing.from_wire(wire_ctx)
            if ref is not None:
                tracing.emit_span(
                    "serve.compute", t_run_wall, compute_s, ref,
                    component="worker",
                    attrs={"replica": args.replica,
                           "generation": args.generation,
                           "bucket": msg["bucket"], "n": msg["n"]})
        try:
            send_msg(sock, {"kind": "result", "id": msg["id"],
                            "outputs": outs, "seconds": compute_s})
        except OSError:
            break  # router went away: nothing to serve into
    try:
        sock.close()
    except OSError:
        pass
    _LOG.info("replica %d gen %d exiting after %d batches",
              args.replica, args.generation, seq)
    return 0


if __name__ == "__main__":
    sys.exit(worker_main())
