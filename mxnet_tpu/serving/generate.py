"""Continuous-batching autoregressive decode with a paged KV cache.

The serving stack through PR 9 (replica pools, failover, compile cache,
memory-budget admission) serves batch-synchronous classification — the
wrong shape for sequence generation, where requests FINISH AT DIFFERENT
LENGTHS: a batch-synchronous batcher holds every finished sequence
hostage to the longest one, and a naive contiguous KV cache reserves
max-length memory per sequence. This module rebuilds the two techniques
that fixed decode serving at scale, TPU-natively on the machinery the
repo already has:

  * **token-level continuous batching** (Orca, OSDI'22):
    `GenerateScheduler` admits and retires requests at STEP granularity —
    each scheduler lap first prefills any waiting requests that fit
    (pages + batch slots), then runs ONE decode step for the whole active
    set, padded to a power-of-two batch bucket. Prefill and decode are
    separate executables, each resolved through the `mxnet_tpu.compile`
    registry — one cached decode executable per (batch bucket, KV page
    geometry), so steady-state decode is zero-compile and a late joiner
    never restarts the running batch.
  * **paged KV cache** (PagedAttention, SOSP'23): `KVPageAllocator` hands
    out fixed-size pages from a free list; each sequence owns a page
    table, pages return to the pool the step the sequence finishes. The
    whole pool is allocated at load and priced into the model footprint,
    so `MXTPU_SERVE_MEMORY_BUDGET` admission 507s a load whose KV pool
    cannot fit BEFORE it can OOM the device mid-decode
    (`mxtpu_serve_kv_pages_{total,used}` gauges track occupancy).
    Admission reserves a sequence's worst-case pages up front
    (prompt + max_new_tokens), so a running batch can never deadlock on
    the pool.
  * **decode attention** runs the flash-decode Pallas kernel
    (`ops/pallas_kernels.paged_attention` — page tables via scalar
    prefetch, online softmax over streamed pages) on TPU, the dense-
    gather jnp fallback elsewhere (`MXTPU_PALLAS_DECODE`).
  * **sampling** (greedy / temperature / top-k / top-p) is folded into
    the decode executable with PER-ROW parameter arrays
    (`ops/random_ops.sample_token_logits`), so a mixed batch of greedy
    and stochastic requests stays one executable; every step consumes
    one threefry subkey from the global chain.

`TransformerLMEngine` runs a `gluon.model_zoo.transformer.TransformerLM`
(decoder-only, tied embedding head) in incremental form: the pure-jax
prefill/decode functions here compute exactly the block's full-sequence
forward (tests/test_generate.py proves logits parity and greedy-sequence
equality), with parameters passed as executable arguments so two models
with one geometry share executables.

`ServedLM` is the repository-facing model: in-process it owns a
scheduler; with ``replicas=N`` it routes requests through a
`ReplicaPool` in generate mode (each replica worker runs its own
scheduler — continuous batching happens replica-side, request routing
router-side) over the existing supervisor wire protocol.
"""
from __future__ import annotations

import collections
import hashlib
import itertools
import json
import logging
import math
import os
import threading
import time

import numpy as _np

from .. import compile as _compile
from .. import env as _env
from .. import random as _random
from .. import telemetry
from ..telemetry import slo as _slo
from ..base import MXNetError
from ..telemetry import tracing as _tracing
from .batcher import (DeadlineExceededError, DrainingError, QueueFullError,
                      ServingError, bucket_for, drain_timeout_s,
                      power_of_two_buckets)

__all__ = ["KVPageAllocator", "GenRequest", "GenerateScheduler",
           "TransformerLMEngine", "ServedLM", "save_lm", "load_lm"]

_LOG = logging.getLogger("mxnet_tpu.serving.generate")

_LM_FORMAT = "mxtpu-lm-v1"


# ---------------------------------------------------------------------------
# KV page allocator
# ---------------------------------------------------------------------------

class KVPageAllocator:
    """Free-list allocator over a fixed pool of KV-cache pages.

    Pages are identity-only here (integers 0..num_pages-1); the device
    arrays they index live in the engine. Allocation is all-or-nothing
    (`alloc` returns None rather than a partial grant) and O(n) in the
    grant size; `free` returns pages for immediate reuse — a completed
    sequence's pages serve the next admission the same scheduler lap.
    Occupancy rides the `mxtpu_serve_kv_pages_{total,used}` gauges.
    """

    def __init__(self, num_pages, page_size, name="default"):
        if num_pages < 1 or page_size < 1:
            raise MXNetError("KV pool needs >=1 pages of >=1 tokens, got "
                             "%d x %d" % (num_pages, page_size))
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self._lock = threading.Lock()
        # LIFO free list: recently-freed pages are re-issued first (their
        # cache lines / artifact pages are warmest)
        self._free = list(range(self.num_pages - 1, -1, -1))
        labels = {"model": name}
        self._m_total = telemetry.gauge("mxtpu_serve_kv_pages_total", labels)
        self._m_used = telemetry.gauge("mxtpu_serve_kv_pages_used", labels)
        # used/total as one ratio gauge: the SLO occupancy-ceiling
        # objective and /statusz read a single windowed series
        self._m_occ = telemetry.gauge("mxtpu_serve_kv_occupancy", labels)
        self._m_total.set(self.num_pages)
        self._m_used.set(0)
        self._m_occ.set(0.0)

    def pages_for(self, tokens):
        """Pages needed to hold ``tokens`` tokens."""
        return -(-int(tokens) // self.page_size)

    @property
    def free_pages(self):
        with self._lock:
            return len(self._free)

    @property
    def used_pages(self):
        return self.num_pages - self.free_pages

    def alloc(self, n):
        """Grant ``n`` pages, or None when the pool cannot serve them
        (callers keep the request queued — backpressure, not failure)."""
        n = int(n)
        with self._lock:
            if n < 0 or n > len(self._free):
                return None
            pages = self._free[-n:][::-1] if n else []
            del self._free[len(self._free) - n:]
            used = self.num_pages - len(self._free)
            self._m_used.set(used)
            self._m_occ.set(used / float(self.num_pages))
        return pages

    def free(self, pages):
        """Return a grant to the pool (double-free is a bug upstream and
        raises — a page owned by two sequences corrupts both)."""
        with self._lock:
            live = set(self._free)
            for p in pages:
                if p in live or not (0 <= p < self.num_pages):
                    raise MXNetError("double-free/corrupt KV page %r" % (p,))
            self._free.extend(pages)
            used = self.num_pages - len(self._free)
            self._m_used.set(used)
            self._m_occ.set(used / float(self.num_pages))


# ---------------------------------------------------------------------------
# requests
# ---------------------------------------------------------------------------

class GenRequest:
    """One admitted generation request. ``wait()`` yields the generated
    token list (prompt excluded); `finish_reason` is ``eos`` / ``length``
    after a normal completion."""

    __slots__ = ("tokens", "max_new_tokens", "temperature", "top_k",
                 "top_p", "deadline", "outputs", "finish_reason", "error",
                 "trace", "retried", "tag", "on_complete", "queue_seconds",
                 "_event", "_rlock", "_t_submit")

    def __init__(self, tokens, max_new_tokens, temperature=0.0, top_k=0,
                 top_p=1.0, deadline=None, trace=None):
        self.tokens = [int(t) for t in tokens]
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.deadline = deadline
        self.outputs = None
        self.finish_reason = None
        self.error = None
        self.queue_seconds = None
        self.retried = False     # pooled failover: one retry per request
        self.tag = None          # wire id (pooled mode)
        self.on_complete = None  # worker-side completion hook
        self.trace = trace if trace is not None else _tracing.capture()
        self._event = threading.Event()
        self._rlock = threading.Lock()
        self._t_submit = time.monotonic()

    def done(self):
        return self._event.is_set()

    def wait(self, timeout=None):
        self._event.wait(timeout)
        if not self._event.is_set():
            raise DeadlineExceededError(
                "generation expired after %.0f ms"
                % ((time.monotonic() - self._t_submit) * 1e3))
        if self.error is not None:
            raise self.error
        return self.outputs

    def _resolve(self, outputs=None, finish_reason=None, error=None):
        # first resolution wins, atomically (scheduler thread, pooled
        # dispatch thread, abort paths and deadline expiry can race)
        with self._rlock:
            if self._event.is_set():
                return
            self.outputs = outputs
            self.finish_reason = finish_reason
            self.error = error
            self._event.set()
            cb = self.on_complete
        if cb is not None:
            try:
                cb(self)
            except Exception as e:  # a dead socket must not kill the
                _LOG.warning("generate completion hook failed: %r", e)


# ---------------------------------------------------------------------------
# the continuous-batching scheduler
# ---------------------------------------------------------------------------

class _Sequence:
    """Scheduler-internal state of one RUNNING sequence."""

    __slots__ = ("req", "pages", "page_row", "pos", "generated", "t_last",
                 "n_steps")

    def __init__(self, req, pages, page_row, pos, first_token):
        self.req = req
        self.pages = pages
        self.page_row = page_row
        self.pos = pos            # position of the NEXT token to feed
        self.generated = [first_token]
        self.t_last = time.monotonic()
        self.n_steps = 0


_SCHED_SEQ = itertools.count()


class GenerateScheduler:
    """Token-level continuous batching over one decode engine.

    One worker thread (``mxtpu-decode-<name>``) owns the engine, the
    active set and the page allocator's grants. Each lap:

      1. **admit**: pop waiting requests while batch slots AND worst-case
         pages are available; run one PREFILL each (its own bucketed
         executable), which also samples the first token.
      2. **decode**: one step for the whole active set, padded to the
         smallest power-of-two batch bucket — one cached executable per
         bucket, zero-compile steady state.
      3. **retire**: sequences hitting EOS / ``max_new_tokens`` / their
         deadline resolve immediately and return their pages — the next
         lap's admissions reuse them. Requests join and leave at step
         granularity; nobody waits for the longest sequence in the batch.

    The engine must be single-threaded-driven; only the worker thread
    (plus `close` after joining it) touches it.
    """

    def __init__(self, engine, name="default", queue_depth=None, warm=True):
        self.engine = engine
        self.name = str(name)
        self.buckets = sorted(int(b) for b in engine.buckets)
        self.max_active = self.buckets[-1]
        if queue_depth is None:
            queue_depth = _env.get("MXTPU_SERVE_QUEUE_DEPTH")
        self.queue_depth = max(1, int(queue_depth))
        self.allocator = KVPageAllocator(engine.num_pages, engine.page_size,
                                         name=self.name)

        self._cv = threading.Condition()
        self._queue = collections.deque()
        self._active = []     # _Sequence list; mutated under _cv
        self._stop = False
        self._draining = False

        labels = {"model": self.name}
        self._m_queue = telemetry.gauge("mxtpu_serve_queue_depth", labels)
        self._m_reqs = telemetry.counter("mxtpu_serve_requests_total", labels)
        self._m_active = telemetry.gauge("mxtpu_serve_active_sequences",
                                         labels)
        self._m_steps = telemetry.counter("mxtpu_serve_decode_steps_total",
                                          labels)
        self._m_tokens = telemetry.counter(
            "mxtpu_serve_generated_tokens_total", labels)
        self._m_rej_full = telemetry.counter(
            "mxtpu_serve_rejected_total",
            {"model": self.name, "reason": "queue_full"})
        self._m_rej_dead = telemetry.counter(
            "mxtpu_serve_rejected_total",
            {"model": self.name, "reason": "deadline"})
        # in-flight expiry (admitted, partially decoded, then timed out)
        # is NOT an admission rejection: a dashboard alerting on
        # rejected_total must not fire during slow-decode incidents
        self._m_expired = telemetry.counter(
            "mxtpu_serve_rejected_total",
            {"model": self.name, "reason": "decode_expired"})
        # inter-token latency IS decode serving latency: its p99 is the
        # serve_bench decode row's headline SLO figure
        self._m_intertoken = telemetry.histogram(
            "mxtpu_serve_intertoken_seconds", labels,
            bounds=(.001, .0025, .005, .01, .025, .05, .1, .25, .5, 1., 2.5))
        self._m_prefill = telemetry.histogram("mxtpu_serve_prefill_seconds",
                                              labels)
        # built-in generation SLOs: inter-token p99 + KV-occupancy
        # ceiling + admission-queue ceiling (docs/observability.md §SLOs)
        _slo.wire_generate_objectives(self.name,
                                      queue_depth=self.queue_depth)

        # the RNG chain is thread-local (mxnet_tpu/random.py) and the
        # worker thread would otherwise lazily seed itself with the
        # DEFAULT seed — every replica/restart drawing one identical
        # "random" stream, deaf to mx.random.seed(). Derive the worker
        # chain from the CONSTRUCTING thread's seed (so an in-process
        # seed() before load stays reproducible) folded with the pid and
        # a per-process scheduler index (so co-located replicas and
        # restarted workers decorrelate).
        self._rng_seed = (_random.current_seed() * 1000003
                          + os.getpid() * 10007
                          + next(_SCHED_SEQ)) % (1 << 31)

        self.warm_seconds = None
        if warm:
            self.warm_seconds = engine.warm()
        self._worker = threading.Thread(
            target=self._loop, name="mxtpu-decode-%s" % self.name,
            daemon=True)
        self._worker.start()

    # -- admission ---------------------------------------------------------
    def submit(self, tokens, max_new_tokens=None, temperature=0.0, top_k=0,
               top_p=1.0, deadline=None, trace=None, on_complete=None):
        """Admit one generation request; returns a `GenRequest`.
        ``on_complete`` (optional) fires on EVERY resolution — success,
        expiry or abort (the replica worker's reply hook)."""
        tokens = [int(t) for t in tokens]
        if not tokens:
            raise MXNetError("generation needs at least one prompt token")
        if len(tokens) > self.engine.max_prompt:
            raise MXNetError(
                "prompt has %d tokens; this model admits up to %d "
                "(MXTPU_SERVE_MAX_PROMPT)" % (len(tokens),
                                              self.engine.max_prompt))
        vocab = self.engine.vocab_size
        if any(t < 0 or t >= vocab for t in tokens):
            raise MXNetError("prompt token out of range [0, %d)" % vocab)
        cap = self.engine.max_new_tokens
        if max_new_tokens is None:
            max_new_tokens = cap
        max_new_tokens = int(max_new_tokens)
        if max_new_tokens < 1 or max_new_tokens > cap:
            raise MXNetError(
                "max_new_tokens must be in 1..%d (MXTPU_SERVE_MAX_NEW_"
                "TOKENS), got %d" % (cap, max_new_tokens))
        req = GenRequest(tokens, max_new_tokens, temperature=temperature,
                         top_k=top_k, top_p=top_p, deadline=deadline,
                         trace=trace)
        req.on_complete = on_complete
        with self._cv:
            if self._stop or self._draining:
                raise DrainingError("model %r is draining" % self.name)
            if len(self._queue) >= self.queue_depth:
                self._m_rej_full.inc()
                raise QueueFullError(
                    "generation queue for %r is full (%d requests; "
                    "MXTPU_SERVE_QUEUE_DEPTH)" % (self.name,
                                                  self.queue_depth))
            self._queue.append(req)
            self._m_queue.set(len(self._queue))
            self._m_reqs.inc()
            self._cv.notify()
        return req

    def pending(self):
        with self._cv:
            return len(self._queue) + len(self._active)

    # -- shutdown ----------------------------------------------------------
    def drain(self, timeout=None):
        """Stop admitting; let running sequences finish. Bounded."""
        with self._cv:
            self._draining = True
            self._cv.notify_all()
        if timeout is None:
            timeout = drain_timeout_s()
        deadline = time.monotonic() + timeout
        while self.pending():
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.005)
        return True

    def abort_pending(self, error=None):
        """Force-resolve every queued and RUNNING request (bounded-drain
        escape hatch). Running sequences' pages are reclaimed by the
        worker on its next lap (or by `close` once the worker is joined);
        first-resolution-wins makes the race benign."""
        if error is None:
            error = DrainingError(
                "model %r drain timed out; generation force-completed"
                % self.name)
        with self._cv:
            victims = list(self._queue) + [s.req for s in self._active
                                           if not s.req.done()]
            self._queue.clear()
            self._m_queue.set(0)
        for req in victims:
            req._resolve(error=error)
        return len(victims)

    def close(self, drain=True, timeout=None):
        drained = self.drain(timeout) if drain else False
        with self._cv:
            self._stop = True
            self._draining = True
            self._cv.notify_all()
        self._worker.join(timeout=10.0)
        self.abort_pending(DrainingError(
            "model %r shut down before this generation ran" % self.name))
        if not self._worker.is_alive():
            # the worker is gone: reclaim whatever the aborted sequences
            # still held so the used gauge reads 0 after shutdown
            with self._cv:
                leftovers, self._active = self._active, []
            for seq in leftovers:
                self.allocator.free(seq.pages)
            self._m_active.set(0)
        # verdicts for a gone model are noise on /statusz
        _slo.unregister_model(self.name)
        return drained

    # -- the worker --------------------------------------------------------
    def _loop(self):
        _random.seed(self._rng_seed)   # this thread's sampling chain
        while True:
            with self._cv:
                while not self._queue and not self._active:
                    if self._stop:
                        return
                    self._cv.wait(0.05)
                if self._stop:
                    return
            try:
                self._admit()
                if self._active:
                    self._step()
            except Exception as e:  # the lone decode worker must not die
                telemetry.record_event("serve_decode_error",
                                       model=self.name, error=repr(e))
                _LOG.exception("decode loop error on %r", self.name)
                err = ServingError("decode loop for %r failed: %r"
                                   % (self.name, e))
                err.__cause__ = e
                with self._cv:
                    dead, self._active = self._active, []
                for seq in dead:
                    self.allocator.free(seq.pages)
                    seq.req._resolve(error=err)
                self._m_active.set(0)

    def _admit(self):
        """Pop waiting requests while batch slots + worst-case pages are
        available and run their prefill — the join-mid-decode half of
        continuous batching."""
        while len(self._active) < self.max_active:
            with self._cv:
                if not self._queue:
                    break
                req = self._queue[0]
                now = time.monotonic()
                if req.deadline is not None and now >= req.deadline:
                    self._queue.popleft()
                    self._m_queue.set(len(self._queue))
                    self._m_rej_dead.inc()
                    req._resolve(error=DeadlineExceededError(
                        "deadline expired after %.0f ms in queue"
                        % ((now - req._t_submit) * 1e3)))
                    continue
                if req.done():       # externally aborted while queued
                    self._queue.popleft()
                    self._m_queue.set(len(self._queue))
                    continue
                # worst-case reservation: prompt + max_new tokens. Pages
                # are granted up front so a RUNNING sequence can never
                # stall mid-decode waiting for the pool (no deadlock,
                # no mid-flight eviction)
                need = self.allocator.pages_for(
                    len(req.tokens) + req.max_new_tokens)
                pages = self.allocator.alloc(need)
                if pages is None:
                    break            # pool pressure: stays queued
                self._queue.popleft()
                self._m_queue.set(len(self._queue))
            req.queue_seconds = time.monotonic() - req._t_submit
            page_row = _np.zeros(self.engine.max_pages_per_seq, _np.int32)
            page_row[:len(pages)] = pages
            t0 = time.monotonic()
            t0_wall = time.time()
            try:
                first = self.engine.prefill(
                    req.tokens, page_row,
                    (req.temperature, req.top_k, req.top_p),
                    _random.next_key())
            except Exception as e:  # bad prompt/model: answer, free pages
                self.allocator.free(pages)
                err = ServingError("prefill on %r failed: %r"
                                   % (self.name, e))
                err.__cause__ = e
                telemetry.record_event("serve_decode_error",
                                       model=self.name, error=repr(e))
                req._resolve(error=err)
                continue
            prefill_s = time.monotonic() - t0
            self._m_prefill.observe(
                prefill_s,
                exemplar=req.trace.trace_id if req.trace is not None
                else None)
            _tracing.emit_span(
                "serve.queue", t0_wall - req.queue_seconds,
                req.queue_seconds, req.trace, component="decode")
            _tracing.emit_span(
                "decode.prefill", t0_wall, prefill_s, req.trace,
                component="decode",
                attrs={"prompt": len(req.tokens), "pages": len(pages)})
            self._m_tokens.inc()
            seq = _Sequence(req, pages, page_row, len(req.tokens), first)
            if not self._finish_if_done(seq):
                with self._cv:
                    self._active.append(seq)
            self._m_active.set(len(self._active))

    def _step(self):
        """One decode step for the whole active set, padded to the
        smallest batch bucket; then retire finished sequences."""
        # sequences resolved externally (abort, expired deadline) retire
        # first — never spend a step on an answer nobody is waiting for
        now = time.monotonic()
        live = []
        for seq in self._active:
            if seq.req.done():
                self.allocator.free(seq.pages)
            elif seq.req.deadline is not None and now >= seq.req.deadline:
                self._retire(seq, None, error=DeadlineExceededError(
                    "deadline expired after %d generated token(s)"
                    % len(seq.generated)))
            else:
                live.append(seq)
        if len(live) != len(self._active):
            with self._cv:
                self._active = live
            self._m_active.set(len(live))
        if not live:
            return
        n = len(live)
        bucket = bucket_for(n, self.buckets)
        ps = self.engine.page_size
        nump = self.engine.num_pages
        tokens = _np.zeros(bucket, _np.int32)
        positions = _np.zeros(bucket, _np.int32)
        dest_pages = _np.full(bucket, nump, _np.int32)  # OOB = dropped
        dest_slots = _np.zeros(bucket, _np.int32)
        tables = _np.zeros((bucket, self.engine.max_pages_per_seq),
                           _np.int32)
        lengths = _np.zeros(bucket, _np.int32)
        temps = _np.zeros(bucket, _np.float32)
        top_ks = _np.zeros(bucket, _np.int32)
        top_ps = _np.ones(bucket, _np.float32)
        for i, seq in enumerate(live):
            tokens[i] = seq.generated[-1]
            positions[i] = seq.pos
            dest_pages[i] = seq.page_row[seq.pos // ps]
            dest_slots[i] = seq.pos % ps
            tables[i] = seq.page_row
            lengths[i] = seq.pos + 1
            temps[i] = seq.req.temperature
            top_ks[i] = seq.req.top_k
            top_ps[i] = seq.req.top_p
        t0 = time.monotonic()
        t0_wall = time.time()
        nxt = self.engine.decode_step(tokens, positions, dest_pages,
                                      dest_slots, tables, lengths, temps,
                                      top_ks, top_ps, _random.next_key())
        step_s = time.monotonic() - t0
        self._m_steps.inc()
        now = time.monotonic()
        still = []
        for i, seq in enumerate(live):
            seq.pos += 1
            seq.n_steps += 1
            seq.generated.append(int(nxt[i]))
            self._m_tokens.inc()
            self._m_intertoken.observe(
                now - seq.t_last,
                exemplar=seq.req.trace.trace_id
                if seq.req.trace is not None else None)
            seq.t_last = now
            _tracing.emit_span(
                "decode.step", t0_wall, step_s, seq.req.trace,
                component="decode",
                attrs={"bucket": bucket, "n": n, "step": seq.n_steps})
            if not self._finish_if_done(seq):
                still.append(seq)
        with self._cv:
            self._active = still
        self._m_active.set(len(still))

    def _finish_if_done(self, seq):
        """Retire a sequence that hit EOS or its token budget."""
        eos = self.engine.eos_id
        if eos is not None and seq.generated[-1] == eos:
            self._retire(seq, "eos")
            return True
        if len(seq.generated) >= seq.req.max_new_tokens:
            self._retire(seq, "length")
            return True
        return False

    def _retire(self, seq, finish_reason, error=None):
        self.allocator.free(seq.pages)
        if error is not None:
            self._m_expired.inc()
            seq.req._resolve(error=error)
        else:
            seq.req._resolve(outputs=list(seq.generated),
                             finish_reason=finish_reason)


# ---------------------------------------------------------------------------
# the Transformer-LM decode engine
# ---------------------------------------------------------------------------

def _ln(x, p):
    from ..ops import nn as _opsnn

    return _opsnn.layer_norm(x, p["g"], p["b"])


def _dense(x, p):
    return x @ p["w"].T + p["b"]


class TransformerLMEngine:
    """Incremental (paged-KV) execution of a `TransformerLM`.

    Prefill computes the full causal forward of a padded prompt bucket,
    writes every token's K/V into the sequence's pages and samples the
    first token; decode_step feeds one token per active sequence, appends
    its K/V and attends over the page table
    (`ops/pallas_kernels.paged_attention`). Both are pure functions of
    (params, kv, inputs) resolved through the `mxnet_tpu.compile`
    registry — parameters ride as arguments, so the executables are keyed
    purely by geometry. Single-threaded: only the scheduler worker may
    drive an engine.
    """

    def __init__(self, lm=None, params=None, config=None, num_pages=None,
                 page_size=None, max_prompt=None, max_new_tokens=None,
                 max_batch=None, decode_buckets=None, prefill_buckets=None,
                 eos_id=None, kv_dtype="float32"):
        import jax

        if lm is not None:
            config = lm.config
            params = lm.decode_params()
        if config is None or params is None:
            raise MXNetError("TransformerLMEngine needs an lm= block or "
                             "params= + config=")
        self.config = dict(config)
        self.vocab_size = int(config["vocab_size"])
        self.units = int(config["units"])
        self.num_heads = int(config["num_heads"])
        self.head_dim = self.units // self.num_heads
        self.num_layers = int(config["num_layers"])
        self.eos_id = None if eos_id is None else int(eos_id)
        self.page_size = int(page_size if page_size is not None
                             else _env.get("MXTPU_SERVE_KV_PAGE_SIZE"))
        self.num_pages = int(num_pages if num_pages is not None
                             else _env.get("MXTPU_SERVE_KV_PAGES"))
        self.max_prompt = int(max_prompt if max_prompt is not None
                              else _env.get("MXTPU_SERVE_MAX_PROMPT"))
        self.max_new_tokens = int(
            max_new_tokens if max_new_tokens is not None
            else _env.get("MXTPU_SERVE_MAX_NEW_TOKENS"))
        max_total = self.max_prompt + self.max_new_tokens
        if max_total > int(config["max_length"]):
            raise MXNetError(
                "max_prompt + max_new_tokens = %d exceeds the model's "
                "position table (max_length=%d)"
                % (max_total, config["max_length"]))
        self.max_pages_per_seq = -(-max_total // self.page_size)
        if self.max_pages_per_seq > self.num_pages:
            raise MXNetError(
                "one sequence can need %d pages but the pool has only %d "
                "(MXTPU_SERVE_KV_PAGES)" % (self.max_pages_per_seq,
                                            self.num_pages))
        if decode_buckets is None:
            if max_batch is None:
                max_batch = _env.get("MXTPU_SERVE_MAX_BATCH")
            decode_buckets = power_of_two_buckets(max_batch)
        self.buckets = sorted(int(b) for b in decode_buckets)
        if prefill_buckets is None:
            lo = min(8, self.max_prompt)
            prefill_buckets = [b for b in
                               power_of_two_buckets(self.max_prompt)
                               if b >= lo]
        self.prefill_buckets = sorted(int(b) for b in prefill_buckets)
        self.kv_dtype = str(kv_dtype)

        self._params = jax.tree_util.tree_map(
            lambda a: jax.numpy.asarray(a, jax.numpy.float32), params)
        self._param_bytes = int(sum(
            a.size * a.dtype.itemsize
            for a in jax.tree_util.tree_leaves(self._params)))
        self._kv = jax.numpy.zeros(
            (self.num_layers, 2, self.num_pages, self.num_heads,
             self.page_size, self.head_dim), dtype=self.kv_dtype)
        # executable identity: architecture + geometry (params are args,
        # so two engines with one geometry share executables)
        self._fingerprint = hashlib.sha256(json.dumps(
            {"config": self.config, "pages": self.num_pages,
             "page_size": self.page_size, "maxp": self.max_pages_per_seq,
             "kv": self.kv_dtype}, sort_keys=True).encode()).hexdigest()[:32]

    # -- sizing ------------------------------------------------------------
    def kv_bytes(self):
        """Device bytes of the page pool (allocated in full at load —
        the figure `MXTPU_SERVE_MEMORY_BUDGET` admission prices)."""
        return int(self._kv.size) * _np.dtype(self.kv_dtype).itemsize

    def param_bytes(self):
        return self._param_bytes

    def geometry(self):
        return {"num_pages": self.num_pages, "page_size": self.page_size,
                "max_pages_per_seq": self.max_pages_per_seq,
                "max_prompt": self.max_prompt,
                "max_new_tokens": self.max_new_tokens,
                "decode_buckets": list(self.buckets),
                "prefill_buckets": list(self.prefill_buckets),
                "kv_dtype": self.kv_dtype,
                "kv_bytes": self.kv_bytes(),
                "param_bytes": self.param_bytes()}

    # -- executables -------------------------------------------------------
    def _key(self, kind, shape_sig):
        # no_persist: plain memory-tier entries (the decode loop's hit
        # path is a dict get; serializing pallas/jnp decode graphs buys
        # little and the artifact trust story nothing)
        # donation=(1,): every executable minted through this key (prefill
        # AND per-bucket decode) donates the KV pool at argnum 1, and the
        # fill-hook donation verifier (telemetry.memory.verify_donation)
        # only audits keys that declare it
        return _compile.ExecutableKey(
            kind, self._fingerprint, shapes=shape_sig, donation=(1,),
            static=(("pages", self.num_pages),
                    ("page_size", self.page_size),
                    ("maxp", self.max_pages_per_seq),
                    ("kv", self.kv_dtype)),
            no_persist=True)

    def _build_prefill(self, lp):
        import jax
        import jax.numpy as jnp

        from ..ops.pallas_kernels import _NEG_INF
        from ..ops.random_ops import sample_token_logits

        H, Dh, ps = self.num_heads, self.head_dim, self.page_size
        nump, scale = self.num_pages, 1.0 / math.sqrt(self.head_dim)

        def fn(params, kv, tokens, length, page_row, temp, top_k, top_p,
               key):
            # tokens (lp,) int32 padded; length () int32; page_row (maxp,)
            x = params["word"][tokens] + params["pos"][jnp.arange(lp)]
            x = _ln(x, params["embed_norm"])                     # (lp, C)
            causal = jnp.arange(lp)[None, :] <= jnp.arange(lp)[:, None]
            t_idx = jnp.arange(lp)
            tpage = jnp.where(t_idx < length, page_row[t_idx // ps], nump)
            tslot = t_idx % ps
            for li, layer in enumerate(params["layers"]):
                qh = _dense(x, layer["q"]).reshape(lp, H, Dh)
                kh = _dense(x, layer["k"]).reshape(lp, H, Dh)
                vh = _dense(x, layer["v"]).reshape(lp, H, Dh)
                kv = kv.at[li, 0, tpage, :, tslot, :].set(
                    kh.astype(kv.dtype), mode="drop")
                kv = kv.at[li, 1, tpage, :, tslot, :].set(
                    vh.astype(kv.dtype), mode="drop")
                s = jnp.einsum("qhd,khd->hqk", qh, kh) * scale
                s = jnp.where(causal[None], s, _NEG_INF)
                p = jax.nn.softmax(s, axis=-1)
                att = jnp.einsum("hqk,khd->qhd", p, vh).reshape(lp, -1)
                x = _ln(x + _dense(att, layer["o"]), layer["attn_norm"])
                h = jax.nn.gelu(_dense(x, layer["ffn1"]), approximate=False)
                x = _ln(x + _dense(h, layer["ffn2"]), layer["ffn_norm"])
            logits = x[length - 1] @ params["word"].T            # (V,)
            tok = sample_token_logits(key, logits[None], temp, top_k,
                                      top_p)
            return tok[0], kv

        # the kv pool is DONATED: without it every call materializes a
        # second full pool for the output (transient 2x kv_bytes — the
        # exact OOM the load-time budget admission promises to preclude)
        return lambda: jax.jit(fn, donate_argnums=(1,))

    def _build_decode(self, bucket):
        import jax
        import jax.numpy as jnp

        from ..ops.pallas_kernels import paged_attention
        from ..ops.random_ops import sample_token_logits

        H, Dh = self.num_heads, self.head_dim
        scale = 1.0 / math.sqrt(self.head_dim)

        def fn(params, kv, tokens, positions, dest_pages, dest_slots,
               tables, lengths, temp, top_k, top_p, key):
            b = tokens.shape[0]
            x = params["word"][tokens] + params["pos"][positions]  # (b, C)
            x = _ln(x, params["embed_norm"])
            for li, layer in enumerate(params["layers"]):
                qh = _dense(x, layer["q"]).reshape(b, H, Dh)
                kh = _dense(x, layer["k"]).reshape(b, H, Dh)
                vh = _dense(x, layer["v"]).reshape(b, H, Dh)
                kv = kv.at[li, 0, dest_pages, :, dest_slots, :].set(
                    kh.astype(kv.dtype), mode="drop")
                kv = kv.at[li, 1, dest_pages, :, dest_slots, :].set(
                    vh.astype(kv.dtype), mode="drop")
                att = paged_attention(qh, kv[li, 0], kv[li, 1], tables,
                                      lengths, sm_scale=scale)
                att = att.astype(x.dtype).reshape(b, -1)
                x = _ln(x + _dense(att, layer["o"]), layer["attn_norm"])
                h = jax.nn.gelu(_dense(x, layer["ffn1"]), approximate=False)
                x = _ln(x + _dense(h, layer["ffn2"]), layer["ffn_norm"])
            logits = x @ params["word"].T                        # (b, V)
            return sample_token_logits(key, logits, temp, top_k, top_p), kv

        # kv donated: the per-step update must alias, not copy, the pool
        return lambda: jax.jit(fn, donate_argnums=(1,))

    def _prefill_exe(self, lp, example_args=None):
        # example_args routes a miss through the registry's AOT fill, so
        # the donation verifier actually audits the declared KV-pool
        # donation at fill time (misses only; hits never evaluate it)
        return _compile.get_or_build(
            self._key("lm_prefill", ("prompt", lp)),
            self._build_prefill(lp), label="lm_prefill:l%d" % lp,
            example_args=example_args)

    def _decode_exe(self, bucket, example_args=None):
        return _compile.get_or_build(
            self._key("lm_decode", ("batch", bucket)),
            self._build_decode(bucket), label="lm_decode:b%d" % bucket,
            example_args=example_args)

    # -- driving -----------------------------------------------------------
    def prefill(self, tokens, page_row, sampling, key):
        """Run one prompt through its padded prefill bucket; writes the
        prompt's K/V into `page_row`'s pages and returns the sampled
        first token (int)."""
        lp = bucket_for(len(tokens), self.prefill_buckets)
        if lp is None:
            raise MXNetError("prompt of %d tokens overflows the prefill "
                             "buckets %s" % (len(tokens),
                                             self.prefill_buckets))
        padded = _np.zeros(lp, _np.int32)
        padded[:len(tokens)] = tokens
        temp, top_k, top_p = sampling
        args = (self._params, self._kv, padded,
                _np.int32(len(tokens)), _np.asarray(page_row, _np.int32),
                _np.float32([temp]), _np.int32([top_k]),
                _np.float32([top_p]), key)
        tok, self._kv = self._prefill_exe(lp, lambda: args)(*args)
        return int(tok)

    def decode_step(self, tokens, positions, dest_pages, dest_slots,
                    tables, lengths, temps, top_ks, top_ps, key):
        """One token for every row (rows with length 0 are inert padding:
        their K/V writes drop and their sampled token is discarded).
        Returns an int32 numpy array of next tokens."""
        args = (self._params, self._kv, tokens, positions, dest_pages,
                dest_slots, tables, lengths, temps, top_ks, top_ps, key)
        out, self._kv = self._decode_exe(len(tokens), lambda: args)(*args)
        return _np.asarray(out)

    def warm(self):
        """Compile every prefill + decode bucket (dummy data, dropped
        writes) so steady-state generation is zero-compile. Returns
        seconds."""
        t0 = time.monotonic()
        maxp = self.max_pages_per_seq
        for lp in self.prefill_buckets:
            # a full-bucket prompt so EVERY prefill bucket compiles (a
            # 1-token prompt would only ever warm the smallest)
            self.prefill([1] * lp, _np.zeros(maxp, _np.int32),
                         (0.0, 0, 1.0), _random.next_key())
        for b in self.buckets:
            self.decode_step(
                _np.zeros(b, _np.int32), _np.zeros(b, _np.int32),
                _np.full(b, self.num_pages, _np.int32),
                _np.zeros(b, _np.int32), _np.zeros((b, maxp), _np.int32),
                _np.zeros(b, _np.int32), _np.zeros(b, _np.float32),
                _np.zeros(b, _np.int32), _np.ones(b, _np.float32),
                _random.next_key())
            telemetry.record_event("serve_decode_warm", model="engine",
                                   bucket=b)
        return time.monotonic() - t0


# ---------------------------------------------------------------------------
# artifact IO — <prefix>-lmconfig.json + <prefix>-lm.params
# ---------------------------------------------------------------------------

def save_lm(lm, prefix):
    """Write a generation-serving artifact: the architecture header and
    the parameters. This is what `tools/serve.py --model name=PREFIX@
    generate` and replica workers load."""
    from .. import nd
    from ..base import atomic_writer

    if any(p._data is None for p in lm.collect_params().values()):
        # deferred Dense/LayerNorm shapes materialize on first forward
        lm(nd.array([[0]], dtype="int32"))
    prefix = os.fspath(prefix)
    with atomic_writer(prefix + "-lmconfig.json", "w") as f:
        json.dump({"format": _LM_FORMAT, "config": lm.config}, f, indent=1)
    lm.save_parameters(prefix + "-lm.params")
    return prefix


def load_lm(prefix):
    """Rebuild a `TransformerLM` from a `save_lm` artifact."""
    from ..gluon.model_zoo.transformer import TransformerLM

    prefix = os.fspath(prefix)
    cfg_path = prefix + "-lmconfig.json"
    if not os.path.exists(cfg_path):
        raise MXNetError("no generation artifact at %r (expected %s)"
                         % (prefix, cfg_path))
    with open(cfg_path) as f:
        header = json.load(f)
    if header.get("format") != _LM_FORMAT:
        raise MXNetError("%s: unknown LM artifact format %r"
                         % (cfg_path, header.get("format")))
    lm = TransformerLM(**header["config"])
    lm.load_parameters(prefix + "-lm.params")
    return lm


# ---------------------------------------------------------------------------
# the repository-facing served model
# ---------------------------------------------------------------------------

class ServedLM:
    """One served generation model (`ModelRepository` duck type).

    In-process it owns a `GenerateScheduler`; pooled (``replicas >= 1``)
    it routes each request to a replica worker over the supervisor wire
    protocol — every worker runs its own scheduler, so continuous
    batching happens replica-side while routing, failover (exactly-once
    re-dispatch) and health checks stay router-side.
    """

    def __init__(self, name, version, scheduler=None, pool=None, info=None,
                 meta=None):
        self.name = str(name)
        self.version = int(version)
        self._scheduler = scheduler
        self._pool = pool
        self.meta = dict(meta or {})
        self.loaded_at = time.time()
        # autoscaling policy (docs/serving.md §Autoscaling)
        self.min_replicas = None
        self.max_replicas = None
        self.pinned = False
        self.warmed = True
        if scheduler is not None:
            self.generate_info = dict(scheduler.engine.geometry())
            self.warm_seconds = scheduler.warm_seconds
        else:
            self.generate_info = dict((info or {}).get("generate") or {})
            self.warm_seconds = (info or {}).get("warm_seconds")
        self.memory_bytes = (
            (self.generate_info.get("kv_bytes") or 0)
            + (self.generate_info.get("param_bytes") or 0)) or None
        if self.effective_memory_bytes:
            telemetry.gauge("mxtpu_serve_model_memory_bytes",
                            {"model": "%s/%d" % (self.name, self.version)}
                            ).set(self.effective_memory_bytes)

    # -- construction ------------------------------------------------------
    @staticmethod
    def load(name, version, prefix, replicas=0, queue_depth=None,
             worker_args=None, pool_kwargs=None, **engine_kwargs):
        """Load a `save_lm` artifact as a served generation model.

        ``replicas`` = 0 runs the scheduler in-process; N >= 1 spawns a
        supervised `ReplicaPool` in generate mode (``engine_kwargs`` with
        geometry meaning — kv pages/page size/max batch — are forwarded
        to the workers as argv so router and replicas agree)."""
        version = int(version)
        if replicas and replicas > 0:
            from .replica_pool import ReplicaPool

            if worker_args is None:
                if prefix is None:
                    raise MXNetError("pooled ServedLM.load needs an "
                                     "artifact prefix (or worker_args)")
                worker_args = ["--generate", os.fspath(prefix)]
                flag_for = {"num_pages": "--kv-pages",
                            "page_size": "--kv-page-size",
                            "max_prompt": "--max-prompt",
                            "max_new_tokens": "--max-new-tokens",
                            "max_batch": "--max-batch"}
                for k, flag in flag_for.items():
                    if engine_kwargs.get(k) is not None:
                        worker_args += [flag, str(engine_kwargs[k])]
            pool = ReplicaPool("%s/%d" % (name, version), worker_args,
                               replicas, generate=True,
                               gen_queue_depth=queue_depth,
                               **(pool_kwargs or {}))
            try:
                info = pool.wait_ready()
            except Exception:
                pool.close()
                raise
            # router-side SLOs over the pool's own admission→resolution
            # latency/volume series (the workers' scheduler objectives
            # are per-replica-process): THE breach signal the autoscaler
            # reads for pooled LMs (docs/serving.md §Autoscaling).
            # queue_depth=None: the router has no queue-depth gauge
            _slo.wire_serving_objectives("%s/%d" % (name, version))
            return ServedLM(name, version, pool=pool, info=info,
                            meta={"artifact": "generate",
                                  "path": None if prefix is None
                                  else os.fspath(prefix),
                                  "replicas": int(replicas)})
        engine = TransformerLMEngine(lm=load_lm(prefix), **engine_kwargs)
        sched = GenerateScheduler(engine,
                                  name="%s/%d" % (name, version),
                                  queue_depth=queue_depth)
        return ServedLM(name, version, scheduler=sched,
                        meta={"artifact": "generate",
                              "path": os.fspath(prefix)})

    # -- serving surface ---------------------------------------------------
    @property
    def pool(self):
        return self._pool

    @property
    def scheduler(self):
        return self._scheduler

    @property
    def resident_copies(self):
        # live pool size, so budget math tracks autoscaler resizes
        if self._pool is not None:
            return max(1, int(self._pool.size))
        try:
            return max(1, int(self.meta.get("replicas") or 1))
        except (TypeError, ValueError):
            return 1

    @property
    def effective_memory_bytes(self):
        if not self.memory_bytes:
            return None
        return self.memory_bytes * self.resident_copies

    def generate(self, tokens, max_new_tokens=None, temperature=0.0,
                 top_k=0, top_p=1.0, timeout_ms=None):
        """Admit one generation request and wait for it: returns
        ``{"tokens": [...], "finish_reason": ...}``. Raises the typed
        admission errors (429/503/504/400 mapping) like predict."""
        if timeout_ms is None:
            timeout_ms = _env.get("MXTPU_SERVE_TIMEOUT_MS")
        deadline = None
        if timeout_ms and timeout_ms > 0:
            deadline = time.monotonic() + float(timeout_ms) / 1e3
        if self._pool is not None:
            req = self._make_pool_request(tokens, max_new_tokens,
                                          temperature, top_k, top_p,
                                          deadline)
            self._pool.submit_generate(req)
        else:
            req = self._scheduler.submit(
                tokens, max_new_tokens=max_new_tokens,
                temperature=temperature, top_k=top_k, top_p=top_p,
                deadline=deadline)
        timeout = None if deadline is None \
            else max(0.0, deadline - time.monotonic())
        out = req.wait(timeout)
        return {"tokens": out, "finish_reason": req.finish_reason}

    def _make_pool_request(self, tokens, max_new_tokens, temperature,
                           top_k, top_p, deadline):
        """Router-side validation mirrors the scheduler's (the worker
        re-validates, but a malformed request should 400 here, not ride
        the wire)."""
        tokens = [int(t) for t in tokens]
        gi = self.generate_info
        if not tokens:
            raise MXNetError("generation needs at least one prompt token")
        if gi.get("max_prompt") and len(tokens) > gi["max_prompt"]:
            raise MXNetError(
                "prompt has %d tokens; this model admits up to %d"
                % (len(tokens), gi["max_prompt"]))
        cap = gi.get("max_new_tokens") \
            or _env.get("MXTPU_SERVE_MAX_NEW_TOKENS")
        if max_new_tokens is None:
            max_new_tokens = cap
        if int(max_new_tokens) < 1 or int(max_new_tokens) > cap:
            raise MXNetError("max_new_tokens must be in 1..%d, got %s"
                             % (cap, max_new_tokens))
        return GenRequest(tokens, max_new_tokens, temperature=temperature,
                          top_k=top_k, top_p=top_p, deadline=deadline)

    # -- repository lifecycle ---------------------------------------------
    def pending(self):
        if self._scheduler is not None:
            return self._scheduler.pending()
        return self._pool.generate_pending()

    def drain(self, timeout=None):
        if self._scheduler is not None:
            return self._scheduler.drain(timeout)
        return self._pool.drain_generate(timeout)

    def abort_pending(self, error=None):
        if self._scheduler is not None:
            return self._scheduler.abort_pending(error)
        return self._pool.abort_generate(error)

    def close(self, drain=True, timeout=None):
        drained = False
        if self._scheduler is not None:
            drained = self._scheduler.close(drain=drain, timeout=timeout)
        if self._pool is not None:
            if drain:
                drained = self._pool.drain_generate(timeout)
            self._pool.close()
            # retire the router-side objectives wired at pooled load —
            # verdicts for a gone model are noise on /statusz
            _slo.unregister_model("%s/%d" % (self.name, self.version))
        return drained

    def describe(self):
        out = {
            "name": self.name,
            "version": self.version,
            "kind": "generate",
            "generate": dict(self.generate_info),
            "warmed": self.warmed,
            "warm_seconds": self.warm_seconds,
            "pending": self.pending(),
            "loaded_at": self.loaded_at,
            "meta": self.meta,
            "memory": {"total_bytes": self.memory_bytes,
                       "copies": self.resident_copies,
                       "effective_bytes": self.effective_memory_bytes},
        }
        if self._scheduler is not None:
            alloc = self._scheduler.allocator
            out["kv"] = {"pages_total": alloc.num_pages,
                         "pages_used": alloc.used_pages,
                         "page_size": alloc.page_size}
        if self._pool is not None:
            out["pool"] = self._pool.describe()
        return out
