"""Dynamic micro-batcher: the request queue at the heart of the serving
subsystem (docs/serving.md).

The predict API (`predict.py`) was built for the one-caller-one-forward
case; between requests the accelerator idles. This module closes that gap
with the request/micro-batch design of clipper/triton-style model servers:

  * concurrent requests land in a bounded queue (admission control:
    ``MXTPU_SERVE_QUEUE_DEPTH``, overflow is rejected immediately — the
    HTTP layer maps that to 429);
  * a single worker thread coalesces them into one batch, closing it when
    it reaches ``MXTPU_SERVE_MAX_BATCH`` examples or when the oldest
    admitted request has waited ``MXTPU_SERVE_MAX_DELAY_MS``;
  * the batch is padded up to a POWER-OF-TWO bucket so every bucket maps
    to exactly one cached XLA executable (the Executor caches one
    compiled forward per input signature) — steady state never
    recompiles, whatever batch sizes arrive;
  * results are unpadded and split back per request (the shared
    `base.unpad_outputs` helper — same code path as module predict's
    last-batch unpad).

One worker thread per batcher means the underlying predictor is only ever
driven single-threaded — executor forward needs no locking — while any
number of frontend threads block cheaply on their request's event.

Everything here is framework-agnostic: the ``runner`` callable owns the
model; numpy in, numpy out.
"""
from __future__ import annotations

import collections
import logging
import threading
import time

import numpy as _np

from .. import env as _env
from .. import telemetry
from ..telemetry import slo as _slo
from ..telemetry import tracing as _tracing
from ..base import MXNetError, unpad_outputs

__all__ = [
    "ServingError", "QueueFullError", "DeadlineExceededError",
    "ModelUnavailableError", "DrainingError", "OverloadedError",
    "MemoryBudgetError",
    "power_of_two_buckets", "bucket_for", "pad_batch", "DynamicBatcher",
    "drain_timeout_s",
]

_LOG = logging.getLogger("mxnet_tpu.serving.batcher")
_warned_drain_s = False


def drain_timeout_s():
    """Effective graceful-drain budget in seconds: the
    `MXTPU_SERVE_DRAIN_TIMEOUT_MS` default, honoring the deprecated
    seconds-typed `MXTPU_SERVE_DRAIN_TIMEOUT_S` (with a one-time warning)
    when only the old name is set — one fallback shared by every drain
    reader, so a deployment's configured budget survives the rename no
    matter which drain path runs."""
    global _warned_drain_s
    timeout = _env.get("MXTPU_SERVE_DRAIN_TIMEOUT_MS") / 1e3
    if not _env.is_set("MXTPU_SERVE_DRAIN_TIMEOUT_MS") \
            and _env.is_set("MXTPU_SERVE_DRAIN_TIMEOUT_S"):
        timeout = _env.get("MXTPU_SERVE_DRAIN_TIMEOUT_S")
        if not _warned_drain_s:
            _warned_drain_s = True
            _LOG.warning(
                "MXTPU_SERVE_DRAIN_TIMEOUT_S is deprecated; set "
                "MXTPU_SERVE_DRAIN_TIMEOUT_MS=%d instead (honoring the "
                "old value as %.0fs)", int(timeout * 1e3), timeout)
    return timeout


class ServingError(MXNetError):
    """Base serving-layer error; `status` is the HTTP mapping and
    `retry_after` (seconds, optional) becomes a ``Retry-After`` header."""

    status = 500
    retry_after = None


class QueueFullError(ServingError):
    """Admission control: the bounded request queue is full."""

    status = 429
    retry_after = 1


class DeadlineExceededError(ServingError):
    """The request's deadline expired before a result was produced."""

    status = 504


class ModelUnavailableError(ServingError):
    """No such model/version (or it has been unloaded)."""

    status = 404


class DrainingError(ServingError):
    """The server/model is draining and admits no new work."""

    status = 503


class OverloadedError(ServingError):
    """Deterministic load shedding: the model's replica pool is degraded
    and taking this request would queue it into a black hole. The reply
    carries ``Retry-After`` scaled to the healthy-replica count
    (docs/serving.md resilience section)."""

    status = 503

    def __init__(self, msg, retry_after=1):
        super().__init__(msg)
        self.retry_after = max(1, int(retry_after))


class MemoryBudgetError(ServingError):
    """A model load's computed device footprint (per-executable
    `memory_analysis()` figures, docs/observability.md §Memory) exceeds
    ``MXTPU_SERVE_MEMORY_BUDGET``: the load is rejected BEFORE publish —
    at admission time, deterministically — instead of letting the
    process OOM under traffic. 507 Insufficient Storage.

    ``details`` carries the machine-readable footprint breakdown
    (requested bytes, per-resident-model ``effective_memory_bytes``,
    budget, headroom, shortfall) so an operator can see WHAT to evict;
    the HTTP layer ships it in the 507 body."""

    status = 507

    def __init__(self, msg, details=None):
        super().__init__(msg)
        self.details = details


# ---------------------------------------------------------------------------
# buckets
# ---------------------------------------------------------------------------

def power_of_two_buckets(max_batch):
    """The padding buckets for a given max batch: every power of two below
    ``max_batch``, plus ``max_batch`` itself as the terminal bucket (so a
    non-power-of-two max still gets exactly one executable)."""
    max_batch = int(max_batch)
    if max_batch < 1:
        raise MXNetError("max_batch must be >= 1, got %d" % max_batch)
    buckets, b = [], 1
    while b < max_batch:
        buckets.append(b)
        b *= 2
    buckets.append(max_batch)
    return buckets


def bucket_for(n, buckets):
    """Smallest bucket holding ``n`` examples (None when n overflows)."""
    for b in buckets:
        if n <= b:
            return b
    return None


def pad_batch(batch, total, buckets):
    """Concatenate the requests' input arrays and zero-pad up to the
    smallest bucket holding ``total`` examples. Returns ``(padded_arrays,
    bucket)``. Shared by the inline runner path and the replica-pool
    dispatchers (each pads in its own thread)."""
    bucket = bucket_for(total, buckets)
    names = batch[0].arrays.keys()
    padded = {}
    for name in names:
        parts = [r.arrays[name] for r in batch]
        a = parts[0] if len(parts) == 1 else _np.concatenate(parts)
        if a.shape[0] < bucket:
            pad = _np.zeros((bucket - a.shape[0],) + a.shape[1:],
                            dtype=a.dtype)
            a = _np.concatenate([a, pad])
        padded[name] = a
    return padded, bucket


# ---------------------------------------------------------------------------
# requests
# ---------------------------------------------------------------------------

class ServeRequest:
    """One admitted inference request: ``arrays`` is a dict of input name ->
    numpy array whose leading dim is this request's example count."""

    __slots__ = ("arrays", "n", "deadline", "outputs", "error", "bucket",
                 "_event", "_rlock", "_t_submit", "queue_seconds",
                 "compute_seconds", "retried", "trace")

    def __init__(self, arrays, n, deadline):
        self.arrays = arrays
        self.n = n
        self.deadline = deadline
        self.outputs = None
        self.error = None
        self.bucket = None
        self.queue_seconds = None
        self.compute_seconds = None
        self.retried = False  # failover re-enqueue happened (exactly once)
        # span context captured at admission (the HTTP handler's request
        # span); every later phase — whichever thread or process runs it —
        # parents its spans here, so one trace follows the request
        self.trace = _tracing.capture()
        self._event = threading.Event()
        self._rlock = threading.Lock()
        self._t_submit = time.monotonic()

    def done(self):
        return self._event.is_set()

    def wait(self, timeout=None):
        """Block until the batcher resolves this request (or the wait times
        out / the deadline passes). Returns the per-request output list or
        raises the ServingError the batcher recorded."""
        self._event.wait(timeout)
        if not self._event.is_set():
            raise DeadlineExceededError(
                "request expired after %.0f ms in queue"
                % ((time.monotonic() - self._t_submit) * 1e3))
        if self.error is not None:
            raise self.error
        return self.outputs

    def _resolve(self, outputs=None, error=None):
        # first resolution wins, ATOMICALLY: a replica dispatch thread, the
        # drain thread's abort_pending and the worker's expiry path can race
        # here, and an unlocked check-then-act could interleave their writes
        # so a waiter wakes to outputs=None, error=None
        with self._rlock:
            if self._event.is_set():
                return  # a late error must not clobber a result a waiter
                #         may already be reading
            self.outputs = outputs
            self.error = error
            self._event.set()


# ---------------------------------------------------------------------------
# the batcher
# ---------------------------------------------------------------------------

class DynamicBatcher:
    """Coalesce concurrent requests into padded, bucketed batches.

    Parameters
    ----------
    runner : callable(batch_arrays, bucket, n) -> list of numpy arrays
        Runs one padded batch (leading dim == bucket) and returns the model
        outputs, each with leading dim == bucket. Called only from the
        batcher's single worker thread.
    buckets : list of int
        Ascending padding buckets; the last is the max coalesced batch.
    max_delay_ms / queue_depth : admission + coalescing knobs
        Default to ``MXTPU_SERVE_MAX_DELAY_MS`` / ``MXTPU_SERVE_QUEUE_DEPTH``.
    name : str
        Telemetry label (``model="<name>"`` on every serving metric).
    dispatcher : callable(batch, total), optional
        Takes over batch execution (the replica pool's hook): called from
        the worker thread with an assembled, expiry-filtered batch; the
        dispatcher must eventually route every request through
        `resolve_batch` / `fail_batch` / `requeue` so in-flight accounting
        closes. When None (default), batches run inline on ``runner``.
    admission_gate : callable(queued_len) -> ServingError or None, optional
        Consulted under the queue lock on every submit BEFORE the depth
        check — the replica pool sheds load here when degraded (an error
        return is raised to the caller; the request never queues).
    """

    def __init__(self, runner, buckets, max_delay_ms=None, queue_depth=None,
                 name="default", dispatcher=None, admission_gate=None):
        self._runner = runner
        self._dispatcher = dispatcher
        self._admission_gate = admission_gate
        self.buckets = sorted(int(b) for b in buckets)
        if not self.buckets:
            raise MXNetError("need at least one bucket")
        self.max_batch = self.buckets[-1]
        if max_delay_ms is None:
            max_delay_ms = _env.get("MXTPU_SERVE_MAX_DELAY_MS")
        if queue_depth is None:
            queue_depth = _env.get("MXTPU_SERVE_QUEUE_DEPTH")
        self.max_delay_s = max(0.0, float(max_delay_ms)) / 1e3
        self.queue_depth = max(1, int(queue_depth))
        self.name = name

        self._queue = collections.deque()
        self._cv = threading.Condition()
        self._stop = False
        self._draining = False
        # requests popped but not yet resolved — a SET (not a count) so a
        # forced drain can resolve work stuck inside a wedged runner
        self._inflight = set()

        labels = {"model": name}
        self._m_queue = telemetry.gauge("mxtpu_serve_queue_depth", labels)
        self._m_reqs = telemetry.counter("mxtpu_serve_requests_total", labels)
        self._m_examples = telemetry.counter("mxtpu_serve_examples_total",
                                             labels)
        self._m_batches = telemetry.counter("mxtpu_serve_batches_total",
                                            labels)
        self._m_rej_full = telemetry.counter(
            "mxtpu_serve_rejected_total", {"model": name, "reason": "queue_full"})
        self._m_rej_dead = telemetry.counter(
            "mxtpu_serve_rejected_total", {"model": name, "reason": "deadline"})
        self._m_rej_shed = telemetry.counter(
            "mxtpu_serve_rejected_total", {"model": name, "reason": "shed"})
        # how full each dispatched bucket was (n / bucket): the occupancy
        # evidence serve_bench reports
        self._m_occupancy = telemetry.histogram(
            "mxtpu_serve_batch_occupancy", labels,
            bounds=tuple(i / 10.0 for i in range(1, 11)))
        self._m_batch_size = telemetry.histogram(
            "mxtpu_serve_batch_size", labels,
            bounds=tuple(float(b) for b in self.buckets))
        # queue-wait vs compute split per request — the first thing to read
        # when serving latency is off (is it admission or the model?)
        self._m_queue_s = telemetry.histogram("mxtpu_serve_queue_seconds",
                                              labels)
        self._m_compute_s = telemetry.histogram("mxtpu_serve_compute_seconds",
                                                labels)
        # end-to-end admission→resolution latency per request: THE serving
        # SLO figure (the built-in p99 objective and /statusz windowed
        # rates read it), with trace-id exemplars so a breach names a
        # renderable trace
        self._m_request_s = telemetry.histogram("mxtpu_serve_request_seconds",
                                                labels)
        # built-in SLOs for this model: p99 / availability / queue-depth
        # ceiling (docs/observability.md §SLOs); dropped again in close()
        _slo.wire_serving_objectives(name, queue_depth=self.queue_depth)

        self._worker = threading.Thread(
            target=self._loop, name="mxtpu-serve-batcher-%s" % name,
            daemon=True)
        self._worker.start()

    # -- admission ---------------------------------------------------------
    def submit(self, arrays, deadline=None):
        """Admit one request. ``arrays``: dict name -> numpy array, leading
        dim = example count (1..max_batch). Returns a `ServeRequest` whose
        ``wait()`` yields the unpadded per-request outputs."""
        ns = {int(a.shape[0]) for a in arrays.values()}
        if not ns:
            raise MXNetError("request carries no input arrays")
        if len(ns) != 1:
            raise MXNetError("inconsistent leading dims across inputs: %s"
                             % sorted(ns))
        n = ns.pop()
        if n < 1 or n > self.max_batch:
            raise MXNetError(
                "request carries %d examples; this model serves 1..%d per "
                "request (MXTPU_SERVE_MAX_BATCH)" % (n, self.max_batch))
        req = ServeRequest(arrays, n, deadline)
        with self._cv:
            if self._stop or self._draining:
                raise DrainingError("model %r is draining" % self.name)
            if self._admission_gate is not None:
                err = self._admission_gate(len(self._queue))
                if err is not None:
                    self._m_rej_shed.inc()
                    raise err
            if len(self._queue) >= self.queue_depth:
                self._m_rej_full.inc()
                raise QueueFullError(
                    "queue for model %r is full (%d requests; "
                    "MXTPU_SERVE_QUEUE_DEPTH)" % (self.name, self.queue_depth))
            self._queue.append(req)
            self._m_queue.set(len(self._queue))
            self._m_reqs.inc()
            self._cv.notify()
        return req

    def pending(self):
        """Queued + in-flight request count (drain progress)."""
        with self._cv:
            return len(self._queue) + len(self._inflight)

    # -- shutdown ----------------------------------------------------------
    def drain(self, timeout=None):
        """Stop admitting, let the worker finish everything queued, and wait
        up to ``timeout`` seconds (default `MXTPU_SERVE_DRAIN_TIMEOUT_MS` —
        a wedged model must not hang shutdown forever). Returns True when
        fully drained."""
        with self._cv:
            self._draining = True
            self._cv.notify_all()
        if timeout is None:
            timeout = drain_timeout_s()
        deadline = time.monotonic() + timeout
        while self.pending():
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.005)
        return True

    def abort_pending(self, error=None):
        """Force-resolve every queued AND in-flight request (bounded-drain
        escape hatch: a wedged runner must not strand its waiters — they
        get a deterministic 503 instead of a connection reset when the
        process exits). Safe against late runner completion: first
        resolution wins. Returns how many requests were force-resolved."""
        if error is None:
            error = DrainingError(
                "model %r drain timed out; request force-completed"
                % self.name)
        with self._cv:
            victims = [r for r in self._queue] + \
                [r for r in self._inflight if not r.done()]
            self._queue.clear()
            self._inflight.clear()
            self._m_queue.set(0)
        for req in victims:
            req._resolve(error=error)
        return len(victims)

    def close(self, drain=True, timeout=None):
        """Drain (optionally) then stop the worker thread."""
        drained = self.drain(timeout) if drain else False
        with self._cv:
            self._stop = True
            self._draining = True
            self._cv.notify_all()
        self._worker.join(timeout=5.0)
        # anything still queued after a failed/skipped drain gets an answer
        self.abort_pending(DrainingError(
            "model %r shut down before this request ran" % self.name))
        # verdicts for a gone model are noise on /statusz
        _slo.unregister_model(self.name)
        return drained

    # -- the worker --------------------------------------------------------
    def _pop_live(self, max_n=None):
        """Pop the next request that is still live (expired ones are
        resolved 504 on the spot) AND fits within ``max_n`` examples — the
        fit check must be applied to the request actually popped, not the
        pre-expiry queue head. Returns None when the queue is empty or the
        next live request would overflow. Caller holds _cv."""
        now = time.monotonic()
        while self._queue:
            req = self._queue[0]
            if req.deadline is not None and now >= req.deadline:
                self._queue.popleft()
                self._m_queue.set(len(self._queue))
                self._expire(req, now)
                continue
            if max_n is not None and req.n > max_n:
                return None  # stays queued for the next batch
            self._queue.popleft()
            self._m_queue.set(len(self._queue))
            self._inflight.add(req)
            return req
        return None

    def _expire(self, req, now=None):
        """Resolve one request 504. In-flight accounting is the CALLER's
        job (close it under ``_cv`` before calling): the old ``locked=``
        parameter made this method's locking depend on caller-supplied
        control flow, which the lock-discipline/lock-order checkers
        rightly cannot prove safe — and neither could a reviewer."""
        if now is None:
            now = time.monotonic()
        self._m_rej_dead.inc()
        req._resolve(error=DeadlineExceededError(
            "deadline expired after %.0f ms in queue"
            % ((now - req._t_submit) * 1e3)))

    def _prune_expired(self, batch):
        """Drop (and 504) every already-expired request from an assembled
        batch — the batch may have aged in the coalescing window or a
        dispatcher queue since its members were popped live. Returns the
        still-live remainder. Spending executor time on an answer nobody is
        waiting for is exactly the work a degraded pool cannot afford."""
        now = time.monotonic()
        live, dead = [], []
        for req in batch:
            if req.deadline is not None and now >= req.deadline \
                    and not req.done():
                dead.append(req)
            elif not req.done():
                live.append(req)
        if dead:
            with self._cv:
                self._inflight.difference_update(dead)
            for req in dead:
                self._expire(req, now)
        return live

    def _loop(self):
        while True:
            batch = []
            total = 0
            with self._cv:
                while not self._queue:
                    if self._stop:
                        return
                    self._cv.wait(0.05)
                first = self._pop_live()
            if first is None:
                continue
            batch.append(first)
            total = first.n
            t_assembly = time.monotonic()
            close_at = t_assembly + self.max_delay_s
            # coalesce until the bucket ceiling or the delay window closes;
            # when draining, take whatever is queued without waiting
            while total < self.max_batch:
                with self._cv:
                    req = self._pop_live(self.max_batch - total)
                    if req is None:
                        if self._queue:
                            break  # live head would overflow: next batch's
                        if self._draining or self._stop:
                            break
                        remaining = close_at - time.monotonic()
                        if remaining <= 0:
                            break
                        self._cv.wait(min(remaining, 0.05))
                        continue
                batch.append(req)
                total += req.n
            # assembly-time expiry: members can age out during the
            # coalescing window (or while queued behind a long batch) —
            # 504 them NOW instead of spending executor time on answers
            # nobody is waiting for
            batch = self._prune_expired(batch)
            total = sum(r.n for r in batch)
            if not batch:
                continue
            # the coalescing window, per traced request (retroactive span:
            # only the window's end knows the batch composition)
            assembly_s = time.monotonic() - t_assembly
            assembly_wall = time.time() - assembly_s
            for req in batch:
                _tracing.emit_span("serve.assembly", assembly_wall,
                                   assembly_s, req.trace, component="router",
                                   attrs={"batch": len(batch), "n": total})
            try:
                if self._dispatcher is not None:
                    self._dispatcher(batch, total)
                else:
                    self._dispatch(batch, total)
            except Exception as e:  # the lone worker must NEVER die
                telemetry.record_event("serve_batcher_error",
                                       model=self.name, error=repr(e))
                self.fail_batch(batch, ServingError(
                    "batcher for %r failed: %r" % (self.name, e)))

    # -- batch resolution (shared by the inline path and pool dispatchers) -
    def resolve_batch(self, batch, outputs, bucket, total, compute_s):
        """Unpad `outputs` (leading dim == bucket), split them back per
        request, resolve every request, and close in-flight accounting.
        `batch` must be the exact request list the outputs were computed
        for (order preserved)."""
        now = time.monotonic()
        t_unpad = time.perf_counter()
        unpad_wall = time.time()
        outs = unpad_outputs(outputs, bucket - total)
        offset = 0
        splits = []
        for req in batch:
            req.bucket = bucket
            req.queue_seconds = max(0.0, now - compute_s - req._t_submit)
            req.compute_seconds = compute_s
            trace_id = req.trace.trace_id if req.trace is not None else None
            self._m_queue_s.observe(req.queue_seconds, exemplar=trace_id)
            self._m_request_s.observe(max(0.0, now - req._t_submit),
                                      exemplar=trace_id)
            # queue-phase span, start rebased to the request's submit time
            # (wall clock = now minus the monotonic elapsed)
            _tracing.emit_span(
                "serve.queue", unpad_wall - (now - req._t_submit),
                req.queue_seconds, req.trace, component="router")
            per_req = [o[offset:offset + req.n].copy() for o in outs]
            offset += req.n
            splits.append((req, per_req))
        unpad_s = time.perf_counter() - t_unpad
        for req, per_req in splits:
            _tracing.emit_span("serve.unpad", unpad_wall, unpad_s,
                               req.trace, component="router",
                               attrs={"bucket": bucket,
                                      "pad": bucket - total})
            req._resolve(outputs=per_req)
        with self._cv:
            self._inflight.difference_update(batch)
        self._m_examples.inc(total)
        self._m_batches.inc()
        self._m_batch_size.observe(total)
        if bucket:
            self._m_occupancy.observe(total / float(bucket))
        self._m_compute_s.observe(
            compute_s, exemplar=next(
                (r.trace.trace_id for r in batch
                 if r.trace is not None and r.trace.recorded), None))

    def fail_batch(self, batch, error, compute_s=None):
        """Resolve every request in `batch` with `error` and close
        accounting (already-resolved members are left alone). Failed
        batches still count toward the dispatch-volume metrics —
        batches/examples flatlining during an incident would read as "no
        traffic" on a dashboard, and compute burned on batches that then
        error must stay visible (occupancy is success-only: the bucket
        is not always known on the failure path)."""
        for req in batch:
            req._resolve(error=error)
        with self._cv:
            self._inflight.difference_update(batch)
        total = sum(r.n for r in batch)
        self._m_examples.inc(total)
        self._m_batches.inc()
        self._m_batch_size.observe(total)
        if compute_s is not None:
            self._m_compute_s.observe(compute_s)

    def requeue(self, batch):
        """Failover path: push a dead replica's in-flight batch back to the
        FRONT of the queue, EXACTLY ONCE per request (predict is
        idempotent, so one retry is safe; unbounded retries could double
        work without bound). Expired members are 504ed; members that
        already failed over once get a retryable 503 instead of a second
        ride. Returns the number of requests actually requeued."""
        now = time.monotonic()
        requeued = 0
        # requests requeued by THIS call — `req.retried` alone cannot tell
        # "just went back on the queue" from "already used its one retry
        # on an earlier failover" (the latter must get the 503 below, not
        # be skipped unresolved)
        taken = set()
        with self._cv:
            self._inflight.difference_update(batch)
            accept = not (self._stop or self._draining)
            for req in reversed(batch):
                if req.done():
                    continue
                if req.deadline is not None and now >= req.deadline:
                    continue  # expired: resolved below, outside the lock
                if req.retried or not accept:
                    continue
                req.retried = True
                taken.add(req)
                self._queue.appendleft(req)
                requeued += 1
            self._m_queue.set(len(self._queue))
            if requeued:
                self._cv.notify()
        for req in batch:
            if req in taken or req.done():
                continue
            if req.deadline is not None and now >= req.deadline:
                self._expire(req, now)
            elif req.retried:
                # second replica death under the same request: answer a
                # retryable 503 rather than loop the failover
                req._resolve(error=OverloadedError(
                    "request already failed over once on model %r"
                    % self.name))
            else:
                # never retried, but the batcher stopped accepting: the
                # 503 is about draining, not a failover the request never
                # had
                req._resolve(error=OverloadedError(
                    "model %r is draining; in-flight request not retried"
                    % self.name))
        return requeued

    def _dispatch(self, batch, total):
        t0 = time.monotonic()
        try:
            padded, bucket = pad_batch(batch, total, self.buckets)
            t_run = time.monotonic()
            run_wall = time.time()
            outs = self._runner(padded, bucket, total)
            compute_s = time.monotonic() - t_run
            for req in batch:
                _tracing.emit_span("serve.compute", run_wall, compute_s,
                                   req.trace, component="worker",
                                   attrs={"bucket": bucket, "n": total})
            self.resolve_batch(batch, outs, bucket, total,
                               time.monotonic() - t0)
        except ServingError as e:
            self.fail_batch(batch, e, compute_s=time.monotonic() - t0)
        except Exception as e:  # a model failure answers 500, never hangs
            err = ServingError("model %r failed: %r" % (self.name, e))
            err.__cause__ = e
            telemetry.record_event("serve_batch_error", model=self.name,
                                   error=repr(e))
            self.fail_batch(batch, err, compute_s=time.monotonic() - t0)
