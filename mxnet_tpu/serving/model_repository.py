"""Versioned multi-model registry for the serving subsystem.

A repository maps ``name/version`` to a `ServedModel`: a loaded inference
artifact plus its per-bucket executables and its own `DynamicBatcher`
(one worker thread per served model — the executor is only ever driven
single-threaded; any number of HTTP threads block on their request event).

Two artifact kinds load (the same two the deployment layer produces):

  * ``prefix`` -> ``prefix-symbol.json`` + ``prefix-%04d.params``
    (`HybridBlock.export` / `model.save_checkpoint`): a live `Predictor`
    is bound per padding bucket, every clone SHARING the prototype's
    device weight buffers (the `predict._clone_with` mechanism — the
    reference's MXPredCreateMultiThread semantics) so N buckets cost one
    copy of the weights plus N small IO buffers.
  * ``*.mxc`` / ``MXTPUAOT1`` blobs (`Predictor.export_compiled`): a
    `CompiledPredictor` whose geometry is frozen at build — its frozen
    batch size is the single padding bucket.

Loading WARMS every bucket (one forward of zeros per bucket) before the
model is published, so the executable cache is fully populated and steady-
state traffic never sees a compile. Unloading drains the model's queue
and in-flight work before dropping it (hot load/unload).
"""
from __future__ import annotations

import os
import re
import threading
import time

import numpy as _np

from .. import compile as _compile
from .. import env as _env
from .. import telemetry
from ..base import MXNetError
from ..parallel import resilience as _resilience
from ..telemetry import memory as _tm_memory
from .batcher import (DynamicBatcher, MemoryBudgetError,
                      ModelUnavailableError, drain_timeout_s,
                      power_of_two_buckets)

__all__ = ["ServedModel", "ModelRepository", "build_runner"]


def _resolved_max_batch(max_batch):
    """The max_batch that actually shapes the bucket set (env default
    applied) — warmup-manifest ids key on THIS value on both the
    repository and replica-worker sides, so a geometry change cleanly
    partitions manifests (docs/compile_cache.md)."""
    if max_batch is not None:
        return int(max_batch)
    return _env.get("MXTPU_SERVE_MAX_BATCH")


class ServedModel:
    """One ``name/version``: bucketed forward + dynamic batcher.

    ``runner(batch_arrays, bucket, n) -> [numpy outputs]`` owns the actual
    model; the constructors below build it from deployment artifacts, and
    tests may inject a stub (the repository only needs this interface).
    """

    def __init__(self, name, version, runner, buckets, example_shapes,
                 input_dtypes=None, meta=None, max_delay_ms=None,
                 queue_depth=None, pool=None):
        self.name = str(name)
        self.version = int(version)
        self.example_shapes = {k: tuple(v) for k, v in example_shapes.items()}
        self.input_dtypes = {k: _np.dtype(input_dtypes[k])
                             if input_dtypes and k in input_dtypes
                             else _np.dtype(_np.float32)
                             for k in self.example_shapes}
        self.meta = dict(meta or {})
        self.loaded_at = time.time()
        # autoscaling policy (docs/serving.md §Autoscaling): None defers
        # to the MXTPU_AUTOSCALE_{MIN,MAX}_REPLICAS defaults; `pinned`
        # exempts the model from budget-pressure eviction
        self.min_replicas = None
        self.max_replicas = None
        self.pinned = False
        self.warmed = False
        self.warm_seconds = None
        self.manifest_id = None     # warmup-manifest id (artifact models)
        self.compile_digests = []   # executable-cache digests the warm
        #                             filled/loaded (docs/compile_cache.md)
        self.bucket_flops = {}  # bucket -> FLOPs per batch (warm-time
        #                         cost analysis; {} when unavailable)
        self.bucket_memory = {}  # bucket -> memory_analysis figures of
        #                          the executables the bucket warm
        #                          filled/loaded ({} when unavailable)
        self.memory_bytes = None  # model device footprint from the
        #                           figures (docs/observability.md §Memory)
        self._runner = runner
        self._pool = pool
        if pool is not None:
            # resilient mode: batches are dispatched to the replica pool's
            # worker processes; admission runs through the pool's
            # load-shedding gate (docs/serving.md §resilience)
            self._batcher = DynamicBatcher(
                None, buckets, max_delay_ms=max_delay_ms,
                queue_depth=queue_depth,
                name="%s/%d" % (self.name, self.version),
                dispatcher=pool.dispatch_batch,
                admission_gate=pool.admission_gate)
            pool.bind(self._batcher)
        else:
            self._batcher = DynamicBatcher(
                runner, buckets, max_delay_ms=max_delay_ms,
                queue_depth=queue_depth,
                name="%s/%d" % (self.name, self.version))

    # -- construction from artifacts --------------------------------------
    @staticmethod
    def pooled(name, version, path, replicas, input_shapes=None,
               input_dtypes=None, max_batch=None, max_delay_ms=None,
               queue_depth=None, heartbeat_ms=None, backoff_ms=None,
               extra_env=None, spawn_timeout_s=120.0, teardown_grace=None,
               worker_args=None, wedge_timeout_ms=None):
        """Serve an artifact through a supervised `ReplicaPool` of
        ``replicas`` worker processes (docs/serving.md §resilience).
        ``worker_args`` overrides the artifact argv entirely (tests pass
        ``--stub`` specs). The pool spawns, loads and warms every replica
        BEFORE the model is returned — a half-warm pool never publishes."""
        from .replica_pool import ReplicaPool

        if worker_args is None:
            if path is None:
                raise MXNetError("pooled() needs an artifact path (or "
                                 "explicit worker_args)")
            worker_args = ["--artifact", os.fspath(path)]
            for iname, dims in (input_shapes or {}).items():
                spec = "%s=%s" % (iname, "x".join(str(d) for d in dims))
                if input_dtypes and iname in input_dtypes:
                    spec += ":%s" % input_dtypes[iname]
                worker_args += ["--input", spec]
            if max_batch is not None:
                worker_args += ["--max-batch", str(max_batch)]
        pool = ReplicaPool("%s/%d" % (name, int(version)), worker_args,
                           replicas, heartbeat_ms=heartbeat_ms,
                           backoff_ms=backoff_ms, extra_env=extra_env,
                           spawn_timeout_s=spawn_timeout_s,
                           teardown_grace=teardown_grace,
                           wedge_timeout_ms=wedge_timeout_ms)
        try:
            info = pool.wait_ready(spawn_timeout_s)
        except Exception:
            pool.close()
            raise
        model = ServedModel(
            name, version, None, info["buckets"], info["example_shapes"],
            input_dtypes=info.get("input_dtypes"),
            meta={"artifact": "pooled", "path": None if path is None
                  else os.fspath(path), "replicas": int(replicas)},
            max_delay_ms=max_delay_ms, queue_depth=queue_depth, pool=pool)
        # every replica warmed its buckets before reporting ready
        model.warmed = True
        model.warm_seconds = info.get("warm_seconds")
        if info.get("bucket_flops"):
            model.set_bucket_flops(info["bucket_flops"])
        if info.get("bucket_memory"):
            # figures computed replica-side during its warm (ready frame)
            model.set_bucket_memory(info["bucket_memory"])
        # the replica's executable key-set (it wrote the warmup manifest
        # worker-side, next to the artifacts it filled/loaded)
        if path is not None:
            model.manifest_id = _compile.model_manifest_id(
                path, _resolved_max_batch(max_batch), input_shapes)
        model.compile_digests = sorted(info.get("compile_digests") or [])
        return model

    @staticmethod
    def from_path(name, version, path, input_shapes=None, input_dtypes=None,
                  ctx=None, max_batch=None, max_delay_ms=None,
                  queue_depth=None):
        """Load a deployment artifact: a ``*.mxc``/``MXTPUAOT1`` compiled
        blob, or an export ``prefix`` (with ``input_shapes`` = per-example
        shapes, batch dim EXCLUDED)."""
        kind, parts = _resolve_artifact(path)
        if kind == "compiled":
            model = ServedModel._from_compiled(
                name, version, parts, max_delay_ms=max_delay_ms,
                queue_depth=queue_depth)
        else:
            symbol_file, param_file = parts
            model = ServedModel._from_symbol(
                name, version, symbol_file, param_file,
                input_shapes=input_shapes, input_dtypes=input_dtypes,
                ctx=ctx, max_batch=max_batch, max_delay_ms=max_delay_ms,
                queue_depth=queue_depth)
        # ties this artifact + geometry to its warmup manifest (the SAME
        # id a replica worker derives from its argv — manifest.py)
        model.manifest_id = _compile.model_manifest_id(
            path, _resolved_max_batch(max_batch), input_shapes)
        return model

    @staticmethod
    def _from_symbol(name, version, symbol_file, param_file, input_shapes,
                     input_dtypes=None, ctx=None, max_batch=None,
                     max_delay_ms=None, queue_depth=None):
        runner, buckets, example_shapes, dtypes, meta = _symbol_runner(
            symbol_file, param_file, input_shapes,
            input_dtypes=input_dtypes, ctx=ctx, max_batch=max_batch)
        return ServedModel(name, version, runner, buckets, example_shapes,
                           input_dtypes=dtypes, meta=meta,
                           max_delay_ms=max_delay_ms,
                           queue_depth=queue_depth)

    @staticmethod
    def _from_compiled(name, version, path, max_delay_ms=None,
                       queue_depth=None):
        runner, buckets, example_shapes, dtypes, meta = \
            _compiled_runner(path)
        return ServedModel(name, version, runner, buckets, example_shapes,
                           input_dtypes=dtypes, meta=meta,
                           max_delay_ms=max_delay_ms,
                           queue_depth=queue_depth)

    # -- serving surface ---------------------------------------------------
    @property
    def pool(self):
        """The model's `ReplicaPool` (None when served in-process).
        serve_bench's failover row kills/observes replicas through it."""
        return self._pool

    @property
    def buckets(self):
        return list(self._batcher.buckets)

    @property
    def max_batch(self):
        return self._batcher.max_batch

    def validate(self, arrays):
        """Check names/shapes/dtypes against the model signature; returns
        the (cast) arrays. Raises MXNetError on mismatch (HTTP 400)."""
        want = set(self.example_shapes)
        got = set(arrays)
        if want != got:
            raise MXNetError("inputs %s != model inputs %s"
                             % (sorted(got), sorted(want)))
        out = {}
        for k, a in arrays.items():
            a = _np.asarray(a, dtype=self.input_dtypes[k])
            if tuple(a.shape[1:]) != self.example_shapes[k]:
                raise MXNetError(
                    "input %r per-example shape %s != declared %s"
                    % (k, tuple(a.shape[1:]), self.example_shapes[k]))
            out[k] = a
        return out

    def predict(self, arrays, timeout_ms=None):
        """Validate, admit, and wait: returns the list of per-request
        output arrays. Raises QueueFullError / DeadlineExceededError /
        DrainingError per the admission-control contract."""
        arrays = self.validate(arrays)
        if timeout_ms is None:
            timeout_ms = _env.get("MXTPU_SERVE_TIMEOUT_MS")
        deadline = None
        if timeout_ms and timeout_ms > 0:
            deadline = time.monotonic() + float(timeout_ms) / 1e3
        req = self._batcher.submit(arrays, deadline)
        timeout = None if deadline is None \
            else max(0.0, deadline - time.monotonic())
        return req.wait(timeout)

    def record_compile_entries(self, entries):
        """Record the executable key-set the load+warm filled or loaded
        from the persistent tier (``(ExecutableKey, digest)`` pairs from
        `compile.keys_since`), and publish it as this model's warmup
        manifest so a future cold start prefetches instead of compiling
        (docs/compile_cache.md)."""
        self.compile_digests = sorted({d for _, d in entries})
        directory = _compile.cache_dir()
        if directory and self.manifest_id and entries:
            _compile.write_manifest(directory, self.manifest_id, entries,
                                    model=self.name, version=self.version)

    def set_bucket_flops(self, bucket_flops):
        """Publish per-bucket FLOP cost (from warm-time cost analysis) as
        ``mxtpu_serve_bucket_flops`` gauges — the serving arm of the
        automatic FLOP accounting (docs/observability.md)."""
        self.bucket_flops = {int(b): f for b, f in bucket_flops.items() if f}
        for b, f in self.bucket_flops.items():
            telemetry.gauge("mxtpu_serve_bucket_flops",
                            {"model": "%s/%d" % (self.name, self.version),
                             "bucket": str(b)}).set(f)

    @property
    def resident_copies(self):
        """How many full copies of the model are resident: each replica
        worker process warms its own weights + executables, so a pooled
        model costs N× its single-copy footprint. Read LIVE from the
        pool so budget math tracks autoscaler resizes, not the size the
        model loaded with."""
        if self._pool is not None:
            return max(1, int(self._pool.size))
        try:
            return max(1, int(self.meta.get("replicas") or 1))
        except (TypeError, ValueError):
            return 1

    @property
    def effective_memory_bytes(self):
        """Single-copy footprint × resident copies — what the
        ``MXTPU_SERVE_MEMORY_BUDGET`` admission check charges."""
        if not self.memory_bytes:
            return None
        return self.memory_bytes * self.resident_copies

    def set_bucket_memory(self, bucket_memory):
        """Record per-bucket memory figures (summed `memory_analysis()`
        of the executables each bucket warm filled or loaded from the
        persistent tier), derive the model's single-copy device
        footprint, and publish the EFFECTIVE (× replicas) figure as
        ``mxtpu_serve_model_memory_bytes`` — the number the
        ``MXTPU_SERVE_MEMORY_BUDGET`` admission check enforces and the
        answer to "how many replicas of this model fit on a chip"."""
        self.bucket_memory = {int(b): dict(f)
                              for b, f in bucket_memory.items() if f}
        self.memory_bytes = _tm_memory.model_footprint(self.bucket_memory)
        if self.effective_memory_bytes:
            telemetry.gauge("mxtpu_serve_model_memory_bytes",
                            {"model": "%s/%d" % (self.name, self.version)}
                            ).set(self.effective_memory_bytes)

    def warm(self):
        """One zeros-forward per bucket: populates the executable cache so
        steady-state traffic never compiles, and — with automatic FLOP
        accounting on — prices each bucket's executable from the compile's
        cost analysis. Emits one ``serve_bucket_warm`` event per bucket."""
        from ..telemetry import flops as _flops

        if self._pool is not None:
            # pooled models warm replica-side before each replica reports
            # ready (supervisor.worker_main) — nothing to do here
            self.warmed = True
            return self.warm_seconds
        t_all = time.monotonic()
        bucket_flops = {}
        bucket_memory = {}
        for b in self._batcher.buckets:
            zeros = {k: _np.zeros((b,) + s, dtype=self.input_dtypes[k])
                     for k, s in self.example_shapes.items()}
            t0 = time.monotonic()
            f0 = _flops.total()
            m0 = _tm_memory.recorded_mark()
            _compile.begin_touch_log()
            try:
                self._runner(zeros, b, b)
            finally:
                touched = _compile.end_touch_log()
            bucket_flops[b] = _flops.total() - f0
            # memory figures of the executables THIS bucket's warm filled,
            # deserialized (zero-compile cold starts read them from the
            # artifact headers) or merely TOUCHED as memory-tier hits (the
            # reload path) — docs/observability.md §Memory
            bucket_memory[b] = _tm_memory.bucket_figures(
                touched, _tm_memory.recorded_since(m0))
            telemetry.record_event(
                "serve_bucket_warm", model=self.name, version=self.version,
                bucket=b, seconds=round(time.monotonic() - t0, 4),
                flops=bucket_flops[b] or None,
                memory_bytes=_tm_memory.footprint_bytes(bucket_memory[b])
                or None)
        self.set_bucket_flops(bucket_flops)
        self.set_bucket_memory(bucket_memory)
        self.warm_seconds = time.monotonic() - t_all
        self.warmed = True
        return self.warm_seconds

    def pending(self):
        return self._batcher.pending()

    def drain(self, timeout=None):
        return self._batcher.drain(timeout)

    def abort_pending(self, error=None):
        """Force-complete every queued + in-flight request (bounded-drain
        escape hatch); returns how many were force-resolved."""
        return self._batcher.abort_pending(error)

    def close(self, drain=True, timeout=None):
        drained = self._batcher.close(drain=drain, timeout=timeout)
        if self._pool is not None:
            self._pool.close()
        return drained

    def describe(self):
        out = {
            "name": self.name,
            "version": self.version,
            "buckets": self.buckets,
            "max_batch": self.max_batch,
            "inputs": {k: {"shape": list(s),
                           "dtype": self.input_dtypes[k].name}
                       for k, s in self.example_shapes.items()},
            "warmed": self.warmed,
            "warm_seconds": self.warm_seconds,
            "pending": self.pending(),
            "loaded_at": self.loaded_at,
            "meta": self.meta,
            "compile": {"manifest": self.manifest_id,
                        "digests": list(self.compile_digests)},
            "memory": {"total_bytes": self.memory_bytes,
                       "copies": self.resident_copies,
                       "effective_bytes": self.effective_memory_bytes,
                       "per_bucket": {str(b): f for b, f in
                                      sorted(self.bucket_memory.items())}},
        }
        if self._pool is not None:
            out["pool"] = self._pool.describe()
        return out


# ---------------------------------------------------------------------------
# artifact loading — shared by ServedModel (in-process) and the replica
# worker (mxnet_tpu/serving/supervisor.py), which needs a bucketed runner
# WITHOUT a batcher attached
# ---------------------------------------------------------------------------

def build_runner(path, input_shapes=None, input_dtypes=None, ctx=None,
                 max_batch=None):
    """Load a deployment artifact into a bucketed ``runner(arrays, bucket,
    n) -> [numpy outputs]``. Returns ``(runner, buckets, example_shapes,
    input_dtypes, meta)``."""
    kind, parts = _resolve_artifact(path)
    if kind == "compiled":
        return _compiled_runner(parts)
    symbol_file, param_file = parts
    return _symbol_runner(symbol_file, param_file, input_shapes,
                          input_dtypes=input_dtypes, ctx=ctx,
                          max_batch=max_batch)


def _symbol_runner(symbol_file, param_file, input_shapes, input_dtypes=None,
                   ctx=None, max_batch=None):
    from ..predict import Predictor, _clone_with

    if not input_shapes:
        raise MXNetError(
            "symbol/params models need input_shapes (per-example, "
            "batch dim excluded), e.g. {'data': (8,)}")
    example_shapes = {k: tuple(v) for k, v in input_shapes.items()}
    if max_batch is None:
        max_batch = _env.get("MXTPU_SERVE_MAX_BATCH")
    buckets = power_of_two_buckets(max_batch)

    def shapes_at(b):
        return {k: (b,) + s for k, s in example_shapes.items()}

    # one Predictor per bucket, all sharing the prototype's device
    # weight buffers — N buckets cost one weight copy + N IO buffers
    proto = Predictor(symbol_file, param_file, ctx=ctx,
                      input_shapes=shapes_at(buckets[-1]),
                      input_dtypes=input_dtypes)
    by_bucket = {buckets[-1]: proto}
    for b in buckets[:-1]:
        by_bucket[b] = _clone_with(proto, shapes_at(b), shared=proto)
    num_outputs = proto.num_outputs

    def runner(arrays, bucket, n):
        pred = by_bucket[bucket]
        pred.forward(**arrays)
        return [pred.get_output(i).asnumpy() for i in range(num_outputs)]

    meta = {"artifact": "symbol", "symbol_file": str(symbol_file),
            "param_file": str(param_file)}
    return runner, buckets, example_shapes, input_dtypes, meta


def _compiled_runner(path):
    from ..predict import CompiledPredictor

    comp = CompiledPredictor.load(path)
    shapes = comp._input_shapes
    batches = {s[0] for s in shapes.values() if s}
    if len(batches) != 1:
        raise MXNetError(
            "compiled artifact has ambiguous batch dim across inputs: "
            "%s" % shapes)
    frozen = batches.pop()
    example_shapes = {k: tuple(s[1:]) for k, s in shapes.items()}
    dtypes = {k: comp._input_dtypes.get(k, _np.dtype(_np.float32))
              for k in shapes}

    def runner(arrays, bucket, n):
        comp.forward(**arrays)
        return [comp.get_output(i).asnumpy()
                for i in range(comp.num_outputs)]

    # geometry is frozen at build (TensorRT-engine semantics): the
    # frozen batch is the one and only padding bucket
    meta = {"artifact": "compiled", "path": str(path),
            "platforms": list(comp.platforms)}
    return runner, [frozen], example_shapes, dtypes, meta


# ---------------------------------------------------------------------------
# artifact resolution
# ---------------------------------------------------------------------------

_PARAMS_RE = re.compile(r"-(\d{4})\.params$")


def _resolve_artifact(path):
    """Classify ``path``: ('compiled', file) for .mxc/MXTPUAOT blobs,
    ('symbol', (symbol_json, params)) for an export prefix."""
    from ..predict import _MXC_MAGIC

    path = os.fspath(path)
    if os.path.isfile(path):
        with open(path, "rb") as f:
            magic = f.read(len(_MXC_MAGIC))
        if magic == _MXC_MAGIC:
            return "compiled", path
        if path.endswith("-symbol.json"):
            path = path[:-len("-symbol.json")]  # accept the json itself
        else:
            raise MXNetError(
                "%r is neither a compiled (.mxc) artifact nor a "
                "*-symbol.json / export prefix" % path)
    symbol_file = path + "-symbol.json"
    if not os.path.exists(symbol_file):
        raise MXNetError("no artifact at %r (expected %s or a compiled "
                         ".mxc file)" % (path, symbol_file))
    directory, base = os.path.split(path)
    candidates = []
    for fn in os.listdir(directory or "."):
        if fn.startswith(base + "-"):
            m = _PARAMS_RE.search(fn)
            if m and fn == "%s-%s.params" % (base, m.group(1)):
                candidates.append((int(m.group(1)), fn))
    if not candidates:
        raise MXNetError("no %s-NNNN.params next to %s" % (base, symbol_file))
    _, newest = max(candidates)
    return "symbol", (symbol_file, os.path.join(directory, newest))


# ---------------------------------------------------------------------------
# the repository
# ---------------------------------------------------------------------------

class ModelRepository:
    """name/version -> ServedModel, with hot load/unload.

    Loading warms before publishing (a half-warm model never serves);
    unloading marks the version draining, waits for queued + in-flight
    work, then drops it. `get` resolves ``version=None`` to the highest
    published version.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._models = {}   # name -> {version: ServedModel}
        self._loading = set()  # (name, version) reservations mid-load
        self._m_loaded = telemetry.gauge("mxtpu_serve_models_loaded")

    def load(self, name, path, version=None, input_shapes=None,
             input_dtypes=None, ctx=None, max_batch=None, max_delay_ms=None,
             queue_depth=None, warm=True, replicas=0, generate=False,
             generate_opts=None, min_replicas=None, max_replicas=None,
             pinned=False, **pool_kwargs):
        """Load an artifact as ``name/version`` (auto-increment when
        ``version`` is None) and publish it after warmup. The version is
        RESERVED for the whole load, so two concurrent loads of the same
        name never collide after both paid bind+warm; a failed load tears
        its half-built model (and batcher thread) down.

        ``replicas`` > 0 serves the model through a supervised replica
        pool (`ServedModel.pooled`; ``pool_kwargs`` — heartbeat_ms,
        backoff_ms, extra_env, spawn_timeout_s, teardown_grace — pass
        through) instead of in-process.

        ``generate=True`` loads ``path`` as a generation LM artifact
        (`generate.save_lm` prefix) served through the continuous-
        batching decode scheduler instead of the DynamicBatcher
        (docs/serving.md §Generation; ``generate_opts`` forwards KV/
        bucket geometry to `TransformerLMEngine`). The KV page pool is
        part of the model footprint, so the memory-budget admission in
        `add` 507s a load whose pages cannot fit.

        ``min_replicas`` / ``max_replicas`` bound the autoscaler for
        this model (None = the ``MXTPU_AUTOSCALE_{MIN,MAX}_REPLICAS``
        defaults); ``pinned=True`` exempts it from budget-pressure
        eviction. A load that would overflow the memory budget first
        tries to reclaim residency (shrink cold pools, evict
        idle-beyond-TTL unpinned models) before 507ing
        (docs/serving.md §Autoscaling)."""
        with self._lock:
            have = self._models.get(name, {})
            reserved = [v for (n, v) in self._loading if n == name]
            if version is None:
                version = max(list(have) + reserved, default=0) + 1
            version = int(version)
            if version in have or (name, version) in self._loading:
                raise MXNetError("model %s/%d is already loaded"
                                 % (name, version))
            self._loading.add((name, version))
        try:
            if generate:
                from .generate import ServedLM

                # predict-only knobs must not be silently ignored: a
                # caller passing them believes they took effect
                if input_shapes or input_dtypes or ctx is not None \
                        or max_delay_ms is not None or not warm:
                    raise MXNetError(
                        "generate=True loads take geometry through "
                        "generate_opts (and always warm); input_shapes/"
                        "input_dtypes/ctx/max_delay_ms/warm=False do "
                        "not apply")
                opts = dict(generate_opts or {})
                if max_batch is not None:
                    opts.setdefault("max_batch", max_batch)
                model = ServedLM.load(
                    name, version, path, replicas=int(replicas or 0),
                    queue_depth=queue_depth, pool_kwargs=pool_kwargs,
                    **opts)
                model.min_replicas = min_replicas
                model.max_replicas = max_replicas
                model.pinned = bool(pinned)
                try:
                    return self._add_with_reclaim(model)
                except Exception:
                    model.close(drain=False, timeout=0)
                    raise
            if replicas and replicas > 0:
                model = ServedModel.pooled(
                    name, version, path, replicas,
                    input_shapes=input_shapes, input_dtypes=input_dtypes,
                    max_batch=max_batch, max_delay_ms=max_delay_ms,
                    queue_depth=queue_depth, **pool_kwargs)
            else:
                # warmup-manifest prefetch BEFORE binding: with the
                # persistent tier armed and a previous publish of this
                # artifact, every executable deserializes instead of
                # compiling (cold start, warm cache — docs/compile_cache.md)
                _compile.prefetch(_compile.model_manifest_id(
                    path, _resolved_max_batch(max_batch), input_shapes))
                cursor = _compile.mark()
                model = ServedModel.from_path(
                    name, version, path, input_shapes=input_shapes,
                    input_dtypes=input_dtypes, ctx=ctx, max_batch=max_batch,
                    max_delay_ms=max_delay_ms, queue_depth=queue_depth)
            try:
                if warm:
                    model.warm()
                if model.pool is None:
                    model.record_compile_entries(_compile.keys_since(cursor))
                    # drop staged prefetch entries the warm never claimed
                    # (stale manifest rows must not stay pinned)
                    _compile.clear_staged()
                model.min_replicas = min_replicas
                model.max_replicas = max_replicas
                model.pinned = bool(pinned)
                # memory-budget admission happens inside add(), under the
                # repository lock; a short load first reclaims cold
                # residency (shrink/evict) before the 507 stands
                return self._add_with_reclaim(model)
            except Exception:
                model.close(drain=False, timeout=0)  # no thread/weight leak
                raise
        finally:
            with self._lock:
                self._loading.discard((name, version))

    def _check_memory_budget_locked(self, model):
        """The ``MXTPU_SERVE_MEMORY_BUDGET`` admission check, evaluated
        UNDER the repository lock so two concurrent loads cannot both
        pass against the same headroom: already-published models'
        footprints plus this one must fit the budget. Returns the
        over-budget message for warn-only mode, raises `MemoryBudgetError`
        (HTTP 507) otherwise; unknown footprints (no figures recorded —
        accounting off, or a backend without memory_analysis) never
        block a load.

        The rejection carries a full footprint breakdown — requested
        bytes, every resident model's ``effective_memory_bytes``, the
        budget, headroom and shortfall — in the message AND a
        machine-readable ``details`` dict the HTTP 507 body ships, so an
        operator can see WHAT to evict, not just that nothing fit."""
        limit, warn_only = _tm_memory.serve_memory_budget()
        needed = model.effective_memory_bytes  # N replicas = N copies
        if not limit or not needed:
            return None
        resident = 0
        resident_models = []
        for vs in self._models.values():
            for m in vs.values():
                eff = m.effective_memory_bytes or 0
                resident += eff
                resident_models.append({
                    "model": "%s/%d" % (m.name, m.version),
                    "effective_bytes": eff or None,
                    "copies": m.resident_copies,
                    "pinned": bool(getattr(m, "pinned", False)),
                })
        total = resident + needed
        if total <= limit:
            return None
        telemetry.record_event(
            "serve_memory_budget", model=model.name, version=model.version,
            footprint_bytes=needed, copies=model.resident_copies,
            resident_bytes=resident, budget_bytes=limit,
            action="warn" if warn_only else "reject")
        details = {
            "requested_bytes": needed,
            "per_copy_bytes": model.memory_bytes,
            "copies": model.resident_copies,
            "budget_bytes": limit,
            "resident_bytes": resident,
            "headroom_bytes": max(0, limit - resident),
            "shortfall_bytes": total - limit,
            "resident_models": resident_models,
        }
        msg = ("loading %s/%d needs %d bytes (%d bytes/copy x %d "
               "replica(s)); budget MXTPU_SERVE_MEMORY_BUDGET=%d has %d "
               "bytes headroom (%d resident), short %d bytes — resident: "
               "%s"
               % (model.name, model.version, needed, model.memory_bytes,
                  model.resident_copies, limit, details["headroom_bytes"],
                  resident, details["shortfall_bytes"],
                  ", ".join("%s=%s bytes (x%d%s)"
                            % (r["model"], r["effective_bytes"],
                               r["copies"],
                               ", pinned" if r["pinned"] else "")
                            for r in resident_models) or "nothing"))
        if not warn_only:
            raise MemoryBudgetError(msg, details=details)
        return msg

    def reclaim_memory(self, needed_bytes, exclude=None, reason="load",
                       now=None):
        """Budget-pressure bin-packing (docs/serving.md §Autoscaling):
        try to free at least ``needed_bytes`` of budgeted residency so a
        new load (or an autoscaler scale-up) fits, instead of answering
        a flat 507 while cold models pin HBM. Two phases, coldest first
        (LRU by the windowed request-rate staleness of each model's
        request counters):

          1. **shrink** idle pooled models toward their ``min_replicas``
             (`ReplicaPool.remove_replica(drain=True)` — zero request
             loss, each removal frees one ``memory_bytes`` copy);
          2. **evict** whole models that are unpinned and idle beyond
             ``MXTPU_AUTOSCALE_EVICT_TTL_S`` (a drained `unload`; the
             model's warmup manifest persists, so a future reload warms
             in seconds).

        Emits ``autoscale_down`` / ``autoscale_evict`` decisions. Never
        touches ``exclude`` (the model being admitted) and never runs
        under the repository lock — drains block. Returns bytes freed."""
        from . import autoscaler as _asc

        needed = int(needed_bytes or 0)
        if needed <= 0:
            return 0
        if now is None:
            now = time.time()
        idle_s = _env.get("MXTPU_AUTOSCALE_IDLE_S")
        ttl_s = _env.get("MXTPU_AUTOSCALE_EVICT_TTL_S")
        freed = 0
        candidates = [m for m in self.models()
                      if "%s/%d" % (m.name, m.version) != exclude]
        # coldest first: the model whose request counters have been
        # still the longest gives up residency first
        candidates.sort(key=lambda m: -_asc.idle_age_s(m, now))
        for m in candidates:
            if freed >= needed:
                break
            pool = getattr(m, "pool", None)
            per_copy = getattr(m, "memory_bytes", None)
            if pool is None or not per_copy:
                continue
            if _asc.idle_age_s(m, now) < idle_s:
                continue  # hot pools keep their replicas
            label = "%s/%d" % (m.name, m.version)
            floor = _asc.min_replicas(m)
            while pool.size > floor and freed < needed:
                try:
                    # floor re-checked atomically inside remove_replica:
                    # a concurrent autoscaler drain racing this loop
                    # must not shrink below the model's min_replicas
                    # (and the loser's MXNetError must not escape as a
                    # 400 where the caller expects the enriched 507)
                    replica = pool.remove_replica(drain=True, floor=floor)
                except MXNetError:
                    break  # lost the race: this pool is done shrinking
                freed += per_copy
                _asc.record_decision(
                    "down", label, reason="budget_pressure",
                    trigger=reason, replica=replica, size=pool.size,
                    freed_bytes=per_copy)
        for m in candidates:
            if freed >= needed:
                break
            if getattr(m, "pinned", False):
                continue
            eff = getattr(m, "effective_memory_bytes", None)
            if not eff:
                continue
            age = _asc.idle_age_s(m, now)
            if age < ttl_s:
                continue
            label = "%s/%d" % (m.name, m.version)
            try:
                self.unload(m.name, m.version)
            except ModelUnavailableError:
                continue  # a concurrent unload beat us to it
            freed += eff
            _asc.record_decision(
                "evict", label, reason=reason, idle_s=round(age, 3),
                freed_bytes=eff)
        return freed

    def _add_with_reclaim(self, model):
        """Publish, and on a budget rejection try to reclaim the
        shortfall (shrink cold pools / evict idle models) ONCE before
        retrying — the retry's admission check runs fresh under the
        lock, so concurrent loads stay consistent. A load that still
        cannot fit raises the enriched 507 and records an
        ``autoscale_blocked`` decision."""
        from . import autoscaler as _asc

        label = "%s/%d" % (model.name, model.version)
        try:
            return self.add(model)
        except MemoryBudgetError as e:
            details = getattr(e, "details", None) or {}
            shortfall = details.get("shortfall_bytes") \
                or model.effective_memory_bytes or 0
            freed = self.reclaim_memory(shortfall, exclude=label,
                                        reason="load")
            if freed > 0:
                try:
                    return self.add(model)
                except MemoryBudgetError as e2:
                    _asc.record_decision(
                        "blocked", label, reason="load_budget",
                        freed_bytes=freed,
                        shortfall_bytes=(getattr(e2, "details", None)
                                         or {}).get("shortfall_bytes"))
                    raise
            _asc.record_decision(
                "blocked", label, reason="load_budget", freed_bytes=0,
                shortfall_bytes=shortfall)
            raise

    def add(self, model):
        """Publish an already-built ServedModel (tests inject stubs here).
        The memory-budget admission check runs here, under the lock —
        a rejected model raises `MemoryBudgetError` and is never
        published (`load` tears it down)."""
        with self._lock:
            if model.version in self._models.get(model.name, {}):
                raise MXNetError("model %s/%d is already loaded"
                                 % (model.name, model.version))
            # raises BEFORE any mutation: a rejected name never appears
            # half-registered in names()/describe()
            over_budget = self._check_memory_budget_locked(model)
            self._models.setdefault(model.name, {})[model.version] = model
            self._m_loaded.set(sum(len(v) for v in self._models.values()))
        if over_budget:
            import logging

            logging.getLogger("mxnet_tpu.serving").warning(
                "%s (warn-only budget: publishing anyway)", over_budget)
        telemetry.record_event("serve_model_load", model=model.name,
                               version=model.version)
        # chaos hook: a `load_surge@` MXTPU_FAULT_INJECT entry arms a
        # synthetic open-loop burst against this model's admission queue
        # (docs/fault_tolerance.md §5 — the autoscaler test vector)
        _resilience.maybe_inject_load_surge(model)
        return model

    def get(self, name, version=None):
        with self._lock:
            versions = self._models.get(name)
            if not versions:
                raise ModelUnavailableError("no model named %r" % (name,))
            if version is None:
                return versions[max(versions)]
            model = versions.get(int(version))
            if model is None:
                raise ModelUnavailableError(
                    "model %r has no version %s (have %s)"
                    % (name, version, sorted(versions)))
            return model

    def unload(self, name, version=None, timeout=None):
        """Drain and drop ``name/version`` (newest when None). Returns True
        when the drain completed within ``timeout``."""
        model = self.get(name, version)
        with self._lock:
            versions = self._models.get(name, {})
            versions.pop(model.version, None)
            if not versions:
                self._models.pop(name, None)
            self._m_loaded.set(sum(len(v) for v in self._models.values()))
        if timeout is None:
            timeout = drain_timeout_s()
        drained = model.close(drain=True, timeout=timeout)
        telemetry.record_event("serve_model_unload", model=model.name,
                               version=model.version, drained=drained)
        return drained

    def names(self):
        with self._lock:
            return sorted(self._models)

    def models(self):
        """Flat list of every published ServedModel."""
        with self._lock:
            return [m for vs in self._models.values()
                    for _, m in sorted(vs.items())]

    def describe(self):
        return {"models": [m.describe() for m in self.models()]}

    def pending(self):
        return sum(m.pending() for m in self.models())

    def drain_all(self, timeout=None):
        """Drain every model (graceful-shutdown path). Returns True when
        everything finished in time."""
        if timeout is None:
            timeout = drain_timeout_s()
        deadline = time.monotonic() + timeout
        ok = True
        for m in self.models():
            ok = m.drain(max(0.0, deadline - time.monotonic())) and ok
        return ok

    def abort_pending(self):
        """Force-complete every model's stranded requests (the bounded
        SIGTERM drain's escape hatch). Returns the total force-resolved."""
        return sum(m.abort_pending() for m in self.models())
