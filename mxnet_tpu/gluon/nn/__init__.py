"""Gluon neural-net layers (reference: python/mxnet/gluon/nn)."""
from .basic_layers import *  # noqa: F401,F403
from .conv_layers import *  # noqa: F401,F403
from .basic_layers import Sequential, HybridSequential  # noqa: F401
from .conv_layers import layout_scope, in_channels_last_scope  # noqa: F401
from ..block import Block, HybridBlock, SymbolBlock  # noqa: F401
