"""Convolution / pooling layers.

Reference: python/mxnet/gluon/nn/conv_layers.py (Conv1D/2D/3D,
Conv1DTranspose/2D/3D, MaxPool/AvgPool 1-3D, GlobalMax/AvgPool, ReflectionPad2D).
All lower onto ops/nn.py Convolution/Pooling -> lax conv/reduce_window on MXU."""
from __future__ import annotations

from ..block import HybridBlock

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose", "Conv2DTranspose",
           "Conv3DTranspose", "MaxPool1D", "MaxPool2D", "MaxPool3D",
           "AvgPool1D", "AvgPool2D", "AvgPool3D", "GlobalMaxPool1D",
           "GlobalMaxPool2D", "GlobalMaxPool3D", "GlobalAvgPool1D",
           "GlobalAvgPool2D", "GlobalAvgPool3D", "ReflectionPad2D"]


def _pair(x, n):
    if isinstance(x, (list, tuple)):
        return tuple(x)
    return (x,) * n


# ---------------------------------------------------------------------------
# construction-time default-layout scope (TPU extension)
#
# Channels-last is the MXU-preferred layout, but the reference zoo/API
# defaults are channels-first. Instead of threading a layout kwarg through
# every model builder, `with layout_scope(): net = vision.resnet50_v1()`
# flips the *default* layout of conv/pool layers (and BatchNorm's default
# axis, see basic_layers) while they are constructed. An explicit
# layout=/axis= argument always wins; layers built outside the scope keep
# reference (channels-first) defaults.
# ---------------------------------------------------------------------------

import threading

_LAYOUT_SCOPE = threading.local()  # per-thread, like Context._stack

_CHANNELS_LAST = {1: "NWC", 2: "NHWC", 3: "NDHWC"}


class layout_scope:
    def __init__(self, channels_last=True):
        self._want = channels_last

    def __enter__(self):
        self._prev = getattr(_LAYOUT_SCOPE, "channels_last", False)
        _LAYOUT_SCOPE.channels_last = self._want
        return self

    def __exit__(self, *exc):
        _LAYOUT_SCOPE.channels_last = self._prev
        return False


def in_channels_last_scope():
    return getattr(_LAYOUT_SCOPE, "channels_last", False)


def _default_layout(nsp, explicit, channels_first):
    if explicit is not None:
        return explicit
    if in_channels_last_scope():
        return _CHANNELS_LAST[nsp]
    return channels_first


class _Conv(HybridBlock):
    def __init__(self, channels, kernel_size, strides, padding, dilation, groups,
                 layout, in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros", op_name="Convolution",
                 adj=None, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self._channels = channels
            self._in_channels = in_channels
            ndim = len(kernel_size)
            self._layout = layout
            # channels-last layouts (NWC/NHWC/NDHWC): weight carries the
            # reference's ConvertLayout(OI*k -> layout) shape — (O, *k, I)
            # for conv, (I, *k, O/g) for deconv (convolution.cc:158)
            from ...ops.nn import _channels_last

            ch_last = _channels_last(layout)
            self._ch_axis = len(layout) - 1 if ch_last else 1
            self._kwargs = {
                "kernel": kernel_size, "stride": strides, "dilate": dilation,
                "pad": padding, "num_filter": channels, "num_group": groups,
                "no_bias": not use_bias, "layout": layout}
            if adj is not None:
                self._kwargs["adj"] = adj
            self._op_name = op_name
            in_cg = in_channels // groups if in_channels else 0
            if op_name == "Convolution":
                wshape = (channels,) + tuple(kernel_size) + (in_cg,) if ch_last \
                    else (channels, in_cg) + tuple(kernel_size)
            elif ch_last:  # Deconvolution channels-last
                wshape = (in_channels,) + tuple(kernel_size) + (channels // groups,)
            else:  # Deconvolution: (in_c, out_c/g, *k)
                wshape = (in_channels, channels // groups) + tuple(kernel_size)
            self.weight = self.params.get("weight", shape=wshape,
                                          init=weight_initializer,
                                          allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get("bias", shape=(channels,),
                                            init=bias_initializer,
                                            allow_deferred_init=True)
            else:
                self.bias = None
            from .basic_layers import Activation

            self.act = Activation(activation, prefix=activation + "_") \
                if activation is not None else None

    def _shape_hook(self, x):
        c = x.shape[self._ch_axis]
        w = self.weight
        if w.shape and (0 in w.shape):
            g = self._kwargs["num_group"]
            k = tuple(self._kwargs["kernel"])
            ch_last = self._ch_axis != 1
            if self._op_name == "Convolution":
                w.shape = (self._channels,) + k + (c // g,) if ch_last \
                    else (self._channels, c // g) + k
            elif ch_last:
                w.shape = (c,) + k + (self._channels // g,)
            else:
                w.shape = (c, self._channels // g) + k

    def hybrid_forward(self, F, x, weight, bias=None):
        op = getattr(F, self._op_name)
        out = op(x, weight, bias, **self._kwargs)
        if self.act is not None:
            out = self.act(out)
        return out


class Conv1D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0, dilation=1,
                 groups=1, layout=None, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros", in_channels=0,
                 **kwargs):
        super().__init__(channels, _pair(kernel_size, 1), _pair(strides, 1),
                         _pair(padding, 1), _pair(dilation, 1), groups,
                         _default_layout(1, layout, "NCW"),
                         in_channels, activation, use_bias, weight_initializer,
                         bias_initializer, **kwargs)


class Conv2D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 dilation=(1, 1), groups=1, layout=None, activation=None,
                 use_bias=True, weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super().__init__(channels, _pair(kernel_size, 2), _pair(strides, 2),
                         _pair(padding, 2), _pair(dilation, 2), groups,
                         _default_layout(2, layout, "NCHW"),
                         in_channels, activation, use_bias, weight_initializer,
                         bias_initializer, **kwargs)


class Conv3D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1), padding=(0, 0, 0),
                 dilation=(1, 1, 1), groups=1, layout=None, activation=None,
                 use_bias=True, weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super().__init__(channels, _pair(kernel_size, 3), _pair(strides, 3),
                         _pair(padding, 3), _pair(dilation, 3), groups,
                         _default_layout(3, layout, "NCDHW"),
                         in_channels, activation, use_bias, weight_initializer,
                         bias_initializer, **kwargs)


class Conv1DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0, output_padding=0,
                 dilation=1, groups=1, layout=None, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros", in_channels=0,
                 **kwargs):
        super().__init__(channels, _pair(kernel_size, 1), _pair(strides, 1),
                         _pair(padding, 1), _pair(dilation, 1), groups,
                         _default_layout(1, layout, "NCW"),
                         in_channels, activation, use_bias, weight_initializer,
                         bias_initializer, op_name="Deconvolution",
                         adj=_pair(output_padding, 1), **kwargs)


class Conv2DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 output_padding=(0, 0), dilation=(1, 1), groups=1, layout=None,
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, _pair(kernel_size, 2), _pair(strides, 2),
                         _pair(padding, 2), _pair(dilation, 2), groups,
                         _default_layout(2, layout, "NCHW"),
                         in_channels, activation, use_bias, weight_initializer,
                         bias_initializer, op_name="Deconvolution",
                         adj=_pair(output_padding, 2), **kwargs)


class Conv3DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1), padding=(0, 0, 0),
                 output_padding=(0, 0, 0), dilation=(1, 1, 1), groups=1,
                 layout=None, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros", in_channels=0,
                 **kwargs):
        super().__init__(channels, _pair(kernel_size, 3), _pair(strides, 3),
                         _pair(padding, 3), _pair(dilation, 3), groups,
                         _default_layout(3, layout, "NCDHW"),
                         in_channels, activation, use_bias, weight_initializer,
                         bias_initializer, op_name="Deconvolution",
                         adj=_pair(output_padding, 3), **kwargs)


class _Pooling(HybridBlock):
    def __init__(self, pool_size, strides, padding, ceil_mode, global_pool,
                 pool_type, count_include_pad=None, layout=None, **kwargs):
        super().__init__(**kwargs)
        if strides is None:
            strides = pool_size
        self._kwargs = {
            "kernel": pool_size, "stride": strides, "pad": padding,
            "global_pool": global_pool, "pool_type": pool_type,
            "pooling_convention": "full" if ceil_mode else "valid",
            "layout": layout}
        if count_include_pad is not None:
            self._kwargs["count_include_pad"] = count_include_pad

    def hybrid_forward(self, F, x):
        return F.Pooling(x, **self._kwargs)


class MaxPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout=None,
                 ceil_mode=False, **kwargs):
        super().__init__(_pair(pool_size, 1), _pair(strides, 1) if strides is not None else None,
                         _pair(padding, 1), ceil_mode, False, "max",
                         layout=_default_layout(1, layout, "NCW"), **kwargs)


class MaxPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0, layout=None,
                 ceil_mode=False, **kwargs):
        super().__init__(_pair(pool_size, 2), _pair(strides, 2) if strides is not None else None,
                         _pair(padding, 2), ceil_mode, False, "max",
                         layout=_default_layout(2, layout, "NCHW"), **kwargs)


class MaxPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0, layout=None,
                 ceil_mode=False, **kwargs):
        super().__init__(_pair(pool_size, 3), _pair(strides, 3) if strides is not None else None,
                         _pair(padding, 3), ceil_mode, False, "max",
                         layout=_default_layout(3, layout, "NCDHW"), **kwargs)


class AvgPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout=None,
                 ceil_mode=False, count_include_pad=True, **kwargs):
        super().__init__(_pair(pool_size, 1), _pair(strides, 1) if strides is not None else None,
                         _pair(padding, 1), ceil_mode, False, "avg",
                         count_include_pad,
                         layout=_default_layout(1, layout, "NCW"), **kwargs)


class AvgPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0, layout=None,
                 ceil_mode=False, count_include_pad=True, **kwargs):
        super().__init__(_pair(pool_size, 2), _pair(strides, 2) if strides is not None else None,
                         _pair(padding, 2), ceil_mode, False, "avg",
                         count_include_pad,
                         layout=_default_layout(2, layout, "NCHW"), **kwargs)


class AvgPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0, layout=None,
                 ceil_mode=False, count_include_pad=True, **kwargs):
        super().__init__(_pair(pool_size, 3), _pair(strides, 3) if strides is not None else None,
                         _pair(padding, 3), ceil_mode, False, "avg",
                         count_include_pad,
                         layout=_default_layout(3, layout, "NCDHW"), **kwargs)


class GlobalMaxPool1D(_Pooling):
    def __init__(self, layout=None, **kwargs):
        super().__init__((1,), None, (0,), True, True, "max", layout=_default_layout(1, layout, "NCW"), **kwargs)


class GlobalMaxPool2D(_Pooling):
    def __init__(self, layout=None, **kwargs):
        super().__init__((1, 1), None, (0, 0), True, True, "max", layout=_default_layout(2, layout, "NCHW"), **kwargs)


class GlobalMaxPool3D(_Pooling):
    def __init__(self, layout=None, **kwargs):
        super().__init__((1, 1, 1), None, (0, 0, 0), True, True, "max", layout=_default_layout(3, layout, "NCDHW"), **kwargs)


class GlobalAvgPool1D(_Pooling):
    def __init__(self, layout=None, **kwargs):
        super().__init__((1,), None, (0,), True, True, "avg", layout=_default_layout(1, layout, "NCW"), **kwargs)


class GlobalAvgPool2D(_Pooling):
    def __init__(self, layout=None, **kwargs):
        super().__init__((1, 1), None, (0, 0), True, True, "avg", layout=_default_layout(2, layout, "NCHW"), **kwargs)


class GlobalAvgPool3D(_Pooling):
    def __init__(self, layout=None, **kwargs):
        super().__init__((1, 1, 1), None, (0, 0, 0), True, True, "avg", layout=_default_layout(3, layout, "NCDHW"), **kwargs)


class ReflectionPad2D(HybridBlock):
    def __init__(self, padding=0, **kwargs):
        super().__init__(**kwargs)
        if isinstance(padding, int):
            padding = (0, 0, 0, 0, padding, padding, padding, padding)
        self._padding = padding

    def hybrid_forward(self, F, x):
        return F.Pad(x, mode="reflect", pad_width=self._padding)
