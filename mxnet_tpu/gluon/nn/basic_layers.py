"""Basic neural network layers.

Reference: python/mxnet/gluon/nn/basic_layers.py (Dense, Dropout, BatchNorm,
InstanceNorm, LayerNorm, Embedding, Flatten, Lambda, HybridLambda,
Sequential, HybridSequential, activations in activations.py)."""
from __future__ import annotations

import numpy as _np

from ... import ndarray as nd
from ...base import MXNetError
from ..block import Block, HybridBlock

__all__ = ["Sequential", "HybridSequential", "Dense", "Dropout", "BatchNorm",
           "InstanceNorm", "LayerNorm", "Embedding", "Flatten", "Lambda",
           "HybridLambda", "Activation", "LeakyReLU", "PReLU", "ELU", "SELU",
           "Swish", "GELU"]


class Sequential(Block):
    """Sequential container (reference: basic_layers.py:29)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers)
            return net
        return layers

    def __len__(self):
        return len(self._children)


class HybridSequential(HybridBlock):
    """Hybridizable sequential container (reference: basic_layers.py:99)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def _eager_forward(self, x):
        for block in self._children.values():
            x = block(x)
        return x

    def hybrid_forward(self, F, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers)
            return net
        return layers

    def __len__(self):
        return len(self._children)


class Dense(HybridBlock):
    """Fully-connected layer (reference: basic_layers.py:161). Lowers to
    FullyConnected -> one MXU matmul."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None, bias_initializer="zeros",
                 in_units=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self._units = units
            self._in_units = in_units
            self._flatten = flatten
            self.weight = self.params.get("weight", shape=(units, in_units),
                                          init=weight_initializer, dtype=dtype,
                                          allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get("bias", shape=(units,),
                                            init=bias_initializer, dtype=dtype,
                                            allow_deferred_init=True)
            else:
                self.bias = None
            self.act = Activation(activation, prefix=activation + "_") \
                if activation is not None else None

    def _shape_hook(self, x):
        if self.weight.shape and self.weight.shape[1] == 0:
            in_units = int(_np.prod(x.shape[1:])) if self._flatten else x.shape[-1]
            self.weight.shape = (self._units, in_units)

    def hybrid_forward(self, F, x, weight, bias=None):
        out = F.FullyConnected(x, weight, bias, num_hidden=self._units,
                               flatten=self._flatten, no_bias=bias is None)
        if self.act is not None:
            out = self.act(out)
        return out


class Activation(HybridBlock):
    def __init__(self, activation, prefix=None, params=None):
        self._act_type = activation
        super().__init__(prefix=prefix, params=params)

    def _alias(self):
        return self._act_type

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act_type)


class LeakyReLU(HybridBlock):
    def __init__(self, alpha, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="leaky", slope=self._alpha)


class PReLU(HybridBlock):
    def __init__(self, alpha_initializer=None, **kwargs):
        super().__init__(**kwargs)
        from ... import initializer as init_mod

        with self.name_scope():
            self.alpha = self.params.get("alpha", shape=(1,),
                                         init=alpha_initializer or init_mod.Constant(0.25))

    def hybrid_forward(self, F, x, alpha):
        return F.LeakyReLU(x, gamma=alpha, act_type="prelu")


class ELU(HybridBlock):
    def __init__(self, alpha=1.0, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="elu", slope=self._alpha)


class SELU(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="selu")


class GELU(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="gelu")


class Swish(HybridBlock):
    def __init__(self, beta=1.0, **kwargs):
        super().__init__(**kwargs)
        self._beta = beta

    def hybrid_forward(self, F, x):
        return x * F.sigmoid(self._beta * x)


class Dropout(HybridBlock):
    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        return F.Dropout(x, p=self._rate, axes=self._axes)


class Flatten(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.Flatten(x)


class BatchNorm(HybridBlock):
    """Batch normalization (reference: basic_layers.py:310). Moving stats are
    aux parameters updated functionally (see ops/nn.py batch_norm).

    TPU extension: `act_type="relu"` folds the following activation into the
    op (BatchNormRelu), and calling the layer with a second input —
    ``bn(x, residual)`` — folds a residual add in front of the activation
    (BatchNormAddRelu). Parameter names/shapes are identical to the plain
    layer, so fused and unfused models share checkpoints; under
    MXTPU_PALLAS_CONV_EPILOGUE the fused op lowers to the Pallas
    conv-epilogue kernels (ops/pallas_kernels.conv_epilogue)."""

    def __init__(self, axis=None, momentum=0.9, epsilon=1e-5, center=True, scale=True,
                 use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones", running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0,
                 act_type=None, **kwargs):
        super().__init__(**kwargs)
        self._act_type = act_type
        if axis is None:
            # reference default is the channels-first axis (1); inside a
            # channels-last layout_scope the default follows the layout
            from .conv_layers import in_channels_last_scope

            axis = -1 if in_channels_last_scope() else 1
        self._kwargs = {"axis": axis, "eps": epsilon, "momentum": momentum,
                        "fix_gamma": not scale, "use_global_stats": use_global_stats}
        self._axis = axis
        self._in_channels = in_channels
        with self.name_scope():
            self.gamma = self.params.get("gamma",
                                         grad_req="write" if scale else "null",
                                         shape=(in_channels,), init=gamma_initializer,
                                         allow_deferred_init=True,
                                         differentiable=scale)
            self.beta = self.params.get("beta",
                                        grad_req="write" if center else "null",
                                        shape=(in_channels,), init=beta_initializer,
                                        allow_deferred_init=True,
                                        differentiable=center)
            self.running_mean = self.params.get("running_mean", grad_req="null",
                                                shape=(in_channels,),
                                                init=running_mean_initializer,
                                                allow_deferred_init=True,
                                                differentiable=False)
            self.running_var = self.params.get("running_var", grad_req="null",
                                               shape=(in_channels,),
                                               init=running_variance_initializer,
                                               allow_deferred_init=True,
                                               differentiable=False)

    def _shape_hook(self, x, addend=None):
        if self._in_channels == 0:
            c = x.shape[self._axis]
            for p in (self.gamma, self.beta, self.running_mean, self.running_var):
                if p.shape and p.shape[0] == 0:
                    p.shape = (c,)

    def cast(self, dtype):
        if _np.dtype(dtype) == _np.float16:
            dtype = "float32"  # BN stats stay fp32 (reference does the same)
        super().cast(dtype)

    def hybrid_forward(self, F, x, addend=None, gamma=None, beta=None,
                       running_mean=None, running_var=None):
        if addend is not None:
            if self._act_type is None:
                raise ValueError(
                    "BatchNorm: a residual input requires act_type "
                    "(the fused BatchNormAddRelu path)")
            return F.BatchNormAddRelu(x, addend, gamma, beta, running_mean,
                                      running_var, act_type=self._act_type,
                                      **self._kwargs)
        if self._act_type is not None:
            return F.BatchNormRelu(x, gamma, beta, running_mean, running_var,
                                   act_type=self._act_type, **self._kwargs)
        return F.BatchNorm(x, gamma, beta, running_mean, running_var, **self._kwargs)


class InstanceNorm(HybridBlock):
    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._epsilon = epsilon
        self._axis = axis
        with self.name_scope():
            self.gamma = self.params.get("gamma", grad_req="write" if scale else "null",
                                         shape=(in_channels,), init=gamma_initializer,
                                         allow_deferred_init=True)
            self.beta = self.params.get("beta", grad_req="write" if center else "null",
                                        shape=(in_channels,), init=beta_initializer,
                                        allow_deferred_init=True)

    def _shape_hook(self, x):
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta):
            if p.shape and p.shape[0] == 0:
                p.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.InstanceNorm(x, gamma, beta, eps=self._epsilon)


class LayerNorm(HybridBlock):
    """Layer normalization (reference: basic_layers.py:480) — the BERT/
    transformer normalizer; fused by XLA into neighbouring ops."""

    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get("gamma", grad_req="write" if scale else "null",
                                         shape=(in_channels,), init=gamma_initializer,
                                         allow_deferred_init=True)
            self.beta = self.params.get("beta", grad_req="write" if center else "null",
                                        shape=(in_channels,), init=beta_initializer,
                                        allow_deferred_init=True)

    def _shape_hook(self, x):
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta):
            if p.shape and p.shape[0] == 0:
                p.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, axis=self._axis, eps=self._epsilon)


class Embedding(HybridBlock):
    """Embedding lookup (reference: basic_layers.py:550). Gather on TPU.
    sparse_grad=True marks the weight's grad_stype row_sparse: Trainer casts
    the tape gradient to row_sparse and sparse-capable optimizers take the
    lazy row-update path (untouched rows skip wd/momentum — same semantics
    as the reference's sparse kernels)."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super().__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        with self.name_scope():
            self.weight = self.params.get("weight", shape=(input_dim, output_dim),
                                          init=weight_initializer, dtype=dtype,
                                          grad_stype="row_sparse" if sparse_grad
                                          else "default")

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, input_dim=self._input_dim,
                           output_dim=self._output_dim)


class Lambda(Block):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            assert hasattr(nd, function), "function %s not found in nd" % function
            self._func_impl = getattr(nd, function)
        else:
            self._func_impl = function

    def forward(self, *args):
        return self._func_impl(*args)


class HybridLambda(HybridBlock):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            self._func_name = function
            self._func = None
        else:
            self._func = function
            self._func_name = getattr(function, "__name__", "lambda")

    def hybrid_forward(self, F, *args):
        fn = self._func if self._func is not None else getattr(F, self._func_name)
        return fn(*args)
