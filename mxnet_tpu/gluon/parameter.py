"""Gluon Parameter / ParameterDict.

Reference: python/mxnet/gluon/parameter.py (Parameter :43 — deferred init,
per-ctx data/grad copies, grad_req; ParameterDict :508). TPU-native notes:
per-ctx copies remain for API parity (the local-DP path); the distributed
path (mxnet_tpu.parallel) instead shards ONE logical array over a Mesh with
NamedSharding — per-device copies become XLA-managed replicas.
"""
from __future__ import annotations

from collections import OrderedDict

import numpy as _np

from ..base import MXNetError, np_dtype
from ..context import Context, cpu, current_context
from .. import ndarray as nd
from ..ndarray import NDArray
from .. import initializer


class DeferredInitializationError(MXNetError):
    """Parameter accessed before shape known (reference: parameter.py:38)."""


class Parameter:
    """A trainable parameter (reference: parameter.py:43)."""

    def __init__(self, name, grad_req="write", shape=None, dtype="float32",
                 lr_mult=1.0, wd_mult=1.0, init=None, allow_deferred_init=False,
                 differentiable=True, stype="default", grad_stype="default"):
        self.name = name
        self._grad_req = grad_req if differentiable else "null"
        if isinstance(shape, int):
            shape = (shape,)
        self._shape = tuple(shape) if shape is not None else None
        self.dtype = np_dtype(dtype)
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self._differentiable = differentiable
        self._stype = stype
        self._grad_stype = grad_stype
        self._data = None   # OrderedDict[Context, NDArray]
        self._grad = None
        self._deferred_init = ()
        self._ctx_list = None

    def __repr__(self):
        return "Parameter %s (shape=%s, dtype=%s)" % (self.name, self._shape, self.dtype)

    # -- shape (mergeable for deferred init) ------------------------------
    @property
    def shape(self):
        return self._shape

    @shape.setter
    def shape(self, new_shape):
        if self._shape is None:
            self._shape = tuple(new_shape)
            return
        # 0 is the unknown-dim wildcard on either side (reference:
        # parameter.py shape setter — weight sharing with deferred init
        # passes 0 for dims the sharing layer hasn't inferred yet)
        assert len(self._shape) == len(new_shape) and all(
            s == 0 or n == 0 or s == n
            for s, n in zip(self._shape, new_shape)), \
            "cannot update shape %s -> %s for %s" % (self._shape, new_shape, self.name)
        self._shape = tuple(s if n == 0 else n
                            for s, n in zip(self._shape, new_shape))

    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        assert req in ("write", "add", "null")
        if not self._differentiable:
            req = "null"
        if self._grad_req == req:
            return
        self._grad_req = req
        if req == "null":
            self._grad = None
            if self._data:
                for arr in self._data.values():
                    arr._grad = None
                    arr._grad_req = "null"
        elif self._data is not None:
            self._init_grad()

    # -- init --------------------------------------------------------------
    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        """reference: parameter.py Parameter.initialize"""
        if self._data is not None and not force_reinit:
            return
        if ctx is None:
            ctx = [current_context()]
        if isinstance(ctx, Context):
            ctx = [ctx]
        if init is None:
            init = self.init if self.init is not None else (default_init or initializer.Uniform())
        if self._shape is None or any(s == 0 for s in self._shape):
            if self.allow_deferred_init:
                self._deferred_init = (init, ctx)
                return
            raise MXNetError("cannot initialize %s: shape %s unknown; set "
                             "allow_deferred_init or give full shape"
                             % (self.name, self._shape))
        self._init_impl(init, ctx)

    def _init_impl(self, init, ctx_list):
        host = nd.zeros(self._shape, ctx=cpu(), dtype=self.dtype)
        init_obj = initializer.create(init) if isinstance(init, str) else init
        init_obj(initializer.InitDesc(self.name), host)
        self._ctx_list = list(ctx_list)
        self._data = OrderedDict((c, host.copyto(c)) for c in ctx_list)
        self._deferred_init = ()
        if self._grad_req != "null":
            self._init_grad()

    def _init_grad(self):
        self._grad = OrderedDict(
            (c, nd.zeros(self._shape, ctx=c, dtype=self.dtype)) for c in self._data)
        from .. import autograd

        for c, arr in self._data.items():
            autograd.mark_variables([arr], [self._grad[c]], self._grad_req)

    def _finish_deferred_init(self):
        if not self._deferred_init:
            return
        init, ctx = self._deferred_init
        if self._shape is None or any(s == 0 for s in self._shape):
            raise DeferredInitializationError(
                "Parameter %s has unknown shape %s" % (self.name, self._shape))
        self._init_impl(init, ctx)

    # -- access ------------------------------------------------------------
    def _check_and_get(self, store, ctx):
        if store is None:
            if self._deferred_init:
                raise DeferredInitializationError(
                    "Parameter %s not initialized yet (deferred)" % self.name)
            raise MXNetError(
                "Parameter %s has not been initialized. Call .initialize() first"
                % self.name)
        if ctx is None:
            if len(store) == 1:
                return next(iter(store.values()))
            ctx = current_context()
        if ctx in store:
            return store[ctx]
        raise MXNetError("Parameter %s not initialized on context %s (has %s)"
                         % (self.name, ctx, list(store)))

    def data(self, ctx=None):
        return self._check_and_get(self._data, ctx)

    def list_data(self):
        self._check_and_get(self._data, list(self._data)[0] if self._data else None)
        return list(self._data.values())

    def grad(self, ctx=None):
        if self._grad is None and self._data is not None:
            raise MXNetError("Parameter %s grad_req='null'" % self.name)
        return self._check_and_get(self._grad, ctx)

    def list_grad(self):
        return list(self._grad.values()) if self._grad else []

    def list_ctx(self):
        if self._data is None and self._deferred_init:
            return self._deferred_init[1]
        return list(self._data) if self._data else []

    def zero_grad(self):
        if self._grad is None:
            return
        for g in self._grad.values():
            g[:] = 0

    def set_data(self, data):
        self.shape = data.shape
        if self._data is None:
            assert self._deferred_init, \
                "set_data on uninitialized parameter %s" % self.name
            self._deferred_init = self._deferred_init[:2] + (data,)
            init, ctx = self._deferred_init[:2]
            self._init_impl(initializer.Constant(0), ctx)
            for c in self._data:
                self._data[c]._set_data(data.as_in_context(c)._data)
            return
        for c in self._data:
            self._data[c]._set_data(data.as_in_context(c)._data)

    def row_sparse_data(self, row_id):
        raise MXNetError("row_sparse parameters: use stype='row_sparse' (sparse "
                         "module) — dense fallback active in this build")

    def reset_ctx(self, ctx):
        if isinstance(ctx, Context):
            ctx = [ctx]
        if self._data:
            data = next(iter(self._data.values()))
            self._data = OrderedDict((c, data.as_in_context(c)) for c in ctx)
            if self._grad_req != "null":
                self._init_grad()
        elif self._deferred_init:
            init, _ = self._deferred_init
            self._deferred_init = (init, ctx)

    def cast(self, dtype):
        self.dtype = np_dtype(dtype)
        if self._data is None:
            return
        for c in list(self._data):
            self._data[c]._set_data(self._data[c].astype(dtype)._data)
        if self._grad:
            for c in list(self._grad):
                self._grad[c]._set_data(self._grad[c].astype(dtype)._data)
            from .. import autograd

            for c, arr in self._data.items():
                autograd.mark_variables([arr], [self._grad[c]], self._grad_req)

    def var(self):
        from .. import symbol

        return symbol.var(self.name, shape=self._shape, dtype=self.dtype)


class Constant(Parameter):
    """Non-differentiable constant parameter (reference: parameter.py Constant)."""

    def __init__(self, name, value):
        if not isinstance(value, NDArray):
            value = nd.array(value)
        self.value = value

        class _CInit(initializer.Initializer):
            def __call__(self, desc, arr):
                arr[:] = value.asnumpy()

        super().__init__(name, grad_req="null", shape=value.shape,
                         dtype=value.dtype, init=_CInit(), differentiable=False)


class ParameterDict:
    """Dict of Parameters with prefix + sharing (reference: parameter.py:508)."""

    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params = OrderedDict()
        self._shared = shared

    @property
    def prefix(self):
        return self._prefix

    def __repr__(self):
        return "ParameterDict(%s)" % ", ".join(str(p) for p in self._params.values())

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def __iter__(self):
        return iter(self._params)

    def __len__(self):
        return len(self._params)

    def __getitem__(self, key):
        return self._params[key]

    def __contains__(self, key):
        return key in self._params

    def get(self, name, **kwargs):
        """Create-or-retrieve `prefix+name` (reference: parameter.py get)."""
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            param = Parameter(name, **kwargs)
            self._params[name] = param
        else:
            shape = kwargs.get("shape")
            if shape is not None:
                param.shape = (shape,) if isinstance(shape, int) else tuple(shape)
        return param

    def get_constant(self, name, value=None):
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            if value is None:
                raise MXNetError("no constant %s and no value given" % name)
            param = Constant(name, value)
            self._params[name] = param
        return param

    def _get_impl(self, name):
        if name in self._params:
            return self._params[name]
        if self._shared is not None and name in self._shared._params:
            self._params[name] = self._shared._params[name]
            return self._params[name]
        return None

    def update(self, other):
        for k, v in other.items():
            if k in self._params:
                assert self._params[k] is v, "duplicate parameter name %s" % k
            else:
                self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False, force_reinit=False):
        for p in self._params.values():
            p.initialize(init=None, ctx=ctx, default_init=init, force_reinit=force_reinit)

    def zero_grad(self):
        for p in self._params.values():
            p.zero_grad()

    def reset_ctx(self, ctx):
        for p in self._params.values():
            p.reset_ctx(ctx)

    def setattr(self, name, value):
        for p in self._params.values():
            setattr(p, name, value)

    def save(self, filename, strip_prefix=""):
        args = {}
        for p in self._params.values():
            block = p.list_data()
            weight = sum(b.copyto(cpu()) for b in block) / len(block)
            name = p.name
            if strip_prefix and name.startswith(strip_prefix):
                name = name[len(strip_prefix):]
            args[name] = weight
        nd.save(filename, args)

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix=""):
        loaded = nd.load(filename)
        # checkpoint files prefix entries with arg:/aux: (reference
        # model.py:394 format); strip for parameter matching
        loaded = {(k.split(":", 1)[1] if k.startswith(("arg:", "aux:"))
                   else k): v for k, v in loaded.items()}
        if restore_prefix:
            loaded = {restore_prefix + k: v for k, v in loaded.items()}
        if not allow_missing:
            for name in self.keys():
                assert name in loaded, \
                    "Parameter %s missing in file %s" % (name, filename)
        for name, val in loaded.items():
            if name not in self._params:
                if not ignore_extra:
                    raise MXNetError("Parameter %s in file not in ParameterDict" % name)
                continue
            p = self._params[name]
            if p._data is None:
                p.shape = val.shape
                p.initialize(ctx=ctx or [current_context()])
                if p._deferred_init:
                    p._finish_deferred_init()
            p.set_data(val)
