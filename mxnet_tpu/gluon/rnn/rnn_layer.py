"""Fused recurrent layers.

Reference: python/mxnet/gluon/rnn/rnn_layer.py — RNN/LSTM/GRU wrapping the
fused RNN op (src/operator/rnn-inl.h). TPU-native: the op is a lax.scan whose
input projection is hoisted into one large MXU matmul per layer
(ops/rnn.py). Parameters are kept as separate i2h/h2h weights per
layer/direction (same naming as the reference) and packed into the flat
cuDNN-layout vector at forward, so checkpoints interchange."""
from __future__ import annotations

from ... import ndarray as nd
from ...base import MXNetError
from ..block import HybridBlock

__all__ = ["RNN", "LSTM", "GRU"]

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


class _RNNLayer(HybridBlock):
    def __init__(self, hidden_size, num_layers, layout, dropout, bidirectional,
                 input_size, i2h_weight_initializer, h2h_weight_initializer,
                 i2h_bias_initializer, h2h_bias_initializer, mode, **kwargs):
        super().__init__(**kwargs)
        assert layout in ("TNC", "NTC"), "Invalid layout %s; must be TNC or NTC" % layout
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._mode = mode
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._gates = _GATES[mode]
        ng, ni, nh = self._gates, input_size, hidden_size
        with self.name_scope():
            for i in range(num_layers):
                for j in ["l", "r"][: self._dir]:
                    self._register_param("%s%d_i2h_weight" % (j, i),
                                         (ng * nh, ni), i2h_weight_initializer)
                    self._register_param("%s%d_h2h_weight" % (j, i),
                                         (ng * nh, nh), h2h_weight_initializer)
                    self._register_param("%s%d_i2h_bias" % (j, i),
                                         (ng * nh,), i2h_bias_initializer)
                    self._register_param("%s%d_h2h_bias" % (j, i),
                                         (ng * nh,), h2h_bias_initializer)
                ni = nh * self._dir

    def _register_param(self, name, shape, init):
        p = self.params.get(name, shape=shape, init=init, allow_deferred_init=True)
        setattr(self, name, p)
        return p

    def _shape_hook(self, x, *args):
        if self._input_size == 0:
            ni = x.shape[2] if self._layout == "TNC" else x.shape[-1]
            for j in ["l", "r"][: self._dir]:
                p = getattr(self, "%s0_i2h_weight" % j)
                if p.shape and p.shape[1] == 0:
                    p.shape = (self._gates * self._hidden_size, ni)
            self._input_size = ni

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        """Initial recurrent states (reference: rnn_layer.py begin_state)."""
        func = func or nd.zeros
        states = []
        for info in self.state_info(batch_size):
            states.append(func(shape=info["shape"], **kwargs))
        return states

    def _eager_forward(self, inputs, states=None):
        self._shape_hook(inputs)
        skip_states = states is None
        batch_axis = 1 if self._layout == "TNC" else 0
        batch_size = inputs.shape[batch_axis]
        if states is None:
            states = self.begin_state(batch_size, ctx=inputs.context,
                                      dtype=inputs.dtype)
        if isinstance(states, nd.NDArray):
            states = [states]
        if self._layout == "NTC":
            inputs = inputs.swapaxes(0, 1)
        out, out_states = self._forward_kernel(inputs, states)
        if self._layout == "NTC":
            out = out.swapaxes(0, 1)
        return out if skip_states else (out, out_states)

    def forward(self, inputs, states=None):
        from ..block import _is_tracing

        if self._active and not _is_tracing():
            # compiled path keyed on (shape, states-given)
            return self._call_cached(inputs, states) if states is not None \
                else self._call_cached(inputs)
        try:
            return self._eager_forward(inputs, states)
        except Exception as e:
            from ..parameter import DeferredInitializationError

            if isinstance(e, DeferredInitializationError):
                self._finish_deferred(inputs)
                return self._eager_forward(inputs, states)
            raise

    def _finish_deferred(self, inputs):
        self._shape_hook(inputs)
        for p in self.collect_params().values():
            if p._deferred_init:
                p._finish_deferred_init()

    def _forward_kernel(self, inputs, states):
        """Pack params into the flat cuDNN layout and run the fused op."""
        ctx = inputs.context
        weights = []
        biases = []
        for i in range(self._num_layers):
            for j in ["l", "r"][: self._dir]:
                weights.append(getattr(self, "%s%d_i2h_weight" % (j, i)).data(ctx).reshape((-1,)))
                weights.append(getattr(self, "%s%d_h2h_weight" % (j, i)).data(ctx).reshape((-1,)))
                biases.append(getattr(self, "%s%d_i2h_bias" % (j, i)).data(ctx))
                biases.append(getattr(self, "%s%d_h2h_bias" % (j, i)).data(ctx))
        params = nd.concat(*(weights + biases), dim=0)
        if self._mode == "lstm":
            rnn_args = (states[0], states[1])
        else:
            rnn_args = (states[0],)
        outs = nd.invoke("RNN", (inputs, params) + rnn_args, {
            "state_size": self._hidden_size, "num_layers": self._num_layers,
            "bidirectional": self._dir == 2, "mode": self._mode,
            "p": self._dropout, "state_outputs": True})
        outs = outs if isinstance(outs, list) else [outs]
        return outs[0], list(outs[1:])

    def __repr__(self):
        s = "{name}({mapping}, {_layout}"
        if self._num_layers != 1:
            s += ", num_layers={_num_layers}"
        if self._dropout != 0:
            s += ", dropout={_dropout}"
        if self._dir == 2:
            s += ", bidirectional"
        s += ")"
        mapping = "{0} -> {1}".format(self._input_size if self._input_size else None,
                                      self._hidden_size)
        return s.format(name=self.__class__.__name__, mapping=mapping,
                        **self.__dict__)


class RNN(_RNNLayer):
    """Vanilla RNN layer (reference: rnn_layer.py RNN)."""

    def __init__(self, hidden_size, num_layers=1, activation="relu", layout="TNC",
                 dropout=0, bidirectional=False, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout, bidirectional,
                         input_size, i2h_weight_initializer, h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer,
                         "rnn_" + activation, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size, self._hidden_size),
                 "__layout__": "LNC"}]


class LSTM(_RNNLayer):
    """LSTM layer (reference: rnn_layer.py LSTM)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout, bidirectional,
                         input_size, i2h_weight_initializer, h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer, "lstm", **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size, self._hidden_size),
                 "__layout__": "LNC"},
                {"shape": (self._num_layers * self._dir, batch_size, self._hidden_size),
                 "__layout__": "LNC"}]


class GRU(_RNNLayer):
    """GRU layer (reference: rnn_layer.py GRU)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout, bidirectional,
                         input_size, i2h_weight_initializer, h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer, "gru", **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size, self._hidden_size),
                 "__layout__": "LNC"}]
