"""Recurrent cells + explicit unroll (reference: python/mxnet/gluon/rnn/rnn_cell.py)."""
from __future__ import annotations

from ... import ndarray as nd
from ...base import MXNetError
from ..block import HybridBlock

__all__ = ["RecurrentCell", "HybridRecurrentCell", "RNNCell", "LSTMCell",
           "GRUCell", "SequentialRNNCell", "DropoutCell", "ZoneoutCell",
           "ResidualCell", "BidirectionalCell"]


def _format_sequence(length, inputs, layout, merge):
    """Split/merge helpers (reference: rnn_cell.py:46 _format_sequence)."""
    batch_axis = layout.find("N")
    axis = layout.find("T")
    if isinstance(inputs, nd.NDArray):
        batch_size = inputs.shape[batch_axis]
        if merge is False:
            inputs = [x.squeeze(axis=axis) for x in
                      nd.split(inputs, num_outputs=inputs.shape[axis], axis=axis,
                               squeeze_axis=False)]
    else:
        batch_size = inputs[0].shape[0]
        if merge is True:
            inputs = nd.stack(*inputs, axis=axis)
    return inputs, axis, batch_size


class RecurrentCell(HybridBlock):
    """Base recurrent cell (reference: rnn_cell.py:120)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        assert not self._modified
        states = []
        func = func or nd.zeros
        for info in self.state_info(batch_size):
            self._init_counter += 1
            info = dict(info)
            info.pop("__layout__", None)
            states.append(func(**dict(info, **kwargs)))
        return states

    def __call__(self, inputs, states):
        self._counter += 1
        return self.forward(inputs, states)

    def forward(self, inputs, states):
        return self._eager_forward(inputs, states)

    def _eager_forward(self, inputs, states):
        self._shape_hook(inputs)
        for p in self._reg_params.values():
            if p._deferred_init and not (p._shape is None or any(s == 0 for s in p._shape)):
                p._finish_deferred_init()
        params = {name: p.data(inputs.context if isinstance(inputs, nd.NDArray)
                               else None)
                  for name, p in self._reg_params.items()}
        return self.hybrid_forward(nd, inputs, states, **params)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """Unroll the cell over `length` steps (reference: rnn_cell.py unroll).
        Python loop — under hybridize/CachedOp the whole unroll traces into
        one XLA program (XLA unrolls or loops as it sees fit)."""
        self.reset()
        inputs, axis, batch_size = _format_sequence(length, inputs, layout, False)
        if begin_state is None:
            ctx = inputs[0].context
            begin_state = self.begin_state(batch_size, ctx=ctx, dtype=inputs[0].dtype)
        states = begin_state
        outputs = []
        all_states = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
            if valid_length is not None:
                all_states.append(states)
        if valid_length is not None:
            states = [nd.invoke("SequenceLast", (nd.stack(*ele_list, axis=0), valid_length),
                                {"use_sequence_length": True, "axis": 0})
                      for ele_list in zip(*all_states)]
            outputs = [nd.invoke("SequenceMask", (nd.stack(*outputs, axis=0), valid_length),
                                 {"use_sequence_length": True, "axis": 0})]
            outputs = [o.squeeze(axis=0) for o in
                       nd.split(outputs[0], num_outputs=length, axis=0)] \
                if merge_outputs is False else outputs[0].swapaxes(0, 1) \
                if layout == "NTC" else outputs[0]
            if merge_outputs is None:
                merge_outputs = True
            return outputs, states
        if merge_outputs is None or merge_outputs is True:
            outputs = nd.stack(*outputs, axis=axis)
        return outputs, states

    def _get_activation(self, F, inputs, activation, **kwargs):
        if isinstance(activation, str):
            return F.Activation(inputs, act_type=activation, **kwargs)
        return activation(inputs, **kwargs)


HybridRecurrentCell = RecurrentCell


class RNNCell(RecurrentCell):
    """Elman cell (reference: rnn_cell.py RNNCell)."""

    def __init__(self, hidden_size, activation="tanh", i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get("i2h_weight", shape=(hidden_size, input_size),
                                              init=i2h_weight_initializer,
                                              allow_deferred_init=True)
            self.h2h_weight = self.params.get("h2h_weight", shape=(hidden_size, hidden_size),
                                              init=h2h_weight_initializer,
                                              allow_deferred_init=True)
            self.i2h_bias = self.params.get("i2h_bias", shape=(hidden_size,),
                                            init=i2h_bias_initializer,
                                            allow_deferred_init=True)
            self.h2h_bias = self.params.get("h2h_bias", shape=(hidden_size,),
                                            init=h2h_bias_initializer,
                                            allow_deferred_init=True)

    def _shape_hook(self, x, *a):
        if self.i2h_weight.shape and self.i2h_weight.shape[1] == 0:
            self.i2h_weight.shape = (self._hidden_size, x.shape[-1])

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "rnn"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight, i2h_bias,
                       h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size)
        output = self._get_activation(F, i2h + h2h, self._activation)
        return output, [output]


class LSTMCell(RecurrentCell):
    """LSTM cell (reference: rnn_cell.py LSTMCell; gate order i,f,g,o)."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get("i2h_weight", shape=(4 * hidden_size, input_size),
                                              init=i2h_weight_initializer,
                                              allow_deferred_init=True)
            self.h2h_weight = self.params.get("h2h_weight", shape=(4 * hidden_size, hidden_size),
                                              init=h2h_weight_initializer,
                                              allow_deferred_init=True)
            self.i2h_bias = self.params.get("i2h_bias", shape=(4 * hidden_size,),
                                            init=i2h_bias_initializer,
                                            allow_deferred_init=True)
            self.h2h_bias = self.params.get("h2h_bias", shape=(4 * hidden_size,),
                                            init=h2h_bias_initializer,
                                            allow_deferred_init=True)

    def _shape_hook(self, x, *a):
        if self.i2h_weight.shape and self.i2h_weight.shape[1] == 0:
            self.i2h_weight.shape = (4 * self._hidden_size, x.shape[-1])

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "lstm"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight, i2h_bias,
                       h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=4 * self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=4 * self._hidden_size)
        gates = i2h + h2h
        slice_gates = F.split(gates, num_outputs=4, axis=1)
        in_gate = F.sigmoid(slice_gates[0])
        forget_gate = F.sigmoid(slice_gates[1])
        in_transform = F.tanh(slice_gates[2])
        out_gate = F.sigmoid(slice_gates[3])
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * F.tanh(next_c)
        return next_h, [next_h, next_c]


class GRUCell(RecurrentCell):
    """GRU cell (reference: rnn_cell.py GRUCell; gate order r,z,n)."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get("i2h_weight", shape=(3 * hidden_size, input_size),
                                              init=i2h_weight_initializer,
                                              allow_deferred_init=True)
            self.h2h_weight = self.params.get("h2h_weight", shape=(3 * hidden_size, hidden_size),
                                              init=h2h_weight_initializer,
                                              allow_deferred_init=True)
            self.i2h_bias = self.params.get("i2h_bias", shape=(3 * hidden_size,),
                                            init=i2h_bias_initializer,
                                            allow_deferred_init=True)
            self.h2h_bias = self.params.get("h2h_bias", shape=(3 * hidden_size,),
                                            init=h2h_bias_initializer,
                                            allow_deferred_init=True)

    def _shape_hook(self, x, *a):
        if self.i2h_weight.shape and self.i2h_weight.shape[1] == 0:
            self.i2h_weight.shape = (3 * self._hidden_size, x.shape[-1])

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "gru"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight, i2h_bias,
                       h2h_bias):
        prev_state_h = states[0]
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=3 * self._hidden_size)
        h2h = F.FullyConnected(prev_state_h, h2h_weight, h2h_bias,
                               num_hidden=3 * self._hidden_size)
        i2h_r, i2h_z, i2h = F.split(i2h, num_outputs=3, axis=1)
        h2h_r, h2h_z, h2h = F.split(h2h, num_outputs=3, axis=1)
        reset_gate = F.sigmoid(i2h_r + h2h_r)
        update_gate = F.sigmoid(i2h_z + h2h_z)
        next_h_tmp = F.tanh(i2h + reset_gate * h2h)
        next_h = (1.0 - update_gate) * next_h_tmp + update_gate * prev_state_h
        return next_h, [next_h]


class SequentialRNNCell(RecurrentCell):
    """Stack of cells (reference: rnn_cell.py SequentialRNNCell)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return sum([c.state_info(batch_size) for c in self._children.values()], [])

    def begin_state(self, batch_size=0, **kwargs):
        assert not self._modified
        return sum([c.begin_state(batch_size, **kwargs)
                    for c in self._children.values()], [])

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.extend(state)
        return inputs, next_states

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return list(self._children.values())[i]


class DropoutCell(RecurrentCell):
    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):
        return []

    def _alias(self):
        return "dropout"

    def hybrid_forward(self, F, inputs, states):
        if self._rate > 0:
            inputs = F.Dropout(inputs, p=self._rate, axes=self._axes)
        return inputs, states


class ModifierCell(RecurrentCell):
    """Base for cells wrapping another cell (reference: rnn_cell.py)."""

    def __init__(self, base_cell):
        assert not base_cell._modified
        base_cell._modified = True
        super().__init__(prefix=base_cell.prefix + self._alias(), params=None)
        self.base_cell = base_cell

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, func=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(batch_size, func=func, **kwargs)
        self.base_cell._modified = True
        return begin


class ZoneoutCell(ModifierCell):
    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self._prev_output = None

    def _alias(self):
        return "zoneout"

    def __call__(self, inputs, states):
        cell = self.base_cell
        next_output, next_states = cell(inputs, states)
        mask = lambda p, like: nd.Dropout(nd.ones_like(like), p=p)
        prev_output = self._prev_output if self._prev_output is not None \
            else nd.zeros_like(next_output)
        output = nd.where(mask(self.zoneout_outputs, next_output), next_output,
                          prev_output) if self.zoneout_outputs > 0.0 else next_output
        new_states = [nd.where(mask(self.zoneout_states, new_s), new_s, old_s)
                      for new_s, old_s in zip(next_states, states)] \
            if self.zoneout_states > 0.0 else next_states
        self._prev_output = output
        return output, new_states

    def reset(self):
        super().reset()
        self._prev_output = None


class ResidualCell(ModifierCell):
    def _alias(self):
        return "residual"

    def __call__(self, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = output + inputs
        return output, states


class BidirectionalCell(RecurrentCell):
    """Bidirectional wrapper (reference: rnn_cell.py BidirectionalCell)."""

    def __init__(self, l_cell, r_cell, output_prefix="bi_"):
        super().__init__(prefix="", params=None)
        self.register_child(l_cell, "l_cell")
        self.register_child(r_cell, "r_cell")
        self._output_prefix = output_prefix

    def __call__(self, inputs, states):
        raise MXNetError("BidirectionalCell cannot be stepped; use unroll()")

    def state_info(self, batch_size=0):
        return sum([c.state_info(batch_size) for c in self._children.values()], [])

    def begin_state(self, batch_size=0, **kwargs):
        return sum([c.begin_state(batch_size, **kwargs)
                    for c in self._children.values()], [])

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        inputs, axis, batch_size = _format_sequence(length, inputs, layout, False)
        if begin_state is None:
            ctx = inputs[0].context
            begin_state = self.begin_state(batch_size, ctx=ctx, dtype=inputs[0].dtype)
        states = begin_state
        l_cell, r_cell = self._children["l_cell"], self._children["r_cell"]
        n_l = len(l_cell.state_info(batch_size))
        l_outputs, l_states = l_cell.unroll(length, inputs, states[:n_l], layout,
                                            merge_outputs=False,
                                            valid_length=valid_length)
        rev_inputs = list(reversed(inputs))
        r_outputs, r_states = r_cell.unroll(length, rev_inputs, states[n_l:], layout,
                                            merge_outputs=False,
                                            valid_length=valid_length)
        r_outputs = list(reversed(r_outputs))
        outputs = [nd.concat(l_o, r_o, dim=1)
                   for l_o, r_o in zip(l_outputs, r_outputs)]
        if merge_outputs is None or merge_outputs is True:
            outputs = nd.stack(*outputs, axis=axis)
        return outputs, l_states + r_states
