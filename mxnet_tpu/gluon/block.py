"""Gluon Block / HybridBlock.

Reference: python/mxnet/gluon/block.py (Block :127, HybridBlock :671 —
`_build_cache` :748 creating a CachedOp, `hybridize` :832, `export` :868;
SymbolBlock :952). TPU-native mapping:

- Block: identical imperative semantics (eager NDArray ops on the tape).
- HybridBlock.hybridize(): instead of tracing into an NNVM Symbol executed by
  the C++ CachedOp (src/imperative/cached_op.cc), the block's forward is
  traced by `jax.jit` into ONE XLA executable per (input signature,
  train-mode): parameters become executable inputs, BatchNorm aux-state
  updates become extra outputs written back after the call (the functional
  form of the reference's aux mutation), and RNG ops consume a key passed in
  at each call. The whole forward — and, via a cached jax.vjp, the whole
  backward — runs as one fused TPU program: this is where MXNet's
  "hybridize for speed" story maps onto XLA's compile-once-run-many model.
"""
from __future__ import annotations

import re
import threading
from collections import OrderedDict

from ..base import MXNetError
from ..context import current_context
from .. import ndarray as nd
from ..ndarray import NDArray
from .parameter import Parameter, ParameterDict, DeferredInitializationError

__all__ = ["Block", "HybridBlock", "SymbolBlock"]

_naming = threading.local()


def _name_counter():
    if not hasattr(_naming, "counts"):
        _naming.counts = {}
    return _naming.counts


class _BlockScope:
    """Name/prefix manager (reference: block.py:33 _BlockScope)."""

    _current = threading.local()

    def __init__(self, block):
        self._block = block
        self._counter = {}
        self._old_scope = None

    @staticmethod
    def create(prefix, params, hint):
        current = getattr(_BlockScope._current, "value", None)
        if current is None:
            if prefix is None:
                counts = _name_counter()
                count = counts.get(hint, 0)
                counts[hint] = count + 1
                prefix = "%s%d_" % (hint, count)
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, params)
            return prefix, params
        if prefix is None:
            count = current._counter.get(hint, 0)
            current._counter[hint] = count + 1
            prefix = "%s%d_" % (hint, count)
        if params is None:
            parent = current._block.params
            params = ParameterDict(parent.prefix + prefix, parent._shared)
        else:
            params = ParameterDict(params.prefix, params)
        return current._block.prefix + prefix, params

    def __enter__(self):
        if self._block._empty_prefix:
            return self
        self._old_scope = getattr(_BlockScope._current, "value", None)
        _BlockScope._current.value = self
        return self

    def __exit__(self, *args):
        if self._block._empty_prefix:
            return
        _BlockScope._current.value = self._old_scope


class Block:
    """Base building block (reference: block.py:127)."""

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        self._prefix, self._params = _BlockScope.create(prefix, params,
                                                        self._alias())
        self._name = self._prefix[:-1] if self._prefix.endswith("_") else self._prefix
        self._scope = _BlockScope(self)
        self._children = OrderedDict()
        self._reg_params = {}

    def _alias(self):
        return self.__class__.__name__.lower()

    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    @property
    def params(self):
        return self._params

    def name_scope(self):
        return self._scope

    def __repr__(self):
        s = "{name}(\n{modstr}\n)" if self._children else "{name}()"
        modstr = "\n".join("  (%s): %s" % (k, re.sub("\n", "\n  ", repr(v)))
                           for k, v in self._children.items())
        return s.format(name=self.__class__.__name__, modstr=modstr)

    def __setattr__(self, name, value):
        if isinstance(value, Block):
            existing = self._children.get(name)
            if existing is not None:
                self._children[name] = value
            else:
                self.register_child(value, name)
        elif isinstance(value, Parameter):
            self._reg_params[name] = value
        super().__setattr__(name, value)

    def register_child(self, block, name=None):
        if name is None:
            name = str(len(self._children))
        self._children[name] = block

    def collect_params(self, select=None):
        """All params of self + descendants (reference: block.py collect_params)."""
        ret = ParameterDict(self._params.prefix)
        if select is None:
            ret.update(self.params)
        else:
            pattern = re.compile(select)
            ret.update({name: value for name, value in self.params.items()
                        if pattern.match(name)})
        for child in self._children.values():
            ret.update(child.collect_params(select=select))
        return ret

    def initialize(self, init=None, ctx=None, verbose=False, force_reinit=False):
        self.collect_params().initialize(init, ctx, verbose, force_reinit)

    def cast(self, dtype):
        for child in self._children.values():
            child.cast(dtype)
        for _, p in self.params.items():
            p.cast(dtype)

    def apply(self, fn):
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    # -- persistence -------------------------------------------------------
    def save_parameters(self, filename):
        """reference: block.py:315 save_parameters (params only)."""
        params = self._collect_params_with_prefix()
        arg_dict = {key: val._reduce() if hasattr(val, "_reduce") else
                    val.data().copyto(__import__("mxnet_tpu").cpu())
                    for key, val in params.items()}
        nd.save(filename, arg_dict)

    save_params = save_parameters

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False):
        """reference: block.py:356 load_parameters."""
        loaded = nd.load(filename)
        params = self._collect_params_with_prefix()
        if not allow_missing:
            for name in params:
                assert name in loaded, "Parameter %s missing in %s" % (name, filename)
        for name in loaded:
            if name not in params:
                if not ignore_extra:
                    raise MXNetError("Parameter %s in file not in Block" % name)
                continue
            p = params[name]
            if p._data is None:
                p.shape = loaded[name].shape
                p.initialize(ctx=ctx or [current_context()])
                p._finish_deferred_init()
            p.set_data(loaded[name])

    load_params = load_parameters

    def _collect_params_with_prefix(self, prefix=""):
        if prefix:
            prefix += "."
        ret = {prefix + key: val for key, val in self._reg_params.items()}
        for name, child in self._children.items():
            ret.update(child._collect_params_with_prefix(prefix + name))
        return ret

    # -- execution ---------------------------------------------------------
    def __call__(self, *args):
        return self.forward(*args)

    def forward(self, *args):
        raise NotImplementedError

    def hybridize(self, active=True, **kwargs):
        for child in self._children.values():
            child.hybridize(active, **kwargs)

    def summary(self, *inputs):
        summary_rows = []

        def walk(block, depth):
            n_params = sum(int(__import__("numpy").prod(p.shape or ()))
                           for p in block._reg_params.values())
            summary_rows.append(("  " * depth + block.__class__.__name__, n_params))
            for c in block._children.values():
                walk(c, depth + 1)

        walk(self, 0)
        total = sum(n for _, n in summary_rows)
        lines = ["%-40s %12s" % ("Layer", "Params"), "-" * 53]
        lines += ["%-40s %12d" % r for r in summary_rows]
        lines += ["-" * 53, "%-40s %12d" % ("Total (direct)", total)]
        print("\n".join(lines))  # allow-print


_TRACING = threading.local()

# marks an NDArray slot in a cached trace's static-arg skeleton; a unique
# object so literal-None arguments can never be mistaken for a slot
_ARRAY_SLOT = object()


def _is_tracing():
    return getattr(_TRACING, "flag", False)


class _CachedGraph:
    """One compiled entry: jitted fn + aux bookkeeping for a signature."""

    __slots__ = ("jitted", "aux_params", "n_outputs", "single", "bwd")

    def __init__(self):
        self.jitted = None
        self.aux_params = []
        self.n_outputs = 0
        self.single = True
        self.bwd = None


class HybridBlock(Block):
    """Block tracable into a compiled executable (reference: block.py:671)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._flags = {}
        self._cached = {}

    def hybridize(self, active=True, static_alloc=False, static_shape=False,
                  **kwargs):
        """reference: block.py:832. static_alloc/static_shape accepted for
        parity; XLA executables are always statically allocated."""
        self._active = active
        self._flags = dict(static_alloc=static_alloc, static_shape=static_shape)
        self._cached = {}
        super().hybridize(active, static_alloc=static_alloc,
                          static_shape=static_shape, **kwargs)

    def infer_shape(self, *args):
        """Finish deferred param init from input shapes. Layers override
        `_shape_hook` (the TPU build's per-layer equivalent of the reference's
        symbolic _deferred_infer_shape, block.py:810)."""
        self._shape_hook(*args)

    def _shape_hook(self, *args):
        pass

    def _finish_deferred(self, *args):
        params = [p for p in self.collect_params().values() if p._deferred_init]
        if not params:
            return
        # give every descendant a chance to infer shapes from the args flowing
        # through an eager probe pass
        self._shape_probe(*args)
        for p in params:
            if p._deferred_init:
                p._finish_deferred_init()

    def _shape_probe(self, *args):
        """Run one eager forward in probe mode: each HybridBlock's
        _shape_hook fires with its actual inputs before executing."""
        with _probe_scope():
            from .. import autograd

            with autograd.pause():
                self._eager_forward(*args)

    def _eager_forward(self, *args):
        ctx = None
        for a in args:
            if isinstance(a, NDArray):
                ctx = a.context
                break
        self._shape_hook(*args)
        for p in self._reg_params.values():
            if p._deferred_init and not (p._shape is None or any(s == 0 for s in p._shape)):
                p._finish_deferred_init()
        params = {}
        for name, p in self._reg_params.items():
            params[name] = p.data(ctx)
        return self.hybrid_forward(nd, *args, **params)

    def forward(self, *args):
        from ..symbol.symbol import Symbol

        if any(isinstance(a, Symbol) for a in args):
            # symbolic tracing: hybrid_forward composes a Symbol graph, with
            # parameters as named vars (the reference's HybridBlock Symbol
            # path, block.py:748 _build_cache) — used by export()/predictor
            return self._symbolic_forward(*args)
        if self._active and not _is_tracing():
            return self._call_cached(*args)
        try:
            return self._eager_forward(*args)
        except DeferredInitializationError:
            self._finish_deferred(*args)
            return self._eager_forward(*args)

    def _symbolic_forward(self, *args):
        from .. import symbol as sym_mod

        params = {name: sym_mod.var(p.name, shape=p.shape)
                  for name, p in self._reg_params.items()}
        return self.hybrid_forward(sym_mod, *args, **params)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError

    # -- compiled path -----------------------------------------------------
    def _call_cached(self, *args):
        import jax

        from .. import autograd, random as _random

        try:
            param_list = [(n, p) for n, p in sorted(self.collect_params().items())]
            param_nds = []
            ctx = None
            for a in args:
                if isinstance(a, NDArray):
                    ctx = a.context
                    break
            for _, p in param_list:
                param_nds.append(p.data(ctx))
        except DeferredInitializationError:
            self._finish_deferred(*args)
            return self._call_cached(*args)

        is_train = autograd.is_training()
        sig = (tuple((a.shape, str(a.dtype)) if isinstance(a, NDArray) else ("<s>", repr(a))
                     for a in args), is_train)
        entry = self._cached.get(sig)
        if entry is None:
            entry = self._build_cache(args, param_nds, is_train, sig)
            self._cached[sig] = entry

        key = _random.next_key()
        arg_arrays = tuple(a._data for a in args if isinstance(a, NDArray))
        param_arrays = tuple(p._data for p in param_nds)
        outs, aux_new = entry.jitted(key, arg_arrays, param_arrays)

        # write aux-state updates (BatchNorm moving stats) back
        for idx, new in zip(entry.aux_params, aux_new):
            param_nds[idx]._set_data(new)

        arg_nds = [a for a in args if isinstance(a, NDArray)]
        out_nds = [NDArray(o, ctx=ctx or current_context()) for o in outs]
        if autograd.is_recording():
            self._record_cached(entry, key, arg_nds, param_nds, arg_arrays,
                                param_arrays, out_nds)
        if entry.single:
            return out_nds[0]
        return out_nds

    def _cached_key(self, kind, sig):
        """`mxnet_tpu.compile` key for this block instance's CachedOp
        executables. The fingerprint is a process-local instance token
        (a live block's graph has no stable content identity — params and
        sub-block structure are python state), so entries are memory-tier
        only (``no_persist``); the hybridized hot path still gets the
        registry's counters, fill spans, FLOP hook and LRU accounting."""
        from .. import compile as _compile

        if not hasattr(self, "_compile_token"):
            self._compile_token = _compile.instance_token(
                type(self).__name__)
        return _compile.ExecutableKey(kind, self._compile_token,
                                      shapes=sig[0], static=(sig[1],),
                                      no_persist=True)

    def _build_cache(self, args, param_nds, is_train, sig):
        """Trace the whole block into one jitted executable
        (reference: block.py:748 _build_cache -> CachedOp)."""
        import jax

        from .. import autograd, random as _random

        entry = _CachedGraph()
        arg_ctx = None
        for a in args:
            if isinstance(a, NDArray):
                arg_ctx = a.context
                break
        # dedicated placeholder sentinel: a literal None ARGUMENT (e.g. an
        # optional mask passed as None) must not collide with the
        # array-slot marker, or the trace consumes one array too many
        _slot = _ARRAY_SLOT
        static_args = [_slot if isinstance(a, NDArray) else a for a in args]
        block = self

        # mxlint: trace-pure — the whole body is cache-entry bookkeeping
        # that MUST run at trace time (entry.single/n_outputs/aux_params
        # describe the trace; push/pop routes the traced key through the
        # RNG chain for the trace's duration and restores it in finally)
        def traced(key, arg_arrays, param_arrays):
            prev_key = _random.push_trace_key(key)
            saved = [(p, p._data, p._version) for p in param_nds]
            _TRACING.flag = True
            try:
                for p, arr in zip(param_nds, param_arrays):
                    p._data = arr
                arg_it = iter(arg_arrays)
                call_args = [NDArray(next(arg_it), ctx=arg_ctx)
                             if a is _slot else a for a in static_args]
                # enter the args' ctx during the trace: fresh arrays created
                # mid-forward (arange position ids, masks) must carry it, or
                # sub-blocks fed by them fetch params on the ambient default
                trace_ctx = arg_ctx if arg_ctx is not None else current_context()
                with trace_ctx:
                    with autograd._scope(recording=False, training=is_train):
                        out = block._eager_forward(*call_args)
                outs = out if isinstance(out, (list, tuple)) else (out,)
                entry.single = not isinstance(out, (list, tuple))
                entry.n_outputs = len(outs)
                out_arrays = tuple(o._data for o in outs)
                mutated = []
                entry.aux_params = []
                for i, (p, _, _) in enumerate(saved):
                    if p._data is not param_arrays[i]:
                        entry.aux_params.append(i)
                        mutated.append(p._data)
                return out_arrays, tuple(mutated)
            finally:
                for p, old, ver in saved:
                    p._data = old
                    p._version = ver
                _TRACING.flag = False
                _random.pop_trace_key(prev_key)

        from .. import compile as _compile

        # the hybridized forward/backward resolve through the unified
        # executable registry: FLOP accounting, jit_compile events and
        # LRU accounting ride the fill hook (mxnet_tpu.compile.registry)
        label = "cachedop:%s" % type(self).__name__
        entry.jitted = _compile.get_or_build(
            self._cached_key("cachedop_fwd", sig),
            lambda: jax.jit(traced), label=label)

        def bwd(key, arg_arrays, param_arrays, out_cots):
            def pure(a, p):
                o, aux = traced(key, a, p)
                return o

            _, pull = jax.vjp(pure, arg_arrays, param_arrays)
            return pull(tuple(out_cots))

        entry.bwd = _compile.get_or_build(
            self._cached_key("cachedop_bwd", sig),
            lambda: jax.jit(bwd), label="%s:bwd" % label)
        return entry

    def _record_cached(self, entry, key, arg_nds, param_nds, arg_arrays,
                       param_arrays, out_nds):
        from .. import autograd

        inputs = arg_nds + param_nds
        node = autograd._Node(
            None, (), None,
            [(i, i._version) for i in inputs],
            tuple(arg_arrays) + tuple(param_arrays),
            [(o._uid, o._version) for o in out_nds],
            [o.shape for o in out_nds], [o.dtype for o in out_nds])
        n_args = len(arg_arrays)

        def py_backward(cots):
            acots, pcots = entry.bwd(key, tuple(arg_arrays), tuple(param_arrays),
                                     tuple(cots))
            return list(acots) + list(pcots)

        node.py_backward = py_backward
        autograd._st().tape.append(node)

    # -- export ------------------------------------------------------------
    def export(self, path, epoch=0, n_inputs=1, input_names=None):
        """Serialize for deployment (reference: block.py:868 — symbol.json +
        params, reloadable by SymbolBlock.imports / the predict API). The
        symbol json is produced by tracing hybrid_forward with Symbol
        inputs; params are saved under their full names with the
        reference's 'arg:' prefix."""
        from .. import symbol as sym_mod

        if input_names is None:
            input_names = ["data"] if n_inputs == 1 else \
                ["data%d" % i for i in range(n_inputs)]
        inputs = [sym_mod.var(n) for n in input_names]
        out = self(*inputs)
        if isinstance(out, (list, tuple)):
            out = sym_mod.Group(list(out))
        out.save("%s-symbol.json" % path)
        params = self.collect_params()
        aux_names = set(out.list_auxiliary_states())
        # aux states (BatchNorm running stats) carry the aux: prefix so
        # load_params/Predictor bind them as aux, not args (reference
        # format, model.py:394)
        save_dict = {("aux:" if k in aux_names else "arg:") + k: v.data()
                     for k, v in params.items()}
        nd.save("%s-%04d.params" % (path, epoch), save_dict)


import contextlib


@contextlib.contextmanager
def _probe_scope():
    prev = getattr(_TRACING, "probe", False)
    _TRACING.probe = True
    try:
        yield
    finally:
        _TRACING.probe = prev


class SymbolBlock(HybridBlock):
    """Run a symbolic graph as a Block (reference: block.py:952): wraps an
    exported symbol; every non-input argument becomes a Parameter."""

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix="", params=params)
        self._sym_outputs = outputs
        self._inputs = inputs
        input_names = {i.name for i in inputs}
        for name in outputs.list_arguments():
            if name not in input_names:
                self.params.get(name, allow_deferred_init=True)
        for name in outputs.list_auxiliary_states():
            if name not in input_names:
                self.params.get(name, grad_req="null",
                                allow_deferred_init=True)

    @staticmethod
    def imports(symbol_file, input_names, param_file=None, ctx=None):
        from .. import symbol as sym_mod

        symbol = sym_mod.load(symbol_file)
        if isinstance(input_names, str):
            input_names = [input_names]
        inputs = [sym_mod.var(n) for n in input_names]
        ret = SymbolBlock(symbol, inputs)
        if param_file is not None:
            ret.collect_params().load(param_file, ctx=ctx)
        return ret

    def forward(self, *args):
        arg_names = [i.name for i in self._inputs]
        kwargs = dict(zip(arg_names, args))
        params = {name: p.data() for name, p in self.collect_params().items()}
        return self._sym_outputs.eval_with(dict(kwargs, **params))
