"""Mixture-of-experts layers (expert parallelism over the `ep` mesh axis).

Not in the reference — the EP extension SURVEY §2.3 plans for. MoEFFN drops
into a transformer cell where PositionwiseFFN sits; under DistributedTrainer
with an `ep` axis the expert tables shard over `ep` (parallel/sharding.py
names any parameter containing "expert" onto it) and the dispatch/combine
einsums become ICI all_to_alls.
"""
from __future__ import annotations

from ...base import MXNetError
from ..block import HybridBlock

__all__ = ["MoEFFN"]


class MoEFFN(HybridBlock):
    """MoE feed-forward: x (..., units) -> (..., units).

    Default is Switch-style top-1 routing; `num_experts_per_token=k` (>=2)
    switches to GShard/Mixtral-style top-k dispatch (normalized gates,
    capacity `capacity_factor * k * T / E` shared across choices in
    priority order), and `z_loss_coef` (>0, ~1e-3) folds the ST-MoE router
    z-loss into the aux loss.

    Load-balancing aux loss (Switch Transformer, alpha~0.01): in EAGER
    training, read `self.aux_loss` after the forward and add
    `moe.aux_loss * alpha` to the loss. Inside a compiled/traced step
    (hybridize, DistributedTrainer) attribute side-channels would capture
    dead tracers, so construct with `return_aux=True` — the forward then
    returns `(out, aux)` and the training function folds `aux` into its
    loss directly."""

    def __init__(self, units, hidden_size, num_experts,
                 capacity_factor=1.25, return_aux=False,
                 num_experts_per_token=1, z_loss_coef=0.0, **kwargs):
        super().__init__(**kwargs)
        if num_experts < 2:
            raise MXNetError("num_experts must be >= 2")
        if not 1 <= int(num_experts_per_token) <= num_experts:
            raise MXNetError("num_experts_per_token must be in [1, "
                             "num_experts]")
        self._cf = float(capacity_factor)
        self._return_aux = bool(return_aux)
        self._k = int(num_experts_per_token)
        self._z_coef = float(z_loss_coef)
        with self.name_scope():
            self.gate_weight = self.params.get(
                "gate_weight", shape=(num_experts, units))
            self.expert_w_in = self.params.get(
                "expert_w_in", shape=(num_experts, units, hidden_size))
            self.expert_w_out = self.params.get(
                "expert_w_out", shape=(num_experts, hidden_size, units))
        self.aux_loss = None

    def hybrid_forward(self, F, x, gate_weight, expert_w_in, expert_w_out):
        if self._k == 1 and self._z_coef == 0.0:
            out, aux = F.contrib.switch_moe(x, gate_weight, expert_w_in,
                                            expert_w_out,
                                            capacity_factor=self._cf)
        else:
            out, lb, z = F.contrib.topk_moe(x, gate_weight, expert_w_in,
                                            expert_w_out, k=self._k,
                                            capacity_factor=self._cf)
            aux = lb + self._z_coef * z
        if self._return_aux:
            return out, aux
        from ..block import _is_tracing

        if not _is_tracing():
            # concrete eager value only — a traced assignment would leak a
            # dead tracer into later (non-traced) reads
            self.aux_loss = aux
        return out
