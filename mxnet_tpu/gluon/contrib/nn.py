"""Contrib layers (reference: python/mxnet/gluon/contrib/nn/basic_layers.py)."""
from __future__ import annotations

from ...base import MXNetError
from ..nn.basic_layers import BatchNorm, Embedding, HybridBlock
from ... import ndarray as nd

__all__ = ["SyncBatchNorm", "Concurrent", "HybridConcurrent", "Identity",
           "SparseEmbedding", "PixelShuffle1D", "PixelShuffle2D",
           "PixelShuffle3D"]


class SyncBatchNorm(BatchNorm):
    """Cross-device BatchNorm (reference: contrib sync_batch_norm.cc). On TPU
    the distributed trainer computes BN stats under pjit where XLA inserts the
    cross-replica psum automatically when the batch axis is sharded; the
    single-process layer is therefore identical to BatchNorm."""

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9, epsilon=1e-5,
                 center=True, scale=True, use_global_stats=False, **kwargs):
        super().__init__(axis=1, momentum=momentum, epsilon=epsilon, center=center,
                         scale=scale, use_global_stats=use_global_stats,
                         in_channels=in_channels, **kwargs)


class Concurrent(HybridBlock):
    """Parallel branches concatenated (reference: contrib basic_layers)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def _eager_forward(self, x):
        out = [block(x) for block in self._children.values()]
        return nd.concat(*out, dim=self.axis)


HybridConcurrent = Concurrent


class Identity(HybridBlock):
    def hybrid_forward(self, F, x):
        return x


class SparseEmbedding(Embedding):
    """Embedding whose weight gradient is row_sparse (reference: contrib
    basic_layers.py:118 SparseEmbedding, whose point was the
    sparse-storage weight + kvstore row_sparse_pull path). The TPU build's
    nn.Embedding already supports `sparse_grad=True` — this subclass pins
    it on for API parity; the Trainer's lazy row-update path does the rest
    (see nn.Embedding docstring)."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, **kwargs):
        if not kwargs.pop("sparse_grad", True):
            raise MXNetError("SparseEmbedding is sparse_grad by definition; "
                             "use nn.Embedding for a dense gradient")
        super().__init__(input_dim, output_dim, dtype=dtype,
                         weight_initializer=weight_initializer,
                         sparse_grad=True, **kwargs)


def _pixel_shuffle(F, x, factors, dims):
    """(N, C*prod(f), *S) -> (N, C, *(s_i * f_i)): split the factor axes
    out of channels, interleave each next to its spatial axis, merge. Uses
    the reference's reshape codes (0=copy, -1=infer, -4=split, -3=merge —
    basic_layers.py:292) so the graph stays shape-polymorphic: the same
    code traces eagerly, under hybridize, and through the Symbol export
    path; XLA fuses the reshape/transpose chain into neighbors."""
    if dims == 1:
        (f,) = factors
        x = F.reshape(x, shape=(0, -4, -1, f, 0))         # (N, C, f, W)
        x = F.transpose(x, axes=(0, 1, 3, 2))             # (N, C, W, f)
        return F.reshape(x, shape=(0, 0, -3))             # (N, C, W*f)
    if dims == 2:
        f1, f2 = factors
        x = F.reshape(x, shape=(0, -4, -1, f1 * f2, 0, 0))
        x = F.reshape(x, shape=(0, 0, -4, f1, f2, 0, 0))  # (N,C,f1,f2,H,W)
        x = F.transpose(x, axes=(0, 1, 4, 2, 5, 3))       # (N,C,H,f1,W,f2)
        return F.reshape(x, shape=(0, 0, -3, -3))
    f1, f2, f3 = factors
    x = F.reshape(x, shape=(0, -4, -1, f1 * f2 * f3, 0, 0, 0))
    x = F.reshape(x, shape=(0, 0, -4, f1, f2 * f3, 0, 0, 0))
    x = F.reshape(x, shape=(0, 0, 0, -4, f2, f3, 0, 0, 0))
    x = F.transpose(x, axes=(0, 1, 5, 2, 6, 3, 7, 4))     # interleave
    return F.reshape(x, shape=(0, 0, -3, -3, -3))


class PixelShuffle1D(HybridBlock):
    """(N, C*f, W) -> (N, C, W*f) (reference: contrib basic_layers.py:244)."""

    def __init__(self, factor):
        super().__init__()
        self._factors = (int(factor),)

    def hybrid_forward(self, F, x):
        return _pixel_shuffle(F, x, self._factors, 1)


class PixelShuffle2D(HybridBlock):
    """(N, C*f1*f2, H, W) -> (N, C, H*f1, W*f2); scalar or (f1, f2) factor
    (reference: contrib basic_layers.py:292)."""

    def __init__(self, factor):
        super().__init__()
        f = factor if isinstance(factor, (tuple, list)) else (factor,) * 2
        self._factors = tuple(int(v) for v in f)

    def hybrid_forward(self, F, x):
        # NOT depth_to_space: that op splits channels as (f1, f2, C) — DCR,
        # matching the reference's op — while PixelShuffle splits (C, f1,
        # f2), matching the reference layer (basic_layers.py:292). The old
        # fast path silently permuted channels.
        return _pixel_shuffle(F, x, self._factors, 2)


class PixelShuffle3D(HybridBlock):
    """(N, C*f1*f2*f3, D, H, W) -> (N, C, D*f1, H*f2, W*f3) (reference:
    contrib basic_layers.py:354)."""

    def __init__(self, factor):
        super().__init__()
        f = factor if isinstance(factor, (tuple, list)) else (factor,) * 3
        self._factors = tuple(int(v) for v in f)

    def hybrid_forward(self, F, x):
        return _pixel_shuffle(F, x, self._factors, 3)
