"""Contrib layers (reference: python/mxnet/gluon/contrib/nn/basic_layers.py)."""
from __future__ import annotations

from ..nn.basic_layers import BatchNorm, HybridBlock
from ... import ndarray as nd

__all__ = ["SyncBatchNorm", "Concurrent", "HybridConcurrent", "Identity",
           "PixelShuffle2D"]


class SyncBatchNorm(BatchNorm):
    """Cross-device BatchNorm (reference: contrib sync_batch_norm.cc). On TPU
    the distributed trainer computes BN stats under pjit where XLA inserts the
    cross-replica psum automatically when the batch axis is sharded; the
    single-process layer is therefore identical to BatchNorm."""

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9, epsilon=1e-5,
                 center=True, scale=True, use_global_stats=False, **kwargs):
        super().__init__(axis=1, momentum=momentum, epsilon=epsilon, center=center,
                         scale=scale, use_global_stats=use_global_stats,
                         in_channels=in_channels, **kwargs)


class Concurrent(HybridBlock):
    """Parallel branches concatenated (reference: contrib basic_layers)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def _eager_forward(self, x):
        out = [block(x) for block in self._children.values()]
        return nd.concat(*out, dim=self.axis)


HybridConcurrent = Concurrent


class Identity(HybridBlock):
    def hybrid_forward(self, F, x):
        return x


class PixelShuffle2D(HybridBlock):
    def __init__(self, factor):
        super().__init__()
        self._factor = int(factor)

    def hybrid_forward(self, F, x):
        return F.depth_to_space(x, block_size=self._factor)
