"""Contrib RNN cells (reference: python/mxnet/gluon/contrib/rnn)."""
from __future__ import annotations

from ..rnn.rnn_cell import ModifierCell, RecurrentCell
from ... import ndarray as nd

__all__ = ["VariationalDropoutCell", "Conv2DLSTMCell"]


class VariationalDropoutCell(ModifierCell):
    """Same dropout mask across time steps (reference: contrib/rnn/rnn_cell.py)."""

    def __init__(self, base_cell, drop_inputs=0.0, drop_states=0.0, drop_outputs=0.0):
        super().__init__(base_cell)
        self.drop_inputs = drop_inputs
        self.drop_states = drop_states
        self.drop_outputs = drop_outputs
        self._input_mask = None
        self._state_mask = None
        self._output_mask = None

    def _alias(self):
        return "vardrop"

    def reset(self):
        super().reset()
        self._input_mask = None
        self._state_mask = None
        self._output_mask = None

    def _mask(self, p, like):
        return nd.Dropout(nd.ones_like(like), p=p, mode="always")

    def __call__(self, inputs, states):
        if self.drop_inputs:
            if self._input_mask is None:
                self._input_mask = self._mask(self.drop_inputs, inputs)
            inputs = inputs * self._input_mask
        if self.drop_states:
            if self._state_mask is None:
                self._state_mask = self._mask(self.drop_states, states[0])
            states = [s * self._state_mask for s in states]
        out, next_states = self.base_cell(inputs, states)
        if self.drop_outputs:
            if self._output_mask is None:
                self._output_mask = self._mask(self.drop_outputs, out)
            out = out * self._output_mask
        return out, next_states


class Conv2DLSTMCell(RecurrentCell):
    """ConvLSTM (reference: contrib/rnn/conv_rnn_cell.py)."""

    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 i2h_pad=(0, 0), prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._input_shape = input_shape
        self._hc = hidden_channels
        k = i2h_kernel if isinstance(i2h_kernel, tuple) else (i2h_kernel,) * 2
        hk = h2h_kernel if isinstance(h2h_kernel, tuple) else (h2h_kernel,) * 2
        self._i2h_kernel, self._h2h_kernel = k, hk
        self._i2h_pad = i2h_pad
        self._h2h_pad = (hk[0] // 2, hk[1] // 2)
        in_c = input_shape[0]
        with self.name_scope():
            self.i2h_weight = self.params.get("i2h_weight",
                                              shape=(4 * hidden_channels, in_c) + k)
            self.h2h_weight = self.params.get("h2h_weight",
                                              shape=(4 * hidden_channels, hidden_channels) + hk)
            self.i2h_bias = self.params.get("i2h_bias", shape=(4 * hidden_channels,),
                                            init="zeros")
            self.h2h_bias = self.params.get("h2h_bias", shape=(4 * hidden_channels,),
                                            init="zeros")

    def state_info(self, batch_size=0):
        c, h, w = self._input_shape
        oh = (h + 2 * self._i2h_pad[0] - self._i2h_kernel[0]) + 1
        ow = (w + 2 * self._i2h_pad[1] - self._i2h_kernel[1]) + 1
        shape = (batch_size, self._hc, oh, ow)
        return [{"shape": shape, "__layout__": "NCHW"},
                {"shape": shape, "__layout__": "NCHW"}]

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight, i2h_bias,
                       h2h_bias):
        i2h = F.Convolution(inputs, i2h_weight, i2h_bias,
                            kernel=self._i2h_kernel, pad=self._i2h_pad,
                            num_filter=4 * self._hc)
        h2h = F.Convolution(states[0], h2h_weight, h2h_bias,
                            kernel=self._h2h_kernel, pad=self._h2h_pad,
                            num_filter=4 * self._hc)
        gates = i2h + h2h
        sg = F.split(gates, num_outputs=4, axis=1)
        i = F.sigmoid(sg[0])
        f = F.sigmoid(sg[1])
        g = F.tanh(sg[2])
        o = F.sigmoid(sg[3])
        next_c = f * states[1] + i * g
        next_h = o * F.tanh(next_c)
        return next_h, [next_h, next_c]
