"""Contrib RNN cells (reference: python/mxnet/gluon/contrib/rnn — the
VariationalDropoutCell/LSTMPCell of rnn_cell.py and the
Conv{1,2,3}D{RNN,LSTM,GRU}Cell family of conv_rnn_cell.py, rebuilt on this
package's Convolution op so every step is one fused XLA program; the
recurrence itself unrolls/scans via the base-cell machinery)."""
from __future__ import annotations

from ...base import MXNetError
from ..rnn.rnn_cell import ModifierCell, RecurrentCell
from ... import ndarray as nd

__all__ = ["VariationalDropoutCell", "LSTMPCell", "dynamic_unroll",
           "Conv1DRNNCell", "Conv2DRNNCell", "Conv3DRNNCell",
           "Conv1DLSTMCell", "Conv2DLSTMCell", "Conv3DLSTMCell",
           "Conv1DGRUCell", "Conv2DGRUCell", "Conv3DGRUCell"]


class VariationalDropoutCell(ModifierCell):
    """Same dropout mask across time steps (reference: contrib/rnn/rnn_cell.py)."""

    def __init__(self, base_cell, drop_inputs=0.0, drop_states=0.0, drop_outputs=0.0):
        super().__init__(base_cell)
        self.drop_inputs = drop_inputs
        self.drop_states = drop_states
        self.drop_outputs = drop_outputs
        self._input_mask = None
        self._state_mask = None
        self._output_mask = None

    def _alias(self):
        return "vardrop"

    def reset(self):
        super().reset()
        self._input_mask = None
        self._state_mask = None
        self._output_mask = None

    def _mask(self, p, like):
        return nd.Dropout(nd.ones_like(like), p=p, mode="always")

    def __call__(self, inputs, states):
        if self.drop_inputs:
            if self._input_mask is None:
                self._input_mask = self._mask(self.drop_inputs, inputs)
            inputs = inputs * self._input_mask
        if self.drop_states:
            if self._state_mask is None:
                self._state_mask = self._mask(self.drop_states, states[0])
            states = [s * self._state_mask for s in states]
        out, next_states = self.base_cell(inputs, states)
        if self.drop_outputs:
            if self._output_mask is None:
                self._output_mask = self._mask(self.drop_outputs, out)
            out = out * self._output_mask
        return out, next_states


class LSTMPCell(RecurrentCell):
    """LSTM with a projected recurrent state (reference: contrib/rnn/
    rnn_cell.py:198, the LSTMP of arXiv:1402.1128): gates see the
    `projection_size` recurrent vector r instead of the full hidden h, and
    r = h2r(next_h) after every step — cuts h2h FLOPs/params for large
    hidden sizes. States: [r (B, proj), c (B, hidden)]."""

    def __init__(self, hidden_size, projection_size,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 h2r_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = int(hidden_size)
        self._projection_size = int(projection_size)
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(4 * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(4 * hidden_size, projection_size),
                init=h2h_weight_initializer)
            self.h2r_weight = self.params.get(
                "h2r_weight", shape=(projection_size, hidden_size),
                init=h2r_weight_initializer)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(4 * hidden_size,),
                init=i2h_bias_initializer)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(4 * hidden_size,),
                init=h2h_bias_initializer)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._projection_size),
                 "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "lstmp"

    def _shape_hook(self, x, *a):
        if self.i2h_weight.shape[1] == 0:
            self.i2h_weight.shape = (self.i2h_weight.shape[0], x.shape[-1])

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       h2r_weight, i2h_bias, h2h_bias):
        r, c = states
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=4 * self._hidden_size)
        h2h = F.FullyConnected(r, h2h_weight, h2h_bias,
                               num_hidden=4 * self._hidden_size)
        gates = i2h + h2h
        sg = F.split(gates, num_outputs=4, axis=1)
        i = F.sigmoid(sg[0])
        f = F.sigmoid(sg[1])
        g = F.tanh(sg[2])
        o = F.sigmoid(sg[3])
        next_c = f * c + i * g
        next_h = o * F.tanh(next_c)
        next_r = F.FullyConnected(next_h, h2r_weight, None, no_bias=True,
                                  num_hidden=self._projection_size)
        return next_r, [next_r, next_c]


def dynamic_unroll(cell, inputs, begin_state, drop_inputs=0, drop_outputs=0,
                   layout="TNC", valid_length=None):
    """Unroll `cell` over a sequence whose length is the DATA's time
    dimension (reference: contrib/rnn/rnn_cell.py:326 dynamic_unroll, which
    lowers to while_loop). Accepts merged (T,N,C)/(N,T,C) input, applies
    optional input/output dropout, masks outputs past `valid_length`, and
    returns (outputs merged in `layout`, final states at each sequence's
    valid end)."""
    axis = layout.find("T")
    if axis not in (0, 1):
        raise MXNetError("dynamic_unroll: layout must contain T in "
                         "position 0 or 1, got %r" % layout)
    if drop_inputs:
        inputs = nd.Dropout(inputs, p=drop_inputs,
                            axes=(axis,))  # same mask every step
    length = inputs.shape[axis]
    outputs, states = cell.unroll(length, inputs, begin_state=begin_state,
                                  layout=layout, merge_outputs=True,
                                  valid_length=valid_length)
    if drop_outputs:
        outputs = nd.Dropout(outputs, p=drop_outputs, axes=(axis,))
    return outputs, states


# ---------------------------------------------------------------------------
# Convolutional recurrent cells (reference: contrib/rnn/conv_rnn_cell.py).
# One base handles every spatial rank; subclasses pin rank + recurrence.
# ---------------------------------------------------------------------------

def _tuple(v, n):
    if isinstance(v, (tuple, list)):
        if len(v) != n:
            raise MXNetError("expected %d values, got %s" % (n, v))
        return tuple(int(x) for x in v)
    return (int(v),) * n


class _BaseConvRNNCell(RecurrentCell):
    """Shared conv-recurrence plumbing: an input conv (geometry from
    `input_shape`, user stride/pad/dilation) plus a 'same'-padded hidden
    conv, both emitting `num_gates * hidden_channels` feature maps."""

    _num_gates = None  # subclass

    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 i2h_pad=0, i2h_dilate=1, h2h_dilate=1, activation="tanh",
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 prefix=None, params=None, conv_layout="NCHW"):
        super().__init__(prefix=prefix, params=params)
        dims = len(input_shape) - 1
        if conv_layout != "NC" + "DHW"[3 - dims:]:
            raise MXNetError("only channel-first conv_layout is supported "
                             "(got %r)" % conv_layout)
        self._dims = dims
        self._input_shape = tuple(input_shape)
        self._hc = int(hidden_channels)
        self._activation = activation
        self._i2h_kernel = _tuple(i2h_kernel, dims)
        self._h2h_kernel = _tuple(h2h_kernel, dims)
        for k in self._h2h_kernel:
            if k % 2 == 0:
                raise MXNetError("h2h_kernel must be odd for 'same' "
                                 "padding, got %s" % (self._h2h_kernel,))
        self._i2h_pad = _tuple(i2h_pad, dims)
        self._i2h_dilate = _tuple(i2h_dilate, dims)
        self._h2h_dilate = _tuple(h2h_dilate, dims)
        self._h2h_pad = tuple(d * (k - 1) // 2 for k, d in
                              zip(self._h2h_kernel, self._h2h_dilate))
        in_c = input_shape[0]
        ng = self._num_gates
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(ng * self._hc, in_c) + self._i2h_kernel,
                init=i2h_weight_initializer)
            self.h2h_weight = self.params.get(
                "h2h_weight",
                shape=(ng * self._hc, self._hc) + self._h2h_kernel,
                init=h2h_weight_initializer)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(ng * self._hc,),
                init=i2h_bias_initializer)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(ng * self._hc,),
                init=h2h_bias_initializer)

    def _state_shape(self, batch_size):
        spatial = self._input_shape[1:]
        out = tuple(
            (s + 2 * p - d * (k - 1) - 1) + 1
            for s, p, d, k in zip(spatial, self._i2h_pad, self._i2h_dilate,
                                  self._i2h_kernel))
        return (batch_size, self._hc) + out

    def state_info(self, batch_size=0):
        shape = self._state_shape(batch_size)
        layout = "NC" + "DHW"[3 - self._dims:]
        return [{"shape": shape, "__layout__": layout}
                for _ in range(len(self._state_names))]

    def _convs(self, F, inputs, h, i2h_weight, h2h_weight, i2h_bias,
               h2h_bias):
        ng = self._num_gates
        i2h = F.Convolution(inputs, i2h_weight, i2h_bias,
                            kernel=self._i2h_kernel, pad=self._i2h_pad,
                            dilate=self._i2h_dilate,
                            num_filter=ng * self._hc)
        h2h = F.Convolution(h, h2h_weight, h2h_bias,
                            kernel=self._h2h_kernel, pad=self._h2h_pad,
                            dilate=self._h2h_dilate,
                            num_filter=ng * self._hc)
        return i2h, h2h


class _ConvRNNCell(_BaseConvRNNCell):
    """out = act(conv(x) + conv(h)); states: [h]."""

    _num_gates = 1
    _state_names = ("h",)

    def _alias(self):
        return "conv_rnn"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._convs(F, inputs, states[0], i2h_weight, h2h_weight,
                               i2h_bias, h2h_bias)
        out = self._get_activation(F, i2h + h2h, self._activation)
        return out, [out]


class _ConvLSTMCell(_BaseConvRNNCell):
    """Shi et al. 2015 ConvLSTM; states: [h, c]."""

    _num_gates = 4
    _state_names = ("h", "c")

    def _alias(self):
        return "conv_lstm"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._convs(F, inputs, states[0], i2h_weight, h2h_weight,
                               i2h_bias, h2h_bias)
        sg = F.split(i2h + h2h, num_outputs=4, axis=1)
        i = F.sigmoid(sg[0])
        f = F.sigmoid(sg[1])
        g = self._get_activation(F, sg[2], self._activation)
        o = F.sigmoid(sg[3])
        next_c = f * states[1] + i * g
        next_h = o * self._get_activation(F, next_c, self._activation)
        return next_h, [next_h, next_c]


class _ConvGRUCell(_BaseConvRNNCell):
    """Conv GRU; reset gate modulates the hidden conv's candidate chunk;
    states: [h]."""

    _num_gates = 3
    _state_names = ("h",)

    def _alias(self):
        return "conv_gru"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._convs(F, inputs, states[0], i2h_weight, h2h_weight,
                               i2h_bias, h2h_bias)
        ir, iz, inw = F.split(i2h, num_outputs=3, axis=1)
        hr, hz, hnw = F.split(h2h, num_outputs=3, axis=1)
        r = F.sigmoid(ir + hr)
        z = F.sigmoid(iz + hz)
        n = self._get_activation(F, inw + r * hnw, self._activation)
        next_h = (1.0 - z) * n + z * states[0]
        return next_h, [next_h]


def _make_cell(base, dims, name, doc):
    # positional order matches the reference cells exactly
    # (conv_rnn_cell.py Conv1DRNNCell.__init__ et al.), so reference-
    # positional construction binds every argument correctly
    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 i2h_pad=0, i2h_dilate=1, h2h_dilate=1,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 conv_layout="NC" + "DHW"[3 - dims:], activation="tanh",
                 prefix=None, params=None):
        if len(input_shape) != dims + 1:
            raise MXNetError("%s expects input_shape (C%s), got %s"
                             % (name, ", " + ", ".join("DHW"[3 - dims:]),
                                input_shape))
        base.__init__(self, input_shape, hidden_channels, i2h_kernel,
                      h2h_kernel, i2h_pad=i2h_pad, i2h_dilate=i2h_dilate,
                      h2h_dilate=h2h_dilate, activation=activation,
                      i2h_weight_initializer=i2h_weight_initializer,
                      h2h_weight_initializer=h2h_weight_initializer,
                      i2h_bias_initializer=i2h_bias_initializer,
                      h2h_bias_initializer=h2h_bias_initializer,
                      conv_layout=conv_layout, prefix=prefix, params=params)

    return type(name, (base,), {"__init__": __init__, "__doc__": doc})


_DOC = ("%dD %s cell over feature maps (reference: contrib/rnn/"
        "conv_rnn_cell.py %s): recurrence where every dense matmul is a "
        "convolution, preserving spatial structure in the state.")

Conv1DRNNCell = _make_cell(_ConvRNNCell, 1, "Conv1DRNNCell",
                           _DOC % (1, "RNN", "Conv1DRNNCell"))
Conv2DRNNCell = _make_cell(_ConvRNNCell, 2, "Conv2DRNNCell",
                           _DOC % (2, "RNN", "Conv2DRNNCell"))
Conv3DRNNCell = _make_cell(_ConvRNNCell, 3, "Conv3DRNNCell",
                           _DOC % (3, "RNN", "Conv3DRNNCell"))
Conv1DLSTMCell = _make_cell(_ConvLSTMCell, 1, "Conv1DLSTMCell",
                            _DOC % (1, "LSTM", "Conv1DLSTMCell"))
Conv2DLSTMCell = _make_cell(_ConvLSTMCell, 2, "Conv2DLSTMCell",
                            _DOC % (2, "LSTM", "Conv2DLSTMCell"))
Conv3DLSTMCell = _make_cell(_ConvLSTMCell, 3, "Conv3DLSTMCell",
                            _DOC % (3, "LSTM", "Conv3DLSTMCell"))
Conv1DGRUCell = _make_cell(_ConvGRUCell, 1, "Conv1DGRUCell",
                           _DOC % (1, "GRU", "Conv1DGRUCell"))
Conv2DGRUCell = _make_cell(_ConvGRUCell, 2, "Conv2DGRUCell",
                           _DOC % (2, "GRU", "Conv2DGRUCell"))
Conv3DGRUCell = _make_cell(_ConvGRUCell, 3, "Conv3DGRUCell",
                           _DOC % (3, "GRU", "Conv3DGRUCell"))
