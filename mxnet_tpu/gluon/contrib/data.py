"""Contrib datasets & samplers (reference: python/mxnet/gluon/contrib/data —
sampler.py IntervalSampler, text.py WikiText2/WikiText103). Like the
in-tree vision datasets, the text corpora read pre-downloaded files from
`root` (this build runs without network egress) and raise a clear error
otherwise; file formats match the reference's extracted archives."""
from __future__ import annotations

import os

import numpy as _np

from ...base import MXNetError
from .. import data as _gdata
from ... import ndarray as nd

__all__ = ["IntervalSampler", "WikiText2", "WikiText103"]

EOS_TOKEN = "<eos>"


class IntervalSampler(_gdata.Sampler):
    """Samples [0, length) at fixed strides (reference: contrib/data/
    sampler.py:25): 0, k, 2k, ...; with `rollover` it restarts from each
    skipped offset until every index is visited exactly once."""

    def __init__(self, length, interval, rollover=True):
        if not 1 <= interval <= length:
            raise MXNetError("interval %d must be in [1, length=%d]"
                             % (interval, length))
        self._length = length
        self._interval = interval
        self._rollover = rollover

    def __iter__(self):
        for i in range(self._interval if self._rollover else 1):
            yield from range(i, self._length, self._interval)

    def __len__(self):
        if self._rollover:
            return self._length
        return len(range(0, self._length, self._interval))


class _WikiText(_gdata.Dataset):
    """Word-level LM dataset over an extracted WikiText token file: one
    long token stream (EOS appended per line), indexed into (seq_len,)
    data/label windows shifted by one token (reference: contrib/data/
    text.py:58)."""

    _filename = None  # subclass: {segment: file name}

    def __init__(self, root, segment="train", vocab=None, seq_len=35):
        self._root = os.path.expanduser(root)
        self._segment = segment
        self._seq_len = seq_len
        if segment not in self._filename:
            raise MXNetError("segment must be one of %s"
                             % sorted(self._filename))
        path = os.path.join(self._root, self._filename[segment])
        if not os.path.exists(path):
            raise MXNetError(
                "%s not found. This build has no network egress: download "
                "the %s archive yourself and extract its token files into "
                "%r (reference layout)." % (path, type(self).__name__,
                                            self._root))
        with open(path, encoding="utf8") as f:
            content = f.read()
        tokens = []
        for line in content.splitlines():
            words = line.strip().split()
            if words:
                tokens.extend(words)
                tokens.append(EOS_TOKEN)
        if vocab is None:
            import collections

            from ...contrib.text import Vocabulary

            vocab = Vocabulary(collections.Counter(tokens))
        self.vocabulary = vocab
        idx = _np.asarray(vocab.to_indices(tokens), dtype=_np.int32)
        n = (len(idx) - 1) // seq_len
        self._data = idx[:n * seq_len].reshape(n, seq_len)
        self._label = idx[1:n * seq_len + 1].reshape(n, seq_len)

    def __getitem__(self, i):
        from ...base import HOST_ARRAY_MODE

        d, l = self._data[i], self._label[i]
        if HOST_ARRAY_MODE:
            return d, l
        return nd.array(d, dtype="int32"), nd.array(l, dtype="int32")

    def __len__(self):
        return len(self._label)


class WikiText2(_WikiText):
    """reference: contrib/data/text.py:105 (wiki.{train,valid,test}.tokens)."""

    _filename = {"train": "wiki.train.tokens",
                 "validation": "wiki.valid.tokens",
                 "test": "wiki.test.tokens"}


class WikiText103(_WikiText):
    """reference: contrib/data/text.py:143 (same layout, 103M-token corpus)."""

    _filename = WikiText2._filename
