"""DataLoader.

Reference: python/mxnet/gluon/data/dataloader.py:98-120 — multi-worker loader
feeding shared-memory NDArrays. TPU-native: workers are a thread pool doing
host-side decode/augment into numpy, with a prefetch queue that overlaps the
pipeline with device steps (PJRT transfers are async); there is no fork+shm
dance because buffers go straight to device via device_put. A
`num_workers>0` therefore means prefetch depth here."""
from __future__ import annotations

import queue
import threading

import numpy as _np

from ... import ndarray as nd
from ...base import MXNetError
from .sampler import BatchSampler, RandomSampler, SequentialSampler

__all__ = ["DataLoader", "default_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (reference: dataloader.py default_batchify_fn)."""
    if isinstance(data[0], nd.NDArray):
        return nd.stack(*data, axis=0)
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(i) for i in data]
    data = _np.asarray(data)
    return nd.array(data, dtype=data.dtype if data.dtype != _np.float64 else "float32")


class DataLoader:
    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None,
                 thread_pool=False):
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise MXNetError("batch_size required when batch_sampler is None")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle \
                    else SequentialSampler(len(dataset))
            elif shuffle:
                raise MXNetError("shuffle must be False with custom sampler")
            batch_sampler = BatchSampler(sampler, batch_size, last_batch or "keep")
        elif (batch_size is not None or shuffle or sampler is not None
              or last_batch is not None):
            raise MXNetError("batch_size/shuffle/sampler/last_batch incompatible "
                             "with batch_sampler")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = max(0, num_workers)
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers)

    def __len__(self):
        return len(self._batch_sampler)

    def _load(self, batch_indices):
        return self._batchify_fn([self._dataset[i] for i in batch_indices])

    def __iter__(self):
        if self._num_workers == 0:
            for batch in self._batch_sampler:
                yield self._load(batch)
            return
        # threaded prefetch pipeline
        q = queue.Queue(maxsize=self._prefetch or 2)
        sentinel = object()

        def producer():
            try:
                for batch in self._batch_sampler:
                    q.put(self._load(batch))
            except Exception as e:  # propagate worker errors
                q.put(e)
            finally:
                q.put(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is sentinel:
                break
            if isinstance(item, Exception):
                raise item
            yield item
        t.join()
