"""DataLoader.

Reference: python/mxnet/gluon/data/dataloader.py:98-120 — multi-worker loader
feeding shared-memory NDArrays. TPU-native equivalent:

- `num_workers>0` runs decode/augment in worker *processes* (the reference's
  design: Python-side JPEG decode + augmentation is GIL-bound, so threads
  cannot scale it), returning batches through POSIX shared memory
  (multiprocessing.shared_memory — the reference's cpu_shared_storage_manager
  role). The parent wraps the segment, uploads to device (device_put copies
  anyway), and unlinks.
- Workers default to the *fork* context (like the reference; spawn and
  forkserver both re-import the user's __main__, breaking unguarded
  scripts). A forked child can never run jax (the inherited PJRT client's
  threadpool does not survive fork), so workers run in HOST_ARRAY_MODE:
  decode/dataset stages return plain numpy and the whole per-sample path
  stays host-pure. At pool creation the dataset is probed once in host mode;
  if its __getitem__ still yields device arrays (e.g. a jax-backed
  transform), the loader logs a warning and falls back to the threaded
  prefetcher instead of deadlocking. `ctx="spawn"` is available for
  datasets that need a fresh interpreter (requires the standard
  `if __name__ == "__main__"` guard).
- `thread_pool=True` keeps the round-1 threaded prefetcher (useful when the
  dataset is already numpy and pickling would dominate).
"""
from __future__ import annotations

import os
import pickle

import numpy as _np

from ... import ndarray as nd
from ...base import MXNetError
from .sampler import BatchSampler, RandomSampler, SequentialSampler

__all__ = ["DataLoader", "default_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (reference: dataloader.py default_batchify_fn)."""
    if isinstance(data[0], nd.NDArray):
        return nd.stack(*data, axis=0)
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(i) for i in data]
    data = _np.asarray(data)
    return nd.array(data, dtype=data.dtype if data.dtype != _np.float64 else "float32")


# ---------------------------------------------------------------------------
# worker-process machinery
# ---------------------------------------------------------------------------

def _np_batchify(data):
    """Worker-side batchify: same stacking as default_batchify_fn but
    producing plain numpy (workers never hand jax buffers across the
    process boundary)."""
    first = data[0]
    if isinstance(first, nd.NDArray):
        return _np.stack([d.asnumpy() for d in data])
    if isinstance(first, tuple):
        return tuple(_np_batchify(list(f)) for f in zip(*data))
    if isinstance(first, list):
        return tuple(_np_batchify(list(f)) for f in zip(*data))
    arr = _np.asarray(data)
    if arr.dtype == _np.float64:
        arr = arr.astype(_np.float32)
    return arr


def _to_shm(obj, segments):
    """Replace numpy arrays in a (possibly nested tuple) batch with
    shared-memory descriptors; created segments collect into `segments`."""
    from multiprocessing import shared_memory

    if isinstance(obj, tuple):
        return tuple(_to_shm(o, segments) for o in obj)
    assert isinstance(obj, _np.ndarray)
    if obj.nbytes == 0:
        return ("__nd0__", obj.shape, obj.dtype.str, None)
    shm = shared_memory.SharedMemory(create=True, size=obj.nbytes)
    view = _np.ndarray(obj.shape, dtype=obj.dtype, buffer=shm.buf)
    view[:] = obj
    # ownership transfers to the parent (which unlinks after upload); drop
    # this process's resource_tracker registration or its exit handler
    # double-unlinks and spams warnings
    try:
        from multiprocessing import resource_tracker

        resource_tracker.unregister(shm._name, "shared_memory")
    except Exception:
        pass
    segments.append(shm)
    return ("__nd__", obj.shape, obj.dtype.str, shm.name)


def _from_shm(desc):
    """Parent-side: materialize NDArrays from shm descriptors and release
    the segments."""
    from multiprocessing import shared_memory

    if isinstance(desc, tuple) and len(desc) == 4 and \
            desc[0] in ("__nd__", "__nd0__"):
        tag, shape, dtype, name = desc
        if tag == "__nd0__":
            return nd.array(_np.empty(shape, _np.dtype(dtype)))
        shm = shared_memory.SharedMemory(name=name)
        try:
            view = _np.ndarray(shape, dtype=_np.dtype(dtype), buffer=shm.buf)
            # owned host copy BEFORE unlinking: jax's CPU backend may alias
            # the numpy buffer zero-copy, and unmapping the segment under a
            # live alias segfaults later
            out = nd.array(_np.array(view))
        finally:
            shm.close()
            shm.unlink()
        return out
    return [_from_shm(d) for d in desc]


def _unlink_desc(desc):
    """Release shm segments of an unconsumed batch."""
    from multiprocessing import shared_memory

    if isinstance(desc, tuple) and len(desc) == 4 and \
            desc[0] in ("__nd__", "__nd0__"):
        if desc[3] is not None:
            try:
                shm = shared_memory.SharedMemory(name=desc[3])
                shm.close()
                shm.unlink()
            except Exception:
                pass
        return
    for d in desc:
        _unlink_desc(d)


_WORKER_DATASET = None
_WORKER_BATCHIFY = None


def _worker_initializer(dataset_bytes, batchify_bytes):
    """Runs once in each worker process."""
    import os

    from ... import base as _base

    os.environ["JAX_PLATFORMS"] = "cpu"  # data workers never own a TPU
    _base.HOST_ARRAY_MODE = True        # decode/dataset stages stay numpy
    global _WORKER_DATASET, _WORKER_BATCHIFY
    _WORKER_DATASET = pickle.loads(dataset_bytes)
    _WORKER_BATCHIFY = pickle.loads(batchify_bytes) if batchify_bytes \
        else None


def _has_nd(x):
    if isinstance(x, nd.NDArray):
        return True
    if isinstance(x, (tuple, list)):
        return any(_has_nd(i) for i in x)
    return False


def _worker_probe():
    """Runs INSIDE a worker: fetch one sample and report host-purity. A
    dataset whose __getitem__ needs jax either returns NDArray leaves
    (reported False) or hangs on the forked runtime (caught by the parent's
    result timeout)."""
    try:
        return not _has_nd(_WORKER_DATASET[0])
    except Exception:
        return False


def _host_safe_probe(dataset, pool_factory, timeout=None):
    """True iff the dataset is picklable and one sample round-trips through
    a real worker process without producing device arrays, hanging, or
    raising. The probe runs in the worker itself (never toggling parent
    state — other threads may be decoding concurrently); a worker that
    deadlocks on the forked jax runtime is caught by the timeout
    (MXTPU_DATALOADER_PROBE_TIMEOUT, default 20s — the legit probe path
    touches no jax and returns in well under a second)."""
    if timeout is None:
        from ... import env as _env

        timeout = _env.get("MXTPU_DATALOADER_PROBE_TIMEOUT")
    try:
        pickle.dumps(dataset)
    except Exception:
        return False, None
    pool = pool_factory()
    try:
        ok = bool(pool.apply_async(_worker_probe).get(timeout=timeout))
    except Exception:
        ok = False
    if not ok:
        try:
            pool.terminate()
        except Exception:
            pass
        pool = None
    return ok, pool


def _worker_fn(indices):
    samples = [_WORKER_DATASET[i] for i in indices]
    if _WORKER_BATCHIFY is not None:
        batch = _WORKER_BATCHIFY(samples)
        # custom fn may return NDArray(s); flatten to numpy for shm
        def to_np(b):
            if isinstance(b, nd.NDArray):
                return b.asnumpy()
            if isinstance(b, (list, tuple)):
                return tuple(to_np(x) for x in b)
            return _np.asarray(b)
        batch = to_np(batch)
    else:
        batch = _np_batchify(samples)
    segments = []
    desc = _to_shm(batch if isinstance(batch, tuple) else (batch,), segments)
    single = not isinstance(batch, tuple)
    for s in segments:
        s.close()  # parent unlinks
    return single, desc


class _MultiWorkerIter:
    """Ordered async iterator over a process pool (reference:
    dataloader.py _MultiWorkerIter — pushes 2*num_workers tasks ahead,
    yields strictly in batch order)."""

    def __init__(self, pool, batch_sampler, prefetch):
        self._pool = pool
        self._batches = iter(batch_sampler)
        self._pending = {}
        self._sent = 0
        self._recv = 0
        self._exhausted = False
        for _ in range(max(1, prefetch)):
            self._push_next()

    def _push_next(self):
        if self._exhausted:
            return
        try:
            batch = next(self._batches)
        except StopIteration:
            self._exhausted = True
            return
        self._pending[self._sent] = self._pool.apply_async(
            _worker_fn, (list(batch),))
        self._sent += 1

    def __iter__(self):
        return self

    def __next__(self):
        if self._recv == self._sent and self._exhausted:
            raise StopIteration
        result = self._pending.pop(self._recv)
        self._recv += 1
        self._push_next()
        # bounded wait: a worker killed mid-task (OOM, native segfault)
        # leaves its AsyncResult forever pending — surface an error instead
        # of hanging the training loop
        from ... import env as _env

        timeout = _env.get("MXTPU_DATALOADER_TIMEOUT")
        try:
            single, desc = result.get(timeout=timeout)
        except Exception as e:
            self.close()
            raise MXNetError(
                "DataLoader worker batch did not arrive within %.0fs "
                "(worker died or is stuck; raise MXTPU_DATALOADER_TIMEOUT "
                "for very slow pipelines): %r" % (timeout, e)) from e
        out = _from_shm(desc)
        return out[0] if single else out

    def close(self):
        """Unlink segments of batches that were produced but never
        consumed — an abandoned iterator (break mid-epoch) must not leak
        /dev/shm (workers deliberately unregister from their
        resource_tracker because ownership passes to the parent)."""
        self._exhausted = True
        for idx in sorted(self._pending):
            result = self._pending.pop(idx)
            try:
                _, desc = result.get(timeout=30)
                _unlink_desc(desc)
            except Exception:
                pass

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class DataLoader:
    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None,
                 thread_pool=False, ctx=None):
        from ... import env as _env

        self._mp_ctx = ctx or _env.get("MXTPU_DATALOADER_CTX")
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise MXNetError("batch_size required when batch_sampler is None")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle \
                    else SequentialSampler(len(dataset))
            elif shuffle:
                raise MXNetError("shuffle must be False with custom sampler")
            batch_sampler = BatchSampler(sampler, batch_size, last_batch or "keep")
        elif (batch_size is not None or shuffle or sampler is not None
              or last_batch is not None):
            raise MXNetError("batch_size/shuffle/sampler/last_batch incompatible "
                             "with batch_sampler")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._custom_batchify = batchify_fn
        self._num_workers = max(0, num_workers)
        self._thread_pool = thread_pool
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers)
        self._pool = None
        self._host_safe = None

    def __len__(self):
        return len(self._batch_sampler)

    def _load(self, batch_indices):
        return self._batchify_fn([self._dataset[i] for i in batch_indices])

    def _make_pool(self):
        import multiprocessing as mp

        ctx = mp.get_context(self._mp_ctx)
        return ctx.Pool(
            self._num_workers, initializer=_worker_initializer,
            initargs=(pickle.dumps(self._dataset),
                      pickle.dumps(self._custom_batchify)
                      if self._custom_batchify else b""))

    def _get_pool(self):
        if self._pool is None:
            import atexit

            self._pool = self._make_pool()
            # terminate at exit while the interpreter is intact — letting
            # the GC find the pool during teardown trips Pool.__del__ noise
            atexit.register(self._pool.terminate)
        return self._pool

    def __del__(self):
        try:
            if self._pool is not None:
                self._pool.terminate()
        except Exception:
            pass  # interpreter teardown: pool internals may already be gone

    def __iter__(self):
        """Instrumented front: yields from the real iterator while feeding
        the telemetry wait-vs-compute split — seconds this consumer spent
        BLOCKED on batch production vs. seconds it held the batch (its own
        step compute) between `next` calls. A starved accelerator shows up
        as wait >> compute."""
        import time as _time

        from ... import telemetry

        tm_wait = telemetry.counter("mxtpu_data_wait_seconds_total",
                                    {"src": "dataloader"})
        tm_compute = telemetry.counter("mxtpu_data_compute_seconds_total",
                                       {"src": "dataloader"})
        tm_batches = telemetry.counter("mxtpu_data_batches_total",
                                       {"src": "dataloader"})
        inner = self._iter_raw()
        while True:
            t0 = _time.perf_counter()
            try:
                batch = next(inner)
            except StopIteration:
                return
            t1 = _time.perf_counter()
            tm_wait.inc(t1 - t0)
            tm_batches.inc()
            yield batch
            tm_compute.inc(_time.perf_counter() - t1)

    def _iter_raw(self):
        if self._num_workers == 0:
            for batch in self._batch_sampler:
                yield self._load(batch)
            return
        if self._thread_pool:
            yield from self._iter_threaded()
            return
        if self._host_safe is None:
            self._host_safe, pool = _host_safe_probe(
                self._dataset, self._make_pool)
            if pool is not None:
                self._pool = pool
                import atexit

                atexit.register(pool.terminate)
            if not self._host_safe:
                import logging

                logging.warning(
                    "DataLoader(num_workers=%d): dataset __getitem__ is not "
                    "host-pure (returns device arrays, is unpicklable, or "
                    "its transform needs jax) — falling back to threaded "
                    "prefetch. Return numpy from __getitem__ to enable "
                    "worker processes.", self._num_workers)
        if not self._host_safe:
            yield from self._iter_threaded()
            return
        yield from _MultiWorkerIter(self._get_pool(), self._batch_sampler,
                                    self._prefetch)

    def _iter_threaded(self):
        # threaded prefetch pipeline on the shared mxnet_tpu.data core
        # (thread_pool=True, and the fallback when worker processes are
        # unviable); bounded put + capture-as-local generation semantics
        # live in data/core.PrefetchBuffer
        from ...data.core import PrefetchBuffer

        batches = iter(self._batch_sampler)

        def produce():
            return self._load(next(batches))

        buf = PrefetchBuffer(produce, depth=self._prefetch or 2,
                             name="mxtpu-dataloader-prefetch",
                             owner="DataLoader", src="dataloader")
        try:
            while True:
                try:
                    yield buf.get()
                except StopIteration:
                    return
        finally:
            # abandoned iterator (break mid-epoch) or natural end: stop +
            # join the producer either way
            buf.close()
