"""Vision datasets.

Reference: python/mxnet/gluon/data/vision/datasets.py (MNIST :33, FashionMNIST,
CIFAR10 :110, CIFAR100, ImageRecordDataset, ImageFolderDataset). This build
runs without network egress: datasets read pre-downloaded files from `root`
(same file formats as the reference) and raise a clear error otherwise."""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as _np

from .... import ndarray as nd
from ....base import MXNetError
from ..dataset import Dataset, RecordFileDataset


class _DownloadedDataset(Dataset):
    def __init__(self, root, transform):
        self._transform = transform
        self._data = None
        self._label = None
        self._root = os.path.expanduser(root)
        if not os.path.isdir(self._root):
            os.makedirs(self._root, exist_ok=True)
        self._get_data()

    def __getitem__(self, idx):
        from ....base import HOST_ARRAY_MODE

        # payloads are stored numpy (host memory); wrapped per-item so that
        # DataLoader worker processes (HOST_ARRAY_MODE) never touch jax
        data = self._data[idx]
        if not HOST_ARRAY_MODE:
            data = nd.array(data, dtype=str(data.dtype))
        if self._transform is not None:
            return self._transform(data, self._label[idx])
        return data, self._label[idx]

    def __len__(self):
        return len(self._label)

    def _get_data(self):
        raise NotImplementedError


class MNIST(_DownloadedDataset):
    """MNIST (reference: datasets.py:33). Reads the standard idx-ubyte files
    (optionally gzipped) from root."""

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "mnist"),
                 train=True, transform=None):
        self._train = train
        self._train_data = ("train-images-idx3-ubyte", "train-labels-idx1-ubyte")
        self._test_data = ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte")
        super().__init__(root, transform)

    def _read_file(self, name):
        path = os.path.join(self._root, name)
        if os.path.exists(path):
            with open(path, "rb") as f:
                return f.read()
        if os.path.exists(path + ".gz"):
            with gzip.open(path + ".gz", "rb") as f:
                return f.read()
        raise MXNetError(
            "MNIST file %s not found under %s (no network egress; place the "
            "standard idx files there)" % (name, self._root))

    def _get_data(self):
        images, labels = self._train_data if self._train else self._test_data
        raw = self._read_file(labels)
        magic, num = struct.unpack(">II", raw[:8])
        label = _np.frombuffer(raw[8:], dtype=_np.uint8).astype(_np.int32)
        raw = self._read_file(images)
        magic, num, rows, cols = struct.unpack(">IIII", raw[:16])
        data = _np.frombuffer(raw[16:], dtype=_np.uint8).reshape(num, rows, cols, 1)
        self._data = data  # numpy uint8 (host)
        self._label = label


class FashionMNIST(MNIST):
    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "fashion-mnist"),
                 train=True, transform=None):
        super().__init__(root, train, transform)


class CIFAR10(_DownloadedDataset):
    """CIFAR10 from the python-pickle batches (reference: datasets.py:110)."""

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "cifar10"),
                 train=True, transform=None, fine_label=False):
        self._train = train
        self._fine = fine_label
        super().__init__(root, transform)

    def _batches(self):
        if self._train:
            return ["data_batch_%d" % i for i in range(1, 6)]
        return ["test_batch"]

    def _load_batch(self, name):
        for cand in (os.path.join(self._root, name),
                     os.path.join(self._root, "cifar-10-batches-py", name)):
            if os.path.exists(cand):
                with open(cand, "rb") as f:
                    d = pickle.load(f, encoding="latin1")
                return d
        tar = os.path.join(self._root, "cifar-10-python.tar.gz")
        if os.path.exists(tar):
            with tarfile.open(tar) as t:
                member = t.getmember("cifar-10-batches-py/" + name)
                d = pickle.load(t.extractfile(member), encoding="latin1")
            return d
        raise MXNetError("CIFAR10 batch %s not found under %s" % (name, self._root))

    def _get_data(self):
        data, labels = [], []
        for name in self._batches():
            d = self._load_batch(name)
            data.append(d["data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1))
            labels.append(_np.asarray(d["labels" if "labels" in d else "fine_labels"]))
        self._data = _np.concatenate(data)  # numpy uint8 (host)
        self._label = _np.concatenate(labels).astype(_np.int32)


class CIFAR100(CIFAR10):
    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "cifar100"),
                 fine_label=False, train=True, transform=None):
        super().__init__(root, train, transform, fine_label)

    def _batches(self):
        return ["train"] if self._train else ["test"]


class ImageRecordDataset(RecordFileDataset):
    """Images packed in a RecordIO file (reference: datasets.py
    ImageRecordDataset; format from tools/im2rec)."""

    def __init__(self, filename, flag=1, transform=None):
        super().__init__(filename)
        self._flag = flag
        self._transform = transform

    def __getitem__(self, idx):
        from .... import recordio, image

        record = super().__getitem__(idx)
        header, img = recordio.unpack(record)
        img = image.imdecode(img, self._flag)
        label = header.label
        if self._transform is not None:
            return self._transform(img, label)
        return img, label


class ImageFolderDataset(Dataset):
    """Images arranged in class folders (reference: datasets.py
    ImageFolderDataset)."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self._exts = [".jpg", ".jpeg", ".png", ".bmp"]
        self._list_images(self._root)

    def _list_images(self, root):
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(root)):
            path = os.path.join(root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for filename in sorted(os.listdir(path)):
                if os.path.splitext(filename)[1].lower() in self._exts:
                    self.items.append((os.path.join(path, filename), label))

    def __getitem__(self, idx):
        from .... import image

        with open(self.items[idx][0], "rb") as f:
            img = image.imdecode(f.read(), self._flag)
        label = self.items[idx][1]
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self.items)
