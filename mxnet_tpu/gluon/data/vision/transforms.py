"""Vision transforms (reference: python/mxnet/gluon/data/vision/transforms.py)."""
from __future__ import annotations

import numpy as _np

from .... import ndarray as nd
from ....base import MXNetError
from ...block import Block, HybridBlock
from ...nn import Sequential, HybridSequential

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "CropResize", "RandomResizedCrop", "RandomFlipLeftRight",
           "RandomFlipTopBottom", "RandomBrightness", "RandomContrast",
           "RandomSaturation", "RandomHue", "RandomColorJitter",
           "RandomLighting"]


class Compose(Sequential):
    """Chain transforms (reference: transforms.py Compose)."""

    def __init__(self, transforms):
        super().__init__()
        for t in transforms:
            self.add(t)


class Cast(HybridBlock):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def hybrid_forward(self, F, x):
        return F.Cast(x, dtype=self._dtype)


class ToTensor(HybridBlock):
    """HWC uint8 [0,255] -> CHW float32 [0,1] (reference: transforms.py ToTensor)."""

    def hybrid_forward(self, F, x):
        out = F.Cast(x, dtype="float32") / 255.0
        if out.ndim == 3:
            return F.transpose(out, axes=(2, 0, 1))
        return F.transpose(out, axes=(0, 3, 1, 2))


class Normalize(HybridBlock):
    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = mean
        self._std = std

    def hybrid_forward(self, F, x):
        mean = nd.array(_np.asarray(self._mean, _np.float32).reshape(-1, 1, 1))
        std = nd.array(_np.asarray(self._std, _np.float32).reshape(-1, 1, 1))
        return (x - mean) / std


class Resize(Block):
    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else size

    def forward(self, x):
        from .... import image

        return image.imresize(x, self._size[0], self._size[1])


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else size

    def forward(self, x):
        h, w = x.shape[0], x.shape[1]
        cw, ch = self._size
        x0 = max((w - cw) // 2, 0)
        y0 = max((h - ch) // 2, 0)
        return x[y0:y0 + ch, x0:x0 + cw]


class RandomResizedCrop(Block):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else size
        self._scale = scale
        self._ratio = ratio

    def forward(self, x):
        from .... import image

        h, w = x.shape[0], x.shape[1]
        area = h * w
        for _ in range(10):
            target_area = _np.random.uniform(*self._scale) * area
            aspect = _np.random.uniform(*self._ratio)
            cw = int(round(_np.sqrt(target_area * aspect)))
            ch = int(round(_np.sqrt(target_area / aspect)))
            if cw <= w and ch <= h:
                x0 = _np.random.randint(0, w - cw + 1)
                y0 = _np.random.randint(0, h - ch + 1)
                crop = x[y0:y0 + ch, x0:x0 + cw]
                return image.imresize(crop, self._size[0], self._size[1])
        return image.imresize(x, self._size[0], self._size[1])


class RandomFlipLeftRight(Block):
    def forward(self, x):
        if _np.random.rand() < 0.5:
            return x.flip(axis=1)
        return x


class RandomFlipTopBottom(Block):
    def forward(self, x):
        if _np.random.rand() < 0.5:
            return x.flip(axis=0)
        return x


class RandomBrightness(Block):
    def __init__(self, brightness):
        super().__init__()
        self._delta = brightness

    def forward(self, x):
        alpha = 1.0 + _np.random.uniform(-self._delta, self._delta)
        return (x.astype("float32") * alpha).clip(0, 255)


class RandomContrast(Block):
    def __init__(self, contrast):
        super().__init__()
        self._delta = contrast

    def forward(self, x):
        alpha = 1.0 + _np.random.uniform(-self._delta, self._delta)
        xf = x.astype("float32")
        gray = xf.mean()
        return ((xf - gray) * alpha + gray).clip(0, 255)


class RandomSaturation(Block):
    def __init__(self, saturation):
        super().__init__()
        self._delta = saturation

    def forward(self, x):
        alpha = 1.0 + _np.random.uniform(-self._delta, self._delta)
        xf = x.astype("float32")
        coef = nd.array(_np.array([0.299, 0.587, 0.114], _np.float32).reshape(1, 1, 3))
        gray = (xf * coef).sum(axis=2, keepdims=True)
        return (xf * alpha + gray * (1.0 - alpha)).clip(0, 255)


class CropResize(Block):
    """Fixed crop at (x, y, width, height) then optional resize (reference:
    transforms.py:231; out-of-bounds crops raise like the reference's
    image.crop rather than silently truncating)."""

    def __init__(self, x, y, width, height, size=None, interpolation=1):
        super().__init__()
        self._x, self._y = int(x), int(y)
        self._w, self._h = int(width), int(height)
        self._size = ((size, size) if isinstance(size, int) else size) \
            if size is not None else None
        self._interp = interpolation

    def forward(self, data):
        batched = data.ndim == 4  # (N, H, W, C), like the reference's crop
        hax = 1 if batched else 0
        h, w = data.shape[hax], data.shape[hax + 1]
        if self._x < 0 or self._y < 0 or self._x + self._w > w \
                or self._y + self._h > h:
            raise MXNetError(
                "CropResize: crop (x=%d, y=%d, w=%d, h=%d) exceeds image "
                "(%dx%d)" % (self._x, self._y, self._w, self._h, w, h))
        ys = slice(self._y, self._y + self._h)
        xs = slice(self._x, self._x + self._w)
        crop = data[:, ys, xs] if batched else data[ys, xs]
        if self._size is None:
            return crop
        from .... import image

        if batched:
            resized = [image.imresize(crop[i], self._size[0], self._size[1],
                                      interp=self._interp)
                       for i in range(crop.shape[0])]
            if isinstance(resized[0], _np.ndarray):
                # DataLoader workers run transforms in HOST_ARRAY_MODE
                # (numpy in, numpy out — jax must not wake up post-fork)
                return _np.stack(resized, axis=0)
            return nd.stack(*resized, axis=0)
        return image.imresize(crop, self._size[0], self._size[1],
                              interp=self._interp)


class RandomHue(Block):
    """Rotate hue by a random angle in [-delta, delta]*pi via the YIQ
    linear approximation the reference's image.random_hue uses
    (transforms.py:483)."""

    _T_YIQ = _np.array([[0.299, 0.587, 0.114],
                        [0.596, -0.274, -0.321],
                        [0.211, -0.523, 0.311]], _np.float32)
    _T_RGB = _np.array([[1.0, 0.956, 0.621],
                        [1.0, -0.272, -0.647],
                        [1.0, -1.107, 1.705]], _np.float32)

    def __init__(self, hue):
        super().__init__()
        self._delta = hue

    def forward(self, x):
        alpha = _np.random.uniform(-self._delta, self._delta)
        theta = alpha * _np.pi
        u, w = _np.cos(theta), _np.sin(theta)
        rot = _np.array([[1.0, 0.0, 0.0],
                         [0.0, u, -w],
                         [0.0, w, u]], _np.float32)
        m = self._T_RGB @ rot @ self._T_YIQ         # rgb -> rgb
        xf = x.astype("float32")
        out = nd.dot(xf, nd.array(m.T.copy()))
        return out.clip(0, 255)


class RandomColorJitter(Block):
    """Randomly-ordered brightness/contrast/saturation/hue jitter
    (reference: transforms.py:508)."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        super().__init__()
        self._ts = []
        if brightness:
            self._ts.append(RandomBrightness(brightness))
        if contrast:
            self._ts.append(RandomContrast(contrast))
        if saturation:
            self._ts.append(RandomSaturation(saturation))
        if hue:
            self._ts.append(RandomHue(hue))

    def forward(self, x):
        for i in _np.random.permutation(len(self._ts)):
            x = self._ts[i](x)
        return x


class RandomLighting(Block):
    """AlexNet-style PCA lighting noise (reference: transforms.py:542):
    per-image normal draws scaled by the ImageNet RGB eigen-decomposition."""

    _EIGVAL = _np.array([55.46, 4.794, 1.148], _np.float32)
    _EIGVEC = _np.array([[-0.5675, 0.7192, 0.4009],
                         [-0.5808, -0.0045, -0.8140],
                         [-0.5836, -0.6948, 0.4203]], _np.float32)

    def __init__(self, alpha):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        draws = _np.random.normal(0, self._alpha, 3).astype(_np.float32)
        rgb = self._EIGVEC @ (self._EIGVAL * draws)
        return (x.astype("float32") + nd.array(rgb.reshape(1, 1, 3))) \
            .clip(0, 255)
