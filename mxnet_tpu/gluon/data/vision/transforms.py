"""Vision transforms (reference: python/mxnet/gluon/data/vision/transforms.py)."""
from __future__ import annotations

import numpy as _np

from .... import ndarray as nd
from ....base import MXNetError
from ...block import Block, HybridBlock
from ...nn import Sequential, HybridSequential

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomResizedCrop", "RandomFlipLeftRight", "RandomFlipTopBottom",
           "RandomBrightness", "RandomContrast", "RandomSaturation"]


class Compose(Sequential):
    """Chain transforms (reference: transforms.py Compose)."""

    def __init__(self, transforms):
        super().__init__()
        for t in transforms:
            self.add(t)


class Cast(HybridBlock):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def hybrid_forward(self, F, x):
        return F.Cast(x, dtype=self._dtype)


class ToTensor(HybridBlock):
    """HWC uint8 [0,255] -> CHW float32 [0,1] (reference: transforms.py ToTensor)."""

    def hybrid_forward(self, F, x):
        out = F.Cast(x, dtype="float32") / 255.0
        if out.ndim == 3:
            return F.transpose(out, axes=(2, 0, 1))
        return F.transpose(out, axes=(0, 3, 1, 2))


class Normalize(HybridBlock):
    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = mean
        self._std = std

    def hybrid_forward(self, F, x):
        mean = nd.array(_np.asarray(self._mean, _np.float32).reshape(-1, 1, 1))
        std = nd.array(_np.asarray(self._std, _np.float32).reshape(-1, 1, 1))
        return (x - mean) / std


class Resize(Block):
    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else size

    def forward(self, x):
        from .... import image

        return image.imresize(x, self._size[0], self._size[1])


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else size

    def forward(self, x):
        h, w = x.shape[0], x.shape[1]
        cw, ch = self._size
        x0 = max((w - cw) // 2, 0)
        y0 = max((h - ch) // 2, 0)
        return x[y0:y0 + ch, x0:x0 + cw]


class RandomResizedCrop(Block):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else size
        self._scale = scale
        self._ratio = ratio

    def forward(self, x):
        from .... import image

        h, w = x.shape[0], x.shape[1]
        area = h * w
        for _ in range(10):
            target_area = _np.random.uniform(*self._scale) * area
            aspect = _np.random.uniform(*self._ratio)
            cw = int(round(_np.sqrt(target_area * aspect)))
            ch = int(round(_np.sqrt(target_area / aspect)))
            if cw <= w and ch <= h:
                x0 = _np.random.randint(0, w - cw + 1)
                y0 = _np.random.randint(0, h - ch + 1)
                crop = x[y0:y0 + ch, x0:x0 + cw]
                return image.imresize(crop, self._size[0], self._size[1])
        return image.imresize(x, self._size[0], self._size[1])


class RandomFlipLeftRight(Block):
    def forward(self, x):
        if _np.random.rand() < 0.5:
            return x.flip(axis=1)
        return x


class RandomFlipTopBottom(Block):
    def forward(self, x):
        if _np.random.rand() < 0.5:
            return x.flip(axis=0)
        return x


class RandomBrightness(Block):
    def __init__(self, brightness):
        super().__init__()
        self._delta = brightness

    def forward(self, x):
        alpha = 1.0 + _np.random.uniform(-self._delta, self._delta)
        return (x.astype("float32") * alpha).clip(0, 255)


class RandomContrast(Block):
    def __init__(self, contrast):
        super().__init__()
        self._delta = contrast

    def forward(self, x):
        alpha = 1.0 + _np.random.uniform(-self._delta, self._delta)
        xf = x.astype("float32")
        gray = xf.mean()
        return ((xf - gray) * alpha + gray).clip(0, 255)


class RandomSaturation(Block):
    def __init__(self, saturation):
        super().__init__()
        self._delta = saturation

    def forward(self, x):
        alpha = 1.0 + _np.random.uniform(-self._delta, self._delta)
        xf = x.astype("float32")
        coef = nd.array(_np.array([0.299, 0.587, 0.114], _np.float32).reshape(1, 1, 3))
        gray = (xf * coef).sum(axis=2, keepdims=True)
        return (xf * alpha + gray * (1.0 - alpha)).clip(0, 255)
