"""Gluon utilities (reference: python/mxnet/gluon/utils.py — split_data,
split_and_load, clip_global_norm, check_sha1, download)."""
from __future__ import annotations

import hashlib
import os

import numpy as _np

from ..base import MXNetError
from .. import ndarray as nd
from ..ndarray import NDArray

__all__ = ["split_data", "split_and_load", "clip_global_norm", "check_sha1",
           "download"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """reference: utils.py:31"""
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise MXNetError(
            "data with shape %s cannot be evenly split into %d slices along axis %d"
            % (str(data.shape), num_slice, batch_axis))
    n_each = size // num_slice
    if even_split:
        return [data.slice_axis(batch_axis, i * n_each, (i + 1) * n_each)
                for i in range(num_slice)]
    slices = []
    step = (size + num_slice - 1) // num_slice
    for i in range(num_slice):
        end = min((i + 1) * step, size)
        if i * step < size:
            slices.append(data.slice_axis(batch_axis, i * step, end))
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """reference: utils.py:79 — slice along batch axis and place per context."""
    if not isinstance(data, NDArray):
        data = nd.array(data, ctx=ctx_list[0])
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """reference: utils.py:115 — one fused global-norm clip."""
    assert len(arrays) > 0
    ctx = arrays[0].context
    total = None
    for a in arrays:
        n = (a.astype("float32") ** 2).sum().as_in_context(ctx)
        total = n if total is None else total + n
    total_norm = float(total.sqrt().asscalar())
    if check_isfinite and not _np.isfinite(total_norm):
        import warnings

        warnings.warn("nan or inf found in clip_global_norm")
    scale = max_norm / (total_norm + 1e-8)
    if scale < 1.0:
        for a in arrays:
            a._set_data((a * scale)._data)
    return total_norm


def check_sha1(filename, sha1_hash):
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None, retries=5,
             verify_ssl=True):
    """reference: utils.py download. This build runs with zero egress: the
    function only serves cache hits (pre-downloaded files); a network fetch
    raises."""
    fname = path
    if path is None or os.path.isdir(path or ""):
        fname = os.path.join(path or ".", url.split("/")[-1])
    if os.path.exists(fname) and not overwrite and \
            (sha1_hash is None or check_sha1(fname, sha1_hash)):
        return fname
    raise MXNetError(
        "download(%s): no network egress in this environment and file %s not "
        "cached locally" % (url, fname))
