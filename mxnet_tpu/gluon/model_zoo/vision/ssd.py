"""SSD detection model family (reference: example/ssd/symbol/symbol_builder.py
get_symbol_train/get_symbol + symbol/common.py multi_layer_feature/
multibox_layer, configs from symbol/symbol_factory.py get_config).

Derived from the reference implementation (Apache-2.0); layer structure and
parameter naming kept for checkpoint compatibility with reference-trained
models.

TPU-native design notes:
- The whole network is a HybridBlock: one jit-compiled XLA program per shape
  covers base features, the extra pyramid, all predictor heads, and the
  anchor constants (MultiBoxPrior folds to a compile-time constant).
- Predictor convs keep NCHW; the (B, A, C+1) / (B, A*4) gathers are pure
  reshapes/transposes that XLA fuses into the conv epilogues.
- Training targets come from the static-shape MultiBoxTarget op
  (ops/contrib.py — vmapped IoU matching + rank-based hard negative
  mining, no data-dependent shapes), so the full train step jits.
"""
from __future__ import annotations

from ....base import MXNetError
from ...block import HybridBlock
from ...loss import Loss
from ... import nn

__all__ = ["SSD", "SSDMultiBoxLoss", "get_ssd", "ssd_512_resnet50_v1",
           "ssd_300_resnet50_v1", "ssd_512_mobilenet1_0", "ssd_test_tiny"]

# per-(network, data_shape) anchor configs
# (reference: example/ssd/symbol/symbol_factory.py get_config)
_SIZES_512 = [[.1, .141], [.2, .272], [.37, .447], [.54, .619],
              [.71, .79], [.88, .961]]
_SIZES_300 = [[.1, .141], [.2, .272], [.37, .447], [.54, .619],
              [.71, .79], [.88, .961]]
_RATIOS_6 = [[1, 2, .5], [1, 2, .5, 3, 1. / 3], [1, 2, .5, 3, 1. / 3],
             [1, 2, .5, 3, 1. / 3], [1, 2, .5, 3, 1. / 3], [1, 2, .5]]


class _ExtraFeature(HybridBlock):
    """One extra downsampling pyramid block: 1x1 channel-reduce then 3x3
    stride-2 (reference: symbol/common.py multi_layer_feature extra-layer
    branch — conv_act_layer pairs)."""

    def __init__(self, num_filters, min_filter=128, stride=2, padding=1,
                 **kwargs):
        super().__init__(**kwargs)
        reduced = max(num_filters // 2, min_filter)
        with self.name_scope():
            self.body = nn.HybridSequential(prefix="")
            self.body.add(nn.Conv2D(reduced, kernel_size=1, use_bias=False))
            self.body.add(nn.BatchNorm())
            self.body.add(nn.Activation("relu"))
            self.body.add(nn.Conv2D(num_filters, kernel_size=3, strides=stride,
                                    padding=padding, use_bias=False))
            self.body.add(nn.BatchNorm())
            self.body.add(nn.Activation("relu"))

    def hybrid_forward(self, F, x):
        return self.body(x)


class SSD(HybridBlock):
    """Single-shot detector over a truncated backbone + extra feature
    pyramid. forward(x) -> (cls_preds (B, A, C+1), loc_preds (B, A*4),
    anchors (1, A, 4)) — the layouts MultiBoxTarget / MultiBoxDetection
    consume (cls_preds transposed to (B, C+1, A) where those ops expect
    the reference layout)."""

    def __init__(self, num_classes, base_blocks, num_extras=4,
                 extra_filters=(512, 256, 256, 128), sizes=None, ratios=None,
                 anchor_clip=False, **kwargs):
        super().__init__(**kwargs)
        if nn.in_channels_last_scope():
            # the detection heads' reshapes and MultiBoxPrior's H/W reads
            # are NCHW-specific; building under a channels-last scope would
            # run without error but scramble predictions and anchors
            raise ValueError(
                "SSD does not support channels-last layout_scope; build it "
                "outside the scope (its heads assume NCHW)")
        nscales = len(base_blocks) + num_extras
        sizes = sizes if sizes is not None else _SIZES_512[:nscales]
        ratios = ratios if ratios is not None else _RATIOS_6[:nscales]
        if not len(sizes) == len(ratios) == nscales:
            raise MXNetError("sizes/ratios must have one entry per scale "
                             "(%d base + %d extra)" % (len(base_blocks),
                                                       num_extras))
        self.num_classes = num_classes
        self._sizes = [tuple(s) for s in sizes]
        self._ratios = [tuple(r) for r in ratios]
        self._anchor_clip = anchor_clip
        with self.name_scope():
            self.base_stages = nn.HybridSequential(prefix="base_")
            for b in base_blocks:
                self.base_stages.add(b)
            self.extras = nn.HybridSequential(prefix="extra_")
            for i, f in enumerate(extra_filters[:num_extras]):
                self.extras.add(_ExtraFeature(f))
            self.class_preds = nn.HybridSequential(prefix="cls_pred_")
            self.box_preds = nn.HybridSequential(prefix="box_pred_")
            for s, r in zip(self._sizes, self._ratios):
                na = len(s) + len(r) - 1
                self.class_preds.add(
                    nn.Conv2D(na * (num_classes + 1), kernel_size=3, padding=1))
                self.box_preds.add(
                    nn.Conv2D(na * 4, kernel_size=3, padding=1))

    def hybrid_forward(self, F, x):
        feats = []
        for stage in self.base_stages._children.values():
            x = stage(x)
            feats.append(x)
        for extra in self.extras._children.values():
            x = extra(x)
            feats.append(x)

        cls_list, loc_list, anchor_list = [], [], []
        for feat, cp, bp, s, r in zip(feats,
                                      self.class_preds._children.values(),
                                      self.box_preds._children.values(),
                                      self._sizes, self._ratios):
            cls = cp(feat)                       # (B, na*(C+1), H, W)
            cls = F.transpose(cls, (0, 2, 3, 1))
            cls_list.append(F.reshape(cls, (0, -1, self.num_classes + 1)))
            loc = bp(feat)                       # (B, na*4, H, W)
            loc = F.transpose(loc, (0, 2, 3, 1))
            loc_list.append(F.reshape(loc, (0, -1)))
            anchor_list.append(F.contrib.MultiBoxPrior(
                feat, sizes=s, ratios=r, clip=self._anchor_clip))
        cls_preds = F.concat(*cls_list, dim=1)   # (B, A, C+1)
        loc_preds = F.concat(*loc_list, dim=1)   # (B, A*4)
        anchors = F.concat(*anchor_list, dim=1)  # (1, A, 4)
        return cls_preds, loc_preds, anchors

    def training_targets(self, anchors, cls_preds, labels,
                         overlap_threshold=0.5, negative_mining_ratio=3,
                         negative_mining_thresh=0.5,
                         variances=(0.1, 0.1, 0.2, 0.2)):
        """Anchor matching + encoding for one batch (reference train symbol:
        the contrib.MultiBoxTarget call in symbol_builder.py get_symbol_train).
        labels: (B, M, 5) [cls, x1, y1, x2, y2], pad rows cls=-1.
        Returns (cls_target (B, A), loc_target (B, A*4), loc_mask (B, A*4))."""
        from .... import ndarray as nd

        cls_t = nd.transpose(cls_preds, (0, 2, 1))  # (B, C+1, A)
        loc_target, loc_mask, cls_target = nd.contrib.MultiBoxTarget(
            anchors, labels, cls_t, overlap_threshold=overlap_threshold,
            ignore_label=-1, negative_mining_ratio=negative_mining_ratio,
            negative_mining_thresh=negative_mining_thresh,
            variances=variances)
        return cls_target, loc_target, loc_mask

    def detections(self, cls_preds, loc_preds, anchors, nms_thresh=0.45,
                   nms_topk=400, threshold=0.01, force_suppress=False,
                   variances=(0.1, 0.1, 0.2, 0.2)):
        """Decode + NMS (reference: get_symbol's contrib.MultiBoxDetection).
        Returns (B, A, 6) rows [cls_id, score, x1, y1, x2, y2], id -1 =
        suppressed/invalid."""
        from .... import ndarray as nd

        cls_prob = nd.softmax(nd.transpose(cls_preds, (0, 2, 1)), axis=1)
        return nd.contrib.MultiBoxDetection(
            cls_prob, loc_preds, anchors, nms_threshold=nms_thresh,
            nms_topk=nms_topk, threshold=threshold,
            force_suppress=force_suppress, variances=variances)


class SSDMultiBoxLoss(Loss):
    """Joint classification + localization loss (reference train symbol:
    SoftmaxOutput(ignore_label=-1, normalization='valid') for classes +
    MakeLoss(smooth_l1(loc_mask*(loc_preds-loc_target))) for boxes,
    symbol_builder.py get_symbol_train)."""

    def __init__(self, negative_mining_ratio=3, lambd=1.0, weight=None,
                 batch_axis=0, **kwargs):
        # negative_mining_ratio is accepted for signature parity but unused
        # here: hard negative mining happens in SSD.training_targets /
        # MultiBoxTarget (where the reference does it), not in the loss
        super().__init__(weight, batch_axis, **kwargs)
        self._lambd = lambd

    def hybrid_forward(self, F, cls_preds, loc_preds, cls_target, loc_target,
                       loc_mask):
        # cls_preds (B, A, C+1); cls_target (B, A) with -1 = ignore
        lp = F.log_softmax(cls_preds, axis=-1)
        valid = cls_target >= 0
        tgt = F.maximum(cls_target, 0.0)
        ce = -F.pick(lp, tgt, axis=-1)
        n_valid = F.maximum(F.sum(valid.astype(lp.dtype)), 1.0)
        cls_loss = F.sum(F.where(valid, ce, F.zeros_like(ce))) / n_valid
        sl1 = F.smooth_l1(loc_mask * (loc_preds - loc_target), scalar=1.0)
        n_loc = F.maximum(F.sum(loc_mask), 1.0)
        loc_loss = F.sum(sl1) / n_loc
        return cls_loss + self._lambd * loc_loss


def _resnet_base(version, num_layers, **kwargs):
    """Backbone stages for SSD: [stem..stage3] (stride 16) and [stage4]
    (stride 32) — the reference's '_plus12'/'_plus15' cut points for
    resnet50 (symbol_factory.py get_config 'resnet50')."""
    from .resnet import get_resnet

    net = get_resnet(version, num_layers, **kwargs)
    children = list(net.features._children.values())
    # [conv, bn, relu, pool, stage1, stage2, stage3, stage4, gap(, flat)]
    stem_through_stage3 = nn.HybridSequential(prefix="")
    for c in children[:7]:
        stem_through_stage3.add(c)
    stage4 = nn.HybridSequential(prefix="")
    stage4.add(children[7])
    return [stem_through_stage3, stage4]


def _mobilenet_base(multiplier=1.0, **kwargs):
    from .mobilenet import get_mobilenet

    net = get_mobilenet(multiplier, **kwargs)
    children = list(net.features._children.values())
    # cut at the stride-16 / stride-32 boundary (dw-conv with stride 2 at
    # index 33 of the conv stack); features end with GlobalAvgPool+Flatten
    body = children[:-2]
    cut = max(1, len(body) * 3 // 4)
    first = nn.HybridSequential(prefix="")
    for c in body[:cut]:
        first.add(c)
    second = nn.HybridSequential(prefix="")
    for c in body[cut:]:
        second.add(c)
    return [first, second]


def get_ssd(base="resnet50_v1", data_shape=512, num_classes=20,
            pretrained_base=False, **kwargs):
    """Factory (reference: symbol_factory.py get_symbol_train(get_config))."""
    if base == "resnet50_v1":
        blocks = _resnet_base(1, 50, pretrained=pretrained_base)
    elif base == "resnet18_v1":
        blocks = _resnet_base(1, 18, pretrained=pretrained_base)
    elif base == "mobilenet1.0":
        blocks = _mobilenet_base(1.0, pretrained=pretrained_base)
    else:
        raise MXNetError("unsupported SSD base '%s'" % base)
    sizes = _SIZES_512 if data_shape >= 512 else _SIZES_300
    return SSD(num_classes, blocks, num_extras=4, sizes=sizes,
               ratios=_RATIOS_6, **kwargs)


def ssd_512_resnet50_v1(num_classes=20, **kwargs):
    return get_ssd("resnet50_v1", 512, num_classes, **kwargs)


def ssd_300_resnet50_v1(num_classes=20, **kwargs):
    return get_ssd("resnet50_v1", 300, num_classes, **kwargs)


def ssd_512_mobilenet1_0(num_classes=20, **kwargs):
    return get_ssd("mobilenet1.0", 512, num_classes, **kwargs)


def ssd_test_tiny(num_classes=3, **kwargs):
    """Small config for unit tests / CPU smoke: resnet18 base, 2 extra
    scales, works from 64x64 inputs."""
    blocks = _resnet_base(1, 18)
    return SSD(num_classes, blocks, num_extras=2, extra_filters=(128, 128),
               sizes=_SIZES_512[:4], ratios=_RATIOS_6[:4], **kwargs)
