"""ResNet v1/v2 (derived from the reference implementation
python/mxnet/gluon/model_zoo/vision/resnet.py — resnet18-152,
BasicBlock/Bottleneck, v1 and pre-activation v2; class structure and
parameter naming kept for checkpoint compatibility).

TPU notes: NCHW at the API (reference layout); build under
`gluon.nn.layout_scope()` for the MXU-preferred channels-last layout.
Two zoo-level performance rewrites ride behind flags (both default to the
reference graph; both are checkpoint-compatible — see each flag):

- ``fuse_epilogue`` (env ``MXTPU_PALLAS_CONV_EPILOGUE``): every
  BN→ReLU(→+residual) epilogue collapses into the fused BatchNormRelu /
  BatchNormAddRelu ops (Pallas conv-epilogue kernels on TPU). Parameter
  names are unchanged — the fused layers are the same ``nn.BatchNorm``
  class, the paramless ``nn.Activation`` blocks simply disappear.
- ``stem_s2d`` (env ``MXTPU_S2D_STEM``): the MXU-hostile 7×7/s2 3-channel
  stem becomes space-to-depth(2) + a 4×4/s1 conv over 12 channels —
  numerically equivalent under the weight-space transform
  ``stem_weight_to_s2d`` (zero-pad the 7×7 kernel to 8×8, regroup into
  2×2 parities); ``convert_stem_params`` converts existing checkpoints.
"""
from __future__ import annotations


from .... import env as _env
from ....base import MXNetError
from ....ops.nn import _channels_last
from ...block import HybridBlock
from ... import nn

__all__ = ["ResNetV1", "ResNetV2", "BasicBlockV1", "BasicBlockV2",
           "BottleneckV1", "BottleneckV2", "resnet18_v1", "resnet34_v1",
           "resnet50_v1", "resnet101_v1", "resnet152_v1", "resnet18_v2",
           "resnet34_v2", "resnet50_v2", "resnet101_v2", "resnet152_v2",
           "get_resnet", "stem_weight_to_s2d", "convert_stem_params"]


def _fuse_epilogue_default(flag):
    """Zoo default for the fused-epilogue graph: explicit flag wins; else
    opt in via MXTPU_PALLAS_CONV_EPILOGUE=1/auto (the op layer makes the
    same env decide Pallas vs pure-jnp lowering — see ops/nn.py)."""
    if flag is not None:
        return bool(flag)
    # NOT get(): the zoo gate is set-and-not-"0" (`auto` builds the fused
    # graph too — the op layer then decides Pallas vs jnp lowering)
    return (_env.raw("MXTPU_PALLAS_CONV_EPILOGUE") or "") not in ("", "0")


def _stem_s2d_default(flag):
    if flag is not None:
        return bool(flag)
    return _env.get("MXTPU_S2D_STEM")


def _conv3x3(channels, stride, in_channels):
    return nn.Conv2D(channels, kernel_size=3, strides=stride, padding=1,
                     use_bias=False, in_channels=in_channels)


def _bn(fused, act=None):
    """BatchNorm, optionally carrying the fused epilogue activation. The
    fused variant is the SAME class (same auto-name counter, same params) —
    only the trailing paramless Activation block is dropped by callers."""
    return nn.BatchNorm(act_type=act if fused else None)


def _fused_body_forward(body, x, residual):
    """Run a fused block body whose TAIL is the BatchNormAddRelu layer:
    every child except the last consumes one input; the last gets the
    residual as its fused addend. Shared by BasicBlockV1/BottleneckV1 so
    the tail-position assumption lives in exactly one place."""
    children = list(body._children.values())
    out = x
    for blk in children[:-1]:
        out = blk(out)
    return children[-1](out, residual)


class _SpaceToDepthStem(HybridBlock):
    """Paramless stem transform: space-to-depth(2) + the asymmetric (2, 1)
    spatial zero-pad that makes a following 4×4/s1 VALID conv reproduce the
    reference 7×7/s2/pad-3 stem exactly (see stem_weight_to_s2d for the
    matching weight-space transform). Requires even spatial dims."""

    def __init__(self, layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        self._layout = layout
        self._ch_last = _channels_last(layout)

    def hybrid_forward(self, F, x):
        shape = getattr(x, "shape", None)
        if shape:  # eager/jit trace: shapes known; Symbol tracing has none
            sp = shape[1:3] if self._ch_last else shape[2:4]
            if any(isinstance(d, int) and d % 2 for d in sp):
                raise MXNetError(
                    "space-to-depth stem requires even spatial dims, got "
                    "%s — the reference 7x7/s2 stem handles odd sizes; "
                    "build with stem_s2d=False for this input" % (sp,))
        z = F.space_to_depth(x, block_size=2, layout=self._layout)
        if self._ch_last:
            pw = (0, 0, 2, 1, 2, 1, 0, 0)
        else:
            pw = (0, 0, 0, 0, 2, 1, 2, 1)
        return F.pad(z, mode="constant", pad_width=pw)


def stem_weight_to_s2d(w, layout="NCHW"):
    """Weight-space transform for the space-to-depth stem: a 7×7 stem conv
    weight (O, C, 7, 7) (NCHW; (O, 7, 7, C) for NHWC) becomes the 4×4
    weight over C·4 space-to-depth channels that computes the IDENTICAL
    convolution (y[p] = Σ w7[i]·x[2p+i-3] = Σ w8[2di+a]·z_a[p+di-2] after
    zero-padding the kernel to 8×8 at the top/left and regrouping by 2×2
    spatial parity). Depth order matches ops space_to_depth:
    channel = a·2C + b·C + c. Accepts numpy or jax arrays."""
    import numpy as np

    w = np.asarray(w)
    if _channels_last(layout):
        o, kh, kw, c = w.shape
        if (kh, kw) != (7, 7):
            raise MXNetError("stem_weight_to_s2d expects a 7x7 kernel, "
                             "got %s" % ((kh, kw),))
        w8 = np.pad(w, ((0, 0), (1, 0), (1, 0), (0, 0)))
        w8 = w8.reshape(o, 4, 2, 4, 2, c)           # (O, di, a, dj, b, C)
        return np.ascontiguousarray(
            w8.transpose(0, 1, 3, 2, 4, 5).reshape(o, 4, 4, 4 * c))
    o, c, kh, kw = w.shape
    if (kh, kw) != (7, 7):
        raise MXNetError("stem_weight_to_s2d expects a 7x7 kernel, got %s"
                         % ((kh, kw),))
    w8 = np.pad(w, ((0, 0), (0, 0), (1, 0), (1, 0)))
    w8 = w8.reshape(o, c, 4, 2, 4, 2)               # (O, C, di, a, dj, b)
    return np.ascontiguousarray(
        w8.transpose(0, 3, 5, 1, 2, 4).reshape(o, 4 * c, 4, 4))


def convert_stem_params(params, layout="NCHW"):
    """Convert a checkpoint dict from the 7×7 stem to the space-to-depth
    stem: every value with a 7×7 stem-conv weight shape is transformed via
    stem_weight_to_s2d, everything else passes through. Works on the dicts
    net.save_parameters/load_parameters exchange. Only the STEM conv is
    converted — matched by its auto-name (first conv: `conv0_weight` /
    `conv2d0_weight`) AND a 7x7 kernel — so other 7x7 convs a custom model
    might contain pass through untouched."""
    ch_last = _channels_last(layout)
    out = {}
    for k, v in params.items():
        shp = tuple(getattr(v, "shape", ()))
        is_stem = (len(shp) == 4
                   and (k.endswith("conv0_weight")
                        or k.endswith("conv2d0_weight"))
                   and (shp[1:3] == (7, 7) if ch_last
                        else shp[2:] == (7, 7)))
        out[k] = stem_weight_to_s2d(v, layout) if is_stem else v
    return out


class BasicBlockV1(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 fuse_epilogue=False, **kwargs):
        super().__init__(**kwargs)
        self._fused = fuse_epilogue
        self.body = nn.HybridSequential(prefix="")
        self.body.add(_conv3x3(channels, stride, in_channels))
        self.body.add(_bn(fuse_epilogue, "relu"))
        if not fuse_epilogue:
            self.body.add(nn.Activation("relu"))
        self.body.add(_conv3x3(channels, 1, channels))
        self.body.add(_bn(fuse_epilogue, "relu"))  # fused tail: bn+add+relu
        if downsample:
            self.downsample = nn.HybridSequential(prefix="")
            self.downsample.add(nn.Conv2D(channels, kernel_size=1, strides=stride,
                                          use_bias=False, in_channels=in_channels))
            self.downsample.add(nn.BatchNorm())
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        if self.downsample is not None:
            residual = self.downsample(x)
        if self._fused:
            return _fused_body_forward(self.body, x, residual)
        out = self.body(x)
        return F.Activation(out + residual, act_type="relu")


class BottleneckV1(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 fuse_epilogue=False, **kwargs):
        super().__init__(**kwargs)
        self._fused = fuse_epilogue
        self.body = nn.HybridSequential(prefix="")
        self.body.add(nn.Conv2D(channels // 4, kernel_size=1, strides=stride,
                                use_bias=False))
        self.body.add(_bn(fuse_epilogue, "relu"))
        if not fuse_epilogue:
            self.body.add(nn.Activation("relu"))
        self.body.add(_conv3x3(channels // 4, 1, channels // 4))
        self.body.add(_bn(fuse_epilogue, "relu"))
        if not fuse_epilogue:
            self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(channels, kernel_size=1, strides=1, use_bias=False))
        self.body.add(_bn(fuse_epilogue, "relu"))  # fused tail: bn+add+relu
        if downsample:
            self.downsample = nn.HybridSequential(prefix="")
            self.downsample.add(nn.Conv2D(channels, kernel_size=1, strides=stride,
                                          use_bias=False, in_channels=in_channels))
            self.downsample.add(nn.BatchNorm())
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        if self.downsample is not None:
            residual = self.downsample(x)
        if self._fused:
            return _fused_body_forward(self.body, x, residual)
        out = self.body(x)
        return F.Activation(out + residual, act_type="relu")


class BasicBlockV2(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 fuse_epilogue=False, **kwargs):
        super().__init__(**kwargs)
        self._fused = fuse_epilogue
        self.bn1 = _bn(fuse_epilogue, "relu")
        self.conv1 = _conv3x3(channels, stride, in_channels)
        self.bn2 = _bn(fuse_epilogue, "relu")
        self.conv2 = _conv3x3(channels, 1, channels)
        if downsample:
            self.downsample = nn.Conv2D(channels, 1, stride, use_bias=False,
                                        in_channels=in_channels)
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        out = self.bn1(x)
        if not self._fused:
            out = F.Activation(out, act_type="relu")
        if self.downsample is not None:
            residual = self.downsample(out)
        out = self.conv1(out)
        out = self.bn2(out)
        if not self._fused:
            out = F.Activation(out, act_type="relu")
        out = self.conv2(out)
        return out + residual


class BottleneckV2(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 fuse_epilogue=False, **kwargs):
        super().__init__(**kwargs)
        self._fused = fuse_epilogue
        self.bn1 = _bn(fuse_epilogue, "relu")
        self.conv1 = nn.Conv2D(channels // 4, kernel_size=1, strides=1, use_bias=False)
        self.bn2 = _bn(fuse_epilogue, "relu")
        self.conv2 = _conv3x3(channels // 4, stride, channels // 4)
        self.bn3 = _bn(fuse_epilogue, "relu")
        self.conv3 = nn.Conv2D(channels, kernel_size=1, strides=1, use_bias=False)
        if downsample:
            self.downsample = nn.Conv2D(channels, 1, stride, use_bias=False,
                                        in_channels=in_channels)
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        out = self.bn1(x)
        if not self._fused:
            out = F.Activation(out, act_type="relu")
        if self.downsample is not None:
            residual = self.downsample(out)
        out = self.conv1(out)
        out = self.bn2(out)
        if not self._fused:
            out = F.Activation(out, act_type="relu")
        out = self.conv2(out)
        out = self.bn3(out)
        if not self._fused:
            out = F.Activation(out, act_type="relu")
        out = self.conv3(out)
        return out + residual


def _add_stem(features, channels0, stem_s2d, fuse_epilogue):
    """The non-thumbnail stem: reference 7×7/s2/pad-3 conv, or the
    space-to-depth rewrite (stem_s2d). The conv keeps auto-name conv0_
    in both variants (the s2d transform block is paramless), so the only
    checkpoint delta is the stem weight's shape — convert_stem_params
    maps one onto the other."""
    from ...nn.conv_layers import in_channels_last_scope

    if stem_s2d:
        layout = "NHWC" if in_channels_last_scope() else "NCHW"
        features.add(_SpaceToDepthStem(layout=layout))
        # in_channels deferred: space_to_depth(2) yields 4*C_in channels
        # (12 for RGB), resolved at first forward like the 7x7 stem
        features.add(nn.Conv2D(channels0, kernel_size=4, strides=1,
                               padding=0, use_bias=False))
    else:
        features.add(nn.Conv2D(channels0, 7, 2, 3, use_bias=False))
    features.add(_bn(fuse_epilogue, "relu"))
    if not fuse_epilogue:
        features.add(nn.Activation("relu"))
    features.add(nn.MaxPool2D(3, 2, 1))


class ResNetV1(HybridBlock):
    def __init__(self, block, layers, channels, classes=1000, thumbnail=False,
                 fuse_epilogue=None, stem_s2d=None, **kwargs):
        super().__init__(**kwargs)
        assert len(layers) == len(channels) - 1
        fuse_epilogue = _fuse_epilogue_default(fuse_epilogue)
        stem_s2d = _stem_s2d_default(stem_s2d)
        self._fuse_epilogue = fuse_epilogue
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            if thumbnail:
                self.features.add(_conv3x3(channels[0], 1, 0))
            else:
                _add_stem(self.features, channels[0], stem_s2d, fuse_epilogue)
            for i, num_layer in enumerate(layers):
                stride = 1 if i == 0 else 2
                self.features.add(self._make_layer(block, num_layer, channels[i + 1],
                                                   stride, i + 1,
                                                   in_channels=channels[i],
                                                   fuse_epilogue=fuse_epilogue))
            self.features.add(nn.GlobalAvgPool2D())
            self.output = nn.Dense(classes, in_units=channels[-1])

    def _make_layer(self, block, layers, channels, stride, stage_index,
                    in_channels=0, fuse_epilogue=False):
        layer = nn.HybridSequential(prefix="stage%d_" % stage_index)
        with layer.name_scope():
            layer.add(block(channels, stride, channels != in_channels,
                            in_channels=in_channels,
                            fuse_epilogue=fuse_epilogue, prefix=""))
            for _ in range(layers - 1):
                layer.add(block(channels, 1, False, in_channels=channels,
                                fuse_epilogue=fuse_epilogue, prefix=""))
        return layer

    def hybrid_forward(self, F, x):
        x = self.features(x)
        x = self.output(x)
        return x


class ResNetV2(HybridBlock):
    def __init__(self, block, layers, channels, classes=1000, thumbnail=False,
                 fuse_epilogue=None, stem_s2d=None, **kwargs):
        super().__init__(**kwargs)
        assert len(layers) == len(channels) - 1
        fuse_epilogue = _fuse_epilogue_default(fuse_epilogue)
        stem_s2d = _stem_s2d_default(stem_s2d)
        self._fuse_epilogue = fuse_epilogue
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(nn.BatchNorm(scale=False, center=False))
            if thumbnail:
                self.features.add(_conv3x3(channels[0], 1, 0))
            else:
                _add_stem(self.features, channels[0], stem_s2d, fuse_epilogue)
            in_channels = channels[0]
            for i, num_layer in enumerate(layers):
                stride = 1 if i == 0 else 2
                self.features.add(self._make_layer(block, num_layer, channels[i + 1],
                                                   stride, i + 1,
                                                   in_channels=in_channels,
                                                   fuse_epilogue=fuse_epilogue))
                in_channels = channels[i + 1]
            self.features.add(_bn(fuse_epilogue, "relu"))
            if not fuse_epilogue:
                self.features.add(nn.Activation("relu"))
            self.features.add(nn.GlobalAvgPool2D())
            self.features.add(nn.Flatten())
            self.output = nn.Dense(classes, in_units=in_channels)

    _make_layer = ResNetV1._make_layer

    def hybrid_forward(self, F, x):
        x = self.features(x)
        x = self.output(x)
        return x


resnet_spec = {18: ("basic_block", [2, 2, 2, 2], [64, 64, 128, 256, 512]),
               34: ("basic_block", [3, 4, 6, 3], [64, 64, 128, 256, 512]),
               50: ("bottle_neck", [3, 4, 6, 3], [64, 256, 512, 1024, 2048]),
               101: ("bottle_neck", [3, 4, 23, 3], [64, 256, 512, 1024, 2048]),
               152: ("bottle_neck", [3, 8, 36, 3], [64, 256, 512, 1024, 2048])}

resnet_net_versions = [ResNetV1, ResNetV2]
resnet_block_versions = [{"basic_block": BasicBlockV1, "bottle_neck": BottleneckV1},
                         {"basic_block": BasicBlockV2, "bottle_neck": BottleneckV2}]


def get_resnet(version, num_layers, pretrained=False, ctx=None, root=None, **kwargs):
    """reference: resnet.py get_resnet. TPU extensions: fuse_epilogue=
    and stem_s2d= (both default to their MXTPU_* env flags; see module
    docstring)."""
    assert num_layers in resnet_spec, \
        "Invalid resnet depth %d; options: %s" % (num_layers, sorted(resnet_spec))
    block_type, layers, channels = resnet_spec[num_layers]
    assert version in (1, 2)
    resnet_class = resnet_net_versions[version - 1]
    block_class = resnet_block_versions[version - 1][block_type]
    net = resnet_class(block_class, layers, channels, **kwargs)
    if pretrained:
        raise MXNetError("pretrained weights unavailable (zero-egress build); "
                         "load with net.load_parameters(path) instead")
    return net


def resnet18_v1(**kwargs):
    return get_resnet(1, 18, **kwargs)


def resnet34_v1(**kwargs):
    return get_resnet(1, 34, **kwargs)


def resnet50_v1(**kwargs):
    return get_resnet(1, 50, **kwargs)


def resnet101_v1(**kwargs):
    return get_resnet(1, 101, **kwargs)


def resnet152_v1(**kwargs):
    return get_resnet(1, 152, **kwargs)


def resnet18_v2(**kwargs):
    return get_resnet(2, 18, **kwargs)


def resnet34_v2(**kwargs):
    return get_resnet(2, 34, **kwargs)


def resnet50_v2(**kwargs):
    return get_resnet(2, 50, **kwargs)


def resnet101_v2(**kwargs):
    return get_resnet(2, 101, **kwargs)


def resnet152_v2(**kwargs):
    return get_resnet(2, 152, **kwargs)
