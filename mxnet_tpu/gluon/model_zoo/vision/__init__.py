"""Model zoo: vision (reference: python/mxnet/gluon/model_zoo/vision/__init__.py).

get_model('resnet50_v1') etc. Pretrained weights are file-based
(net.load_parameters) — this build has zero egress, so the reference's
model_store download path is not available."""
from .resnet import *  # noqa: F401,F403
from .alexnet import *  # noqa: F401,F403
from .vgg import *  # noqa: F401,F403
from .squeezenet import *  # noqa: F401,F403
from .densenet import *  # noqa: F401,F403
from .inception import *  # noqa: F401,F403
from .mobilenet import *  # noqa: F401,F403
from .ssd import *  # noqa: F401,F403

from ....base import MXNetError

# resolve submodules via sys.modules: `from .alexnet import *` binds the
# *function* alexnet over the package attribute, so `from . import alexnet`
# would hand the loop a function with no __all__ and silently skip the family
import sys as _sys

_models = {}
for _mod in [_sys.modules[__name__ + "." + _m]
             for _m in ("resnet", "alexnet", "vgg", "squeezenet", "densenet",
                        "inception", "mobilenet", "ssd")]:
    for _name in getattr(_mod, "__all__", []):
        _obj = getattr(_mod, _name)
        if callable(_obj) and _name[0].islower():
            _models[_name] = _obj


def get_model(name, **kwargs):
    """reference: model_zoo/vision/__init__.py get_model. Accepts the
    reference's dotted names too ('mobilenet0.25', 'squeezenet1.0',
    'inceptionv3' — its key style) alongside the pythonic factory
    names ('mobilenet0_25', 'inception_v3')."""
    name = name.lower()
    if name not in _models:
        # reference key style -> factory-name normalization
        alt = name.replace(".", "_")
        if alt == "inceptionv3":
            alt = "inception_v3"
        alt = alt.replace("mobilenetv2_", "mobilenet_v2_")
        if alt in _models:
            name = alt
    if name not in _models:
        raise MXNetError("Model %s not supported. Available: %s"
                         % (name, sorted(_models)))
    return _models[name](**kwargs)
