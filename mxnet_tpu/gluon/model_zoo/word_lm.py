"""Word-level language model (embedding -> LSTM -> decoder).

Reference: example/rnn/word_lm/model.py (the reference's canonical word-LM:
Embedding + stacked LSTM + FullyConnected decoder with optional weight
tying, trained on PTB via Module/bucketing). TPU-native: the LSTM is the
lax.scan fused layer (gluon/rnn/rnn_layer.py -> ops/rnn.py); sequence
length is static per bucket, so each bucket compiles once — the executable
cache plays the role of BucketingModule's shared executors.
"""
from __future__ import annotations

from ..block import HybridBlock
from .. import nn, rnn

__all__ = ["RNNModel"]


class RNNModel(HybridBlock):
    """reference: example/rnn/word_lm/model.py rnn(bptt, vocab_size, ...)."""

    def __init__(self, vocab_size, embed_size=200, hidden_size=200,
                 num_layers=2, dropout=0.5, tie_weights=False, **kwargs):
        super().__init__(**kwargs)
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        with self.name_scope():
            self.drop = nn.Dropout(dropout) if dropout else None
            self.embedding = nn.Embedding(vocab_size, embed_size,
                                          prefix="embed_")
            self.rnn = rnn.LSTM(hidden_size, num_layers=num_layers,
                                dropout=dropout, input_size=embed_size,
                                layout="TNC", prefix="lstm_")
            if tie_weights:
                if embed_size != hidden_size:
                    raise ValueError("tie_weights requires embed_size == "
                                     "hidden_size (as in reference)")
                self.decoder = nn.Dense(vocab_size, flatten=False,
                                        params=self.embedding.params,
                                        prefix="embed_")
            else:
                self.decoder = nn.Dense(vocab_size, flatten=False,
                                        prefix="decoder_")

    def begin_state(self, batch_size, ctx=None, func=None):
        return self.rnn.begin_state(batch_size=batch_size, ctx=ctx)

    def hybrid_forward(self, F, inputs, state=None):
        """inputs: (T, B) int ids. Returns (logits (T, B, vocab), state)."""
        emb = self.embedding(inputs)
        if self.drop is not None:
            emb = self.drop(emb)
        if state is None:
            out = self.rnn(emb)
            state = None
        else:
            out, state = self.rnn(emb, state)
        if self.drop is not None:
            out = self.drop(out)
        logits = self.decoder(out)
        if state is None:
            return logits
        return logits, state
