"""Transformer encoder + BERT model family.

The reference provides the transformer/BERT *operator* building blocks
in-tree (LayerNorm src/operator/nn/layer_norm.cc, GELU activation,
div_sqrt_dim src/operator/contrib/transformer.cc:34) with the model living
in external GluonNLP; SURVEY §7 phase 6 calls for the model family here.
TPU-native: attention runs the Pallas flash kernel
(ops/pallas_kernels.py) when no padding mask is given — O(L·D) HBM traffic —
and a masked dense path (batch_dot + softmax) when `valid_length` requires
arbitrary masking. All blocks hybridize.
"""
from __future__ import annotations

import math

from ...base import MXNetError
from ..block import HybridBlock
from .. import nn

__all__ = ["MultiHeadAttention", "PositionwiseFFN", "TransformerEncoderCell",
           "TransformerEncoder", "BERTModel", "bert_12_768_12", "bert_mini",
           "TransformerLM", "lm_mini"]


class MultiHeadAttention(HybridBlock):
    """Multi-head self/cross attention (reference building blocks:
    contrib/transformer.cc div_sqrt_dim + batch_dot/softmax assembly)."""

    def __init__(self, units, num_heads, dropout=0.0, use_bias=True, **kwargs):
        super().__init__(**kwargs)
        if units % num_heads != 0:
            raise MXNetError("num_heads (%d) must evenly divide units (%d)"
                             % (num_heads, units))
        self._units = units
        self._num_heads = num_heads
        with self.name_scope():
            self.proj_query = nn.Dense(units, flatten=False, use_bias=use_bias,
                                       prefix="query_")
            self.proj_key = nn.Dense(units, flatten=False, use_bias=use_bias,
                                     prefix="key_")
            self.proj_value = nn.Dense(units, flatten=False, use_bias=use_bias,
                                       prefix="value_")
            self.proj_out = nn.Dense(units, flatten=False, use_bias=use_bias,
                                     prefix="out_")
            self.dropout = nn.Dropout(dropout) if dropout else None

    def _split(self, x, batch, length):
        # (B, L, C) -> (B, H, L, Dh)
        h = self._num_heads
        return x.reshape((batch, length, h, self._units // h)) \
                .transpose((0, 2, 1, 3))

    def hybrid_forward(self, F, query, key=None, value=None, mask=None):
        key = query if key is None else key
        value = key if value is None else value
        b, lq = query.shape[0], query.shape[1]
        lk = key.shape[1]
        q = self._split(self.proj_query(query), b, lq)
        k = self._split(self.proj_key(key), b, lk)
        v = self._split(self.proj_value(value), b, lk)
        dh = self._units // self._num_heads
        if mask is None:
            out = F.contrib.flash_attention(q, k, v, causal=False,
                                            sm_scale=1.0 / math.sqrt(dh))
        else:
            # masked dense path: scores (B, H, Lq, Lk); mask (B, Lq, Lk)
            qf = q.reshape((-1, lq, dh))
            kf = k.reshape((-1, lk, dh))
            vf = v.reshape((-1, lk, dh))
            scores = F.batch_dot(qf, kf, transpose_b=True) / math.sqrt(dh)
            scores = scores.reshape((b, self._num_heads, lq, lk))
            neg = F.ones_like(scores) * -1e30
            m = mask.expand_dims(1).broadcast_to(scores.shape)
            scores = F.where(m > 0, scores, neg)
            att = scores.reshape((-1, lq, lk)).softmax(axis=-1)
            out = F.batch_dot(att, vf).reshape(
                (b, self._num_heads, lq, dh))
        out = out.transpose((0, 2, 1, 3)).reshape((b, lq, self._units))
        out = self.proj_out(out)
        if self.dropout is not None:
            out = self.dropout(out)
        return out


class PositionwiseFFN(HybridBlock):
    """FFN sublayer with GELU (reference op: Activation act_type='gelu')."""

    def __init__(self, units, hidden_size, dropout=0.0, activation="gelu",
                 **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.ffn_1 = nn.Dense(hidden_size, flatten=False, prefix="ffn1_")
            # gelu is a dedicated block (reference reaches it via
            # LeakyReLU(act_type='gelu'), not Activation)
            self.activation = nn.GELU() if activation == "gelu" \
                else nn.Activation(activation)
            self.ffn_2 = nn.Dense(units, flatten=False, prefix="ffn2_")
            self.dropout = nn.Dropout(dropout) if dropout else None

    def hybrid_forward(self, F, x):
        out = self.ffn_2(self.activation(self.ffn_1(x)))
        if self.dropout is not None:
            out = self.dropout(out)
        return out


class TransformerEncoderCell(HybridBlock):
    """Pre-LN-free (post-LN, BERT-style) encoder layer."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.attention = MultiHeadAttention(units, num_heads,
                                                dropout=dropout)
            self.attention_norm = nn.LayerNorm()
            self.ffn = PositionwiseFFN(units, hidden_size, dropout=dropout)
            self.ffn_norm = nn.LayerNorm()

    def hybrid_forward(self, F, x, mask=None):
        out = self.attention_norm(x + self.attention(x, x, x, mask))
        return self.ffn_norm(out + self.ffn(out))


class TransformerEncoder(HybridBlock):
    def __init__(self, units=512, hidden_size=2048, num_layers=6, num_heads=8,
                 dropout=0.0, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        with self.name_scope():
            self.cells = []
            for i in range(num_layers):
                cell = TransformerEncoderCell(units, hidden_size, num_heads,
                                              dropout=dropout,
                                              prefix="layer%d_" % i)
                self.register_child(cell)
                self.cells.append(cell)

    def hybrid_forward(self, F, x, mask=None):
        for cell in self.cells:
            x = cell(x, mask)
        return x


class BERTModel(HybridBlock):
    """BERT encoder with token/segment/position embeddings + pooler
    (model family per SURVEY §7 phase 6; ops parity with the reference's
    LayerNorm/GELU/attention primitives)."""

    def __init__(self, vocab_size=30522, units=768, hidden_size=3072,
                 num_layers=12, num_heads=12, max_length=512,
                 token_types=2, dropout=0.1, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        with self.name_scope():
            self.word_embed = nn.Embedding(vocab_size, units, prefix="word_")
            self.token_type_embed = nn.Embedding(token_types, units,
                                                 prefix="segment_")
            self.position_embed = nn.Embedding(max_length, units, prefix="pos_")
            self.embed_norm = nn.LayerNorm()
            self.embed_dropout = nn.Dropout(dropout) if dropout else None
            self.encoder = TransformerEncoder(units=units,
                                              hidden_size=hidden_size,
                                              num_layers=num_layers,
                                              num_heads=num_heads,
                                              dropout=dropout)
            self.pooler = nn.Dense(units, activation="tanh", prefix="pooler_")

    def _embed_prelude(self, F, inputs, token_types=None, valid_length=None):
        """Embedding front: token+segment+position embed, norm, dropout and
        the (B, Lq, Lk) 1/0 attention mask from per-sample valid lengths —
        the single source of truth for both hybrid_forward and
        pipeline_stages."""
        b, l = inputs.shape[0], inputs.shape[1]
        x = self.word_embed(inputs)
        if token_types is not None:
            x = x + self.token_type_embed(token_types)
        pos = F.arange(0, l, dtype="int32")
        x = x + self.position_embed(pos).expand_dims(0)
        x = self.embed_norm(x)
        if self.embed_dropout is not None:
            x = self.embed_dropout(x)
        mask = None
        if valid_length is not None:
            steps = F.arange(0, l)
            mask = (steps.expand_dims(0) <
                    valid_length.astype("float32").expand_dims(1)) \
                .expand_dims(1).broadcast_to((b, l, l))
        return x, mask

    def _pool_postlude(self, seq):
        """CLS-token pooler (the back end of the pipeline decomposition)."""
        b = seq.shape[0]
        return self.pooler(seq.slice_axis(1, 0, 1).reshape((b, self._units)))

    def hybrid_forward(self, F, inputs, token_types=None, valid_length=None):
        """inputs: (B, L) int token ids. Returns (sequence_out (B, L, C),
        pooled_out (B, C))."""
        x, mask = self._embed_prelude(F, inputs, token_types, valid_length)
        seq = self.encoder(x, mask)
        return seq, self._pool_postlude(seq)

    def pipeline_stages(self):
        """Decompose for parallel.PipelineTrainer: (prelude, cells,
        postlude). prelude embeds tokens (replicated); cells are the
        homogeneous encoder layers (pipelined over `pp`); postlude pools.
        The pooled vector is returned as the prediction (sequence output
        stays available by calling the model directly)."""
        from ... import ndarray as F

        def prelude(inputs, token_types=None, valid_length=None):
            return self._embed_prelude(F, inputs, token_types, valid_length)

        return prelude, list(self.encoder.cells), self._pool_postlude


class TransformerLM(HybridBlock):
    """Decoder-only language model: the BERT encoder cells under a causal
    mask, with a TIED embedding head (the logits projection reuses the
    word-embedding weight — one parameter, GPT/PaLM convention). The
    servable text-generation workload `mxnet_tpu.serving.generate` wraps
    with a paged KV cache; this block is the full-sequence form used for
    training, prefill parity and the greedy oracle."""

    def __init__(self, vocab_size=1000, units=64, hidden_size=128,
                 num_layers=2, num_heads=2, max_length=256, dropout=0.0,
                 **kwargs):
        super().__init__(**kwargs)
        self._config = dict(vocab_size=int(vocab_size), units=int(units),
                            hidden_size=int(hidden_size),
                            num_layers=int(num_layers),
                            num_heads=int(num_heads),
                            max_length=int(max_length),
                            dropout=float(dropout))
        self._vocab = int(vocab_size)
        self._units = int(units)
        with self.name_scope():
            # the tied weight is declared on THIS block (not an Embedding
            # child) so hybrid_forward receives it and can use it for both
            # the lookup and the head projection
            self.word_weight = self.params.get(
                "word_weight", shape=(vocab_size, units))
            self.position_embed = nn.Embedding(max_length, units,
                                               prefix="pos_")
            self.embed_norm = nn.LayerNorm()
            self.embed_dropout = nn.Dropout(dropout) if dropout else None
            self.cells = []
            for i in range(num_layers):
                cell = TransformerEncoderCell(units, hidden_size, num_heads,
                                              dropout=dropout,
                                              prefix="layer%d_" % i)
                self.register_child(cell)
                self.cells.append(cell)

    @property
    def config(self):
        """Architecture dict (`serving.generate` artifact header)."""
        return dict(self._config)

    def hybrid_forward(self, F, inputs, word_weight):
        """inputs: (B, L) int token ids -> logits (B, L, V); position t
        sees tokens [0, t] (causal)."""
        b, l = inputs.shape[0], inputs.shape[1]
        x = F.Embedding(inputs, word_weight, input_dim=self._vocab,
                        output_dim=self._units)
        pos = F.arange(0, l, dtype="int32")
        x = x + self.position_embed(pos).expand_dims(0)
        x = self.embed_norm(x)
        if self.embed_dropout is not None:
            x = self.embed_dropout(x)
        row = F.arange(0, l).expand_dims(1)
        col = F.arange(0, l).expand_dims(0)
        mask = (col <= row).expand_dims(0).broadcast_to((b, l, l))
        for cell in self.cells:
            x = cell(x, mask)
        # tied head: logits = x @ word_weight.T
        return F.FullyConnected(x, word_weight, None, num_hidden=self._vocab,
                                flatten=False, no_bias=True)

    def decode_params(self):
        """The parameters as a structured numpy dict, in the layout the
        `serving.generate.TransformerLMEngine` pure-jax prefill/decode
        functions consume (the engine and this block must compute the
        same function — tests/test_generate.py proves it)."""
        if any(p._data is None for p in self.collect_params().values()):
            # deferred Dense shapes materialize on first forward
            from ... import nd

            self(nd.array([[0]], dtype="int32"))

        def arr(p):
            return p.data().asnumpy()

        def dense(d):
            return {"w": arr(d.weight), "b": arr(d.bias)}

        layers = []
        for cell in self.cells:
            att = cell.attention
            layers.append({
                "q": dense(att.proj_query), "k": dense(att.proj_key),
                "v": dense(att.proj_value), "o": dense(att.proj_out),
                "attn_norm": {"g": arr(cell.attention_norm.gamma),
                              "b": arr(cell.attention_norm.beta)},
                "ffn1": dense(cell.ffn.ffn_1),
                "ffn2": dense(cell.ffn.ffn_2),
                "ffn_norm": {"g": arr(cell.ffn_norm.gamma),
                             "b": arr(cell.ffn_norm.beta)},
            })
        return {"word": arr(self.word_weight),
                "pos": arr(self.position_embed.weight),
                "embed_norm": {"g": arr(self.embed_norm.gamma),
                               "b": arr(self.embed_norm.beta)},
                "layers": layers}


def lm_mini(vocab_size=128, **kwargs):
    """Tiny decoder-only LM for tests/examples (2 layers, d=32)."""
    kwargs.setdefault("units", 32)
    kwargs.setdefault("hidden_size", 64)
    kwargs.setdefault("num_layers", 2)
    kwargs.setdefault("num_heads", 2)
    kwargs.setdefault("max_length", 128)
    return TransformerLM(vocab_size=vocab_size, **kwargs)


def bert_12_768_12(vocab_size=30522, **kwargs):
    """BERT-base geometry."""
    return BERTModel(vocab_size=vocab_size, units=768, hidden_size=3072,
                     num_layers=12, num_heads=12, **kwargs)


def bert_mini(vocab_size=1000, **kwargs):
    """Tiny geometry for tests/examples."""
    return BERTModel(vocab_size=vocab_size, units=64, hidden_size=128,
                     num_layers=2, num_heads=4, max_length=128, **kwargs)
