"""Gluon Trainer.

Reference: python/mxnet/gluon/trainer.py:27 — `step` :298 = allreduce grads
across device copies (:327, via kvstore) + optimizer update per copy (:359).

TPU-native: for the single-process multi-device case the grad reduction is a
kvstore('device') push/pull which lowers onto one XLA add over device buffers;
the *scaled* path is mxnet_tpu.parallel.DistributedTrainer, which keeps ONE
sharded copy of each parameter on the mesh and lets XLA insert the
all-reduces inside the compiled step (SURVEY §2.3 row 1)."""
from __future__ import annotations

import time

from .. import env as _env
from ..base import MXNetError
from .. import optimizer as opt
from .. import telemetry
from .parameter import Parameter, ParameterDict

__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None, kvstore="device",
                 compression_params=None, update_on_kvstore=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise MXNetError("params must be list/dict of Parameters")
        self._params = []
        self._param2idx = {}
        for i, p in enumerate(params):
            if not isinstance(p, Parameter):
                raise MXNetError("invalid parameter %r" % (p,))
            self._params.append(p)
            self._param2idx[p.name] = i
        self._compression_params = compression_params
        self._contexts = self._check_contexts()
        optimizer_params = optimizer_params or {}
        self._init_optimizer(optimizer, optimizer_params)
        self._scale = self._optimizer.rescale_grad
        self._kvstore_type = kvstore
        self._kvstore = None
        self._kv_initialized = False
        self._update_on_kvstore = update_on_kvstore
        # completed-update cursor: drives the fault-injection hook and is
        # saved/restored with the optimizer states so an auto-resumed run
        # keeps a monotonically correct step count (parallel/resilience.py)
        self._step_count = 0

    def _check_contexts(self):
        contexts = None
        for param in self._params:
            ctx = param.list_ctx()
            assert contexts is None or contexts == ctx, \
                "All Parameters must be initialized on the same set of contexts"
            contexts = ctx
        return contexts or []

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: p for i, p in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            assert not optimizer_params, \
                "optimizer_params must be empty when optimizer is an instance"
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)
        self._updaters = [opt.get_updater(self._optimizer)
                          for _ in self._contexts]

    def _is_dist_kvstore(self):
        """Rank-spanning kvstore? (needs grad sync even with ONE local
        device — the reference's standard 1-GPU-per-worker mode,
        trainer.py:169 `'dist' in kvstore.type`)."""
        kt = self._kvstore_type
        if isinstance(kt, str):
            # every name kvstore.create() maps to _DistKVStore
            return "dist" in kt or kt in ("horovod", "tpu")
        return getattr(kt, "num_workers", 1) > 1

    def _init_kvstore(self):
        """Lazily create the kvstore (reference: trainer.py:169)."""
        self._kv_initialized = True
        if not self._kvstore_type or (len(self._contexts) < 2
                                      and not self._is_dist_kvstore()):
            self._kvstore = None
            return
        from .. import kvstore as kvs

        kv = kvs.create(self._kvstore_type) if isinstance(self._kvstore_type, str) \
            else self._kvstore_type
        self._kvstore = kv
        if self._compression_params:
            kv.set_gradient_compression(self._compression_params)
        dist = self._is_dist_kvstore()
        for i, param in enumerate(self._params):
            if param._data is not None:
                kv.init(i, param.list_data()[0])
                if dist:
                    # adopt the group-authoritative (rank 0) initial value
                    # so every rank trains the same replica
                    kv.pull(i, out=param.list_data())

    @property
    def learning_rate(self):
        return self._optimizer.lr_scheduler(self._optimizer.num_update) \
            if self._optimizer.lr_scheduler else self._optimizer.lr

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    @property
    def step_count(self):
        """Number of completed step() calls (survives save/load_states)."""
        return self._step_count

    def step(self, batch_size, ignore_stale_grad=False):
        """Allreduce grads + update (reference: trainer.py:298)."""
        t0 = time.perf_counter()
        # distributed tracing: a sampled step records allreduce/optimizer
        # phase spans (no-op span when tracing is unarmed)
        with telemetry.tracing.root("train.step", component="train",
                                    attrs={"step": self._step_count + 1}):
            if not self._kv_initialized:
                self._init_kvstore()
            self._optimizer.rescale_grad = self._scale / batch_size
            with telemetry.tracing.span("train.allreduce"):
                self._allreduce_grads()
            with telemetry.tracing.span("train.optimizer"):
                self._update(ignore_stale_grad)
            self._step_count += 1
            # always-on telemetry: step wall time, examples/sec, MFU (auto
            # cost-analysis FLOPs, or set_step_flops when declared) + the
            # flight-recorder/watchdog heartbeat
            telemetry.observe_step(time.perf_counter() - t0,
                                   examples=batch_size,
                                   step=self._step_count)
        # step-boundary fault hook; the env guard keeps the hot path free
        # of even the import lookup when injection is unarmed
        if _env.is_set("MXTPU_FAULT_INJECT"):
            from ..parallel import resilience

            resilience.maybe_inject_fault(self._step_count)

    def allreduce_grads(self):
        if not self._kv_initialized:
            self._init_kvstore()
        self._allreduce_grads()

    def _allreduce_grads(self):
        if len(self._contexts) < 2 and self._kvstore is None:
            return
        for i, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            grads = param.list_grad()
            if self._kvstore is not None:
                self._kvstore.push(i, grads)
                self._kvstore.pull(i, out=grads)
            else:
                total = grads[0]
                for g in grads[1:]:
                    total = total + g.as_in_context(total.context)
                for g in grads:
                    g._set_data(total.as_in_context(g.context)._data)

    def update(self, batch_size, ignore_stale_grad=False):
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        for i, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            if not ignore_stale_grad:
                for data in param.list_data():
                    if not data._fresh_grad:
                        raise MXNetError(
                            "Gradient of Parameter `%s` on context %s has not been "
                            "updated by backward since last step. Set "
                            "ignore_stale_grad=True to suppress" % (param.name, data.context))
            for upd, arr, grad in zip(self._updaters, param.list_data(),
                                      param.list_grad()):
                if getattr(param, "_grad_stype", "default") == "row_sparse" \
                        and getattr(self._optimizer, "supports_sparse", False):
                    # tape grads are dense; cast to row_sparse so the
                    # optimizer takes the lazy row-update path (reference:
                    # parameter.py grad_stype + sparse optimizer kernels).
                    # Optimizers without a sparse kernel stay dense, like the
                    # reference's storage-fallback wrappers (common/exec_utils.h)
                    grad = grad.tostype("row_sparse")
                upd(i, grad, arr)
                arr._fresh_grad = False

    def save_states(self, fname):
        """reference: trainer.py:429 — extended with the step cursor so an
        auto-resumed run (parallel/resilience.py) continues the schedule,
        and written atomically (temp + fsync + rename) so a kill mid-save
        never truncates the states file."""
        import pickle

        from ..base import atomic_writer

        assert self._optimizer is not None
        blob = {"__mxtpu_trainer_states__": 1,
                "updater": self._updaters[0].get_states(dump_optimizer=True),
                "step_count": self._step_count}
        with atomic_writer(fname, "wb") as f:
            pickle.dump(blob, f)

    def load_states(self, fname):
        """reference: trainer.py:458 (legacy raw updater blobs still load)."""
        import pickle

        with open(fname, "rb") as f:
            raw = f.read()
        states = raw
        try:
            blob = pickle.loads(raw)
        except Exception:
            blob = None
        if isinstance(blob, dict) and "__mxtpu_trainer_states__" in blob:
            states = blob["updater"]
            self._step_count = int(blob.get("step_count", 0))
        for updater in self._updaters:
            updater.set_states(states)
            updater.optimizer = self._optimizer
