"""Gluon Trainer.

Reference: python/mxnet/gluon/trainer.py:27 — `step` :298 = allreduce grads
across device copies (:327, via kvstore) + optimizer update per copy (:359).

TPU-native: for the single-process multi-device case the grad reduction is a
kvstore('device') push/pull which lowers onto one XLA add over device buffers;
the *scaled* path is mxnet_tpu.parallel.DistributedTrainer, which keeps ONE
sharded copy of each parameter on the mesh and lets XLA insert the
all-reduces inside the compiled step (SURVEY §2.3 row 1).

Promotion (`sharded=True` + ``block=``/``loss=``, or fleet-wide via
``MXTPU_SHARDED_STEP`` when a block is supplied): the trainer internally
becomes a `parallel.ShardedTrainer` — forward + loss + backward + optimizer
update run as ONE compiled executable with donated param/state buffers, and
``step_batch(data, label)`` replaces the record/backward/step() triplet
(the loss scalar stays on device until the caller asks). Promoted
executables persist across processes (docs/sharded_training.md)."""
from __future__ import annotations

import time

from .. import env as _env
from ..base import MXNetError
from .. import optimizer as opt
from .. import telemetry
from .parameter import Parameter, ParameterDict

__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None, kvstore="device",
                 compression_params=None, update_on_kvstore=None, sharded=None,
                 block=None, loss=None, mesh=None, sharding_rules=None,
                 amp_dtype=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise MXNetError("params must be list/dict of Parameters")
        self._params = []
        self._param2idx = {}
        for i, p in enumerate(params):
            if not isinstance(p, Parameter):
                raise MXNetError("invalid parameter %r" % (p,))
            self._params.append(p)
            self._param2idx[p.name] = i
        self._compression_params = compression_params
        self._contexts = self._check_contexts()
        optimizer_params = optimizer_params or {}
        # -- promotion to the fused sharded step -------------------------
        # sharded=None defers to MXTPU_SHARDED_STEP (armed fleet-wide by
        # tools/launch.py --sharded-step), which only promotes when the
        # caller supplied the block — op-by-op callers are untouched
        if sharded is None:
            sharded = block is not None and _env.get("MXTPU_SHARDED_STEP")
        self._sharded = None
        if sharded:
            if block is None:
                raise MXNetError(
                    "Trainer(sharded=True) needs block= (and usually "
                    "loss=): the fused step traces the block's forward — "
                    "see docs/sharded_training.md")
            from ..parallel.sharded_trainer import ShardedTrainer

            self._sharded = ShardedTrainer(
                block, optimizer, optimizer_params=optimizer_params,
                loss=loss, mesh=mesh, rules=sharding_rules,
                amp_dtype=amp_dtype)
            self._optimizer = self._sharded.optimizer
            self._scale = self._optimizer.rescale_grad
            self._kvstore_type = None
            self._kvstore = None
            self._kv_initialized = True
            self._update_on_kvstore = None
            self._step_count = 0
            return
        self._init_optimizer(optimizer, optimizer_params)
        self._scale = self._optimizer.rescale_grad
        self._kvstore_type = kvstore
        self._kvstore = None
        self._kv_initialized = False
        self._update_on_kvstore = update_on_kvstore
        # completed-update cursor: drives the fault-injection hook and is
        # saved/restored with the optimizer states so an auto-resumed run
        # keeps a monotonically correct step count (parallel/resilience.py)
        self._step_count = 0

    @property
    def sharded(self):
        """The promoted `parallel.ShardedTrainer` (None on the op-by-op
        path)."""
        return self._sharded

    def _check_contexts(self):
        contexts = None
        for param in self._params:
            ctx = param.list_ctx()
            assert contexts is None or contexts == ctx, \
                "All Parameters must be initialized on the same set of contexts"
            contexts = ctx
        return contexts or []

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: p for i, p in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            assert not optimizer_params, \
                "optimizer_params must be empty when optimizer is an instance"
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)
        self._updaters = [opt.get_updater(self._optimizer)
                          for _ in self._contexts]

    def _is_dist_kvstore(self):
        """Rank-spanning kvstore? (needs grad sync even with ONE local
        device — the reference's standard 1-GPU-per-worker mode,
        trainer.py:169 `'dist' in kvstore.type`)."""
        kt = self._kvstore_type
        if isinstance(kt, str):
            # every name kvstore.create() maps to _DistKVStore
            return "dist" in kt or kt in ("horovod", "tpu")
        return getattr(kt, "num_workers", 1) > 1

    def _init_kvstore(self):
        """Lazily create the kvstore (reference: trainer.py:169)."""
        self._kv_initialized = True
        if not self._kvstore_type or (len(self._contexts) < 2
                                      and not self._is_dist_kvstore()):
            self._kvstore = None
            return
        from .. import kvstore as kvs

        kv = kvs.create(self._kvstore_type) if isinstance(self._kvstore_type, str) \
            else self._kvstore_type
        self._kvstore = kv
        if self._compression_params:
            kv.set_gradient_compression(self._compression_params)
        dist = self._is_dist_kvstore()
        for i, param in enumerate(self._params):
            if param._data is not None:
                kv.init(i, param.list_data()[0])
                if dist:
                    # adopt the group-authoritative (rank 0) initial value
                    # so every rank trains the same replica
                    kv.pull(i, out=param.list_data())

    @property
    def learning_rate(self):
        return self._optimizer.lr_scheduler(self._optimizer.num_update) \
            if self._optimizer.lr_scheduler else self._optimizer.lr

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    @property
    def step_count(self):
        """Number of completed step() calls (survives save/load_states)."""
        if self._sharded is not None:
            return self._sharded._step_count
        return self._step_count

    def step_batch(self, data, label=None):
        """The promoted hot path: one fused forward+loss+backward+update
        over the batch, returning the (device-resident) scalar loss
        NDArray — no host sync happens unless the caller asks for one.
        Requires promotion (``sharded=True``/``MXTPU_SHARDED_STEP``)."""
        if self._sharded is None:
            raise MXNetError(
                "step_batch() needs a promoted trainer: construct with "
                "sharded=True, block= and loss= (docs/sharded_training.md)")
        return self._sharded.step(data, label)

    def prefetch(self, it, depth=None):
        """Wrap `it` in a mesh-aware `data.DevicePrefetcher` so step_batch
        consumes already-sharded device batches (promoted path only)."""
        if self._sharded is None:
            raise MXNetError(
                "prefetch() needs a promoted trainer: construct with "
                "sharded=True, block= and loss= (docs/sharded_training.md)")
        return self._sharded.prefetch(it, depth=depth)

    def sync_params(self):
        """Copy mesh-trained values back into the block's Parameters (the
        promoted path keeps ONE sharded copy per param; call this before
        save_parameters/export). No-op on the op-by-op path, where the
        Parameters themselves are the training copies."""
        if self._sharded is not None:
            self._sharded.sync_params()

    def step(self, batch_size, ignore_stale_grad=False):
        """Allreduce grads + update (reference: trainer.py:298)."""
        if self._sharded is not None:
            raise MXNetError(
                "this Trainer is promoted to the fused sharded step "
                "(sharded=True/MXTPU_SHARDED_STEP): the parameters live on "
                "the mesh and forward+backward+update run as one "
                "executable — drive it with step_batch(data, label) "
                "instead of record()/backward()/step() "
                "(docs/sharded_training.md)")
        t0 = time.perf_counter()
        telemetry.goodput.step_start(kind="train", t0=t0)
        # distributed tracing: a sampled step records allreduce/optimizer
        # phase spans (no-op span when tracing is unarmed)
        with telemetry.tracing.root("train.step", component="train",
                                    attrs={"step": self._step_count + 1}):
            if not self._kv_initialized:
                self._init_kvstore()
            self._optimizer.rescale_grad = self._scale / batch_size
            with telemetry.tracing.span("train.allreduce"), \
                    telemetry.goodput.phase("collective"):
                self._allreduce_grads()
            telemetry.goodput.mark_launch()
            with telemetry.tracing.span("train.optimizer"), \
                    telemetry.goodput.phase("compute"):
                self._update(ignore_stale_grad)
            self._step_count += 1
            # always-on telemetry: step wall time, examples/sec, MFU (auto
            # cost-analysis FLOPs, or set_step_flops when declared) + the
            # flight-recorder/watchdog heartbeat
            telemetry.observe_step(time.perf_counter() - t0,
                                   examples=batch_size,
                                   step=self._step_count)
            telemetry.goodput.step_end(step=self._step_count)
        # step-boundary fault hook; the env guard keeps the hot path free
        # of even the import lookup when injection is unarmed
        if _env.is_set("MXTPU_FAULT_INJECT"):
            from ..parallel import resilience

            resilience.maybe_inject_fault(self._step_count)

    def allreduce_grads(self):
        if self._sharded is not None:
            return  # the fused step's psum already reduced (in-graph)
        if not self._kv_initialized:
            self._init_kvstore()
        self._allreduce_grads()

    def _allreduce_grads(self):
        if len(self._contexts) < 2 and self._kvstore is None:
            return
        for i, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            grads = param.list_grad()
            if self._kvstore is not None:
                self._kvstore.push(i, grads)
                self._kvstore.pull(i, out=grads)
            else:
                total = grads[0]
                for g in grads[1:]:
                    total = total + g.as_in_context(total.context)
                for g in grads:
                    g._set_data(total.as_in_context(g.context)._data)

    def update(self, batch_size, ignore_stale_grad=False):
        if self._sharded is not None:
            raise MXNetError(
                "promoted Trainer: the optimizer update is fused into "
                "step_batch() — there is no separate update() phase")
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        for i, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            if not ignore_stale_grad:
                for data in param.list_data():
                    if not data._fresh_grad:
                        raise MXNetError(
                            "Gradient of Parameter `%s` on context %s has not been "
                            "updated by backward since last step. Set "
                            "ignore_stale_grad=True to suppress" % (param.name, data.context))
            for upd, arr, grad in zip(self._updaters, param.list_data(),
                                      param.list_grad()):
                if getattr(param, "_grad_stype", "default") == "row_sparse" \
                        and getattr(self._optimizer, "supports_sparse", False):
                    # tape grads are dense; cast to row_sparse so the
                    # optimizer takes the lazy row-update path (reference:
                    # parameter.py grad_stype + sparse optimizer kernels).
                    # Optimizers without a sparse kernel stay dense, like the
                    # reference's storage-fallback wrappers (common/exec_utils.h)
                    grad = grad.tostype("row_sparse")
                upd(i, grad, arr)
                arr._fresh_grad = False

    def save_states(self, fname):
        """reference: trainer.py:429 — extended with the step cursor so an
        auto-resumed run (parallel/resilience.py) continues the schedule,
        and written atomically (temp + fsync + rename) so a kill mid-save
        never truncates the states file."""
        import pickle

        from ..base import atomic_writer

        if self._sharded is not None:
            self._sharded.save_states(fname)
            return
        assert self._optimizer is not None
        blob = {"__mxtpu_trainer_states__": 1,
                "updater": self._updaters[0].get_states(dump_optimizer=True),
                "step_count": self._step_count}
        with atomic_writer(fname, "wb") as f:
            pickle.dump(blob, f)

    def _require_sharded(self, what):
        if self._sharded is None:
            raise MXNetError(
                "%s needs the promoted sharded trainer (sharded=True + "
                "block=, or MXTPU_SHARDED_STEP=1); op-by-op trainers "
                "checkpoint via save_states/load_states" % what)
        return self._sharded

    def save_sharded_checkpoint(self, manager, step=None, meta=None):
        """This rank's shard of an async sharded checkpoint
        (parallel.resilience.CheckpointManager.save_sharded_async);
        promoted trainers only."""
        return self._require_sharded("save_sharded_checkpoint").\
            save_sharded_checkpoint(manager, step=step, meta=meta)

    def emergency_sharded_checkpoint(self, manager, meta=None):
        """Solo synchronous preemption checkpoint (flushes the async
        writer first); promoted trainers only."""
        return self._require_sharded("emergency_sharded_checkpoint").\
            emergency_sharded_checkpoint(manager, meta=meta)

    def restore_sharded_checkpoint(self, manager, step=None):
        """Restore the newest sharded checkpoint onto the current mesh,
        resharding elastically when the topology changed; promoted
        trainers only. Returns the manifest header or None."""
        return self._require_sharded("restore_sharded_checkpoint").\
            restore_sharded_checkpoint(manager, step=step)

    def load_states(self, fname):
        """reference: trainer.py:458 (legacy raw updater blobs still load)."""
        import pickle

        if self._sharded is not None:
            self._sharded.load_states(fname)
            return
        with open(fname, "rb") as f:
            raw = f.read()
        states = raw
        try:
            blob = pickle.loads(raw)
        except Exception:
            blob = None
        if isinstance(blob, dict) and "__mxtpu_trainer_states__" in blob:
            states = blob["updater"]
            self._step_count = int(blob.get("step_count", 0))
        for updater in self._updaters:
            updater.set_states(states)
            updater.optimizer = self._optimizer
