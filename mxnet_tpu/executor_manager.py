"""Legacy multi-device executor manager (reference:
python/mxnet/executor_manager.py — the pre-Module data-parallel helper
that FeedForward used: slice the batch across contexts, one bound executor
per slice, summed gradients).

Functional here, not a stub: each slice binds a jit-compiled Executor
(executor.py); forward/backward run per-slice and `update_metric`
aggregates, mirroring DataParallelExecutorManager's surface. New code
should use Module or gluon.Trainer (as the reference itself advises)."""
from __future__ import annotations

import logging

import numpy as _np

from .base import MXNetError
from .ndarray import NDArray, zeros as _nd_zeros

__all__ = ["DataParallelExecutorManager", "_split_input_slice",
           "_check_arguments"]


def _split_input_slice(batch_size, work_load_list):
    """reference: executor_manager.py:31 — batch ranges per device,
    proportional to work_load_list."""
    total = sum(work_load_list)
    if batch_size < len(work_load_list):
        raise MXNetError("batch size %d smaller than device count %d"
                         % (batch_size, len(work_load_list)))
    slices = []
    start = 0
    for i, w in enumerate(work_load_list):
        if i == len(work_load_list) - 1:
            end = batch_size
        else:
            end = start + int(round(batch_size * w / total))
        if end <= start:
            raise MXNetError("Too many slices. Some splits are empty.")
        slices.append(slice(start, end))
        start = end
    return slices


def _check_arguments(symbol):
    """reference: executor_manager.py:68 — duplicate-name check."""
    names = symbol.list_arguments() + symbol.list_auxiliary_states()
    seen = set()
    for n in names:
        if n in seen:
            raise MXNetError(
                "Find duplicated argument name \"%s\"" % n)
        seen.add(n)


class DataParallelExecutorManager:
    """reference: executor_manager.py:298. One executor per context; the
    batch is sliced by `_split_input_slice`; `update_params`-style gradient
    aggregation is the caller's job (FeedForward/optimizer), exposed via
    `param_arrays`/`grad_arrays` lists-of-per-device-arrays, like the
    reference."""

    def __init__(self, symbol, ctx, train_data, arg_params=None,
                 aux_params=None, param_names=None, arg_names=None,
                 aux_names=None, work_load_list=None, logger=logging,
                 sym_gen=None):
        if sym_gen is not None:
            raise MXNetError(
                "sym_gen (bucketing) is not supported by this manager; "
                "use BucketingModule (module/bucketing_module.py)")
        self.symbol = symbol
        self.ctx = list(ctx)
        if work_load_list is None:
            work_load_list = [1] * len(self.ctx)
        batch_size = train_data.batch_size
        self.slices = _split_input_slice(batch_size, work_load_list)
        _check_arguments(symbol)

        self.arg_names = arg_names or symbol.list_arguments()
        self.aux_names = aux_names or symbol.list_auxiliary_states()
        # provide_data entries are DataDesc tuples (name, shape, dtype, ...)
        data_shapes = {d[0]: tuple(d[1]) for d in train_data.provide_data}
        label_shapes = {d[0]: tuple(d[1])
                        for d in (train_data.provide_label or [])}
        self._data_names = list(data_shapes)
        self._label_names = list(label_shapes)
        self.param_names = param_names or [
            n for n in self.arg_names
            if n not in data_shapes and n not in label_shapes]

        arg_shapes, _, aux_shapes = symbol.infer_shape(
            **data_shapes, **label_shapes)
        # infer_shape returns shapes in the SYMBOL's argument order, which
        # may differ from a caller-supplied arg_names ordering
        shape_of = dict(zip(symbol.list_arguments(), arg_shapes))
        aux_shape_of = dict(zip(symbol.list_auxiliary_states(), aux_shapes))

        self.execs = []
        self._slice_shapes = []
        for dev, sl in zip(self.ctx, self.slices):
            n = sl.stop - sl.start
            args, grads = {}, {}
            for name in self.arg_names:
                if name in data_shapes:
                    shp = (n,) + data_shapes[name][1:]
                elif name in label_shapes:
                    shp = (n,) + label_shapes[name][1:]
                else:
                    shp = shape_of[name]
                args[name] = _nd_zeros(shp, ctx=dev)
                if name in self.param_names:
                    grads[name] = _nd_zeros(shp, ctx=dev)
            aux = {name: _nd_zeros(aux_shape_of[name], ctx=dev)
                   for name in self.aux_names}
            from .executor import Executor

            # grads only for params (Module nulls data/label reqs the
            # same way, module/module.py) — labels are often int dtype and
            # must not enter the VJP's wrt set
            req = {name: ("write" if name in self.param_names else "null")
                   for name in self.arg_names}
            self.execs.append(Executor(symbol, dev, args, args_grad=grads,
                                       grad_req=req, aux_states=aux))
            self._slice_shapes.append(n)

        if arg_params is not None:
            self.set_params(arg_params, aux_params or {})
        self._monitor = None

    # -- reference surface -------------------------------------------------
    @property
    def param_arrays(self):
        return [[e.arg_dict[name] for e in self.execs]
                for name in self.param_names]

    @property
    def grad_arrays(self):
        return [[e.grad_dict[name] for e in self.execs]
                for name in self.param_names]

    @property
    def aux_arrays(self):
        return [[e.aux_dict[name] for e in self.execs]
                for name in self.aux_names]

    def install_monitor(self, monitor):
        for e in self.execs:
            monitor.install(e)

    def set_params(self, arg_params, aux_params):
        for e in self.execs:
            e.copy_params_from(arg_params, aux_params,
                               allow_extra_params=True)

    def copy_to(self, arg_params, aux_params):
        """Average params over devices into the dicts (reference:
        executor_manager.py copy_to)."""
        for name in self.param_names:
            vals = [e.arg_dict[name].asnumpy() for e in self.execs]
            arg_params[name] = NDArray(
                _np.mean(vals, axis=0).astype(vals[0].dtype))
        for name in self.aux_names:
            vals = [e.aux_dict[name].asnumpy() for e in self.execs]
            aux_params[name] = NDArray(
                _np.mean(vals, axis=0).astype(vals[0].dtype))

    def load_data_batch(self, data_batch):
        """Slice the batch across executors (reference: _load_data/_load_label)."""
        import jax.numpy as jnp

        for names, arrays in ((self._data_names, data_batch.data),
                              (self._label_names,
                               data_batch.label or [])):
            for name, arr in zip(names, arrays):
                # device arrays slice on-device; only host sources copy
                if isinstance(arr, NDArray):
                    full = arr._data
                else:
                    full = jnp.asarray(_np.asarray(arr))
                for e, sl in zip(self.execs, self.slices):
                    e.arg_dict[name]._set_data(full[sl])

    def forward(self, is_train=False):
        for e in self.execs:
            e.forward(is_train=is_train)

    def backward(self):
        for e in self.execs:
            e.backward()

    def update_metric(self, metric, labels, pre_sliced=False):
        for i, (e, sl) in enumerate(zip(self.execs, self.slices)):
            lab = labels[i] if pre_sliced else \
                [l[sl.start:sl.stop] for l in labels]
            metric.update(lab, e.outputs)
