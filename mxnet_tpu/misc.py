"""Deprecated learning-rate scheduler aliases (reference:
python/mxnet/misc.py — the pre-`lr_scheduler` module some 0.x-era scripts
import). The modern API is `mxnet_tpu.lr_scheduler`."""
from __future__ import annotations

import math

__all__ = ["LearningRateScheduler", "FactorScheduler", "MultiFactorScheduler"]


class LearningRateScheduler:
    """reference: misc.py:23 — legacy base; call with the iteration count."""

    def __init__(self):
        self.base_lr = 0.01

    def __call__(self, iteration):
        raise NotImplementedError("must override this")


class FactorScheduler(LearningRateScheduler):
    """reference: misc.py:40 — lr = base_lr * factor^(iteration // step)."""

    def __init__(self, step, factor=0.1):
        super().__init__()
        if step < 1:
            raise ValueError("Schedule step must be greater or equal than "
                             "1 round")
        if factor >= 1.0:
            raise ValueError("Factor must be less than 1 to make lr reduce")
        self.step = step
        self.factor = factor

    def __call__(self, iteration):
        return self.base_lr * math.pow(self.factor,
                                       int(iteration / self.step))


class MultiFactorScheduler(LearningRateScheduler):
    """Step-list variant mirroring lr_scheduler.MultiFactorScheduler under
    the legacy calling convention."""

    def __init__(self, step, factor=0.1):
        super().__init__()
        if not isinstance(step, (list, tuple)) or len(step) < 1:
            raise ValueError("step must be a non-empty list of iterations")
        self.step = list(step)
        self.factor = factor

    def __call__(self, iteration):
        lr = self.base_lr
        for s in self.step:
            if iteration >= s:
                lr *= self.factor
        return lr
