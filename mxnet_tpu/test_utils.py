"""Testing utilities.

TPU-native equivalent of the reference's `python/mxnet/test_utils.py` (2k LoC
of fixtures: assert_almost_equal, check_numeric_gradient :?, check_consistency,
rand_ndarray — SURVEY §4). The same three oracles are reproduced:

- **numeric gradients**: central finite differences of an op/graph compared
  against the autograd tape (reference: check_numeric_gradient).
- **cross-backend consistency**: the reference compared CPU vs GPU kernels
  (check_consistency); here the two independent executions are the *naive
  interpreter* (uncompiled, op-by-op eager) and the *jit-compiled* XLA path —
  plus dtype sweeps (fp64/fp32/fp16/bf16) with per-dtype tolerances.
- **seeded RNG**: `with_seed()` decorator (reference:
  tests/python/unittest/common.py) seeding numpy + the framework PRNG, and
  printing the seed on failure so runs reproduce.
"""
from __future__ import annotations

import functools
import os
import random as _pyrandom

import numpy as np

from .base import MXNetError
from .context import Context, cpu, current_context

__all__ = [
    "default_context", "set_default_context", "assert_almost_equal",
    "almost_equal", "same", "same_array", "rand_ndarray", "rand_shape_2d",
    "rand_shape_3d", "rand_shape_nd", "random_arrays", "random_sample",
    "check_numeric_gradient", "check_symbolic_forward", "check_symbolic_backward",
    "check_consistency", "numeric_grad", "simple_forward", "with_seed",
    "assert_exception", "discard_stderr", "DEFAULT_RTOL", "DEFAULT_ATOL",
]

_DEFAULT_CTX = [None]

# per-dtype default tolerances (reference: check_consistency's tol dict)
_DTYPE_TOL = {
    np.dtype(np.float64): (1e-5, 1e-7),
    np.dtype(np.float32): (1e-4, 1e-5),
    np.dtype(np.float16): (1e-2, 1e-3),
}
DEFAULT_RTOL = 1e-4
DEFAULT_ATOL = 1e-5


def default_context():
    """Context used by tests (reference: test_utils.py default_context(),
    switched by env DEV/MXNET_TEST_DEVICE)."""
    if _DEFAULT_CTX[0] is not None:
        return _DEFAULT_CTX[0]
    dev = os.environ.get("MXNET_TEST_DEVICE")
    if dev:
        from . import context as _ctx_mod

        kind, _, idx = dev.partition(":")
        return getattr(_ctx_mod, kind)(int(idx or 0))
    return current_context()


def set_default_context(ctx):
    _DEFAULT_CTX[0] = ctx


def _to_numpy(a):
    from .ndarray.ndarray import NDArray

    if isinstance(a, NDArray):
        return a.asnumpy()
    return np.asarray(a)


def same(a, b):
    return np.array_equal(_to_numpy(a), _to_numpy(b))


def same_array(array1, array2):
    """True if two NDArrays share the same underlying buffer (reference:
    test_utils.py same_array — there it mutates and restores; jax buffers are
    immutable, so identity of the backing jax.Array is the test)."""
    d1 = getattr(array1, "_data", array1)
    d2 = getattr(array2, "_data", array2)
    return d1 is d2


def almost_equal(a, b, rtol=None, atol=None, equal_nan=False):
    a, b = _to_numpy(a), _to_numpy(b)
    rtol = DEFAULT_RTOL if rtol is None else rtol
    atol = DEFAULT_ATOL if atol is None else atol
    return np.allclose(a.astype(np.float64), b.astype(np.float64),
                       rtol=rtol, atol=atol, equal_nan=equal_nan)


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b"),
                        equal_nan=False):
    """Assert all elements close (reference: test_utils.py assert_almost_equal:
    reports max relative error and the worst-offending location)."""
    a_np, b_np = _to_numpy(a), _to_numpy(b)
    rtol = DEFAULT_RTOL if rtol is None else rtol
    atol = DEFAULT_ATOL if atol is None else atol
    if a_np.shape != b_np.shape:
        raise AssertionError("shape mismatch: %s %s vs %s %s"
                             % (names[0], a_np.shape, names[1], b_np.shape))
    af = a_np.astype(np.float64)
    bf = b_np.astype(np.float64)
    if np.allclose(af, bf, rtol=rtol, atol=atol, equal_nan=equal_nan):
        return
    denom = np.maximum(np.abs(af), np.abs(bf))
    denom[denom == 0] = 1.0
    rel = np.abs(af - bf) / denom
    rel[np.isnan(af) & np.isnan(bf)] = 0 if equal_nan else np.inf
    idx = np.unravel_index(np.argmax(rel), rel.shape)
    raise AssertionError(
        "Arrays not almost equal (rtol=%g, atol=%g): max rel err %g at %s: "
        "%s=%r vs %s=%r" % (rtol, atol, float(rel[idx]), list(idx),
                            names[0], af[idx], names[1], bf[idx]))


# --------------------------------------------------------------------------
# random data
# --------------------------------------------------------------------------

def rand_shape_2d(dim0=10, dim1=10):
    return tuple(np.random.randint(1, d + 1) for d in (dim0, dim1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return tuple(np.random.randint(1, d + 1) for d in (dim0, dim1, dim2))


def rand_shape_nd(num_dim, dim=10):
    return tuple(np.random.randint(1, dim + 1, size=num_dim))


def random_sample(population, k):
    return _pyrandom.sample(list(population), k)


def rand_ndarray(shape, stype="default", density=None, dtype="float32",
                 ctx=None, distribution="uniform"):
    """Random NDArray, optionally sparse (reference: test_utils.py
    rand_ndarray / rand_sparse_ndarray)."""
    from . import ndarray as nd

    ctx = ctx or default_context()
    if distribution == "normal":
        arr = np.random.normal(size=shape)
    else:
        arr = np.random.uniform(-1.0, 1.0, size=shape)
    arr = arr.astype(dtype)
    if stype == "default":
        return nd.array(arr, ctx=ctx, dtype=dtype)
    density = 0.3 if density is None else density
    mask = np.random.uniform(0, 1, size=shape) < density
    if stype == "row_sparse":
        row_mask = mask.reshape(shape[0], -1).any(axis=1)
        arr = arr * row_mask.reshape((-1,) + (1,) * (len(shape) - 1))
    else:
        arr = arr * mask
    dense = nd.array(arr, ctx=ctx, dtype=dtype)
    return dense.tostype(stype)


def random_arrays(*shapes):
    arrays = [np.random.randn(*s).astype(np.float32) if s else
              np.array(np.random.randn(), dtype=np.float32) for s in shapes]
    if len(arrays) == 1:
        return arrays[0]
    return arrays


# --------------------------------------------------------------------------
# numeric-gradient oracle
# --------------------------------------------------------------------------

def _as_fn(executor_like):
    """Normalize (Symbol | callable over NDArrays) to fn(dict[str, np]) -> np."""
    from .symbol.symbol import Symbol

    if isinstance(executor_like, Symbol):
        sym = executor_like

        def run(loc, aux):
            vals = dict(loc)
            vals.update(aux or {})
            out = sym.eval_with({k: np.asarray(v) for k, v in vals.items()})
            return [o.asnumpy() for o in out]

        return run, sym.list_arguments()
    raise TypeError("expected Symbol")


def numeric_grad(f, location, eps=1e-4):
    """Central finite differences of scalar-sum(f) wrt each location array
    (reference: test_utils.py numeric_grad)."""
    grads = {}
    loc = {k: np.array(v, dtype=np.float64) for k, v in location.items()}

    def total(vals):
        outs = f(vals)
        return sum(np.asarray(o, dtype=np.float64).sum() for o in outs)

    for name, v in loc.items():
        g = np.zeros_like(v)
        flat = v.reshape(-1)
        gflat = g.reshape(-1)
        for i in range(flat.size):
            old = flat[i]
            flat[i] = old + eps
            fp = total(loc)
            flat[i] = old - eps
            fm = total(loc)
            flat[i] = old
            gflat[i] = (fp - fm) / (2 * eps)
        grads[name] = g
    return grads


def _eval_list(sym, values):
    outs = sym.eval_with(values)
    return outs if isinstance(outs, list) else [outs]


def check_numeric_gradient(sym, location, aux_states=None, numeric_eps=1e-3,
                           rtol=1e-2, atol=None, grad_nodes=None, ctx=None):
    """Finite differences vs the compiled vjp backward on a Symbol
    (reference: test_utils.py check_numeric_gradient). `location`: list or
    dict of numpy arrays for the symbol's arguments."""
    from . import ndarray as nd

    ctx = ctx or default_context()
    args = sym.list_arguments()
    if isinstance(location, (list, tuple)):
        location = dict(zip(args, location))
    location = {k: np.asarray(v, dtype=np.float32) for k, v in location.items()}
    aux_states = {k: np.asarray(v, dtype=np.float32)
                  for k, v in (aux_states or {}).items()}
    grad_nodes = list(grad_nodes) if grad_nodes is not None else list(location)

    # compiled-graph grads of sum(outputs): bind -> forward -> backward(ones)
    arrs = {k: nd.array(v, ctx=ctx) for k, v in location.items()}
    aux = {k: nd.array(v, ctx=ctx) for k, v in aux_states.items()}
    req = {k: ("write" if k in grad_nodes else "null") for k in args}
    exe = sym.bind(ctx, args=arrs, grad_req=req, aux_states=aux)
    exe.forward(is_train=True)
    exe.backward()
    sym_grads = {k: exe.grad_dict[k].asnumpy() for k in grad_nodes}

    # numeric grads
    def f(vals):
        allv = dict(vals)
        allv.update({k: v for k, v in aux_states.items()})
        return [o.asnumpy() for o in _eval_list(sym, allv)]

    num_grads = numeric_grad(f, location, eps=numeric_eps)
    for k in grad_nodes:
        assert_almost_equal(num_grads[k], sym_grads[k], rtol=rtol,
                            atol=atol if atol is not None else rtol,
                            names=("numeric_%s" % k, "autograd_%s" % k))


def check_symbolic_forward(sym, location, expected, rtol=1e-4, atol=None,
                           aux_states=None, ctx=None):
    """Forward outputs vs expected numpy arrays (reference:
    test_utils.py check_symbolic_forward)."""
    ctx = ctx or default_context()
    args = sym.list_arguments()
    if isinstance(location, (list, tuple)):
        location = dict(zip(args, location))
    vals = {k: np.asarray(v) for k, v in location.items()}
    vals.update({k: np.asarray(v) for k, v in (aux_states or {}).items()})
    outs = _eval_list(sym, vals)
    expected = expected if isinstance(expected, (list, tuple)) else [expected]
    for o, e in zip(outs, expected):
        assert_almost_equal(o, e, rtol=rtol, atol=atol, names=("forward", "expected"))
    return [o.asnumpy() for o in outs]


def check_symbolic_backward(sym, location, out_grads, expected, rtol=1e-4,
                            atol=None, grad_req="write", aux_states=None, ctx=None):
    """Backward grads vs expected (reference: test_utils.py
    check_symbolic_backward)."""
    from . import ndarray as nd

    ctx = ctx or default_context()
    args = sym.list_arguments()
    if isinstance(location, (list, tuple)):
        location = dict(zip(args, location))
    if isinstance(expected, (list, tuple)):
        expected = dict(zip(args, expected))
    arrs = {k: nd.array(np.asarray(v), ctx=ctx) for k, v in location.items()}
    aux = {k: nd.array(np.asarray(v), ctx=ctx) for k, v in (aux_states or {}).items()}
    req = {k: (grad_req if k in expected else "null") for k in args}
    exe = sym.bind(ctx, args=arrs, grad_req=req, aux_states=aux)
    exe.forward(is_train=True)
    ograds = [nd.array(np.asarray(g), ctx=ctx) for g in
              (out_grads if isinstance(out_grads, (list, tuple)) else [out_grads])]
    exe.backward(ograds)
    for k, e in expected.items():
        assert_almost_equal(exe.grad_dict[k], e, rtol=rtol, atol=atol,
                            names=("grad_%s" % k, "expected_%s" % k))
    return {k: exe.grad_dict[k].asnumpy() for k in expected}


def check_consistency(sym, location, dtypes=("float64", "float32", "float16"),
                      tol=None, aux_states=None, ctx=None):
    """Cross-backend oracle (reference: test_utils.py check_consistency runs
    one symbol across ctx/dtype list and compares everything against the most
    precise run). Here each dtype runs twice — once through the naive
    op-by-op interpreter, once jit-compiled — and all runs are compared
    against the fp64 naive run."""
    from . import engine

    ctx = ctx or default_context()
    args = sym.list_arguments()
    if isinstance(location, (list, tuple)):
        location = dict(zip(args, location))
    location = {k: np.asarray(v) for k, v in location.items()}
    aux_states = {k: np.asarray(v) for k, v in (aux_states or {}).items()}

    runs = []
    for dt in dtypes:
        for naive in (True, False):
            vals = {k: v.astype(dt) for k, v in location.items()}
            vals.update({k: v.astype(dt) for k, v in aux_states.items()})
            if naive:
                with engine.naive_engine():
                    outs = _eval_list(sym, vals)
            else:
                outs = _eval_list(sym, vals)
            runs.append((dt, naive, [o.asnumpy() for o in outs]))

    ref = runs[0][2]
    for dt, naive, outs in runs[1:]:
        rtol, atol = (tol, tol) if tol is not None else _DTYPE_TOL.get(
            np.dtype(dt), (1e-2, 1e-3))
        for o, r in zip(outs, ref):
            assert_almost_equal(o, r, rtol=rtol, atol=atol,
                                names=("%s%s" % (dt, "/naive" if naive else "/jit"),
                                       "reference"))
    return ref


def simple_forward(sym, ctx=None, is_train=False, **inputs):
    """Evaluate a symbol on numpy kwargs, returning numpy (reference:
    test_utils.py simple_forward)."""
    outs = _eval_list(sym, {k: np.asarray(v) for k, v in inputs.items()})
    outs = [o.asnumpy() for o in outs]
    return outs[0] if len(outs) == 1 else outs


# --------------------------------------------------------------------------
# misc
# --------------------------------------------------------------------------

def with_seed(seed=None):
    """Decorator: seed numpy/python/framework RNG per test, print the seed on
    failure (reference: tests/python/unittest/common.py with_seed)."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            this_seed = seed
            if this_seed is None:
                from . import env as _env_mod

                env = os.environ.get("MXNET_TEST_SEED") \
                    or _env_mod.raw("MXTPU_TEST_SEED")
                this_seed = int(env) if env else np.random.randint(0, 2 ** 31)
            np.random.seed(this_seed)
            _pyrandom.seed(this_seed)
            from . import random as mxrandom

            mxrandom.seed(this_seed)
            try:
                return fn(*args, **kwargs)
            except Exception:
                print("*** test failed with MXNET_TEST_SEED=%d — set this env "
                      "var to reproduce ***" % this_seed)
                raise

        return wrapper

    return deco


def assert_exception(f, exception_type, *args, **kwargs):
    try:
        f(*args, **kwargs)
    except exception_type:
        return
    raise AssertionError("did not raise %s" % exception_type)


class discard_stderr:
    """Context manager silencing stderr (reference: test_utils.py)."""

    def __enter__(self):
        import sys

        self._stderr = os.dup(2)
        self._devnull = os.open(os.devnull, os.O_WRONLY)
        os.dup2(self._devnull, 2)
        return self

    def __exit__(self, *exc):
        os.dup2(self._stderr, 2)
        os.close(self._devnull)
        os.close(self._stderr)
        return False
