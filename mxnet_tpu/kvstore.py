"""Key-value store for parameter synchronization.

Reference surface: python/mxnet/kvstore.py:97 (`KVStore.push` :160, `pull`
:240, `row_sparse_pull` :314, `set_optimizer` :450, rank/num_workers
:513-526) backed natively by src/kvstore/kvstore.cc:40-72 (types
local/device/nccl/dist_sync/dist_async/dist_device_sync) with in-process
reduce strategies (comm.h:103/451) and the ps-lite parameter server
(kvstore_dist.h:44).

TPU-native design (SURVEY §5.8): there is no parameter server and no NCCL.
 - `local` / `device` / `nccl`: single-process multi-device reduction. The
   reduce is one XLA add per key executed on the target device; broadcast is
   a device_put fan-out. (The reference's CommDevice merge-buffer machinery
   is unnecessary — XLA owns transfers.)
 - `dist_sync` / `dist_device_sync` / `horovod` / `tpu`: the same API over
   `jax.distributed` process groups. Under a single process this degrades to
   rank 0 of 1; under multi-host each push/pull additionally all-reduces
   across processes with `jax.make_array_from_process_local_data` +
   collective sum. The *recommended* scaled path keeps gradients inside one
   compiled step function on a Mesh (mxnet_tpu.parallel) so XLA rides ICI;
   this kvstore exists for API parity so Trainer/Module code runs unmodified.
 - `dist_async`: intentionally unsupported (async-PS semantics dropped —
   documented divergence, SURVEY §2.3).

An optimizer can be installed with `set_optimizer` (reference: server-side
update, kvstore_dist_server.h:179); updates then happen during `push` and
`pull` returns updated weights — matching update_on_kvstore semantics.
"""
from __future__ import annotations

import pickle

from .base import MXNetError
from .ndarray import NDArray
from .telemetry.core import counter as _tm_counter

__all__ = ["KVStore", "create"]


def _key_list(key):
    if isinstance(key, (list, tuple)):
        return list(key), True
    return [key], False


def _group_vals(vals, nkeys, batched):
    """Normalize push/pull values to a list (len nkeys) of lists of NDArray."""
    if not batched:
        vals = [vals]
    out = []
    for v in vals:
        if isinstance(v, NDArray):
            out.append([v])
        else:
            out.append(list(v))
    if len(out) != nkeys:
        raise MXNetError("number of keys != number of value groups")
    return out


class KVStore:
    """In-process key-value store; see module docstring for the design."""

    def __init__(self, name="local"):
        self._type = name
        self._store = {}          # key -> NDArray (merged value, on init ctx)
        self._updater = None
        self._optimizer = None
        self._compression_params = None
        self._states = {}         # key -> optimizer state (when optimizer set)

    # ------------------------------------------------------------------ info
    @property
    def type(self):
        return self._type

    @property
    def rank(self):
        """reference: kvstore.py:513 — process rank; single-process = 0."""
        try:
            import jax
            return jax.process_index()
        except Exception:
            return 0

    @property
    def num_workers(self):
        """reference: kvstore.py:526."""
        try:
            import jax
            return jax.process_count()
        except Exception:
            return 1

    # ------------------------------------------------------------- lifecycle
    def init(self, key, value):
        """Initialize key(s) with value(s) (reference: kvstore.py:138)."""
        keys, batched = _key_list(key)
        vals = _group_vals(value, len(keys), batched)
        from .ndarray.sparse import BaseSparseNDArray

        for k, vgroup in zip(keys, vals):
            if k in self._store:
                continue
            v = vgroup[0]
            if isinstance(v, BaseSparseNDArray):
                # store is dense-backed (SURVEY §7.8c): sparse inits densify;
                # row_sparse_pull gathers rows back out
                v = v.tostype("default")
            self._store[k] = v.copy()
            # a (re)initialized key starts with clean error-feedback state —
            # stale residuals from a previous life of the key would inject
            # phantom gradient mass into the first push
            comp = getattr(self, "_compression", None)
            if comp is not None:
                comp.reset(k)

    def push(self, key, value, priority=0):
        """Aggregate value(s) into the store (reference: kvstore.py:160).

        Values for one key (one per device copy) are summed — one XLA add
        chain executed lazily on the first value's device. If an optimizer
        is installed the update is applied here (server-side-update parity).
        """
        keys, batched = _key_list(key)
        vals = _group_vals(value, len(keys), batched)
        _tm_counter("mxtpu_kvstore_ops_total", {"op": "push"}).inc(len(keys))
        from .ndarray.sparse import BaseSparseNDArray, add as _sparse_add

        comp = getattr(self, "_compression", None)
        for k, vgroup in zip(keys, vals):
            if k not in self._store:
                raise MXNetError("key %r has not been initialized" % (k,))
            if comp is not None and not isinstance(vgroup[0], BaseSparseNDArray):
                # quantize each device's contribution separately, with a
                # per-(key, device) residual — keyed by the gradient's
                # context, which is stable even if the number/order of
                # per-device grads changes between pushes (the reference
                # keeps one residual per worker: kvstore_dist.h gc_->Quantize)
                vgroup = [comp.quantize((k, str(v.context)), v)
                          for v in vgroup]
            merged = vgroup[0]
            for v in vgroup[1:]:
                if isinstance(merged, BaseSparseNDArray) or \
                        isinstance(v, BaseSparseNDArray):
                    # row_sparse gradient aggregation (reference: CommCPU
                    # ReduceRowSparse comm.h — union-of-rows merge)
                    merged = _sparse_add(merged, v)
                else:
                    merged = merged + v.as_in_context(merged.context)
            if self._updater is not None:
                self._updater(k, merged, self._store[k])
            else:
                if isinstance(merged, BaseSparseNDArray):
                    merged = merged.tostype("default")
                self._store[k] = merged.as_in_context(self._store[k].context)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        """Broadcast stored value(s) into `out` arrays (reference: :240 —
        like the reference, sparse outs must use row_sparse_pull)."""
        from .ndarray.sparse import BaseSparseNDArray

        keys, batched = _key_list(key)
        outs = _group_vals(out, len(keys), batched)
        _tm_counter("mxtpu_kvstore_ops_total", {"op": "pull"}).inc(len(keys))
        for k, ogroup in zip(keys, outs):
            if k not in self._store:
                raise MXNetError("key %r has not been initialized" % (k,))
            src = self._store[k]
            for o in ogroup:
                if isinstance(o, BaseSparseNDArray):
                    if ignore_sparse:
                        continue  # reference: pull skips sparse when asked
                    raise MXNetError(
                        "pull into a row_sparse array is not supported; use "
                        "row_sparse_pull (matches reference kvstore.py:240)")
                o._set_data(src.as_in_context(o.context)._data)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the requested rows (reference: kvstore.py:314).

        TPU note: row_sparse storage is dense-backed (SURVEY §7.8c); this
        gathers the requested rows with one XLA take per out array.
        """
        if row_ids is None:
            raise MXNetError("row_ids is required for row_sparse_pull")
        keys, batched = _key_list(key)
        outs = _group_vals(out, len(keys), batched)
        rids = row_ids if isinstance(row_ids, (list, tuple)) else [row_ids]
        if len(rids) == 1 and len(outs[0]) > 1:
            rids = rids * len(outs[0])
        for k, ogroup in zip(keys, outs):
            if k not in self._store:
                raise MXNetError("key %r has not been initialized" % (k,))
            src = self._store[k]
            for o, rid in zip(ogroup, rids):
                rows = src.take(rid.as_in_context(src.context))
                o._set_data(rows.as_in_context(o.context)._data)

    def pushpull(self, key, value, out=None, priority=0):
        self.push(key, value, priority=priority)
        self.pull(key, out=out if out is not None else value, priority=priority)

    # ------------------------------------------------------------- optimizer
    def set_updater(self, updater):
        """Install a local updater fn(key, recv, local) (reference: :420)."""
        self._updater = updater

    def set_optimizer(self, optimizer):
        """Run this optimizer inside the store (reference: kvstore.py:450).

        The reference pickles the optimizer to remote servers; here the
        "server" is in-process, so we just build an Updater around it.
        """
        from . import optimizer as opt
        self._optimizer = optimizer
        self._updater = opt.get_updater(optimizer)

    def set_gradient_compression(self, compression_params):
        """reference: kvstore.py:398 — installs 2-bit threshold compression
        with per-key error feedback; every pushed gradient is quantized to
        {-t, 0, +t} before aggregation (gradient_compression.py; reference
        kernels gradient_compression.cc). On an in-process/ICI path this
        reproduces the numerics (the 16x wire saving applies on DCN)."""
        from .gradient_compression import GradientCompression

        params = dict(compression_params)
        if params.get("type", "2bit") == "none":
            self._compression = None
            self._compression_params = params
            return
        self._compression = GradientCompression(**params)
        self._compression_params = params

    def save_optimizer_states(self, fname, dump_optimizer=False):
        """reference: kvstore.py:482 — written atomically (temp + fsync +
        rename) like every other checkpoint path."""
        from .base import atomic_writer

        if self._updater is None:
            raise MXNetError("no optimizer installed on this kvstore")
        with atomic_writer(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer=dump_optimizer))

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("no optimizer installed on this kvstore")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    def barrier(self):
        """Global sync barrier (reference: kvstore.h:364). Single-process:
        just drain pending async work."""
        for v in self._store.values():
            v.wait_to_read()

    def _send_command_to_servers(self, head, body):  # parity stub
        pass

    def __repr__(self):
        return "KVStore(type=%s, keys=%d)" % (self._type, len(self._store))


class _DistKVStore(KVStore):
    """Synchronous multi-process kvstore over jax.distributed.

    Each push reduces device copies locally, then sums across processes.
    Under one process this is identical to `local`. The cross-process sum
    uses a tiny jitted psum over a 1-axis process mesh — DCN-aware via XLA.
    """

    def __init__(self, name="dist_sync"):
        super().__init__(name)
        # ensure the process group exists (bounded rendezvous): a worker
        # that calls kv.create('dist_sync') without an explicit
        # init_process_group() still joins the group — and a group whose
        # peer never arrives fails with a diagnosable MXNetError within
        # MXTPU_RENDEZVOUS_TIMEOUT instead of hanging the first collective.
        # No-op when single-process, env-less, or already initialized.
        from .parallel import collectives

        collectives.init_process_group()

    def init(self, key, value):
        super().init(key, value)
        if self.num_workers > 1:
            # rank 0's value is authoritative (reference ps-lite semantics:
            # worker 0's init lands in the server store and a pull
            # broadcasts it) — without this, ranks that initialize with
            # different random draws would train permanently-diverged
            # replicas (grad sums are identical, so the offset never decays)
            from jax.experimental import multihost_utils
            keys, _ = _key_list(key)
            for k in keys:
                arr = self._store[k]
                arr._set_data(
                    multihost_utils.broadcast_one_to_all(arr._data))

    def push(self, key, value, priority=0):
        super().push(key, value, priority=priority)
        if self.num_workers > 1:
            import jax
            keys, _ = _key_list(key)
            for k in keys:
                arr = self._store[k]
                summed = jax.experimental.multihost_utils.process_allgather(
                    arr._data).sum(axis=0)
                arr._set_data(summed)


def create(name="local"):
    """Factory (reference: src/kvstore/kvstore.cc:40-72)."""
    if not isinstance(name, str):
        raise MXNetError("name must be str")
    name = name.lower()
    if name in ("local", "local_update_cpu", "local_allreduce_cpu",
                "local_allreduce_device", "device", "nccl"):
        return KVStore(name)
    if name in ("dist_sync", "dist_device_sync", "dist_sync_device", "horovod", "tpu"):
        return _DistKVStore(name)
    if name.startswith("dist_async"):
        raise MXNetError(
            "dist_async is not supported by the TPU backend: asynchronous "
            "parameter-server semantics were replaced by synchronous XLA "
            "collectives (see SURVEY.md §2.3). Use dist_sync.")
    raise MXNetError("unknown kvstore type %r" % (name,))
