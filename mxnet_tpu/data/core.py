"""The shared asynchronous input-pipeline core.

One thread/queue implementation behind every prefetching surface in the
library — ``io.PrefetchingIter``, ``image.ImageRecordIterPy``, the gluon
``DataLoader`` threaded path and the device prefetcher
(``data.device_prefetch``). Reference: src/io/iter_prefetcher.h (the
double-buffered prefetcher stage) + src/io/iter_image_recordio_2.cc's
threaded parser pool; design notes in docs/data_pipeline.md.

Two primitives:

* ``PrefetchBuffer`` — a single producer thread filling a bounded queue
  (depth = how many batches may be staged ahead). The worker captures the
  queue and stop event as LOCALS (the PR-12 ``PrefetchingIter`` fix): a
  worker that outlives a timed-out close must never feed a successor
  generation's queue, and a cleared live Event must never resurrect its
  loop. Errors travel the queue as data and re-raise at the consumer.

* ``DecodePool`` — a pipelined decode/augment stage: one feeder thread
  pulls the (not thread-safe) source sequentially, N ``mxtpu-data-worker``
  threads decode in parallel, and delivery is re-sequenced so the consumer
  sees source order deterministically. In-flight work is bounded by a
  semaphore the consumer releases, so an abandoned consumer backpressures
  the whole pipeline instead of buffering the dataset.

Both stop the same way: set the stop event, drain, join within
``MXTPU_DATA_JOIN_TIMEOUT_S``, and raise ``MXNetError`` if a worker cannot
be joined — proceeding would rewind reader state under a live reader.
"""
from __future__ import annotations

import queue
import threading
import time

from .. import env as _env
from ..base import MXNetError

__all__ = ["PrefetchBuffer", "DecodePool"]

# queue sentinel marking normal end-of-stream (StopIteration in the
# producer); module-private on purpose — it must never be a legal payload
_END = object()


class _Raised:
    """Error envelope: a producer exception travels the queue as data and
    re-raises at the consumer (a bare Exception instance must stay a legal
    payload for producers that yield exceptions as values)."""

    __slots__ = ("exc",)

    def __init__(self, exc):
        self.exc = exc


def join_timeout():
    """Seconds close()/reset() wait for pipeline threads to stop."""
    return float(_env.get("MXTPU_DATA_JOIN_TIMEOUT_S"))


def _put_bounded(q, item, stop):
    """Bounded put that honors the stop signal; False if stopped first."""
    while not stop.is_set():
        try:
            q.put(item, timeout=0.1)
            return True
        except queue.Full:
            continue
    return False


class PrefetchBuffer:
    """Single-producer bounded prefetch queue.

    ``produce`` is called repeatedly on a background thread; items are
    staged in a queue of ``depth`` so the consumer's ``get()`` overlaps
    with production of the next items. ``StopIteration`` from ``produce``
    ends the stream (``get()`` raises it to the consumer); any other
    exception is re-raised at the consumer's next ``get()``.

    ``get()`` also attributes each delivery as a prefetch *hit* (item was
    already staged — the pipeline kept up) or *miss* (the consumer
    blocked — production is the bottleneck), exported as the
    ``mxtpu_data_prefetch_{hits,misses}_total{src=...}`` counters that
    docs/data_pipeline.md's "why is data_wait high" playbook reads.
    """

    def __init__(self, produce, depth=2, name="mxtpu-data-prefetch",
                 owner="PrefetchBuffer", src="data", inject=True):
        from .. import telemetry

        self._produce = produce
        self._depth = max(1, int(depth))
        self._name = name
        self._owner = owner
        self._inject = inject
        self._hits = telemetry.counter("mxtpu_data_prefetch_hits_total",
                                       {"src": src})
        self._misses = telemetry.counter("mxtpu_data_prefetch_misses_total",
                                         {"src": src})
        self._thread = None
        self._stop = None
        self._queue = None
        self._finished = False
        self._start()

    @property
    def depth(self):
        return self._depth

    def _start(self):
        # capture-as-local: the worker must never read self._queue /
        # self._stop live — a stale worker surviving a timed-out close
        # would otherwise feed the NEXT generation's queue (the
        # lock-discipline checker flags the reassign-under-use shape this
        # guards against)
        self._stop = stop = threading.Event()
        self._queue = q = queue.Queue(maxsize=self._depth)
        self._finished = False
        produce = self._produce
        inject = self._inject

        def run():
            from ..parallel import resilience

            n = 0
            while not stop.is_set():
                try:
                    item = produce()
                except StopIteration:
                    _put_bounded(q, _END, stop)
                    return
                except Exception as e:
                    _put_bounded(q, _Raised(e), stop)
                    return
                n += 1
                if inject:
                    # producer-side chaos hook (slow_batch@step=,ms=):
                    # one cached-empty check unless MXTPU_FAULT_INJECT is
                    # set — stalls PRODUCTION so the chaos e2e can prove
                    # the buffer absorbs jitter up to depth x step-time
                    resilience.maybe_inject_data_stall(n)
                if not _put_bounded(q, item, stop):
                    return

        self._thread = threading.Thread(target=run, daemon=True,
                                        name=self._name)
        self._thread.start()

    def get(self):
        """Next produced item; raises StopIteration at end-of-stream and
        re-raises producer errors."""
        if self._finished:
            raise StopIteration
        try:
            item = self._queue.get_nowait()
            self._hits.inc()
        except queue.Empty:
            self._misses.inc()
            item = self._queue.get()
        if item is _END:
            self._finished = True
            raise StopIteration
        if isinstance(item, _Raised):
            self._finished = True
            raise item.exc
        return item

    def close(self):
        """Stop + join the producer (draining the queue so a blocked put
        wakes up). Raises MXNetError when the worker cannot be joined —
        the caller must NOT rewind reader state under a live reader."""
        if self._thread is None:
            return
        self._stop.set()
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        timeout = join_timeout()
        self._thread.join(timeout=timeout)
        if self._thread.is_alive():
            raise MXNetError(
                "%s: prefetch worker did not stop within %.0fs (stalled "
                "read?); cannot safely rewind" % (self._owner, timeout))
        self._thread = None

    def restart(self):
        """Start a fresh producer generation (after close() + the caller
        rewinding its source)."""
        if self._thread is not None:
            raise MXNetError("%s: restart() before close()" % self._owner)
        self._start()


class _PoolGen:
    """One DecodePool generation's shared state. Every pipeline thread
    captures the generation object as a local at spawn (capture-as-local):
    a reset swaps in a whole new generation, so a straggler thread from a
    timed-out close can only ever touch its own dead generation."""

    __slots__ = ("cv", "stop", "tasks", "results", "slots", "end_seq",
                 "next_seq")

    def __init__(self, depth, workers):
        self.cv = threading.Condition()
        self.stop = threading.Event()
        # feeder -> workers; bounded so the feeder cannot race ahead
        self.tasks = queue.Queue(maxsize=depth)
        # seq -> decoded item (or _Raised); delivery re-sequences on seq
        self.results = {}
        # total in-flight items (queued + decoding + decoded-undelivered):
        # acquired by the feeder per record, released by the consumer per
        # delivery — the end-to-end backpressure bound
        self.slots = threading.Semaphore(depth + workers)
        self.end_seq = None   # set (under cv) when the source is exhausted
        self.next_seq = 0     # next sequence number the consumer delivers


def _pool_feed(gen, source, nworkers):
    """Feeder thread: pulls the source sequentially (record readers are
    not thread-safe), tags records with sequence numbers, and fans them
    out to the workers."""
    seq = 0
    while not gen.stop.is_set():
        if not gen.slots.acquire(timeout=0.1):
            continue
        try:
            raw = source()
        except StopIteration:
            gen.slots.release()
            break
        except Exception as e:
            # source errors are ordered like data: delivered at this seq,
            # after every earlier record, then the stream ends
            with gen.cv:
                gen.results[seq] = _Raised(e)
                gen.cv.notify_all()
            seq += 1
            break
        if not _put_bounded(gen.tasks, (seq, raw), gen.stop):
            return
        seq += 1
    with gen.cv:
        gen.end_seq = seq
        gen.cv.notify_all()
    for _ in range(nworkers):
        _put_bounded(gen.tasks, _END, gen.stop)


def _pool_work(gen, decode):
    """Worker thread: decode records; errors become that record's result
    so the consumer sees them at the deterministic source position."""
    while not gen.stop.is_set():
        try:
            task = gen.tasks.get(timeout=0.1)
        except queue.Empty:
            continue
        if task is _END:
            return
        seq, raw = task
        try:
            item = decode(raw)
        except Exception as e:
            item = _Raised(e)
        with gen.cv:
            gen.results[seq] = item
            gen.cv.notify_all()


class DecodePool:
    """Pipelined decode stage: N parallel workers, source-order delivery.

    ``source()`` returns the next raw record (StopIteration at end);
    ``decode(raw)`` runs on the worker threads. ``get()`` returns decoded
    items in exact source order regardless of which worker finished first
    — determinism the shuffle/cursor machinery depends on.
    """

    def __init__(self, source, decode, workers=1, depth=2,
                 name="mxtpu-data-worker", owner="DecodePool"):
        self._source = source
        self._decode = decode
        self._workers = max(1, int(workers))
        self._depth = max(1, int(depth))
        self._name = name
        self._owner = owner
        self._gen = None
        self._threads = ()

    @property
    def workers(self):
        return self._workers

    def _start(self):
        gen = _PoolGen(self._depth, self._workers)
        source, decode, nworkers = self._source, self._decode, self._workers
        threads = [threading.Thread(
            target=_pool_feed, args=(gen, source, nworkers), daemon=True,
            name="mxtpu-data-feeder")]
        for i in range(nworkers):
            threads.append(threading.Thread(
                target=_pool_work, args=(gen, decode), daemon=True,
                name="%s-%d" % (self._name, i)))
        for t in threads:
            t.start()
        self._gen = gen
        self._threads = tuple(threads)

    def get(self):
        """Next decoded item in source order; StopIteration at the end,
        decode/source errors re-raised at their source position."""
        if self._gen is None:
            self._start()
        gen = self._gen
        with gen.cv:
            while True:
                if gen.next_seq in gen.results:
                    item = gen.results.pop(gen.next_seq)
                    gen.next_seq += 1
                    gen.slots.release()
                    if isinstance(item, _Raised):
                        raise item.exc
                    return item
                if gen.end_seq is not None and gen.next_seq >= gen.end_seq:
                    raise StopIteration
                gen.cv.wait(timeout=0.5)

    def close(self):
        """Stop + join feeder and workers; MXNetError if any survive the
        join window (the caller must not rewind the source under them)."""
        gen = self._gen
        if gen is None:
            return
        gen.stop.set()
        try:
            while True:
                gen.tasks.get_nowait()
        except queue.Empty:
            pass
        with gen.cv:
            gen.cv.notify_all()
        timeout = join_timeout()
        deadline = time.monotonic() + timeout
        for t in self._threads:
            t.join(timeout=max(0.05, deadline - time.monotonic()))
        if any(t.is_alive() for t in self._threads):
            raise MXNetError(
                "%s: decode pipeline did not stop within %.0fs (stalled "
                "read?); cannot safely rewind" % (self._owner, timeout))
        self._gen = None
        self._threads = ()

    def reset(self):
        """Stop the pipeline; the next get() starts a fresh generation
        (the caller rewinds the source in between)."""
        self.close()
